"""Config-driven construction — the ``allennlp train`` equivalent.

The reference constructs every component from a JSON config via the
AllenNLP registry and trains with ``allennlp train <config> -s <dir>
--include-package MemVul`` (reference: README.md:140-145).  This module
reads the same config *shape* (``dataset_reader`` / ``model`` /
``trainer`` / ``train_data_path`` / ... keys, ``"type"`` registry
selection) and builds the TPU-native components:

* ``build_tokenizer`` / ``build_reader`` — via the Registrable registry;
* ``build_model`` — ``model_memory`` → :class:`MemoryModel`,
  ``model_single`` → :class:`SingleModel`, ``model_cnn`` →
  :class:`TextCNN`, with an ``encoder`` sub-config mapping onto
  :class:`BertConfig` (dtype names resolved to jnp dtypes);
* ``train_from_config`` — full train run + ``model.tar.gz`` archive of
  the best weights (the serialization-dir contract);
* ``evaluate_from_archive`` — the ``predict_memory.py``/
  ``predict_single.py`` flow from an archive with config overrides.
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Any, Dict, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

DTYPES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
}


def encoder_config(cfg: Optional[Dict[str, Any]], vocab_size: Optional[int] = None):
    """``{"preset": "base"|"tiny", "dtype": "bfloat16", ...}`` → BertConfig."""
    from .models import BertConfig

    cfg = dict(cfg or {})
    preset = cfg.pop("preset", "base")
    dtype = cfg.pop("dtype", None)
    if dtype is not None and isinstance(dtype, str):
        cfg["dtype"] = DTYPES[dtype]
    elif dtype is not None:
        cfg["dtype"] = dtype
    if vocab_size is not None:
        cfg.setdefault("vocab_size", vocab_size)
    factory = {
        "tiny": BertConfig.tiny,
        "base": BertConfig.base,
        "large": BertConfig.large,
    }[preset]
    return factory(**cfg)


def build_tokenizer(cfg: Optional[Dict[str, Any]]):
    from .data.tokenizer import TextTokenizer

    return TextTokenizer.from_config(cfg or {})


def build_reader(cfg: Optional[Dict[str, Any]], seed: Optional[int] = None):
    """``seed`` (usually the config's ``random_seed``) reaches the
    reader's pair-sampling RNG unless the reader block pins its own —
    the reference gets the same property from AllenNLP's global
    ``random_seed`` (config_memory.json:6); without it, online pair
    sampling draws from OS entropy and two identically-configured runs
    train on different pair streams."""
    from .data.readers import DatasetReader

    cfg = dict(cfg or {})
    cfg.setdefault("type", "reader_memory")
    if seed is not None:
        cfg.setdefault("seed", seed)
    return DatasetReader.from_config(cfg)


def build_model(model_cfg: Dict[str, Any], vocab_size: int):
    """Construct the model module named by ``model_cfg["type"]``."""
    from .models import MemoryModel, SingleModel
    from .models.textcnn import TextCNN

    cfg = dict(model_cfg or {})
    cfg.pop("pretrained_checkpoint", None)  # handled by the caller
    model_type = cfg.pop("type", "model_memory")
    if model_type == "model_memory":
        return MemoryModel(
            encoder_config(cfg.pop("encoder", None), vocab_size), **cfg
        )
    if model_type == "model_single":
        return SingleModel(
            encoder_config(cfg.pop("encoder", None), vocab_size), **cfg
        )
    if model_type == "model_cnn":
        cfg.pop("encoder", None)
        return TextCNN(vocab_size=vocab_size, **cfg)
    raise ValueError(f"unknown model type {model_type!r}")


def init_params(model, seed: int = 0):
    """Initialize parameters with the dummy-batch shapes each model needs."""
    from .models import MemoryModel

    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    rng = jax.random.PRNGKey(seed)
    if isinstance(model, MemoryModel):
        return model.init(rng, dummy, dummy)
    return model.init(rng, dummy)


def load_pretrained_encoder(params, checkpoint: Union[str, Path]):
    """Transplant a further-pretrained encoder (the MLM subsystem's output,
    reference: custom_PTM_embedder.py:95-99 loading ``out_wwm/``)."""
    from flax import serialization

    from .pretrain.mlm import transplant_encoder

    path = Path(checkpoint)
    if path.is_dir():
        path = path / "encoder.msgpack"
    encoder_subtree = serialization.msgpack_restore(path.read_bytes())
    return transplant_encoder(params, encoder_subtree)


def save_encoder_checkpoint(encoder_params, out_dir: Union[str, Path]) -> Path:
    """Persist an MLM-pretrained encoder for later transplant."""
    from flax import serialization

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    path = out_dir / "encoder.msgpack"
    path.write_bytes(serialization.to_bytes(jax.device_get(encoder_params)))
    return path


def export_hf_checkpoint(
    bert_subtree, config, out_dir: Union[str, Path], tokenizer=None
) -> Path:
    """Write an encoder as an HF-format checkpoint dir (config.json +
    pytorch_model.bin, plus vocab.txt when a tokenizer is given) that
    ``AutoModel.from_pretrained`` loads — so an encoder further-pretrained
    HERE plugs into the reference's embedder
    (custom_PTM_embedder.py:80,95-99) unchanged.  The inverse direction
    (reference/HF → Flax) is models/convert.py:convert_bert_state_dict."""
    import torch

    from .models.convert import export_bert_state_dict

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    sd = export_bert_state_dict(bert_subtree, None, config)
    torch.save(
        {k: torch.tensor(v) for k, v in sd.items()},
        out_dir / "pytorch_model.bin",
    )
    (out_dir / "config.json").write_text(json.dumps({
        "model_type": "bert",
        "architectures": ["BertModel"],
        "vocab_size": config.vocab_size,
        "hidden_size": config.hidden_size,
        "num_hidden_layers": config.num_layers,
        "num_attention_heads": config.num_heads,
        "intermediate_size": config.intermediate_size,
        "max_position_embeddings": config.max_position_embeddings,
        "hidden_act": "gelu",
        "layer_norm_eps": config.layer_norm_eps,
        "hidden_dropout_prob": config.hidden_dropout,
        "attention_probs_dropout_prob": config.attention_dropout,
        "pad_token_id": 0,
        "type_vocab_size": config.type_vocab_size,
    }, indent=2))
    if tokenizer is not None:
        if not hasattr(tokenizer, "save_vocab_txt"):
            raise TypeError(
                f"{type(tokenizer).__name__} cannot export a bert vocab.txt "
                "— HF export needs the wordpiece tokenizer"
            )
        tokenizer.save_vocab_txt(out_dir / "vocab.txt")
    return out_dir


def _tokenizer_file(tok_cfg: Optional[Dict[str, Any]]) -> Optional[str]:
    """The file to embed in the archive — MUST mirror the selection
    precedence of ``WordPieceTokenizer.__init__`` (an existing vocab.txt
    wins) so the archived tokenizer is the one training actually used."""
    tok_cfg = tok_cfg or {}
    vocab = tok_cfg.get("vocab_path")
    if vocab and Path(vocab).exists():
        return vocab
    return tok_cfg.get("tokenizer_path") or vocab


def train_from_config(
    config: Dict[str, Any],
    serialization_dir: Union[str, Path],
    mesh=None,
) -> Dict[str, Any]:
    """Run a full training job described by a reference-shaped config and
    archive the best model as ``<dir>/model.tar.gz``.

    The config's ``telemetry`` section (config.TELEMETRY_DEFAULTS)
    configures the process-wide registry with the serialization dir as
    the run dir, so the trainer's step events / HEARTBEAT.json /
    telemetry.json land beside the checkpoints; ``telemetry.trace_dir``
    routes into the trainer's epoch-0 ``jax.profiler`` trace."""
    from . import telemetry
    from .archive import ARCHIVE_NAME, save_archive
    from .config import telemetry_config

    serialization_dir = Path(serialization_dir)
    serialization_dir.mkdir(parents=True, exist_ok=True)
    (serialization_dir / "config.json").write_text(json.dumps(config, indent=2))

    tel_cfg = telemetry_config(config)
    tel = telemetry.configure(
        run_dir=serialization_dir,
        enabled=bool(tel_cfg["enabled"]),
        events=bool(tel_cfg["events"]),
        heartbeat_every_s=float(tel_cfg["heartbeat_every_s"]),
        step_events=bool(tel_cfg["step_events"]),
    )
    # opt-in live scrape surface for the (multi-hour) run: /metrics +
    # /programz on a daemon thread; 0 (the default) constructs nothing
    metrics_port = int(tel_cfg["metrics_port"] or 0)
    metrics_server = None
    if metrics_port:
        # the history plane rides the exposition server: with both
        # knobs on, /metricsz + /alertz answer over a sampler of the
        # process-wide parts; cadence 0 (default) constructs nothing
        sampler = engine = None
        tsdb_cadence = float(tel_cfg["tsdb_cadence_s"] or 0.0)
        if tsdb_cadence > 0:
            from .telemetry.live import live_parts

            sampler = telemetry.MetricsSampler(
                live_parts,
                store=telemetry.TimeSeriesStore(
                    resolution_s=float(tel_cfg["tsdb_resolution_s"]),
                    retention_s=float(tel_cfg["tsdb_retention_s"]),
                ),
                cadence_s=tsdb_cadence,
            )
            engine = telemetry.AlertEngine(sampler.store)
        metrics_server = telemetry.start_metrics_server(
            metrics_port, sampler=sampler, engine=engine
        )

    seed = int(config.get("random_seed", 2021))
    tokenizer = build_tokenizer(config.get("tokenizer"))
    reader = build_reader(config.get("dataset_reader"), seed=seed)
    model_cfg = config.get("model") or {}
    model = build_model(model_cfg, tokenizer.vocab_size)
    params = init_params(model, seed)
    if model_cfg.get("pretrained_checkpoint"):
        ckpt = Path(model_cfg["pretrained_checkpoint"])
        if ckpt.exists():
            params = load_pretrained_encoder(params, ckpt)
            logger.info("loaded further-pretrained encoder from %s", ckpt)
        else:
            logger.warning(
                "pretrained_checkpoint %s missing — training from scratch", ckpt
            )

    from .config import validate_training_config
    from .tuning.profile import apply_tuned_trainer

    # overlay the device class's tuned profile UNDER the explicit
    # trainer section (docs/tuning.md: explicit config always wins; no
    # configured profile store → the dict passes through untouched),
    # then fail on a bad feed depth / bucket grid here, not minutes
    # into epoch 0 — tuned knobs get exactly the same validation
    trainer_cfg = validate_training_config(
        apply_tuned_trainer(dict(config.get("trainer") or {}), config)
    )
    trainer_cfg.setdefault("seed", seed)
    trainer_cfg["serialization_dir"] = str(serialization_dir)
    if tel_cfg["trace_dir"] and not trainer_cfg.get("profile_dir"):
        # the telemetry.trace_dir knob rides the trainer's existing
        # epoch-0 trace_context; named scopes make the trace legible
        trainer_cfg["profile_dir"] = str(tel_cfg["trace_dir"])
    model_type = model_cfg.get("type", "model_memory")

    if model_type == "model_memory":
        from .training.trainer import MemoryTrainer, TrainerConfig

        trainer = MemoryTrainer(
            model,
            params,
            tokenizer,
            reader,
            train_path=config["train_data_path"],
            validation_path=config.get("validation_data_path"),
            anchor_path=config.get("anchor_path")
            or (config.get("dataset_reader") or {}).get("anchor_path"),
            config=TrainerConfig(**trainer_cfg),
            mesh=mesh,
        )
    else:
        from .training.single_trainer import ClassifierTrainer, ClassifierTrainerConfig

        trainer = ClassifierTrainer(
            model,
            params,
            tokenizer,
            reader,
            train_path=config["train_data_path"],
            validation_path=config.get("validation_data_path"),
            config=ClassifierTrainerConfig(**trainer_cfg),
            mesh=mesh,
        )

    try:
        result = trainer.train()
        best = jax.device_get(trainer.best_params())
        archived = dict(config)
        archived["model"] = dict(model_cfg)
        with tel.span("archive"):
            save_archive(
                serialization_dir / ARCHIVE_NAME,
                archived,
                best,
                tokenizer_file=_tokenizer_file(config.get("tokenizer")),
            )
        (serialization_dir / "metrics.json").write_text(
            json.dumps(result, indent=2, default=float)
        )
    finally:
        # final heartbeat + telemetry.json rollup, even on a crash — the
        # post-mortem is exactly when the summary matters.  The program
        # table lands beside the sinks (telemetry-report's PROGRAMS
        # section), and a SIGTERM-preempted run unwinds through here
        # too, so the exposition port always releases cleanly.
        if tel.enabled:
            telemetry.write_programs(serialization_dir)
        tel.close()
        if metrics_server is not None:
            metrics_server.close()
    result["archive"] = str(serialization_dir / ARCHIVE_NAME)
    return result


def serve_from_archive(
    archive_path: Union[str, Path],
    out_dir: Optional[Union[str, Path]] = None,
    overrides: Optional[Union[str, Dict[str, Any]]] = None,
    golden_file: Optional[Union[str, Path]] = None,
    mesh=None,
    use_mesh: bool = False,
    replicas: Optional[int] = None,
    tsdb_cadence: Optional[float] = None,
    tenants: Optional[str] = None,
):
    """Build a ready :class:`~memvul_tpu.serving.ScoringService` — or,
    with ``replicas > 1`` (argument or the archive's
    ``serving.replicas``), a :class:`~memvul_tpu.serving.ReplicaRouter`
    over that many services — from a model archive (docs/serving.md).

    The archive's ``serving`` section (config.SERVING_DEFAULTS) sizes
    the online predictor — ``max_batch`` is its batch shape, so the AOT
    warmup precompiles exactly the shapes the micro-batcher will
    dispatch — and the service's admission-control envelope.  With
    ``out_dir`` set, telemetry sinks and the versioned anchor-bank
    manifest land there (per replica in ``replica-<i>/`` subdirs for a
    fleet); the caller owns the registry's ``close()`` (the CLI closes
    it after the drain).

    Replica fan-out places one predictor per local device (round-robin
    over ``jax.local_devices()`` — on a multi-host job each host runs
    its own fleet over its own devices, the
    ``parallel/multihost.py`` enumeration); each replica re-encodes the
    anchor bank onto its device and AOT-warms its own shapes via its
    service factory, which the router also uses to *restart* a failed
    replica."""
    from . import telemetry
    from .archive import load_archive
    from .config import bankops_config, serving_config, telemetry_config
    from .data.batching import validate_buckets
    from .evaluate.predict_memory import SiamesePredictor
    from .resilience.retry import RetryPolicy
    from .serving import (
        Replica,
        ReplicaRouter,
        RouterConfig,
        ScoringService,
        ServiceConfig,
    )

    arch = load_archive(archive_path, overrides=overrides)
    model_cfg = arch.config.get("model") or {}
    model_type = model_cfg.get("type", "model_memory")
    if model_type != "model_memory":
        raise ValueError(
            f"serving wraps the Siamese memory model; archive has "
            f"model type {model_type!r}"
        )
    tel_cfg = telemetry_config(arch.config)
    if out_dir is not None:
        out_dir = Path(out_dir)
        out_dir.mkdir(parents=True, exist_ok=True)
        telemetry.configure(
            run_dir=out_dir,
            enabled=bool(tel_cfg["enabled"]),
            events=bool(tel_cfg["events"]),
            heartbeat_every_s=float(tel_cfg["heartbeat_every_s"]),
            step_events=bool(tel_cfg["step_events"]),
        )
    serve_cfg = serving_config(arch.config)
    # overlay the device class's tuned profile UNDER the archive's
    # explicit serving section (docs/tuning.md): a key the archive (or
    # overrides) wrote non-null always wins; tuned knobs fill the rest,
    # BEFORE the validation below so they answer to the same checks
    from .tuning.profile import apply_tuned_serving

    serve_cfg = apply_tuned_serving(
        serve_cfg, arch.config.get("serving") or {}, arch.config
    )
    max_length = int(serve_cfg["max_length"])
    model_positions = getattr(
        getattr(arch.model, "config", None), "max_position_embeddings", None
    )
    if model_positions is not None and max_length > model_positions:
        logger.warning(
            "serving max_length %d exceeds the archived model's "
            "max_position_embeddings %d — clamping",
            max_length, model_positions,
        )
        max_length = model_positions
    buckets = serve_cfg["buckets"]
    if buckets == "auto":
        raise ValueError(
            'serving.buckets "auto" is an offline policy (it samples a '
            "corpus); pass an explicit bucket list for serving"
        )
    if buckets is not None:
        buckets = validate_buckets([int(b) for b in buckets], max_length)
    # ragged serve path (docs/ragged_serving.md): one packed program
    # replaces the bucket grid; sizing defaults derive from the serve
    # envelope (budget covers max_batch typical-length requests only if
    # configured — the default 4×max_length favors a small warm program)
    score_impl = str(serve_cfg["score_impl"])
    if score_impl not in ("bucketed", "ragged", "continuous", "cascade"):
        raise ValueError(
            f"serving.score_impl must be 'bucketed', 'ragged', "
            f"'continuous' or 'cascade', got {score_impl!r}"
        )
    # quantized cascade (docs/quantized_serving.md): the predictor builds
    # a second warmed int8 program family and the dispatcher re-routes
    # only in-band rows to fp32
    encoder_precision = "int8" if score_impl == "cascade" else "fp32"
    cascade_low = float(serve_cfg["cascade_low"])
    cascade_high = float(serve_cfg["cascade_high"])
    if not (0.0 <= cascade_low <= cascade_high <= 1.0):
        raise ValueError(
            "serving.cascade_low/cascade_high must satisfy "
            f"0 <= low <= high <= 1, got [{cascade_low!r}, {cascade_high!r}]"
        )
    token_budget = serve_cfg["token_budget"]
    token_budget = None if token_budget is None else int(token_budget)
    max_rows_per_pack = serve_cfg["max_rows_per_pack"]
    max_rows_per_pack = (
        int(serve_cfg["max_batch"]) if max_rows_per_pack is None
        else int(max_rows_per_pack)
    )
    reader = build_reader(arch.config.get("dataset_reader"))
    golden = golden_file or (
        arch.config.get("dataset_reader") or {}
    ).get("anchor_path")
    if golden is None:
        raise ValueError("serving needs a golden anchor file")
    anchors = list(reader.read_anchors(str(golden)))
    retries = int(serve_cfg["retries"])
    retry_policy = RetryPolicy(attempts=retries) if retries > 0 else None
    bank_cfg = bankops_config(arch.config)
    trace_sample_rate = float(serve_cfg["trace_sample_rate"])
    if not 0.0 <= trace_sample_rate <= 1.0:
        raise ValueError(
            "serving.trace_sample_rate must be in [0, 1], got "
            f"{trace_sample_rate!r}"
        )
    # the metrics-history plane (telemetry/timeseries.py + alerts +
    # serving/incident.py); the argument (the --tsdb-cadence CLI flag)
    # overrides the archive's telemetry.tsdb_cadence_s.  0 (default) =
    # off = nothing constructed, nothing emitted
    tsdb_cadence = float(
        tel_cfg["tsdb_cadence_s"] if tsdb_cadence is None else tsdb_cadence
    )
    if tsdb_cadence < 0:
        raise ValueError(
            f"telemetry.tsdb_cadence_s must be >= 0, got {tsdb_cadence!r}"
        )
    service_config = ServiceConfig(
        max_batch=int(serve_cfg["max_batch"]),
        max_wait_ms=float(serve_cfg["max_wait_ms"]),
        max_queue=int(serve_cfg["max_queue"]),
        default_deadline_ms=float(serve_cfg["default_deadline_ms"]),
        anchor_stats=bool(bank_cfg["anchor_stats"]),
        trace_sample_rate=trace_sample_rate,
        trace_ring=int(serve_cfg["trace_ring"]),
        hbm_gauges=bool(tel_cfg["hbm_gauges"]),
        cache_capacity=int(serve_cfg["cache_capacity"] or 0),
        prefix_share=bool(serve_cfg["prefix_share"]),
    )
    n_replicas = int(
        serve_cfg["replicas"] if replicas is None else replicas
    )

    def _with_slo_monitor(target):
        # the live SLO evaluator (serving/slo.py): slo.* gauges, the
        # /healthz slo block, and the scale_hint autoscaling signal.
        # Attached as an attribute (like drift_monitor) so the CLI can
        # stop it at drain and the harness/frontend can read status().
        if bool(serve_cfg["slo_enabled"]):
            from .serving.slo import SLOConfig, SLOMonitor

            target.slo_monitor = SLOMonitor(
                target,
                registry=telemetry.get_registry(),
                config=SLOConfig(
                    availability_objective=float(
                        serve_cfg["slo_availability_objective"]
                    ),
                    latency_p95_ms=float(serve_cfg["slo_latency_p95_ms"]),
                    fast_window_s=float(serve_cfg["slo_fast_window_s"]),
                    window_s=float(serve_cfg["slo_window_s"]),
                    interval_s=float(serve_cfg["slo_interval_s"]),
                ),
            )
        return target

    def _with_drift_monitor(target):
        # bankops.baseline pins a win-share distribution; a background
        # monitor republishes the bank.anchor_drift gauge from the
        # serving counters (bankops/drift.py; docs/anchor_bank.md).
        # Attached as an attribute so the CLI can stop it at drain.
        baseline_path = bank_cfg["baseline"]
        if baseline_path:
            from .bankops.drift import DriftMonitor, load_baseline

            baseline = load_baseline(baseline_path)
            if baseline:
                target.drift_monitor = DriftMonitor(
                    telemetry.get_registry(),
                    baseline,
                    interval_s=float(bank_cfg["drift_interval_s"]),
                )
            else:
                logger.warning(
                    "bankops.baseline %s missing/unreadable — drift "
                    "gauge disabled", baseline_path,
                )
        return target

    def _with_tenants(target):
        # multi-tenant bank plane (serving/tenancy.py,
        # docs/multitenancy.md): resolve "name=store_dir,..." to per-org
        # BankStores and install each tenant's ACTIVE bank through the
        # gated swap path.  The CLI --tenants flag overrides the
        # archive's serving.tenants; neither set = nothing constructed,
        # the single-tenant path stays byte-identical.  Applied LAST so
        # the installs roll through a fully-assembled target.
        spec = tenants if tenants is not None else serve_cfg["tenants"]
        if spec:
            from .serving.tenancy import configure_tenants

            configure_tenants(
                target, spec, registry=telemetry.get_registry()
            )
        return target

    def _with_flight_recorder(target):
        # the post-hoc "what happened" plane (docs/observability.md):
        # TSDB sampler + alert rules + (with out_dir) incident bundles.
        # attach_flight_recorder is the single on/off gate — cadence 0
        # returns the target untouched, constructing nothing, so the
        # default run's emitted metric/event set stays byte-identical.
        # Must wrap LAST: the sampler/recorder see slo_monitor and
        # autoscaler attributes only if they are already attached.
        if tsdb_cadence > 0:
            from .serving.incident import attach_flight_recorder

            attach_flight_recorder(
                target,
                run_dir=out_dir,
                registry=telemetry.get_registry(),
                cadence_s=tsdb_cadence,
                resolution_s=float(tel_cfg["tsdb_resolution_s"]),
                retention_s=float(tel_cfg["tsdb_retention_s"]),
                alert_interval_s=float(serve_cfg["alert_interval_s"]),
                min_interval_s=float(serve_cfg["incident_min_interval_s"]),
                max_bundles=int(serve_cfg["incident_max_bundles"]),
                window_s=float(serve_cfg["incident_window_s"]),
            )
        return target

    if n_replicas <= 1:
        if mesh is None and use_mesh and len(jax.devices()) > 1:
            from .parallel.mesh import create_mesh

            mesh = create_mesh()
        predictor = SiamesePredictor(
            arch.model,
            arch.params,
            arch.tokenizer,
            mesh=mesh,
            batch_size=int(serve_cfg["max_batch"]),
            max_length=max_length,
            buckets=buckets,
            aot_warmup=True,  # the whole point: no mid-serve compiles
            score_impl=score_impl,
            token_budget=token_budget,
            max_rows_per_pack=max_rows_per_pack,
            encoder_precision=encoder_precision,
            cascade_low=cascade_low,
            cascade_high=cascade_high,
        )
        predictor.encode_anchors(anchors)
        return _with_tenants(_with_flight_recorder(_with_slo_monitor(
            _with_drift_monitor(
                ScoringService(
                    predictor,
                    config=service_config,
                    retry_policy=retry_policy,
                    manifest_dir=out_dir,
                )
            )
        )))

    # -- replica fan-out: one service per assigned local device ------------
    if mesh is not None:
        raise ValueError(
            "--mesh shards ONE service across devices; replicas > 1 runs "
            "one service PER device — pick one scaling axis"
        )
    devices = jax.local_devices()

    def make_factory(index: int):
        device = devices[index % len(devices)]

        def factory(registry):
            # commit this replica's weights to its device: every dispatch
            # (and its compiled programs) follows the committed params
            params = jax.device_put(arch.params, device)
            predictor = SiamesePredictor(
                arch.model,
                params,
                arch.tokenizer,
                batch_size=int(serve_cfg["max_batch"]),
                max_length=max_length,
                buckets=buckets,
                aot_warmup=True,
                score_impl=score_impl,
                token_budget=token_budget,
                max_rows_per_pack=max_rows_per_pack,
                encoder_precision=encoder_precision,
                cascade_low=cascade_low,
                cascade_high=cascade_high,
                # replica-private program registry, bound to the
                # replica's telemetry: /programz fan-out and per-replica
                # xla.* rows stay attributable to one device
                program_registry=telemetry.ProgramRegistry(
                    telemetry=registry
                ),
            )
            predictor.encode_anchors(anchors)
            return ScoringService(
                predictor,
                config=service_config,
                retry_policy=retry_policy,
                manifest_dir=(
                    Path(out_dir) / f"replica-{index}"
                    if out_dir is not None else None
                ),
                registry=registry,
                device=device,  # serve.hbm_* gauges read THIS device
            )

        return factory

    replica_list = [
        Replica(
            i,
            make_factory(i),
            run_dir=out_dir,
            device=devices[i % len(devices)],
            telemetry_enabled=bool(tel_cfg["enabled"]),
            heartbeat_every_s=float(tel_cfg["heartbeat_every_s"]),
        )
        for i in range(n_replicas)
    ]
    logger.info(
        "replica fleet: %d service(s) over %d local device(s)",
        n_replicas, len(devices),
    )
    target = _with_slo_monitor(_with_drift_monitor(ReplicaRouter(
        replica_list,
        config=RouterConfig(
            heartbeat_timeout_s=float(serve_cfg["heartbeat_timeout_s"]),
            max_batch_errors=int(serve_cfg["max_batch_errors"]),
            monitor_interval_s=float(serve_cfg["monitor_interval_s"]),
            max_reroutes=int(serve_cfg["max_reroutes"]),
        ),
        retry_policy=retry_policy,
    )))
    if bool(serve_cfg["autoscale_enabled"]):
        # close the scale_hint loop (serving/autoscaler.py): the
        # controller spawns replicas through the SAME make_factory path
        # a restart takes, so a scale-up is AOT-warmed before admission.
        # Attached as an attribute (like slo_monitor) so the CLI stops
        # it at drain and /healthz carries its status block.
        slo_monitor = getattr(target, "slo_monitor", None)
        if slo_monitor is None:
            raise ValueError(
                "serving.autoscale_enabled requires serving.slo_enabled "
                "(the scale_hint comes from the SLO monitor)"
            )
        from .serving.autoscaler import Autoscaler, AutoscalerConfig

        target.autoscaler = Autoscaler(
            target,
            replica_factory=make_factory,
            slo_monitor=slo_monitor,
            config=AutoscalerConfig(
                min_replicas=int(serve_cfg["autoscale_min_replicas"]),
                max_replicas=int(serve_cfg["autoscale_max_replicas"]),
                interval_s=float(serve_cfg["autoscale_interval_s"]),
                up_cooldown_s=float(serve_cfg["autoscale_up_cooldown_s"]),
                down_cooldown_s=float(
                    serve_cfg["autoscale_down_cooldown_s"]
                ),
                up_consecutive=int(serve_cfg["autoscale_up_consecutive"]),
                down_consecutive=int(
                    serve_cfg["autoscale_down_consecutive"]
                ),
                drain_timeout_s=float(
                    serve_cfg["autoscale_drain_timeout_s"]
                ),
            ),
            registry=telemetry.get_registry(),
            retry_policy=retry_policy,
            run_dir=out_dir,
        )
    return _with_tenants(_with_flight_recorder(target))


def score_corpus_from_archive(
    archive_path: Union[str, Path],
    test_path: Union[str, Path],
    out_dir: Union[str, Path],
    shards: Optional[int] = None,
    overrides: Optional[Union[str, Dict[str, Any]]] = None,
    golden_file: Optional[Union[str, Path]] = None,
    name: Optional[str] = None,
    thres: float = 0.5,
    split: Optional[str] = None,
) -> Dict[str, Any]:
    """Sharded map-reduce corpus scoring: ``evaluate_from_archive``'s
    artifact contract (``{name}_result.json`` + ``{name}_metric_all.json``
    in ``out_dir``), produced by N supervised worker subprocesses with
    exactly-once merge verification (``distributed/``,
    docs/full_corpus.md).  Shard knobs ride ``config.EVALUATION_DEFAULTS``
    (``shards``, ``max_shard_attempts``, ``shard_stall_timeout_s``, …);
    the ``shards`` argument overrides the config."""
    from .distributed import score_corpus

    return score_corpus(
        archive_path,
        test_path,
        out_dir,
        shards=shards,
        overrides=overrides,
        golden_file=golden_file,
        name=name,
        thres=thres,
        split=split,
    )


def _auto_buckets_for_corpus(
    reader, tokenizer, test_path, max_length: int, n_buckets: int = 8,
    sample: int = 2048,
):
    """Token-length sample of the corpus head → DP bucket boundaries."""
    import itertools

    from .data.batching import auto_buckets

    texts = [
        inst["text1"]
        for inst in itertools.islice(
            reader.read(test_path, split="test"), sample
        )
    ]
    lengths = [
        len(ids) for ids in tokenizer.encode_many(texts, max_length=max_length)
    ]
    return auto_buckets(lengths, max_length, n_buckets=n_buckets)


def evaluate_from_archive(
    archive_path: Union[str, Path],
    test_path: Union[str, Path],
    out_dir: Union[str, Path],
    overrides: Optional[Union[str, Dict[str, Any]]] = None,
    golden_file: Optional[Union[str, Path]] = None,
    name: Optional[str] = None,
    mesh=None,
    use_mesh: bool = True,
    thres: float = 0.5,
) -> Dict[str, float]:
    """The reference's eval flow: load archive with overrides, score the
    test corpus, write ``{name}_result.json`` + ``{name}_metric_all.json``
    (reference: predict_memory.py:49-114,159-197)."""
    from . import telemetry
    from .archive import load_archive
    from .config import evaluation_config, telemetry_config
    from .utils.profiling import trace_context

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    arch = load_archive(archive_path, overrides=overrides)
    tel_cfg = telemetry_config(arch.config)
    tel = telemetry.configure(
        run_dir=out_dir,
        enabled=bool(tel_cfg["enabled"]),
        events=bool(tel_cfg["events"]),
        heartbeat_every_s=float(tel_cfg["heartbeat_every_s"]),
        step_events=bool(tel_cfg["step_events"]),
    )
    # live scrape surface for the corpus pass (predict_file's rows/s,
    # journal lag, program table) — opt-in, default off
    metrics_port = int(tel_cfg["metrics_port"] or 0)
    metrics_server = (
        telemetry.start_metrics_server(metrics_port) if metrics_port else None
    )
    model_cfg = arch.config.get("model") or {}
    model_type = model_cfg.get("type", "model_memory")
    name = name or model_type
    reader = build_reader(arch.config.get("dataset_reader"))
    # the evaluation section merged over its documented defaults
    # (config.EVALUATION_DEFAULTS) — null-tolerant in one place
    eval_cfg = evaluation_config(arch.config)
    batch_size = int(eval_cfg["batch_size"])
    max_length = int(eval_cfg["max_length"])
    # overrides written for base geometry (max_length 512) must not crash
    # a smaller-position archive deep in the encoder — clamp to the
    # model's own position table
    model_positions = getattr(
        getattr(arch.model, "config", None), "max_position_embeddings", None
    )
    if model_positions is not None and max_length > model_positions:
        logger.warning(
            "evaluation max_length %d exceeds the archived model's "
            "max_position_embeddings %d — clamping",
            max_length, model_positions,
        )
        max_length = model_positions
    buckets = eval_cfg["buckets"]
    if buckets == "auto":
        # padding-minimizing DP boundaries from a corpus length sample —
        # the same optimizer (and the same n=8 default) the bench uses
        # (data/batching.py auto_buckets), so bench and production eval
        # measure one bucketing policy; the cost model puts auto-8 at
        # 1.339x emitted/true tokens vs 1.445x for hand powers of two
        buckets = _auto_buckets_for_corpus(
            reader,
            arch.tokenizer,
            test_path,
            max_length,
            n_buckets=int(eval_cfg["n_buckets"]),
        )
        logger.info("auto buckets for %s: %s", test_path, buckets)
    elif buckets is not None:
        buckets = [int(b) for b in buckets]
    tokens_per_batch = eval_cfg["tokens_per_batch"]
    if tokens_per_batch is not None:
        tokens_per_batch = int(tokens_per_batch)
    inflight = int(eval_cfg["inflight"])

    out_results = out_dir / f"{name}_result.json"
    out_metrics = out_dir / f"{name}_metric_all.json"
    # telemetry.trace_dir wraps the WHOLE scoring pass in a jax.profiler
    # trace (the named scopes in models/ops make it attributable); the
    # registry rolls up to <out_dir>/telemetry.json on the way out
    try:
        with trace_context(tel_cfg["trace_dir"]):
            if model_type == "model_memory":
                from .evaluate.predict_memory import test_siamese

                golden = golden_file or (
                    arch.config.get("dataset_reader") or {}
                ).get("anchor_path")
                if golden is None:
                    raise ValueError(
                        "memory-model evaluation needs a golden anchor file"
                    )
                return test_siamese(
                    arch.model,
                    arch.params,
                    arch.tokenizer,
                    test_file=test_path,
                    golden_file=golden,
                    out_results=out_results,
                    out_metrics=out_metrics,
                    reader=reader,
                    mesh=mesh,
                    use_mesh=use_mesh,
                    batch_size=batch_size,
                    max_length=max_length,
                    buckets=buckets,
                    tokens_per_batch=tokens_per_batch,
                    thres=thres,
                    inflight=inflight,
                    anchor_match_impl=eval_cfg["anchor_match_impl"],
                    aot_warmup=bool(eval_cfg["aot_warmup"]),
                    resume=bool(eval_cfg["resume"]),
                    quarantine=eval_cfg["quarantine"],
                    heartbeat_batches=int(eval_cfg["heartbeat_batches"]),
                    score_retries=int(eval_cfg["score_retries"]),
                    attribute_anchors=bool(eval_cfg["attribute_anchors"]),
                )
            from .evaluate.predict_single import test_single

            return test_single(
                arch.model,
                arch.params,
                arch.tokenizer,
                test_file=test_path,
                out_results=out_results,
                out_metrics=out_metrics,
                reader=reader,
                mesh=mesh,
                use_mesh=use_mesh,
                batch_size=batch_size,
                max_length=max_length,
                buckets=buckets,
                tokens_per_batch=tokens_per_batch,
                inflight=inflight,
            )
    finally:
        if tel.enabled:
            telemetry.write_programs(out_dir)
        tel.close()
        if metrics_server is not None:
            metrics_server.close()
