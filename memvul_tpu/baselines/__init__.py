from .sklearn_baseline import run_baselines  # noqa: F401
