"""Classical ML baselines (CPU, sklearn).

Reference (Baseline/baseline.py + dimension_reduce.py): bag-of-words
CountVectorizer with English stop words, L1 LinearSVC (C=0.3) feature
selection, then five learners — RandomForest (30 trees, OOB),
MultinomialNB, MLP (max_iter 10), LogisticRegression, KNN — each
emitting ``{learner}_result.json`` + ``{learner}_metric.json`` with the
same measure dict as the neural paths.
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..training.metrics import model_measure

logger = logging.getLogger(__name__)


def _texts_and_labels(samples: Sequence[Dict], target: str) -> Tuple[List[str], np.ndarray]:
    texts, labels = [], []
    for s in samples:
        texts.append(f"{s.get('Issue_Title') or ''}. {s.get('Issue_Body') or ''}")
        labels.append(1 if str(s.get(target)) in ("1", "1.0", "pos") else 0)
    return texts, np.asarray(labels)


def default_learners(seed: int = 2021) -> Dict[str, object]:
    from sklearn.ensemble import RandomForestClassifier
    from sklearn.linear_model import LogisticRegression
    from sklearn.naive_bayes import MultinomialNB
    from sklearn.neighbors import KNeighborsClassifier
    from sklearn.neural_network import MLPClassifier

    return {
        "RF": RandomForestClassifier(
            n_estimators=30, oob_score=True, random_state=seed
        ),
        "NB": MultinomialNB(),
        "MLP": MLPClassifier(max_iter=10, random_state=seed),
        "LR": LogisticRegression(max_iter=1000, random_state=seed),
        "KNN": KNeighborsClassifier(n_jobs=-1),
    }


def run_baselines(
    train_path: Union[str, Path],
    test_path: Union[str, Path],
    out_dir: Union[str, Path],
    target: str = "Security_Issue_Full",
    learners: Optional[Dict[str, object]] = None,
    feature_selection: bool = True,
    seed: int = 2021,
) -> Dict[str, Dict[str, float]]:
    from sklearn.feature_extraction.text import CountVectorizer
    from sklearn.feature_selection import SelectFromModel
    from sklearn.svm import LinearSVC

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    train = json.loads(Path(train_path).read_text())
    test = json.loads(Path(test_path).read_text())
    train_texts, y_train = _texts_and_labels(train, target)
    test_texts, y_test = _texts_and_labels(test, target)
    test_ids = [s.get("Issue_Url") for s in test]

    vectorizer = CountVectorizer(stop_words="english", min_df=1)
    x_train = vectorizer.fit_transform(train_texts)
    x_test = vectorizer.transform(test_texts)

    if feature_selection and x_train.shape[1] > 1:
        # L1 LinearSVC feature selection (reference: dimension_reduce.py:18-25)
        svc = LinearSVC(penalty="l1", C=0.3, dual=False, random_state=seed)
        selector = SelectFromModel(svc.fit(x_train, y_train), prefit=True)
        if int(selector.get_support().sum()) > 0:
            x_train = selector.transform(x_train)
            x_test = selector.transform(x_test)
    logger.info("feature matrix: %s", x_train.shape)

    results: Dict[str, Dict[str, float]] = {}
    for name, learner in (learners or default_learners(seed)).items():
        learner.fit(x_train, y_train)
        preds = learner.predict(x_test)
        if hasattr(learner, "predict_proba"):
            scores = learner.predict_proba(x_test)[:, 1]
        elif hasattr(learner, "decision_function"):
            scores = learner.decision_function(x_test)
        else:
            scores = preds.astype(float)
        measured = model_measure(y_test, preds, scores)
        results[name] = measured
        records = [
            {
                "Issue_Url": test_ids[i],
                "label": "pos" if y_test[i] else "neg",
                "predict": "pos" if preds[i] else "neg",
                "prob": float(scores[i]),
            }
            for i in range(len(y_test))
        ]
        (out_dir / f"{name}_result.json").write_text(json.dumps(records))
        (out_dir / f"{name}_metric.json").write_text(json.dumps(measured, indent=4))
        logger.info("%s: %s", name, measured)
    return results
