"""Command-line interface — the ``allennlp train`` / eval-script parity.

The reference runs ``allennlp train <config> -s <dir> --include-package
MemVul`` plus hand-edited ``predict_*.py``/``utils.py``/``baseline.py``
scripts (reference: README.md:130-147).  Here everything is one CLI:

    python -m memvul_tpu train configs/config_memory.json -s out/
    python -m memvul_tpu evaluate out/model.tar.gz data/test_project.json -o eval/
    python -m memvul_tpu score-corpus out/model.tar.gz data/test_project.json -o eval/ --shards 4
    python -m memvul_tpu serve out/ -o serve_run/
    python -m memvul_tpu pretrain configs/further_pretrain.json
    python -m memvul_tpu baseline data/train_project.json data/test_project.json -o baseline_out/
    python -m memvul_tpu build-data --csv all_samples.csv --out data/
    python -m memvul_tpu analyze data/train_project.json
    python -m memvul_tpu bench
    python -m memvul_tpu bank build --store banks/ --anchors data/CWE_anchor_golden_project.json
    python -m memvul_tpu telemetry-report out/
    python -m memvul_tpu lint --json
    python -m memvul_tpu tune --out profiles/ --cascade
    python -m memvul_tpu doctor
    python -m memvul_tpu parity --hf-dir bert-base-uncased
    python -m memvul_tpu selfcheck

``--mesh data=8`` shards any train/evaluate run over a device mesh.
``python -m memvul_tpu --help`` lists every subcommand with a one-line
description (a tier-1 test pins that list to the registered set).
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
from pathlib import Path


def _honor_platform_env() -> None:
    from .utils.platform import honor_platform_env

    honor_platform_env()


def _parse_mesh(spec):
    """``"data=8"`` or ``"data=4,model=2"`` → mesh, None otherwise.
    Malformed specs exit with a usage message, not a traceback."""
    if not spec:
        return None
    from .parallel import create_mesh

    allowed = {"data", "model"}  # the axes batch_spec/shard_params act on
    axes = {}
    try:
        for part in spec.split(","):
            name, size = part.split("=")
            name = name.strip()
            if name not in allowed:
                # an unknown axis would pass mesh construction but
                # silently shard nothing (batch_spec keys on "data")
                raise ValueError(f"unknown axis {name!r}")
            axes[name] = int(size)
        return create_mesh(axes)
    except ValueError as e:
        print(
            f'--mesh {spec!r}: {e} (expected e.g. "data=8" or '
            f'"data=4,model=2"; axes from {sorted(allowed)}; sizes must '
            "multiply to the device count)",
            file=sys.stderr,
        )
        raise SystemExit(2)  # usage error, distinct from exit 1 = run failed


def cmd_train(args) -> int:
    from .build import train_from_config
    from .config import load_config
    from .utils.profiling import trace_context

    config = load_config(args.config, overrides=args.overrides)
    mesh = _parse_mesh(args.mesh)  # validate BEFORE the trace scope opens
    with trace_context(args.profile):
        result = train_from_config(
            config, args.serialization_dir, mesh=mesh
        )
    print(json.dumps({
        "best_epoch": result.get("best_epoch"),
        "best_validation": result.get("best_validation"),
        "archive": result.get("archive"),
    }, default=float))
    return 0


def cmd_evaluate(args) -> int:
    from .build import evaluate_from_archive
    from .utils.profiling import trace_context

    mesh = _parse_mesh(args.mesh)  # validate BEFORE the trace scope opens
    with trace_context(args.profile):
        metrics = evaluate_from_archive(
            args.archive,
            args.test_path,
            args.out_dir,
            overrides=args.overrides,
            golden_file=args.golden_file,
            name=args.name,
            mesh=mesh,
            use_mesh=not args.no_mesh,
            thres=args.threshold,
        )
    print(json.dumps(metrics, default=float))
    return 0


def cmd_score_corpus(args) -> int:
    """Sharded map-reduce corpus scoring (docs/full_corpus.md): N
    supervised worker subprocesses, exactly-once merge verification,
    metrics byte-identical to a single-process evaluate.  Exit codes:
    0 success, 1 merge-verification/run failure, 2 usage, 3 partial
    completion (quarantined shards; the machine-readable refusal is
    printed as JSON on stdout)."""
    from .distributed import (
        MergeVerificationError,
        PartialCompletionError,
        score_corpus,
    )

    try:
        result = score_corpus(
            args.archive,
            args.test_path,
            args.out_dir,
            shards=args.shards,
            overrides=args.overrides,
            golden_file=args.golden_file,
            name=args.name,
            thres=args.threshold,
            split=args.split,
        )
    except PartialCompletionError as e:
        print(json.dumps(e.payload, default=str))
        return 3
    except MergeVerificationError as e:
        print(json.dumps(e.payload, default=str), file=sys.stderr)
        return 1
    except ValueError as e:
        print(f"score-corpus: {e}", file=sys.stderr)
        return 2
    print(json.dumps(result, default=float))
    return 0


def cmd_pretrain(args) -> int:
    from .build import build_tokenizer, encoder_config, save_encoder_checkpoint
    from .config import load_config
    from .pretrain.mlm import MLMTrainer, MLMTrainerConfig

    from .utils.profiling import trace_context

    if args.export_hf:
        import torch  # noqa: F401 — fail fast, not after hours of training

    config = load_config(args.config, overrides=args.overrides)
    val_path = config.get("validation_data_path")
    if val_path:
        # fail fast on a missing OR empty eval corpus, not after hours of
        # training (same rationale as the torch probe above)
        from .pretrain.mlm import read_corpus_lines

        try:
            read_corpus_lines(val_path)
        except (OSError, ValueError) as e:
            print(f"validation_data_path unusable: {e}", file=sys.stderr)
            return 2
    tokenizer = build_tokenizer(config.get("tokenizer"))
    bert_cfg = encoder_config(config.get("encoder"), tokenizer.vocab_size)
    trainer = MLMTrainer(
        bert_cfg, tokenizer, MLMTrainerConfig(**(config.get("trainer") or {}))
    )
    with trace_context(args.profile):
        result = trainer.train(config["train_data_path"])
    out_dir = Path(config.get("output_dir", "further_pretrain/out_wwm"))
    encoder = trainer.encoder_params()  # one device fetch, shared below
    path = save_encoder_checkpoint(encoder, out_dir)
    report = {"final_loss": result["final_loss"], "checkpoint": str(path)}
    if val_path:
        # the reference script's do_eval path (run_mlm_wwm.py:386-397)
        report.update(trainer.evaluate(val_path))
    if args.export_hf:
        from .build import export_hf_checkpoint

        report["hf_checkpoint"] = str(
            export_hf_checkpoint(
                encoder, bert_cfg, out_dir / "hf", tokenizer=tokenizer
            )
        )
    print(json.dumps(report))
    return 0


def cmd_baseline(args) -> int:
    from .baselines.sklearn_baseline import run_baselines

    metrics = run_baselines(
        args.train_path, args.test_path, args.out_dir,
        feature_selection=not args.no_feature_selection,
    )
    print(json.dumps(metrics, default=float))
    return 0


def cmd_build_data(args) -> int:
    """Offline pipeline: CSV corpus → cleaned project splits + CWE anchors
    + MLM corpus (reference: utils.py:66-152,238-350,30-37)."""
    import csv as _csv

    from .data.corpus import preprocess, split_by_project, write_json, write_mlm_corpus
    from .data.cwe import (
        build_anchors, build_cwe_tree, build_full_view_anchors,
        cwe_distribution, load_research_view_csv, save_anchors,
    )

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    with open(args.csv, newline="", encoding="utf-8") as f:
        reports = list(_csv.DictReader(f))
    cve_dict = json.loads(Path(args.cve_dict).read_text()) if args.cve_dict else {}

    clean = preprocess(reports)
    train, test = split_by_project(clean, held_out_frac=0.1, seed=args.seed)
    train, validation = split_by_project(train, held_out_frac=0.1, seed=args.seed + 1)
    write_json(train, out / "train_project.json")
    write_json(validation, out / "validation_project.json")
    write_json(test, out / "test_project.json")
    n_lines = write_mlm_corpus(clean, out / "train_project_mlm.txt")

    if args.full_view_anchors and not args.cwe_csv:
        print("--full-view-anchors requires --cwe-csv", file=sys.stderr)
        return 2
    n_anchors = 0
    n_full = 0
    tree = (
        build_cwe_tree(load_research_view_csv(args.cwe_csv))
        if args.cwe_csv
        else None
    )
    dist = None
    if tree is not None and cve_dict:
        positives = [
            r for r in train if str(r.get("Security_Issue_Full")) in ("1", "1.0")
        ]
        for r in positives:
            cve = cve_dict.get(r.get("CVE_ID"))
            if cve:
                r.setdefault("CWE_ID", cve.get("CWE_ID"))
        dist = cwe_distribution(positives, cve_dict)
        anchors = build_anchors(dist, tree, cve_dict, seed=args.seed)
        save_anchors(anchors, out / "CWE_anchor_golden_project.json")
        n_anchors = len(anchors)
    if args.full_view_anchors:
        # works with or without a CVE dict (pure-taxonomy bank)
        full = build_full_view_anchors(tree, cve_dict, dist, seed=args.seed)
        save_anchors(full, out / "CWE_anchor_full_view.json")
        n_full = len(full)
    print(json.dumps({
        "train": len(train), "validation": len(validation), "test": len(test),
        "mlm_lines": n_lines, "anchors": n_anchors,
        "full_view_anchors": n_full,
    }))
    return 0


def cmd_analyze(args) -> int:
    """The paper-analysis suite over a corpus JSON — keyword study, IR→CVE
    disclosure-lag histogram, CWE-category ECDF, attack-step counts, repo
    stats (reference: utils.py:415-572, run there by editing __main__)."""
    from .data.analysis import (
        count_attack_steps,
        cumulative_cwe_distribution,
        cwe_report_distribution,
        delta_days_histogram,
        join_positives_with_cve,
        keyword_match_study,
        repo_stats,
    )

    samples = json.loads(Path(args.corpus).read_text())
    cve_dict = (
        json.loads(Path(args.cve_dict).read_text()) if args.cve_dict else {}
    )
    report: dict = {"num_samples": len(samples)}
    report["keyword_match"] = keyword_match_study(samples)
    positives = join_positives_with_cve(samples, cve_dict)
    report["attack_steps"] = count_attack_steps(positives)
    # Published_Date rides on the records themselves when present;
    # the CVE dict is only a fallback, so the histogram always runs
    report["delta_days"] = delta_days_histogram(positives, cve_dict or None)
    if cve_dict:
        dist = cwe_report_distribution(positives)
        report["cwe_cumulative"] = cumulative_cwe_distribution(dist)
    if args.repo_info:
        report["repo_stats"] = repo_stats(
            samples, json.loads(Path(args.repo_info).read_text())
        )
    text = json.dumps(report, indent=2, default=float)
    if args.out:
        Path(args.out).write_text(text)
    print(text)
    return 0


def cmd_parity(args) -> int:
    """One-command real-weights F1-parity chain (evaluate/parity.py):
    convert parity at checkpoint geometry, reference-archive scoring,
    metric diff vs the reference pipeline's own metric file."""
    from .evaluate.parity import run_parity

    try:
        report = run_parity(
            args.hf_dir,
            archive=args.archive,
            corpus=args.corpus,
            anchors=args.anchors,
            ref_metrics=args.ref_metrics,
            out_dir=args.out_dir,
            max_length=args.max_length,
            batch_size=args.batch_size,
            thres=args.threshold,
            atol=args.atol,
            seq_len=args.seq_len,
        )
    except (ValueError, FileNotFoundError) as e:
        # usage / missing-artifact problems exit 2, distinct from exit 1
        # = "parity ran and failed tolerance"
        print(f"parity: {e}", file=sys.stderr)
        return 2
    print(json.dumps(report, indent=2, default=float))
    return 0 if report["ok"] else 1


def cmd_serve(args) -> int:
    """Online scoring service (docs/serving.md): micro-batched, AOT-
    warmed serving of the archived Siamese model over stdlib HTTP, with
    graceful SIGTERM/SIGINT drain."""
    import os
    import signal as _signal
    import threading

    from . import telemetry
    from .build import serve_from_archive
    from .serving.frontend import run_http_server

    mesh = _parse_mesh(args.mesh)
    try:
        if getattr(args, "hosts", None):
            # cross-host fleet mode (serving/fleet.py): front a
            # HostBalancer over already-running per-host serve
            # processes — no archive/model load on the balancer host
            from .serving.fleet import (
                FleetConfig, HostBalancer, ProcessHost, enumerate_hosts,
            )

            urls = enumerate_hosts(args.hosts, default_port=args.port)
            if not urls:
                print("serve: --hosts resolved no hosts", file=sys.stderr)
                return 2
            service = HostBalancer(
                [ProcessHost(i, url=u) for i, u in enumerate(urls)],
                config=FleetConfig(),
            )
            if args.tsdb_cadence and args.tsdb_cadence > 0:
                # the balancer has no archive config to read the knob
                # from — the flag is the only gate in fleet mode.  Its
                # sampler labels every host's part, and a quarantine
                # bundles the merged fleet view.
                from .serving.incident import attach_flight_recorder

                attach_flight_recorder(
                    service,
                    run_dir=args.out_dir,
                    cadence_s=args.tsdb_cadence,
                )
        else:
            if not args.archive:
                print(
                    "serve: an archive is required (or pass --hosts)",
                    file=sys.stderr,
                )
                return 2
            service = serve_from_archive(
                args.archive,
                out_dir=args.out_dir,
                overrides=args.overrides,
                golden_file=args.golden_file,
                mesh=mesh,
                use_mesh=not args.no_mesh,
                replicas=args.replicas,
                tsdb_cadence=args.tsdb_cadence,
                tenants=args.tenants,
            )
    except ValueError as e:
        print(f"serve: {e}", file=sys.stderr)
        return 2
    # the run dir doubles as the /profilez capture root: on-demand
    # jax.profiler traces land beside the telemetry sinks
    server = run_http_server(
        service, host=args.host, port=args.port, profile_dir=args.out_dir
    )
    stop = threading.Event()
    previous = []

    def _stop_handler(signum, frame):
        service.request_drain()
        stop.set()

    for sig in (_signal.SIGTERM, _signal.SIGINT):
        previous.append((sig, _signal.signal(sig, _stop_handler)))
    bound_host, bound_port = server.server_address[:2]
    print(json.dumps({
        "serving": f"http://{bound_host}:{bound_port}",
        "pid": os.getpid(),
        "replicas": len(getattr(service, "replicas", ())) or 1,
        "hosts": len(getattr(service, "hosts", ())) or None,
    }))
    sys.stdout.flush()
    try:
        while not stop.is_set():
            stop.wait(0.5)
    finally:
        server.shutdown()
        for attr in (
            "drift_monitor", "slo_monitor", "autoscaler",
            "alert_engine", "metrics_sampler", "incident_recorder",
        ):
            monitor = getattr(service, attr, None)
            if monitor is not None:
                monitor.stop()
        service.drain()
        for sig, handler in previous:
            _signal.signal(sig, handler)
        telemetry.get_registry().close()
    return 0


def cmd_bench(args) -> int:
    from .bench import main as bench_main

    return int(bench_main() or 0)


# -- anchor-bank lifecycle (bankops/, docs/anchor_bank.md) ---------------------

def _bank_store(args):
    """The subcommand's :class:`~memvul_tpu.bankops.store.BankStore`.
    ``--tenant NAME`` scopes the root to ``<store>/<tenant>`` — the
    per-org layout ``serve --tenants`` points at (docs/multitenancy.md),
    so one ``--store`` root holds every org's versioned bank."""
    from .bankops import BankStore

    tenant = getattr(args, "tenant", None)
    if not tenant:
        return BankStore(args.store)
    from .serving.tenancy import validate_tenant_name

    return BankStore(Path(args.store) / validate_tenant_name(tenant))


def _bank_predictor(args):
    """A warmed serving-shaped predictor over an archive — what the
    shadow/promote subcommands score candidate banks through."""
    from .archive import load_archive
    from .build import build_reader
    from .config import serving_config
    from .evaluate.predict_memory import SiamesePredictor

    arch = load_archive(args.archive, overrides=args.overrides)
    serve_cfg = serving_config(arch.config)
    max_length = int(serve_cfg["max_length"])
    model_positions = getattr(
        getattr(arch.model, "config", None), "max_position_embeddings", None
    )
    if model_positions is not None and max_length > model_positions:
        max_length = model_positions
    buckets = serve_cfg["buckets"]
    predictor = SiamesePredictor(
        arch.model,
        arch.params,
        arch.tokenizer,
        batch_size=int(serve_cfg["max_batch"]),
        max_length=max_length,
        buckets=[int(b) for b in buckets] if buckets else None,
        aot_warmup=False,  # warmed per bank by score_texts callers
    )
    reader = build_reader(arch.config.get("dataset_reader"))
    return predictor, reader


def cmd_bank_build(args) -> int:
    """Commit an anchor set (the ``build-data`` output JSON) as a root
    store version."""
    from .data.cwe import load_anchors

    store = _bank_store(args)
    manifest = store.create(
        load_anchors(args.anchors), source=args.source, note=args.note
    )
    print(json.dumps(manifest, indent=2))
    return 0


def cmd_bank_diff(args) -> int:
    """Derive a new version from a parent via add/retire/reweight/edit
    ops (``--ops`` JSON plus the repeatable conveniences)."""
    from .bankops import BankDiff, BankStoreError

    store = _bank_store(args)
    ops = []
    if args.ops:
        raw = args.ops
        if Path(raw).exists():
            raw = Path(raw).read_text()
        ops.extend(json.loads(raw))
    for cat in args.retire or []:
        ops.append({"op": "retire", "category": cat})
    for spec in args.reweight or []:
        cat, _, weight = spec.partition("=")
        ops.append({"op": "reweight", "category": cat, "weight": float(weight)})
    parent = args.parent or store.latest()
    if parent is None:
        print("bank diff: empty store — run `bank build` first", file=sys.stderr)
        return 2
    try:
        manifest = store.derive(
            parent, BankDiff.from_json(ops), note=args.note
        )
    except BankStoreError as e:
        print(f"bank diff: {e}", file=sys.stderr)
        return 2
    print(json.dumps(manifest, indent=2))
    return 0


def cmd_bank_log(args) -> int:
    """Lineage of a version (default: latest), root first, plus the
    ACTIVE pointer."""
    store = _bank_store(args)
    print(json.dumps({
        "versions": store.versions(),
        "active": store.active(),
        "lineage": store.log(args.version),
    }, indent=2))
    return 0


def cmd_bank_shadow(args) -> int:
    """Offline shadow: replay a journaled ``predict_file`` output
    against a candidate store version; writes ``shadow_deltas.jsonl``
    and prints the gate-consumable summary."""
    from .bankops import replay_results

    store = _bank_store(args)
    predictor, reader = _bank_predictor(args)
    out_dir = Path(args.out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    summary = replay_results(
        predictor,
        store.instances(args.candidate),
        reader,
        corpus_path=args.corpus,
        results_path=args.results,
        out_dir=out_dir,
        split=args.split,
        threshold=args.threshold,
        candidate_version=args.candidate,
    )
    from .resilience.io import atomic_write_text

    atomic_write_text(
        out_dir / "shadow_summary.json", json.dumps(summary, indent=2)
    )
    print(json.dumps(summary, indent=2))
    return 0


def cmd_bank_promote(args) -> int:
    """Run the promotion gate for a candidate: golden-set AUC/F1 parity
    vs the active version plus shadow-summary thresholds.  Prints the
    machine-readable decision; ``--apply`` additionally advances the
    store's ACTIVE pointer (a live fleet promotes in-process via
    ``bankops.promote``).  Exit 0 approved, 1 refused, 2 usage."""
    from .bankops import GateThresholds, evaluate_candidate
    from .bankops.store import BankStoreError

    store = _bank_store(args)
    predictor, reader = _bank_predictor(args)
    shadow_summary = None
    if args.shadow_summary:
        shadow_summary = json.loads(Path(args.shadow_summary).read_text())
    thresholds = GateThresholds(
        max_auc_drop=args.max_auc_drop,
        max_f1_drop=args.max_f1_drop,
        max_flip_rate=args.max_flip_rate,
        min_shadow_samples=args.min_shadow_samples,
        require_shadow=not args.no_shadow,
    )
    try:
        decision = evaluate_candidate(
            predictor,
            store,
            args.candidate,
            reader.read(str(args.golden_set), split=args.split),
            active=args.active,
            shadow_summary=shadow_summary,
            thresholds=thresholds,
        )
    except BankStoreError as e:
        print(f"bank promote: {e}", file=sys.stderr)
        return 2
    store.record_promotion(
        kind="gate_decision", tenant=getattr(args, "tenant", None),
        **decision.to_json()
    )
    if decision.approved and args.apply:
        store.set_active(args.candidate, source="promotion")
    print(json.dumps(decision.to_json(), indent=2))
    return 0 if decision.approved else 1


def cmd_lint(args) -> int:
    """The unified static-analysis engine (docs/static_analysis.md):
    one AST parse per file shared by every checker — bare-print,
    handler/router blocking, artifact-write hygiene, trace purity,
    lock discipline, and the fault/metric/config registry-drift
    checks.  Exit 0 clean, 1 findings, 2 usage."""
    from .analysis.cli import run_lint

    return run_lint(args)


def cmd_telemetry_report(args) -> int:
    """Render a run dir's telemetry sinks (events.jsonl / telemetry.json
    / HEARTBEAT.json) into a human summary: phase table, step-time
    percentiles, counter totals, last-heartbeat age.  ``--json`` emits
    the machine-readable report instead (schema pinned in tests, the
    ``lint --json`` pattern) so bench/CI consume run summaries without
    scraping table text."""
    from .telemetry.report import render_report, report_json

    run_dir = Path(args.run_dir)
    if not run_dir.is_dir():
        print(f"telemetry-report: {run_dir} is not a directory", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report_json(run_dir), indent=2, default=str))
    else:
        print(render_report(run_dir))
    return 0


def cmd_tune(args) -> int:
    """Offline autotuner (docs/tuning.md): sweep the knob space for
    this device class, prune analytically, microbench survivors behind
    the mandatory parity gate, and persist the versioned tuned profile.
    ``--report`` renders the measured roofline markdown instead.  Exit
    0 = tuned (record on stdout), 1 = run produced no usable winner,
    2 = usage / machine-readable ``unknown_device_class`` refusal."""
    if args.report is not None:
        from .tuning.report import (
            report_from_programs_json,
            splice_generated_section,
        )

        path = Path(args.report)
        if path.is_dir():
            path = path / "programs.json"
        if not path.is_file():
            print(
                f"tune --report: {path} not found (pass a run dir that "
                "wrote programs.json, or the file itself)",
                file=sys.stderr,
            )
            return 2
        md = report_from_programs_json(path)
        if args.splice:
            doc = Path(args.splice)
            if not doc.is_file():
                print(f"tune --splice: {doc} not found", file=sys.stderr)
                return 2
            doc.write_text(splice_generated_section(doc.read_text(), md))
            print(f"tune: generated section spliced into {doc}",
                  file=sys.stderr)
        print(md)
        return 0

    from .tuning.autotune import run_tune

    bench_kwargs = dict(
        seed=args.seed, model_size=args.model, seq_len=args.seq_len,
        batch_size=args.batch_size, steps_per_epoch=args.steps,
        n_requests=args.requests, n_clients=args.clients,
        max_batch=args.max_batch,
    )
    # the full grids are a silicon-budget sweep; the default is the
    # slim grid (same axes, fewer points) so a CPU run stays in minutes
    train_space_kwargs = None if args.full_space else dict(
        bucket_grids=[None, "pow2"], dedup_options=(True,),
        prefetch_depths=(2, 8),
    )
    serve_space_kwargs = None if args.full_space else dict(
        wait_ms_options=(2.0, 5.0), budget_factors=(2, 4),
        rows_factors=(1,),
    )
    record = run_tune(
        args.mode,
        device_class=args.device_class,
        allow_unknown_device=args.allow_unknown_device,
        out_dir=args.out,
        cascade=args.cascade,
        target_rescore_rate=args.target_rescore_rate,
        max_programs=args.max_programs,
        hbm_fraction=args.hbm_fraction,
        bench_kwargs=bench_kwargs,
        train_space_kwargs=train_space_kwargs,
        serve_space_kwargs=serve_space_kwargs,
    )
    print(json.dumps(record, indent=2, default=float))
    if record.get("error") == "unknown_device_class":
        return 2
    # a tune that found NO parity-passing winner anywhere leaves the
    # defaults in place — report it as a failed run, not silent success
    return 0 if record.get("profile") else 1


def cmd_doctor(args) -> int:
    """Environment/artifact self-diagnosis (utils/doctor.py)."""
    from .utils.doctor import run_doctor

    report = run_doctor(
        config=args.config,
        device_timeout_s=args.device_timeout,
        skip_device=args.skip_device,
    )
    print(json.dumps(report, indent=2, default=str))
    return 0 if report["ok"] else 1


def cmd_selfcheck(args) -> int:
    """One-command acceptance run: synthetic corpus → tiny Siamese train →
    archive → evaluate → metric-contract check.  Exercises every layer
    (offline pipeline, reader pair-sampling, train step, threshold-swept
    validation, archive round-trip, reference-format metrics) in a few
    minutes on CPU.  The reference has no equivalent — its only
    end-to-end check is a full training run (custom_trainer.py)."""
    import tempfile

    from .build import evaluate_from_archive, train_from_config
    from .data.synthetic import build_workspace, selfcheck_config

    workdir = Path(args.dir) if args.dir else Path(
        tempfile.mkdtemp(prefix="memvul_selfcheck_")
    )
    print(f"selfcheck workspace: {workdir}", file=sys.stderr)
    # 8 projects: the project-level 25% splits need that many for every
    # split (train/validation/test) to be non-empty — with 4, validation
    # gets 0 projects and the threshold sweep would run on nothing
    ws = build_workspace(
        workdir / "data",
        seed=args.seed,
        num_projects=args.projects,
        reports_per_project=args.reports,
    )
    splits = {
        name: len(json.loads(Path(ws["paths"][name]).read_text()))
        for name in ("train", "validation", "test")
    }
    config = selfcheck_config(ws)
    result = train_from_config(config, workdir / "out")
    archive = result.get("archive")
    # the reference applies the validation-swept threshold at test
    # (custom_metric.py:35-52 sweep → predict_memory.py thres); mirror
    # that instead of a hard 0.5 so the toy run's operating point comes
    # from its own validation
    thres = 0.5
    for em in result.get("history", []):
        if em.get("epoch") == result.get("best_epoch") and (
            "validation_s_thres" in em
        ):
            swept = float(em["validation_s_thres"])
            # an empty validation set reports thres 0.0 (metrics.py
            # empty-dict) — a degenerate everything-positive threshold;
            # keep the reference's 0.5 default then
            if swept > 0.0:
                thres = swept
    metrics = evaluate_from_archive(
        str(workdir / "out"),
        ws["paths"]["test"],
        str(workdir / "eval"),
        name="selfcheck",
        use_mesh=False,
        thres=thres,
    )
    required = ("TP", "FN", "TN", "FP", "prec", "f1", "auc")
    missing = [k for k in required if k not in metrics]
    ok = bool(archive) and not missing and all(splits.values())
    print(json.dumps({
        "selfcheck": "ok" if ok else "fail",
        "archive": archive,
        "splits": splits,
        "missing_metric_keys": missing,
        "metrics": {k: metrics.get(k) for k in required},
    }, default=float))
    return 0 if ok else 1


def build_parser() -> argparse.ArgumentParser:
    """The full CLI parser.  Every subcommand registers here with a
    one-line ``help`` (the top-level ``--help`` listing is the CLI's
    table of contents — a tier-1 test asserts it names every registered
    subcommand, so a new command cannot ship invisible)."""
    parser = argparse.ArgumentParser(prog="memvul_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("train", help="train a model from a JSON config")
    p.add_argument("config")
    p.add_argument("-s", "--serialization-dir", required=True)
    p.add_argument("-o", "--overrides", default=None,
                   help="JSON string deep-merged onto the config")
    p.add_argument("--mesh", default=None, help='e.g. "data=8"')
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the whole run")
    p.set_defaults(fn=cmd_train)

    p = sub.add_parser("evaluate", help="evaluate an archived model")
    p.add_argument("archive", help="model.tar.gz or its serialization dir")
    p.add_argument("test_path")
    p.add_argument("-o", "--out-dir", required=True)
    p.add_argument("--overrides", default=None)
    p.add_argument("--golden-file", default=None,
                   help="anchor file (memory model; defaults to the config's)")
    p.add_argument("--name", default=None, help="output file prefix")
    p.add_argument("--threshold", type=float, default=0.5)
    p.add_argument("--mesh", default=None)
    p.add_argument("--no-mesh", action="store_true")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the whole eval "
                   "(same scope bench.py's BENCH_PROFILE uses)")
    p.set_defaults(fn=cmd_evaluate)

    p = sub.add_parser(
        "score-corpus",
        help="score a corpus across N supervised worker subprocesses "
        "(sharded map-reduce with journal resume per shard, heartbeat "
        "supervision + backoff restarts, and exactly-once merge "
        "verification — docs/full_corpus.md); exit 3 = partial "
        "completion with the missing spans named",
    )
    p.add_argument("archive", help="model.tar.gz or its serialization dir")
    p.add_argument("test_path")
    p.add_argument("-o", "--out-dir", required=True)
    p.add_argument("--shards", type=int, default=None,
                   help="worker subprocesses (default: the archive's "
                   "evaluation.shards, 1)")
    p.add_argument("--overrides", default=None)
    p.add_argument("--golden-file", default=None,
                   help="anchor file (defaults to the config's)")
    p.add_argument("--name", default=None, help="output file prefix")
    p.add_argument("--threshold", type=float, default=0.5)
    p.add_argument("--split", default=None,
                   help="corpus split passed to the reader")
    p.set_defaults(fn=cmd_score_corpus)

    p = sub.add_parser("pretrain", help="MLM further-pretraining")
    p.add_argument("config")
    p.add_argument("-o", "--overrides", default=None)
    p.add_argument("--export-hf", action="store_true",
                   help="also write an HF-format checkpoint dir the "
                   "reference's AutoModel.from_pretrained consumes")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the MLM run")
    p.set_defaults(fn=cmd_pretrain)

    p = sub.add_parser("baseline", help="sklearn baselines")
    p.add_argument("train_path")
    p.add_argument("test_path")
    p.add_argument("-o", "--out-dir", required=True)
    p.add_argument("--no-feature-selection", action="store_true")
    p.set_defaults(fn=cmd_baseline)

    p = sub.add_parser("build-data", help="offline corpus pipeline")
    p.add_argument("--csv", required=True, help="all_samples.csv")
    p.add_argument("--cve-dict", default=None, help="CVE_dict.json")
    p.add_argument("--cwe-csv", default=None, help="CWE Research View 1000.csv")
    p.add_argument("--out", required=True)
    p.add_argument("--seed", type=int, default=2021)
    p.add_argument("--full-view-anchors", action="store_true",
                   help="also build the CWE-1000-scale bank (one anchor per "
                   "Research View node; pairs with model-axis bank sharding)")
    p.set_defaults(fn=cmd_build_data)

    p = sub.add_parser("analyze", help="paper-analysis suite over a corpus JSON")
    p.add_argument("corpus", help="corpus JSON (e.g. train_project.json)")
    p.add_argument("--cve-dict", default=None, help="CVE_dict.json")
    p.add_argument("--repo-info", default=None, help="repo star/fork info JSON")
    p.add_argument("-o", "--out", default=None, help="write the report here too")
    p.set_defaults(fn=cmd_analyze)

    p = sub.add_parser(
        "serve",
        help="online scoring service over an archived model: micro-"
        "batched, AOT-warmed, stdlib HTTP front end (POST /score, GET "
        "/healthz, GET /metrics Prometheus scrape, GET /tracez request "
        "traces, POST /profilez on-demand profiler capture), graceful "
        "SIGTERM drain; --replicas N runs a health-gated multi-replica "
        "router, one service per local device; --hosts fronts a cross-"
        "host balancer over already-running serve processes "
        "(docs/serving.md)",
    )
    p.add_argument("archive", nargs="?", default=None,
                   help="model.tar.gz or its serialization dir "
                   "(not needed with --hosts)")
    p.add_argument("--hosts", default=None,
                   help="comma-separated host[:port] or URLs of running "
                   "serve processes to balance across (or set "
                   "MEMVUL_FLEET_HOSTS); merges /healthz, /metrics, "
                   "/tracez, /programz and routes around dead or "
                   "stalled hosts (docs/serving.md, 'Cross-host fleet')")
    p.add_argument("-o", "--out-dir", default=None,
                   help="run dir for telemetry sinks + the anchor-bank "
                   "manifest (default: no sinks; replicas write "
                   "replica-<i>/ subdirs)")
    p.add_argument("--replicas", type=int, default=None,
                   help="scoring services behind the router, one per "
                   "local device round-robin (default: the archive's "
                   "serving.replicas, 1 = no router)")
    p.add_argument("--overrides", default=None,
                   help="JSON deep-merged onto the archived config "
                   '(e.g. \'{"serving": {"max_batch": 32}}\')')
    p.add_argument("--golden-file", default=None,
                   help="anchor file (defaults to the config's)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8341,
                   help="bind port (0 = ephemeral; the bound address is "
                   "printed as one JSON line on stdout)")
    p.add_argument("--tsdb-cadence", type=float, default=None,
                   metavar="SECONDS",
                   help="metrics-history sampling cadence: turns on the "
                   "in-process TSDB (GET /metricsz), alert rules (GET "
                   "/alertz), and — with --out-dir — the incident "
                   "flight recorder (docs/observability.md); default: "
                   "the archive's telemetry.tsdb_cadence_s (0 = off, "
                   "nothing constructed)")
    p.add_argument("--tenants", default=None, metavar="SPEC",
                   help="multi-tenant bank plane: comma-separated "
                   "name=store_dir pairs (e.g. orgA=banks/orgA,orgB="
                   "banks/orgB); each org's ACTIVE bank version is "
                   "installed at startup and requests carry a 'tenant' "
                   "JSON field or X-MemVul-Tenant header (untagged = "
                   "the archive's own bank; overrides the archive's "
                   "serving.tenants; docs/multitenancy.md)")
    p.add_argument("--mesh", default=None)
    p.add_argument("--no-mesh", action="store_true")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("bench", help="run the throughput benchmark")
    p.set_defaults(fn=cmd_bench)

    p = sub.add_parser(
        "bank",
        help="anchor-bank lifecycle: versioned store (build/diff/log), "
        "offline shadow scoring of a candidate version, and the gated "
        "promotion check (docs/anchor_bank.md)",
    )
    bank_sub = p.add_subparsers(dest="bank_command", required=True)

    b = bank_sub.add_parser(
        "build", help="commit an anchor JSON as a root store version"
    )
    b.add_argument("--store", required=True, help="bank store root dir")
    b.add_argument("--anchors", required=True,
                   help="anchor JSON (e.g. CWE_anchor_golden_project.json)")
    b.add_argument("--source", default="build", help="provenance tag")
    b.add_argument("--note", default=None)
    b.add_argument("--tenant", default=None, metavar="NAME",
                   help="scope the store to <store>/<tenant> — the "
                   "per-org layout serve --tenants points at "
                   "(docs/multitenancy.md)")
    b.set_defaults(fn=cmd_bank_build)

    b = bank_sub.add_parser(
        "diff", help="derive a new version via add/retire/reweight/edit ops"
    )
    b.add_argument("--store", required=True)
    b.add_argument("--parent", default=None,
                   help="parent version id (default: latest)")
    b.add_argument("--ops", default=None,
                   help="JSON list of diff ops (inline or a file path)")
    b.add_argument("--retire", action="append", metavar="CATEGORY",
                   help="retire one category (repeatable)")
    b.add_argument("--reweight", action="append", metavar="CATEGORY=W",
                   help="reweight one category (repeatable)")
    b.add_argument("--note", default=None)
    b.add_argument("--tenant", default=None, metavar="NAME",
                   help="scope the store to <store>/<tenant> — the "
                   "per-org layout serve --tenants points at "
                   "(docs/multitenancy.md)")
    b.set_defaults(fn=cmd_bank_diff)

    b = bank_sub.add_parser(
        "log", help="lineage of a version (root first) + the ACTIVE pointer"
    )
    b.add_argument("--store", required=True)
    b.add_argument("version", nargs="?", default=None)
    b.add_argument("--tenant", default=None, metavar="NAME",
                   help="scope the store to <store>/<tenant> — the "
                   "per-org layout serve --tenants points at "
                   "(docs/multitenancy.md)")
    b.set_defaults(fn=cmd_bank_log)

    b = bank_sub.add_parser(
        "shadow",
        help="offline shadow: replay a journaled predict_file output "
        "against a candidate version; writes shadow_deltas.jsonl + the "
        "gate-consumable summary",
    )
    b.add_argument("--store", required=True)
    b.add_argument("--candidate", required=True, help="store version id")
    b.add_argument("--archive", required=True,
                   help="model.tar.gz or its serialization dir")
    b.add_argument("--corpus", required=True,
                   help="the corpus file the recorded run scored")
    b.add_argument("--results", required=True,
                   help="the recorded run's <name>_result.json output")
    b.add_argument("-o", "--out-dir", required=True)
    b.add_argument("--split", default=None)
    b.add_argument("--threshold", type=float, default=0.5)
    b.add_argument("--overrides", default=None)
    b.add_argument("--tenant", default=None, metavar="NAME",
                   help="scope the store to <store>/<tenant> — the "
                   "per-org layout serve --tenants points at "
                   "(docs/multitenancy.md)")
    b.set_defaults(fn=cmd_bank_shadow)

    b = bank_sub.add_parser(
        "promote",
        help="gated promotion check: golden-set AUC/F1 parity + shadow "
        "flip-rate thresholds; prints the machine-readable decision "
        "(exit 0 approved / 1 refused)",
    )
    b.add_argument("--store", required=True)
    b.add_argument("--candidate", required=True, help="store version id")
    b.add_argument("--archive", required=True)
    b.add_argument("--golden-set", required=True,
                   help="pinned labeled eval corpus for the parity check")
    b.add_argument("--active", default=None,
                   help="store version to gate against (default: the "
                   "ACTIVE pointer, else the candidate's parent)")
    b.add_argument("--shadow-summary", default=None,
                   help="shadow summary JSON (bank shadow / ShadowScorer)")
    b.add_argument("--no-shadow", action="store_true",
                   help="gate on golden-set parity alone")
    b.add_argument("--apply", action="store_true",
                   help="advance the store ACTIVE pointer on approval")
    b.add_argument("--split", default=None)
    b.add_argument("--max-auc-drop", type=float, default=0.01)
    b.add_argument("--max-f1-drop", type=float, default=0.01)
    b.add_argument("--max-flip-rate", type=float, default=0.02)
    b.add_argument("--min-shadow-samples", type=int, default=100)
    b.add_argument("--overrides", default=None)
    b.add_argument("--tenant", default=None, metavar="NAME",
                   help="scope the store to <store>/<tenant> — the "
                   "per-org layout serve --tenants points at "
                   "(docs/multitenancy.md)")
    b.set_defaults(fn=cmd_bank_promote)

    p = sub.add_parser(
        "lint",
        help="unified static analysis over the package: trace purity, "
        "lock discipline, handler/artifact hygiene, and fault/metric/"
        "config registry-drift checks — one AST parse per file, inline "
        "suppressions + committed baseline (docs/static_analysis.md)",
    )
    from .analysis.cli import add_lint_arguments

    add_lint_arguments(p)
    p.set_defaults(fn=cmd_lint)

    p = sub.add_parser(
        "telemetry-report",
        help="render a run dir's telemetry (events.jsonl / telemetry.json "
        "/ HEARTBEAT.json) into a human summary: phases, step-time "
        "percentiles, counters, last-heartbeat age; --json for the "
        "machine-readable report",
    )
    p.add_argument("run_dir", help="serialization/output dir of a run")
    p.add_argument("--json", action="store_true",
                   help="emit the machine-readable report (stable schema "
                   "— the lint --json pattern) instead of the table text")
    p.set_defaults(fn=cmd_telemetry_report)

    p = sub.add_parser(
        "tune",
        help="offline autotuner (docs/tuning.md): sweep training/serving "
        "performance knobs for this device class, prune infeasible "
        "points through the program registry's cost/memory analysis, "
        "microbench survivors behind the mandatory parity gate, and "
        "persist a versioned, checksummed tuned profile the build "
        "entry points load by default; --cascade tunes the rescue "
        "band, --report renders the measured roofline table",
    )
    p.add_argument("--mode", choices=("train", "serve", "all"),
                   default="all", help="which knob families to sweep")
    p.add_argument("--out", default=None, metavar="DIR",
                   help="tuned-profile store root (tuning.profile_dir / "
                   "$MEMVUL_TUNED_PROFILES layout); omit for a dry run")
    p.add_argument("--cascade", action="store_true",
                   help="also tune [cascade_low, cascade_high] from the "
                   "golden set's int8 score distribution, gated through "
                   "bankops.evaluate_cascade")
    p.add_argument("--target-rescore-rate", type=float, default=0.1,
                   help="golden-set fraction the cascade band should "
                   "send to the fp32 rescue tier")
    p.add_argument("--report", default=None, metavar="PATH",
                   help="render the measured roofline markdown from a "
                   "run dir's programs.json instead of tuning")
    p.add_argument("--splice", default=None, metavar="DOC",
                   help="with --report: splice the generated section "
                   "into this markdown doc in place")
    p.add_argument("--device-class", default=None,
                   help="tune for this device class instead of the "
                   "default backend's (e.g. 'tpu v5 lite')")
    p.add_argument("--allow-unknown-device", action="store_true",
                   help="tune a class with no PEAK_SPECS row in "
                   "measurement-only mode (analytic HBM pruning "
                   "skipped) instead of the unknown_device_class "
                   "refusal — how CPU harness records are produced")
    p.add_argument("--max-programs", type=int, default=64,
                   help="analytic prune ceiling: worst-case compiled-"
                   "program count per candidate")
    p.add_argument("--hbm-fraction", type=float, default=0.9,
                   help="analytic prune ceiling: fraction of the device "
                   "class's HBM capacity a candidate may project")
    p.add_argument("--full-space", action="store_true",
                   help="sweep the full knob grids (silicon budget) "
                   "instead of the slim default")
    p.add_argument("--model", choices=("tiny", "base"), default="tiny",
                   help="microbench model geometry (base is the one "
                   "that means something on hardware)")
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--batch-size", type=int, default=8,
                   help="training microbench batch size")
    p.add_argument("--steps", type=int, default=4,
                   help="training microbench optimizer steps per epoch")
    p.add_argument("--requests", type=int, default=96,
                   help="serving microbench request count")
    p.add_argument("--clients", type=int, default=4,
                   help="serving microbench closed-loop client threads")
    p.add_argument("--max-batch", type=int, default=8,
                   help="serving default micro-batch cap (the sweep "
                   "center)")
    p.add_argument("--seed", type=int, default=0)
    p.set_defaults(fn=cmd_tune)

    p = sub.add_parser(
        "doctor",
        help="environment/artifact self-diagnosis: device, mesh, "
        "vocabulary genuineness, data artifacts, native normalizer, "
        "compile cache (one JSON report; exit 1 on any failed check)",
    )
    p.add_argument("--config", default="configs/config_memory.json",
                   help="config whose tokenizer/data paths to check")
    p.add_argument("--device-timeout", type=float, default=90.0,
                   help="seconds before declaring the device op wedged")
    p.add_argument("--skip-device", action="store_true",
                   help="skip the device probe (e.g. while another "
                   "process holds the serialized TPU tunnel)")
    p.set_defaults(fn=cmd_doctor)

    p = sub.add_parser(
        "parity",
        help="real-weights parity chain: HF convert check, reference-"
        "archive scoring, metric diff (run on a machine that has the "
        "genuine bert-base-uncased dir / reference model.tar.gz)",
    )
    p.add_argument("--hf-dir", required=True,
                   help="local HF checkpoint dir (config.json + torch "
                   "weights + vocab.txt), e.g. bert-base-uncased")
    p.add_argument("--archive", default=None,
                   help="reference-trained model.tar.gz")
    p.add_argument("--corpus", default=None, help="test_project.json")
    p.add_argument("--anchors", default=None,
                   help="CWE_anchor_golden_project.json")
    p.add_argument("--ref-metrics", default=None,
                   help="metric file the reference pipeline wrote, to diff")
    p.add_argument("-o", "--out-dir", default="parity_out")
    p.add_argument("--max-length", type=int, default=512)
    p.add_argument("--batch-size", type=int, default=512)
    p.add_argument("--threshold", type=float, default=0.5)
    p.add_argument("--atol", type=float, default=5e-4,
                   help="convert-parity max-abs-error acceptance")
    p.add_argument("--seq-len", type=int, default=128,
                   help="convert-parity probe sequence length")
    p.set_defaults(fn=cmd_parity)

    p = sub.add_parser(
        "selfcheck",
        help="end-to-end acceptance run on a synthetic corpus (CPU-friendly)",
    )
    p.add_argument("--dir", default=None, help="workspace dir (default: mkdtemp)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--projects", type=int, default=8,
                   help="synthetic projects (≥8 keeps every split non-empty)")
    p.add_argument("--reports", type=int, default=24, help="reports per project")
    p.set_defaults(fn=cmd_selfcheck)

    return parser


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO, stream=sys.stderr,
                        format="%(levelname)s %(name)s: %(message)s")
    args = build_parser().parse_args(argv)
    _honor_platform_env()
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
