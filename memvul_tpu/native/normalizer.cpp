// Native issue-report normalizer.
//
// C++17 implementation of the ordered tag-replacement passes in
// memvul_tpu/data/normalize.py (behavior-parity with the reference
// normalizer, MemVul/util.py:39-142).  The Python pass table is the
// specification; this library exists because normalization is the
// host-side hot path when preprocessing the 1.2M-report corpus — the
// batch entry point fans documents out over a thread pool, and the
// Python binding (memvul_tpu/data/native.py) only enables it after a
// runtime parity self-check against the Python implementation.
//
// Error contract: any per-document failure (regex engine limits,
// oversized input) returns NULL for that document and the Python side
// falls back to the pure-Python pass table, so the native path can never
// produce a wrong result silently — only a slower one.
//
// Build: g++ -O2 -std=c++17 -shared -fPIC -pthread normalizer.cpp
//        -o libmemvul_native.so   (see memvul_tpu/data/native.py)

#include <algorithm>
#include <array>
#include <atomic>
#include <cstdlib>
#include <cstring>
#include <pthread.h>
#include <regex>
#include <string>
#include <thread>
#include <vector>

namespace {

using std::regex;
using std::regex_constants::icase;
using std::string;

// libstdc++'s std::regex executor recurses per matched character for
// quantified alternations (kUrl, kApiCatchall); large documents can
// overflow the thread stack, which catch(...) cannot intercept.  The
// single-document entry runs on the CALLER's thread (stack size unknown,
// typically 8MB) so it keeps a conservative 16KB cap; the batch entry
// creates its own pool threads with 64MB stacks, which safely covers
// 256KB documents (≈256K frames × ~128B ≪ 64MB) — issue bodies with
// large pasted logs stay on the fast path there.
constexpr size_t kMaxDocBytesCallerStack = 16 << 10;
constexpr size_t kMaxDocBytesPoolStack = 256 << 10;
constexpr size_t kPoolThreadStackBytes = 64ull << 20;
constexpr size_t kMaxApiSpan = 150;       // normalize.py _MAX_API_SPAN

// ---------------------------------------------------------------------------
// pass-table regexes (compiled once; ECMAScript grammar).  Python's `.`
// with re.S becomes [\s\S]; everything else is shared syntax.
// ---------------------------------------------------------------------------

// Python's '.' (no re.S) excludes only \n; ECMAScript '.' also excludes
// \r/ /  — use [^\n] explicitly so comments containing a bare
// carriage return normalize identically on both paths
const regex kCommentLine("<!---[^\\n]*?-->");

const regex kErrorish(
    "exception|error|warning|404|can't|can\\s?not|could\\s?not|un[a-z]{3,}",
    icase);
const regex kProse(
    "^yaml|^\\s*([a-z]+[,\\.\\?]?\\s+)*?[a-z]+[,\\.\\?]?\\s*$", icase);
const regex kOneToken("^\\s*\\S+\\s*$");

const regex kMdLink("!?\\[([\\s\\S]+?)\\]\\((\\S+)\\)");
const regex kUrl(
    "http[s]?://(?:[a-zA-Z]|[0-9]|[$-_@.&+#]|[!*\\(\\),]|(?:%[0-9a-fA-F][0-9a-"
    "fA-F]))+");
const regex kVulnTracker("bugzilla|mitre|bugs", icase);

const regex kAngleRun("<[^>]*>{2,}");
const regex kAngleAttr("<[^>]*?[!;=/$%][^>]*>");

const regex kEscapedPairs(
    "(\\\\r\\\\n)|(\\\\n\\\\n)|(\\\\r\\\\r)|(\\\\t\\\\t)|(\\\\\")|(\\\\')");
const regex kStars("\\*{1,}");
const regex kHashes("#{1,}");
const regex kCve("CVE-[0-9]+-[0-9]+");
const regex kCwe("CWE-[0-9]+");
const regex kEmail("[0-9a-zA-Z_]{0,19}@[0-9a-zA-Z]{1,13}\\.[com,cn,net]{1,3}");
const regex kMention("@[a-zA-Z0-9_\\-]+[,\\.]?\\s");
const regex kError(
    "\\S+?(Error|Exception)([^A-Za-z\\s]\\S*|\\s|$)|404");
const regex kPath("([^\\s\\(\\)]+?[/\\\\]){2,}[^\\s\\(\\)]*");

const regex kFileExt(
    "\\s(\\S+?\\.(ml|xml|png|csv|jar|sh|sbt|zip|exe|md|txt|js|yml|yaml|json|"
    "sql|html|pdf|jsp|php|prod|scss|ts|jpg|png|bmp|gif))[?,\\.]{0,1}\\s",
    icase);

const regex kDash("-");
const regex kLongToken("\\S{30,}");
const regex kApiCatchall(
    "\\S+?((\\(\\))|(\\[\\]))\\S*|[^,;\\.\\s]{3,}?\\.\\S{4,}|"
    "\\S+?([a-z][A-Z]|[A-Z][a-z]{2,}?)\\S*|@\\S+|<\\S*?>");
const regex kNumber(
    "[^a-uwyz]+?\\d[^a-uwyz]*(beta[0-9]+){0,1}|beta[0-9]+", icase);
const regex kCtrlChars("[\\r\\n\\t]");
const regex kEscapedSingles("(\\\\r)|(\\\\n)|(\\\\t)|(\\\\\")|(\\\\')");

// ---------------------------------------------------------------------------
// helpers
// ---------------------------------------------------------------------------

string sub_all(const regex& re, const string& repl, const string& s) {
  return std::regex_replace(s, re, repl);
}

void replace_first(string* s, const string& needle, const string& repl) {
  size_t pos = s->find(needle);
  if (pos != string::npos) s->replace(pos, needle.size(), repl);
}

// Python: re.search(r"\.", s[-5:-1])
bool looks_like_file(const string& s) {
  if (s.size() < 2) return false;
  size_t start = s.size() >= 5 ? s.size() - 5 : 0;
  size_t end = s.size() - 1;  // exclusive
  for (size_t i = start; i < end; ++i)
    if (s[i] == '.') return true;
  return false;
}

// normalize.py _classify_code_span
string classify_code_span(const string& inner) {
  if (inner.empty()) return " ";
  if (std::regex_search(inner, kErrorish)) return " ERRORTAG ";
  if (std::regex_search(inner, kProse)) return " " + inner + " ";
  if (std::regex_search(inner, kOneToken) || inner.size() <= kMaxApiSpan)
    return " APITAG ";
  return " CODETAG ";
}

// normalize.py _rewrite_code_spans: matches collected on the ORIGINAL
// string (lazy, non-overlapping), then sequential first-occurrence
// replacement — the fence finder is hand-rolled (equivalent to
// `fence[\s\S]*?fence`) to avoid regex backtracking on big code blocks.
string rewrite_code_spans(string content, const string& fence) {
  std::vector<string> spans;
  size_t pos = 0;
  const size_t n = fence.size();
  while (true) {
    size_t a = content.find(fence, pos);
    if (a == string::npos) break;
    size_t b = content.find(fence, a + n);
    if (b == string::npos) break;
    spans.push_back(content.substr(a, b + n - a));
    pos = b + n;
  }
  for (const string& span : spans) {
    string inner = span.substr(n, span.size() - 2 * n);
    replace_first(&content, span, classify_code_span(inner));
  }
  return content;
}

string rewrite_md_links(string content) {
  std::vector<std::array<string, 3>> matches;  // whole, text, target
  for (auto it = std::sregex_iterator(content.begin(), content.end(), kMdLink);
       it != std::sregex_iterator(); ++it)
    matches.push_back({it->str(0), it->str(1), it->str(2)});
  for (const auto& m : matches) {
    if (looks_like_file(m[1]) || looks_like_file(m[2]))
      replace_first(&content, m[0], " FILETAG ");
    else
      replace_first(&content, m[0], " " + m[1] + " " + m[2] + " ");
  }
  return content;
}

string rewrite_urls(string content) {
  std::vector<string> urls;
  for (auto it = std::sregex_iterator(content.begin(), content.end(), kUrl);
       it != std::sregex_iterator(); ++it)
    urls.push_back(it->str(0));
  for (const string& url : urls) {
    string repl;
    if (std::regex_search(url, kVulnTracker))
      repl = " CVETAG ";  // cve.mitre.org / bugzilla — leak guard
    else if (looks_like_file(url))
      repl = " FILETAG ";
    else
      repl = " URLTAG ";
    replace_first(&content, url, repl);
  }
  return content;
}

string rewrite_filenames(string content) {
  std::vector<string> names;
  for (auto it =
           std::sregex_iterator(content.begin(), content.end(), kFileExt);
       it != std::sregex_iterator(); ++it)
    names.push_back(it->str(1));
  for (const string& name : names) replace_first(&content, name, " FILETAG ");
  return content;
}

string collapse_spaces(const string& s) {
  // " ".join(tok for tok in content.split(" ") if tok)
  string out;
  out.reserve(s.size());
  size_t i = 0;
  while (i < s.size()) {
    while (i < s.size() && s[i] == ' ') ++i;
    size_t start = i;
    while (i < s.size() && s[i] != ' ') ++i;
    if (i > start) {
      if (!out.empty()) out += ' ';
      out.append(s, start, i - start);
    }
  }
  return out;
}

string normalize_one(const string& input) {
  string content = sub_all(kCommentLine, " ", input);
  content = rewrite_code_spans(content, "```");
  content = rewrite_code_spans(content, "`");
  content = rewrite_md_links(content);
  content = sub_all(kAngleRun, " APITAG ", content);
  content = sub_all(kAngleAttr, " APITAG ", content);
  content = rewrite_urls(content);
  content = sub_all(kEscapedPairs, " ", content);
  content = sub_all(kStars, " ", content);
  content = sub_all(kHashes, " ", content);
  content = sub_all(kCve, " CVETAG ", content);
  content = sub_all(kCwe, " CVETAG ", content);
  content = sub_all(kEmail, " EMAILTAG ", content);
  content = sub_all(kMention, " MENTIONTAG ", content);
  content = sub_all(kError, " ERRORTAG ", content);
  content = sub_all(kPath, " PATHTAG ", content);
  content = rewrite_filenames(content);
  content = sub_all(kDash, " ", content);
  content = sub_all(kLongToken, " APITAG ", content);
  content = sub_all(kApiCatchall, " APITAG ", content);
  content = sub_all(kNumber, " NUMBERTAG ", content);
  content = sub_all(kCtrlChars, " ", content);
  content = sub_all(kEscapedSingles, " ", content);
  return collapse_spaces(content);
}

char* normalize_or_null(const char* text, size_t max_bytes) {
  if (text == nullptr) return nullptr;
  size_t len = std::strlen(text);
  if (len > max_bytes) return nullptr;  // caller falls back to Python
  // non-ASCII documents fall back: byte-oriented std::regex disagrees
  // with Python's unicode-aware \s/\w on e.g. U+00A0, and correctness
  // beats speed by contract
  for (size_t i = 0; i < len; ++i)
    if (static_cast<unsigned char>(text[i]) >= 0x80) return nullptr;
  try {
    string out = normalize_one(string(text, len));
    char* buf = static_cast<char*>(std::malloc(out.size() + 1));
    if (buf == nullptr) return nullptr;
    std::memcpy(buf, out.c_str(), out.size() + 1);
    return buf;
  } catch (...) {
    return nullptr;  // regex limits etc. — caller falls back
  }
}

}  // namespace

extern "C" {

// One document. Returns a malloc'd NUL-terminated string (free with
// mv_free) or NULL when the caller should use the Python fallback.
// Runs on the caller's thread, so only small documents are accepted.
char* mv_normalize(const char* text) {
  return normalize_or_null(text, kMaxDocBytesCallerStack);
}

void mv_free(char* p) { std::free(p); }

namespace {

struct BatchJob {
  const char** texts;
  char** out;
  int n;
  std::atomic<int>* next;
  size_t max_bytes;  // pool threads: 256KB; inline fallback: 16KB
};

void* batch_worker(void* arg) {
  auto* job = static_cast<BatchJob*>(arg);
  while (true) {
    int i = job->next->fetch_add(1);
    if (i >= job->n) break;
    job->out[i] = normalize_or_null(job->texts[i], job->max_bytes);
  }
  return nullptr;
}

}  // namespace

// Batch over a thread pool: out[i] receives the normalization of
// texts[i].  Each out[i] must be released with mv_free (NULL entries
// mean Python fallback).  Pool threads get 64MB stacks so documents up
// to kMaxDocBytesPoolStack survive std::regex recursion.
void mv_normalize_batch(const char** texts, int n, char** out,
                        int n_threads) {
  if (n <= 0) return;
  int workers = std::max(1, n_threads);
  workers = std::min(workers, n);
  std::atomic<int> next{0};
  BatchJob job{texts, out, n, &next, kMaxDocBytesPoolStack};
  pthread_attr_t attr;
  pthread_attr_init(&attr);
  pthread_attr_setstacksize(&attr, kPoolThreadStackBytes);
  std::vector<pthread_t> pool;
  pool.reserve(workers);
  for (int t = 0; t < workers; ++t) {
    pthread_t th;
    if (pthread_create(&th, &attr, batch_worker, &job) == 0) {
      pool.push_back(th);
    }
  }
  pthread_attr_destroy(&attr);
  if (pool.empty()) {
    // thread creation failed — run inline on the CALLER's stack, so only
    // caller-stack-safe document sizes may take the native path
    job.max_bytes = kMaxDocBytesCallerStack;
    batch_worker(&job);
  }
  for (pthread_t th : pool) pthread_join(th, nullptr);
}

int mv_abi_version() { return 1; }

}  // extern "C"
