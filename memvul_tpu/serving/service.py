"""The in-process online scoring service (docs/serving.md).

The offline path (`evaluate/predict_memory.py`) streams a corpus it can
see end-to-end; a service sees one report at a time and must answer in
milliseconds.  The whole design problem is reconciling that with the
shape discipline the TPU demands: XLA compiles one program per input
shape, so the server may only ever dispatch the exact (rows, seq_len)
shapes :meth:`SiamesePredictor.warmup_compile` precompiled at startup —
a mid-serve compile is a multi-second latency cliff for every queued
request behind it (asserted in tests via the ``score_trace_count``
probe).

Three cooperating pieces:

* **dynamic micro-batcher** — requests land in a bounded deque; a
  single batcher thread coalesces them until ``max_batch`` requests are
  pulled or the oldest has waited ``max_wait_ms``, routes each request
  to the smallest warmed length bucket covering its token count, and
  pads every micro-batch to the warmed (rows, bucket) shape with the
  same ``_pad_block`` the offline collator uses — so a served score is
  bitwise-identical to the offline score of the same text.  The batcher
  body is a strategy (serving/dispatch.py): with a
  ``score_impl="ragged"`` predictor the pull instead coalesces by
  token budget: it is packed into fixed ``[1, token_budget]`` flat
  batches and ONE warmed segment-masked program serves any length mix
  (scores ≤1e-6 vs the bucketed path; docs/ragged_serving.md); with
  ``score_impl="continuous"`` there is no pull at all — a persistent
  admission loop writes each request straight into the open pack while
  the previous pack is on device, decoupling queue wait from device
  latency (docs/serving.md, "Continuous admission");
* **admission control** — the queue is bounded (``max_queue``); on
  overflow the *oldest* queued request is shed (it is the one most
  likely to miss its deadline anyway) with status ``"shed"`` instead of
  letting latency grow without bound, and every request carries a
  deadline after which it resolves ``"deadline"`` rather than dispatch;
* **hot anchor-bank swap** — the bank is an immutable versioned
  snapshot; a swap encodes the new bank off the request path, AOT-warms
  the score program if the bank shape changed, then atomically installs
  the new snapshot.  Each micro-batch captures exactly one snapshot, so
  a response is never a torn mix of two banks.

Shutdown mirrors the PR-2 preemption contract: SIGTERM finishes the
in-flight micro-batch, resolves everything still queued with status
``"drain"``, and leaves the telemetry sinks parseable.

Failure routing: each micro-batch dispatch passes through the shared
:class:`~memvul_tpu.resilience.retry.RetryPolicy` with the
``serve.batch`` fault point inside the retried window; a persistent
failure dead-letters the batch — every affected request resolves
``"error"`` with the reason — instead of hanging its clients.

Request-journey tracing (docs/observability.md, "Request tracing"):
with ``trace_sample_rate > 0`` every request carries a :class:`_Trace`
whose monotonic waypoints the batcher thread stamps as the journey
advances — ``received → enqueued → coalesced`` (micro-batch id) ``→
dispatched`` (bucket/pack shape + fill) ``→ device_done → resolved``
(cause) — feeding the ``serve.queue_wait_s`` / ``serve.pack_s`` /
``serve.device_s`` / ``serve.resolve_s`` stage histograms, a bounded
ring ``GET /tracez`` reads, and sampled ``rtrace`` events (always-on
for non-``ok`` outcomes).  At the default rate 0.0 tracing is entirely
off: no stamps, no ring, no events, no extra metrics — the
zero-overhead pin in tests/test_serving.py.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import itertools
import json
import logging
import os
import signal
import threading
import time
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..resilience import faults
from ..resilience.retry import RetryPolicy
from ..telemetry import get_registry
from .tenancy import DEFAULT_TENANT

logger = logging.getLogger(__name__)

# terminal request statuses (docs/serving.md, "Deadline semantics")
STATUS_OK = "ok"            # scored; response carries the anchor probs
STATUS_SHED = "shed"        # evicted by admission control (queue overflow)
STATUS_DEADLINE = "deadline"  # deadline expired before dispatch
STATUS_DRAIN = "drain"      # still queued when the service drained
STATUS_ERROR = "error"      # batch dead-lettered after retries; see "reason"

MANIFEST_NAME = "anchor_bank_manifest.json"


@dataclasses.dataclass(frozen=True)
class ServiceConfig:
    """Knobs of the micro-batcher + admission control; defaults mirror
    ``config.SERVING_DEFAULTS`` (the JSON-facing view)."""

    max_batch: int = 16          # requests pulled per flush cycle
    max_wait_ms: float = 5.0     # oldest-request coalescing window
    max_queue: int = 256         # bounded queue depth (admission control)
    default_deadline_ms: float = 2000.0  # per-request budget; <=0 = none
    # per-anchor attribution: each served decision counts into
    # bank.anchor_wins.<id> + a bank.anchor_score.<id> reservoir — the
    # raw material of the drift table (bankops/drift.py)
    anchor_stats: bool = True
    # request-journey tracing: 0.0 = off entirely (the free default);
    # > 0 stamps waypoints on every request, emits an `rtrace` event
    # for ~this fraction of served requests (ALWAYS for non-served
    # outcomes), and feeds the per-stage serve.*_s histograms
    trace_sample_rate: float = 0.0
    trace_ring: int = 256        # completed traces kept for GET /tracez
    # sample device_memory_stats into serve.hbm_in_use_bytes /
    # serve.hbm_peak_bytes at heartbeat cadence (no-op on backends
    # without memory stats, e.g. CPU)
    hbm_gauges: bool = True
    # content-addressed admission cache (serving/admission_cache.py):
    # > 0 bounds an exact-duplicate LRU that answers repeats without a
    # device call; 0 (default) constructs nothing — the cache-off
    # request path is byte-identical to pre-cache builds
    cache_capacity: int = 0
    # continuous-pack duplicate aliasing (docs/multitenancy.md): an
    # admitted request whose cap-truncated token sequence exactly
    # matches an open-pack row shares that row's segment instead of
    # paying new token slots; off by default (serving.prefix_share)
    prefix_share: bool = False


class ScoreFuture:
    """Resolved exactly once with a response dict; waiters block on an
    event, never on the batcher's locks (the HTTP handler contract the
    ``lint_no_blocking_in_handler`` tool enforces: enqueue + wait only)."""

    __slots__ = ("_event", "_response", "_lock", "_callbacks")

    def __init__(self) -> None:
        self._event = threading.Event()
        self._response: Optional[Dict[str, Any]] = None
        self._lock = threading.Lock()
        self._callbacks: List[Any] = []

    def done(self) -> bool:
        return self._event.is_set()

    def add_done_callback(self, fn) -> None:
        """Run ``fn(response)`` when the future resolves (immediately if
        it already has).  The router's relay path: it registers one
        callback per routed request instead of parking a waiter thread
        per replica.  Callbacks run on the resolving thread (the
        replica's batcher) and must be cheap and non-raising."""
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
            response = self._response
        fn(response)

    def resolve(self, response: Dict[str, Any]) -> bool:
        """First resolution wins; later ones are ignored (a request has
        exactly one owner at a time, this is belt-and-braces)."""
        with self._lock:
            if self._event.is_set():
                return False
            self._response = response
            callbacks, self._callbacks = self._callbacks, []
            self._event.set()
        for fn in callbacks:  # outside the lock: a callback may re-submit
            try:
                fn(response)
            except Exception:  # pragma: no cover - defensive
                logger.exception("score-future callback failed")
        return True

    def result(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        if not self._event.wait(timeout):
            raise TimeoutError("scoring request not resolved in time")
        assert self._response is not None
        return self._response


@dataclasses.dataclass
class _Trace:
    """One request's journey: monotonic waypoints stamped by the
    batcher thread (submit stamps the first two on the caller's way
    into the queue — no emission work happens on a handler thread).
    ``None`` = the journey never reached that stage (a shed request
    has no ``dispatched``)."""

    trace_id: str
    hops: int = 0                # router re-route count (0 = first try)
    received: Optional[float] = None
    enqueued: Optional[float] = None
    coalesced: Optional[float] = None
    dispatched: Optional[float] = None
    device_done: Optional[float] = None
    resolved: Optional[float] = None
    batch: Optional[int] = None  # micro-batch (pull) sequence number
    shape: str = ""              # "bucket:RxL fill=n/R" | "pack:real/budget"
    cause: str = ""              # terminal status (ok/shed/deadline/...)


_WAYPOINT_ORDER = (
    "received", "enqueued", "coalesced", "dispatched", "device_done",
    "resolved",
)
# adjacent waypoint pairs → the stage duration they bound; the four
# stages partition enqueued→resolved exactly, so their sum equals the
# end-to-end latency by construction (the acceptance test's ≤5 ms gate)
_STAGES = (
    ("queue_wait_s", "enqueued", "coalesced"),
    ("pack_s", "coalesced", "dispatched"),
    ("device_s", "dispatched", "device_done"),
    ("resolve_s", "device_done", "resolved"),
)


def _trace_record(trace: _Trace) -> Dict[str, Any]:
    """The JSON shape of one completed trace — what the ring serves on
    ``/tracez`` and the ``rtrace`` event carries."""
    waypoints = {
        name: getattr(trace, name)
        for name in _WAYPOINT_ORDER
        if getattr(trace, name) is not None
    }
    stages = {}
    for stage, begin, end in _STAGES:
        b, e = getattr(trace, begin), getattr(trace, end)
        if b is not None and e is not None:
            stages[stage] = e - b
    record: Dict[str, Any] = {
        "trace_id": trace.trace_id,
        "cause": trace.cause,
        "hops": trace.hops,
        "waypoints": waypoints,
        "stages": stages,
    }
    if trace.batch is not None:
        record["batch"] = trace.batch
    if trace.shape:
        record["shape"] = trace.shape
    if trace.resolved is not None and trace.enqueued is not None:
        record["total_s"] = trace.resolved - trace.enqueued
    return record


@dataclasses.dataclass
class _Request:
    text: str
    future: ScoreFuture
    enqueued_monotonic: float
    deadline_monotonic: Optional[float]  # None = no deadline
    trace: Optional[_Trace] = None       # present only when tracing is on
    # which org's anchor bank scores this request (serving/tenancy.py);
    # untagged requests ride the default tenant — full back-compat
    tenant: str = DEFAULT_TENANT
    # real token count, stamped at encode time by the dispatcher — the
    # admission cache's tokens-saved ledger reads it back on a hit
    n_tokens: int = 0


@dataclasses.dataclass(frozen=True)
class _BankVersion:
    """One immutable anchor-bank snapshot.  ``array`` is the
    device-resident (possibly sharding-padded) bank; ``n_anchors`` the
    real row count; a micro-batch captures one snapshot and labels its
    whole response from it — the no-torn-mix guarantee.

    ``source``/``parent_version``/``store_version`` are provenance:
    where this snapshot came from (startup, a manual swap, a rolling
    swap, or a bankops promotion), which serving version it replaced,
    and — when it came out of a versioned bank store — which store
    version id it is (docs/anchor_bank.md)."""

    version: int
    array: Any
    labels: Tuple[str, ...]
    n_anchors: int
    source: str = "startup"
    parent_version: Optional[int] = None
    store_version: Optional[str] = None
    # which tenant's bank this snapshot is (serving/tenancy.py)
    tenant: str = DEFAULT_TENANT
    # per-anchor weights for the weighted max-over-anchors reweight
    # path (bankops stores them per category).  ``None`` — the all-1.0
    # case — skips the weighting arithmetic entirely, so an unweighted
    # bank's scores are bitwise-unchanged by construction (the
    # evaluate_reweight parity gate's guarantee)
    weights: Any = None


def _bank_weights(instances: List[Dict], n_anchors: int):
    """Per-anchor weight vector pulled from the instances' meta, aligned
    with encode order (``encode_bank`` preserves instance order).
    Returns ``None`` for the trivial all-1.0 bank so the scoring path
    skips the multiply and stays bitwise-identical to pre-reweight
    behavior."""
    if len(instances) != int(n_anchors):
        # an encoder that reorders or resamples its anchors can't be
        # aligned with the per-instance weights — serve unweighted
        # rather than misattribute weights across categories
        logger.warning(
            "bank weights dropped: %d instances vs %d anchors",
            len(instances), n_anchors,
        )
        return None
    raw = [
        float((inst.get("meta") or {}).get("weight", 1.0))
        for inst in instances
    ]
    if all(w == 1.0 for w in raw):
        return None
    return np.asarray(raw, dtype=np.float32)


class ScoringService:
    """Micro-batching scorer over a warmed :class:`SiamesePredictor`.

    The predictor must already have its anchor bank encoded (that run
    included the AOT shape warmup); the service never triggers a compile
    on the request path.  ``manifest_dir`` (usually the telemetry run
    dir) receives the versioned ``anchor_bank_manifest.json`` through
    ``atomic_write_text`` on startup and after every swap.
    """

    def __init__(
        self,
        predictor,
        config: Optional[ServiceConfig] = None,
        retry_policy: Optional[RetryPolicy] = None,
        manifest_dir: Optional[Union[str, Path]] = None,
        registry=None,
        device=None,
    ) -> None:
        if getattr(predictor, "anchor_bank", None) is None:
            raise RuntimeError(
                "predictor has no anchor bank — call encode_anchors() "
                "(with aot_warmup) before constructing the service"
            )
        self.predictor = predictor
        self.config = config or ServiceConfig()
        self.retry_policy = retry_policy
        self.manifest_dir = Path(manifest_dir) if manifest_dir else None
        # warmed shape set: bucket length → padded row count.  Dispatch
        # may ONLY use these shapes (the zero-mid-serve-compile contract).
        self._rows_by_length: Dict[int, int] = {
            length: rows for rows, length in predictor.stream_shapes()
        }
        self._lengths = sorted(self._rows_by_length)
        # dispatch strategy (serving/dispatch.py): the predictor's
        # score_impl decides how accepted requests become device
        # dispatches — bucket routing over the warmed grid ("bucketed"),
        # token-budget packing into the single warmed [1, token_budget]
        # program ("ragged"), or persistent admission into the in-flight
        # pack ("continuous").  Admission, deadlines, drain, swap and
        # the shadow tap are impl-independent and stay here.
        self._score_impl = getattr(predictor, "score_impl", "bucketed")
        if self._score_impl in ("ragged", "continuous"):
            self._token_budget, self._max_rows = predictor.ragged_shape()
        else:
            self._token_budget = self._max_rows = 0
        self._bank = _BankVersion(
            version=1,
            array=predictor.anchor_bank,
            labels=tuple(predictor.anchor_labels),
            n_anchors=predictor.n_anchors,
        )
        self._bank_lock = threading.Lock()
        self._swap_lock = threading.Lock()  # one swap at a time
        # per-tenant bank snapshots (serving/tenancy.py): named tenants
        # only — the default tenant stays ``self._bank`` so every
        # single-tenant code path is untouched.  Guarded by _bank_lock.
        self._banks: Dict[str, _BankVersion] = {}
        self._multi_tenant = False  # flips on the first named install
        # bank geometries the predictor has warmed programs for — a
        # swap only pays the AOT re-warm for a genuinely new shape
        self._warmed_bank_shapes = {tuple(predictor.anchor_bank.shape)}
        # content-addressed admission cache (admission_cache.py): an
        # exact repeat resolves on the submit thread, no device call
        self._precision = getattr(predictor, "encoder_precision", "fp32")
        self.admission_cache = None
        if int(self.config.cache_capacity) > 0:
            from .admission_cache import AdmissionCache

            # same registry fallback the service itself uses below
            self.admission_cache = AdmissionCache(
                int(self.config.cache_capacity), registry=registry
            )
        self._queue: "collections.deque[_Request]" = collections.deque()
        self._cond = threading.Condition()
        # drain is signalled via a bare Event (no lock acquisition) so
        # the SIGTERM handler can run even while the main thread holds
        # the queue condition — same non-reentrancy hazard the trainer's
        # preemption handler avoids by only setting a flag
        self._draining = threading.Event()
        # hard-kill flag: the in-process analogue of SIGKILLing a
        # replica worker — the batcher abandons its work UNRESOLVED (no
        # drain statuses, no counters) so a supervisor must sweep
        # survivors via :meth:`take_unresolved` (serving/replica.py)
        self._killed = threading.Event()
        self._inflight: List[_Request] = []  # guarded by self._cond
        self._closed = threading.Event()
        # shadow tap (bankops/shadow.py): called on the batcher thread
        # AFTER a chunk's futures resolve, with copies of the served
        # texts/probs — it may only enqueue, and a raising tap is
        # swallowed, so active responses are bitwise-unchanged by it
        self._shadow_tap: Optional[Any] = None
        # the replica tier gives each service its own registry so one
        # process can host N replicas with separable health/counters;
        # the single-service path keeps the process-wide default
        self._tel = registry if registry is not None else get_registry()
        # request-journey tracing (docs/observability.md): rate 0 means
        # tracing never allocates, stamps, or emits anything
        cfg = self.config
        self._trace_enabled = cfg.trace_sample_rate > 0
        self._trace_seq = itertools.count(1)
        self._batch_seq = itertools.count(1)
        self._trace_accum = 0.0  # batcher-thread-only sampling credit
        self._trace_prefix = f"{os.getpid():x}"
        self._trace_ring: "collections.deque[Dict[str, Any]]" = (
            collections.deque(maxlen=max(1, int(cfg.trace_ring)))
        )
        self._ring_lock = threading.Lock()
        # HBM liveness gauges: sampled on the batcher thread at the
        # registry's heartbeat cadence; the device this service's bank
        # lives on (None = the process default device)
        self._device = device
        self._hbm_next_monotonic = 0.0
        self._write_manifest()
        # the strategy owns the batcher body; imported lazily because
        # dispatch.py imports this module's status constants
        from .dispatch import make_dispatcher

        self._dispatcher = make_dispatcher(self)
        self._thread = threading.Thread(
            target=self._dispatcher.run,
            name="memvul-serve-batcher",
            daemon=True,
        )
        self._thread.start()

    # -- submission (any thread) ----------------------------------------------

    def submit(
        self,
        text: str,
        deadline_ms: Optional[float] = None,
        trace_id: Optional[str] = None,
        hops: int = 0,
        tenant: Optional[str] = None,
    ) -> ScoreFuture:
        """Enqueue one report text; returns immediately with a future.

        Admission control happens here: during drain the request is
        refused with ``"drain"``; on queue overflow the *oldest* queued
        request is shed with ``"shed"`` to make room (FIFO eviction —
        the newest request has the freshest deadline).

        ``tenant`` routes the request to that org's anchor bank
        (serving/tenancy.py); ``None``/empty means the default tenant —
        every pre-tenancy caller is unchanged.  A tenant with no
        installed bank resolves ``"error"`` (counted in
        ``serve.errors``) without touching the queue.

        With an admission cache installed, an exact repeat of an
        already-served text resolves right here on the submit thread —
        bitwise-identical score fields, no device call, counted as
        served (the exact-counter invariant keeps summing).

        ``trace_id``/``hops`` let the router carry one journey across
        re-routes: a rerouted request keeps its id and its hop count
        grows, so its trace records the whole story.  Both are ignored
        when tracing is off."""
        future = ScoreFuture()
        now = time.monotonic()
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        deadline = now + deadline_ms / 1000.0 if deadline_ms > 0 else None
        trace = None
        if self._trace_enabled:
            trace = _Trace(
                trace_id=trace_id
                or f"{self._trace_prefix}-{next(self._trace_seq)}",
                hops=int(hops),
                received=now,
            )
        tenant = str(tenant) if tenant else DEFAULT_TENANT
        request = _Request(
            text=text, future=future,
            enqueued_monotonic=now, deadline_monotonic=deadline,
            trace=trace, tenant=tenant,
        )
        self._tel.counter("serve.requests").inc()
        self._tenant_count(tenant, "requests")
        # tenant resolution (chaos hook: the bank.resolve fault point).
        # A failed resolution errors THIS request only — the counter
        # invariant still sums and no other tenant is touched.
        bank: Optional[_BankVersion] = None
        try:
            faults.fault_point("bank.resolve")
            bank = self._bank_for(tenant)
        except Exception as e:
            self._tel.counter("serve.errors").inc()
            self._tenant_count(tenant, "errors")
            request.future.resolve({
                "status": STATUS_ERROR,
                "reason": f"tenant resolution failed: {e}",
                "tenant": tenant,
            })
            self._finish_trace(request, STATUS_ERROR)
            return future
        if self._draining.is_set():
            self._finish_unserved(request, STATUS_DRAIN)
            return future
        if self.admission_cache is not None:
            payload = self.admission_cache.lookup(
                tenant, text, bank.version, self._score_impl,
                self._precision,
            )
            if payload is not None:
                self._tel.counter("serve.served").inc()
                self._tenant_count(tenant, "served")
                payload["status"] = STATUS_OK
                payload["latency_ms"] = round(
                    (time.monotonic() - now) * 1000.0, 3
                )
                payload["cached"] = True
                request.future.resolve(payload)
                self._finish_trace(request, STATUS_OK)
                return future
        shed: Optional[_Request] = None
        with self._cond:
            if len(self._queue) >= self.config.max_queue:
                shed = self._queue.popleft()
            self._queue.append(request)
            if trace is not None:
                trace.enqueued = time.monotonic()
            self._tel.gauge("serve.queue_depth").set(len(self._queue))
            self._cond.notify()
        if shed is not None:
            self._finish_unserved(shed, STATUS_SHED)
        return future

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    @property
    def bank_version(self) -> int:
        with self._bank_lock:
            return self._bank.version

    @property
    def bank_labels(self) -> Tuple[str, ...]:
        with self._bank_lock:
            return self._bank.labels

    def bank_snapshot(self) -> _BankVersion:
        """The current immutable bank snapshot (version + provenance) —
        what the shadow scorer compares geometries against and the
        health/manifest paths report."""
        with self._bank_lock:
            return self._bank

    def _bank_for(self, tenant: str) -> _BankVersion:
        """One tenant's current bank snapshot.  Default tenant =
        ``self._bank`` (the pre-tenancy path, bitwise-unchanged);
        a named tenant with no installed bank raises."""
        with self._bank_lock:
            if tenant == DEFAULT_TENANT:
                return self._bank
            bank = self._banks.get(tenant)
        if bank is None:
            raise KeyError(f"unknown tenant {tenant!r}")
        return bank

    def tenant_banks(self) -> Dict[str, _BankVersion]:
        """Snapshot of every installed tenant bank (default included) —
        the health/tenancy introspection view."""
        with self._bank_lock:
            out = {DEFAULT_TENANT: self._bank}
            out.update(self._banks)
        return out

    def _tenant_count(self, tenant: str, what: str, n: int = 1) -> None:
        """Per-tenant ``serve.<tenant>.*`` labels.  Emitted only once a
        named tenant bank is installed (multi-tenant mode), so the
        single-tenant metric surface stays byte-identical; in
        multi-tenant mode EVERY request is labeled (default included),
        making the per-tenant ledgers sum to the fleet invariant."""
        if self._multi_tenant and n:
            self._tel.counter(f"serve.{tenant}.{what}").inc(n)

    # -- shadow tap (bankops/shadow.py) ---------------------------------------

    def set_shadow_tap(self, tap) -> None:
        """Install ``tap(texts, probs, bank_snapshot)`` — called on the
        batcher thread after each successfully served chunk's futures
        resolve.  The tap must only enqueue (the shadow worker scores on
        its own thread); exceptions are swallowed and counted so the
        active path cannot be affected."""
        self._shadow_tap = tap

    def clear_shadow_tap(self) -> None:
        self._shadow_tap = None

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    @property
    def batcher_alive(self) -> bool:
        """Whether the batcher is running (a replica health signal: a
        batcher that exited without a drain is a dead replica).  The
        dispatcher's own liveness is AND-ed in — the continuous
        strategy's device worker dying mid-serve is just as dead as the
        batcher thread itself, even while admission still spins."""
        return self._thread.is_alive() and self._dispatcher.alive

    @property
    def default_deadline_ms(self) -> float:
        """The per-request budget handlers size their waits from — one
        attribute shared with :class:`~memvul_tpu.serving.router
        .ReplicaRouter` so the front end serves either."""
        return self.config.default_deadline_ms

    def health_summary(self) -> Dict[str, Any]:
        """The ``/healthz`` JSON body: drain state plus queue depth and
        the active bank version, so an external probe can tell
        "draining" from "healthy but backed up".  The router's override
        adds the per-replica fleet view (docs/serving.md)."""
        draining = self._draining.is_set()
        bank = self.bank_snapshot()
        out = {
            "status": "draining" if draining else "ok",
            "draining": draining,
            "queue_depth": self.queue_depth,
            # which dispatch strategy serves this replica — the fleet
            # view surfaces it so a mixed rollout (ragged → continuous)
            # is observable per member (serving/dispatch.py)
            "score_impl": self._score_impl,
            "bank_version": bank.version,
            # provenance row: fleet state is traceable to a store
            # version + how it got installed (docs/anchor_bank.md)
            "bank": {
                "version": bank.version,
                "source": bank.source,
                "parent_version": bank.parent_version,
                "store_version": bank.store_version,
            },
        }
        if self._multi_tenant:
            # per-tenant bank rows, additive only — the single-tenant
            # /healthz body stays byte-identical (docs/multitenancy.md)
            with self._bank_lock:
                named = dict(self._banks)
            out["tenants"] = {
                name: {
                    "version": b.version,
                    "n_anchors": b.n_anchors,
                    "source": b.source,
                    "store_version": b.store_version,
                    "weighted": b.weights is not None,
                }
                for name, b in sorted(named.items())
            }
        manager = getattr(self, "tenant_manager", None)
        if manager is not None:
            out["tenancy"] = manager.summary()
        return out

    # -- live exposition (GET /metrics, /tracez) --------------------------------

    def metrics_snapshots(self) -> List[Tuple[Dict[str, str], Dict[str, Any]]]:
        """The snapshot parts ``telemetry.exposition`` renders for
        ``GET /metrics`` — one unlabeled part for a bare service; the
        router's override fans out per replica with ``replica`` labels.
        A pure registry read (the handler contract: snapshots only).
        The predictor's program registry contributes its derived
        ``xla.*`` rows as an extra part — additive only, so the
        pre-registry scrape body is a strict subset."""
        parts = [({}, self._tel.snapshot())]
        programs = getattr(self.predictor, "programs", None)
        if programs is not None:
            part = programs.metrics_part()
            if part:
                parts.append(({}, part))
        return parts

    def programs_snapshot(self) -> List[Dict[str, Any]]:
        """Newest-compile-first rows of the predictor's program registry
        (the ``GET /programz`` body); empty for a predictor that
        predates the registry."""
        programs = getattr(self.predictor, "programs", None)
        return programs.snapshot() if programs is not None else []

    def programs_roofline(self) -> Optional[Dict[str, Any]]:
        """The aggregate roofline reading for ``GET /programz``."""
        programs = getattr(self.predictor, "programs", None)
        return programs.roofline() if programs is not None else None

    def recent_traces(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Completed request traces, newest first — the ``GET /tracez``
        body.  Empty when tracing is off (the ring never fills)."""
        with self._ring_lock:
            records = list(self._trace_ring)
        records.reverse()
        return records[: int(limit)] if limit else records

    # -- shutdown --------------------------------------------------------------

    def request_drain(self) -> None:
        """Begin graceful shutdown (async-signal-safe: sets a flag, takes
        no lock).  The batcher finishes the micro-batch it already
        pulled, resolves everything still queued with ``"drain"``, and
        exits; :meth:`drain` waits for that."""
        self._draining.set()

    def drain(self, timeout: Optional[float] = 30.0) -> None:
        """Graceful shutdown: drain in-flight work, shed the queue with
        the drain status, stop the batcher.  Idempotent."""
        self.request_drain()
        self._thread.join(timeout)
        if self._thread.is_alive():  # pragma: no cover - defensive
            logger.warning("serve batcher did not exit within %ss", timeout)
        self._closed.set()

    close = drain

    def hard_kill(self) -> None:
        """Die like a SIGKILLed worker: stop pulling immediately, resolve
        NOTHING (no drain statuses, no served/shed counters for work in
        flight), leave the queue as-is.  The chaos path behind the
        ``replica.kill`` fault point — a supervisor must follow up with
        :meth:`take_unresolved` to account the casualties and re-enqueue
        them elsewhere (serving/replica.py, docs/serving.md)."""
        self._killed.set()
        self._draining.set()  # wakes the pull loop; _loop checks killed

    @property
    def killed(self) -> bool:
        return self._killed.is_set()

    def take_unresolved(self, timeout: float = 5.0) -> List[_Request]:
        """After :meth:`hard_kill`: every accepted-but-unresolved request
        (queued + the abandoned in-flight pull).  Waits briefly for the
        batcher to notice the kill; a batcher wedged inside a device op
        cannot be interrupted (threads, like SIGKILLed pods, don't get a
        say) — its requests are still returned, and the killed flag
        keeps it from resolving them later."""
        self._thread.join(timeout)
        with self._cond:
            pending = [r for r in self._inflight if not r.future.done()]
            pending += [r for r in self._queue if not r.future.done()]
            self._queue.clear()
            self._inflight = []
        return pending

    def install_signal_handlers(self) -> List[Tuple[int, Any]]:
        """SIGTERM (the managed-pod preemption notice) and SIGINT begin
        a graceful drain — the same finish-the-in-flight-step contract
        the trainer's preemption handler keeps.  Returns the previous
        handlers for :meth:`restore_signal_handlers`."""
        previous: List[Tuple[int, Any]] = []

        def _handler(signum, frame):  # runs in the main thread
            logger.info("signal %s: draining scoring service", signum)
            self.request_drain()

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                previous.append((sig, signal.signal(sig, _handler)))
            except ValueError:  # not the main thread (tests, embedding)
                pass
        return previous

    @staticmethod
    def restore_signal_handlers(previous: List[Tuple[int, Any]]) -> None:
        for sig, handler in previous:
            signal.signal(sig, handler)

    # -- hot anchor-bank swap --------------------------------------------------

    def swap_bank(
        self,
        anchor_instances: Iterable[Dict],
        version: Optional[int] = None,
        source: str = "manual",
        store_version: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> int:
        """Re-encode a new anchor set and atomically install it.

        Runs in the *caller's* thread (callers wrap it in a background
        thread when they must not block): the encode and — if the padded
        bank shape changed — an AOT re-warm of every stream shape happen
        entirely before the swap, so the batcher never sees a shape it
        has not compiled.  In-flight micro-batches keep the snapshot
        they captured; the next batch picks up the new version.  Returns
        the new version number.

        ``version`` pins the installed snapshot's number instead of the
        default ``current + 1`` — the replica tier uses it so every
        member of a fleet stamps one rollout with ONE number (a
        restarted replica re-installs the fleet's bank at the fleet's
        version; its own counter restarted at 1).

        ``source`` and ``store_version`` are provenance, recorded in
        the snapshot, the manifest, and the ``health_summary()`` bank
        row: "manual" for an operator swap, "rolling_swap" for a fleet
        rollout, "promotion"/"demotion" for the bankops gate
        (docs/anchor_bank.md).

        ``tenant`` installs into a *named* tenant's bank slot instead of
        the default bank (serving/tenancy.py): the encoder and its
        warmed programs are shared, the snapshot is not.  Named swaps
        emit ``bank.<tenant>.swaps``/``bank.<tenant>.version`` and do
        not touch the default tenant's manifest."""
        tenant = str(tenant) if tenant else DEFAULT_TENANT
        instances = list(anchor_instances)
        with self._swap_lock:
            # the swap lock is control-plane-only (serializes concurrent
            # swaps); the request path never takes it, so encoding and
            # warming under it is deliberate, not a batcher stall
            bank, labels, n_anchors = self.predictor.encode_bank(  # lint: disable=MV301
                instances
            )
            weights = _bank_weights(instances, n_anchors)
            shape = tuple(bank.shape)
            if shape not in self._warmed_bank_shapes:
                # new bank geometry = new XLA program per stream shape;
                # compile them here, off the request path, so the swap
                # still never costs a mid-serve compile.  The warmed-set
                # is keyed on geometry, not tenant: N tenants sharing a
                # padded bank shape share the programs, so only the
                # first bank of a given geometry pays the warm.
                logger.info(
                    "bank swap introduces shape %s: re-warming %d "
                    "stream shape(s) before install",
                    shape, len(self._rows_by_length),
                )
                with self._tel.span("serve.bank_warmup"):
                    # same contract as the encode above: control-plane
                    # lock, never contended by the request path
                    self.predictor.warmup_bank_shapes(bank)  # lint: disable=MV301
                self._warmed_bank_shapes.add(shape)
            with self._bank_lock:
                current = (
                    self._bank if tenant == DEFAULT_TENANT
                    else self._banks.get(tenant)
                )
                new = _BankVersion(
                    version=(
                        (current.version + 1 if current is not None else 1)
                        if version is None else int(version)
                    ),
                    array=bank,
                    labels=tuple(labels),
                    n_anchors=n_anchors,
                    source=source,
                    parent_version=(
                        current.version if current is not None else None
                    ),
                    store_version=store_version,
                    tenant=tenant,
                    weights=weights,
                )
                if tenant == DEFAULT_TENANT:
                    self._bank = new
                else:
                    self._banks[tenant] = new
                    self._multi_tenant = True
        self._tel.counter("serve.bank_swaps").inc()
        if tenant == DEFAULT_TENANT:
            self._tel.gauge("serve.bank_version").set(new.version)
        else:
            self._tel.counter(f"bank.{tenant}.swaps").inc()
            self._tel.gauge(f"bank.{tenant}.version").set(new.version)
        self._tel.event(
            "bank_swap", version=new.version, n_anchors=new.n_anchors,
            source=source, store_version=store_version, tenant=tenant,
        )
        if self.admission_cache is not None:
            # eager reclamation; the version-in-key already fences
            # correctness (serving/admission_cache.py)
            self.admission_cache.invalidate(tenant)
        if tenant == DEFAULT_TENANT:
            self._write_manifest()
        logger.info(
            "anchor bank v%d installed for tenant %s: %d anchors%s",
            new.version, tenant, new.n_anchors,
            "" if weights is None else " (weighted)",
        )
        return new.version

    def _write_manifest(self) -> None:
        """Versioned bank manifest beside the telemetry sinks, written
        atomically so an operator (or a restarting supervisor) never
        reads a torn view of which bank is live."""
        if self.manifest_dir is None:
            return
        from ..resilience.io import atomic_write_text

        with self._bank_lock:
            bank = self._bank
        digest = hashlib.sha256(
            "\n".join(bank.labels).encode("utf-8")
        ).hexdigest()
        self.manifest_dir.mkdir(parents=True, exist_ok=True)
        atomic_write_text(
            self.manifest_dir / MANIFEST_NAME,
            json.dumps(
                {
                    "version": bank.version,
                    "n_anchors": bank.n_anchors,
                    "labels_sha256": digest,
                    "labels": list(bank.labels),
                    # provenance: which serving version this replaced,
                    # how it was installed (manual swap vs rolling swap
                    # vs promotion), and the bank-store version id when
                    # it came out of one (docs/anchor_bank.md)
                    "parent_version": bank.parent_version,
                    "source": bank.source,
                    "store_version": bank.store_version,
                    "written_wall": time.time(),
                },
                indent=2,
            ),
        )

    # -- batcher thread --------------------------------------------------------
    #
    # The batcher body lives in serving/dispatch.py: the service thread
    # runs ``self._dispatcher.run()``, and the strategy (bucketed /
    # ragged / continuous) decides how accepted requests become device
    # dispatches.  Admission, deadlines, drain, swap, kill and the
    # shadow tap stay here — impl-independent.

    def _count_truncated(self, live: Sequence[_Request], seqs) -> None:
        """``serve.truncated``: requests whose text tokenized PAST the
        serving cap and was clamped into the largest bucket/budget —
        the serving twin of training's ``data.truncated_sequences``
        (the clamp used to be silent here).  Only sequences sitting at
        the cap pay the re-encode probe; encoders without one (test
        fakes) skip the count."""
        probe = getattr(self.predictor.encoder, "encodes_beyond", None)
        if probe is None or not seqs:
            return
        cap = self.predictor.encoder.max_length
        if self._score_impl in ("ragged", "continuous"):
            cap = min(cap, self._token_budget)
        truncated = sum(
            1
            for request, seq in zip(live, seqs)
            if len(seq) >= cap and probe(request.text, cap)
        )
        if truncated:
            self._tel.counter("serve.truncated").inc(truncated)

    # -- shed / drain resolution ----------------------------------------------

    def _finish_unserved(self, request: _Request, status: str) -> None:
        """Resolve a request that will never be scored.  ``serve.shed``
        counts every load-management resolution (overflow + deadline +
        drain) so ``serve.served + serve.shed + serve.errors`` always
        sums to ``serve.requests``; the per-cause sub-counters are what
        the shed/deadline tests pin exactly."""
        sub = {
            STATUS_SHED: "serve.shed_overflow",
            STATUS_DEADLINE: "serve.shed_deadline",
            STATUS_DRAIN: "serve.shed_drain",
        }[status]
        tel = self._tel
        tel.counter("serve.shed").inc()
        tel.counter(sub).inc()
        self._tenant_count(request.tenant, "shed")
        request.future.resolve({"status": status})
        self._finish_trace(request, status)

    def _finish_trace(self, request: _Request, cause: str) -> None:
        """Complete a request's trace: stamp the resolution, ring the
        record for ``/tracez``, and emit an ``rtrace`` event — sampled
        at ``trace_sample_rate`` for served requests, ALWAYS for
        non-``ok`` outcomes (a shed or dead-lettered request is exactly
        the one worth a post-mortem).  No-op when tracing is off."""
        trace = request.trace
        if trace is None:
            return
        trace.cause = cause
        if trace.resolved is None:
            trace.resolved = time.monotonic()
        record = _trace_record(trace)
        with self._ring_lock:
            self._trace_ring.append(record)
        if cause == STATUS_OK:
            # deterministic credit sampling (batcher-thread-only state:
            # ok resolutions all happen on the batcher)
            self._trace_accum += self.config.trace_sample_rate
            if self._trace_accum < 1.0:
                return
            self._trace_accum -= 1.0
        self._tel.counter("serve.traces_sampled").inc()
        self._tel.event("rtrace", **record)

    def _maybe_sample_hbm(self) -> None:
        """``serve.hbm_in_use_bytes`` / ``serve.hbm_peak_bytes``: the
        device's live HBM view at heartbeat cadence — trainers have
        reported this since PR 3, serving never did.  Backends without
        ``memory_stats`` (CPU) report nothing and cost one probe per
        heartbeat window."""
        if not self.config.hbm_gauges:
            return
        now = time.monotonic()
        if now < self._hbm_next_monotonic:
            return
        self._hbm_next_monotonic = now + max(
            1.0, float(self._tel.heartbeat_every_s)
        )
        from ..utils import profiling

        try:
            stats = profiling.device_memory_stats(self._device)
        except Exception:  # pragma: no cover - a device probe must
            return         # never take the batcher down
        if not stats:
            return
        if "bytes_in_use" in stats:
            self._tel.gauge("serve.hbm_in_use_bytes").set(
                stats["bytes_in_use"]
            )
        if "peak_bytes_in_use" in stats:
            self._tel.gauge("serve.hbm_peak_bytes").set(
                stats["peak_bytes_in_use"]
            )

    def _shed_queue(self, status: str) -> None:
        while True:
            with self._cond:
                if not self._queue:
                    self._tel.gauge("serve.queue_depth").set(0)
                    return
                request = self._queue.popleft()
            self._finish_unserved(request, status)
