"""Content-addressed admission cache — repeats never touch the device.

The paper's corpus is duplicate-heavy by construction (bot-filed,
templated, re-opened reports), so production traffic repeats exact
texts constantly.  An exact repeat is the one request class whose
answer is *provably* bitwise-identical to a previous one: the serving
path hands the raw text straight to ``encoder.encode_many`` (no
normalization pass), so identical raw bytes produce the identical
token sequence, the identical warmed program invocation, and the
identical score rows — provided the anchor bank, dispatch impl, and
encoder precision are also identical.  That is exactly the cache key:

    (tenant, sha256(text), bank_version, score_impl, precision)

``bank_version`` in the key makes a bank swap a *structural*
invalidation — stale entries can never be returned — but
:meth:`AdmissionCache.invalidate` additionally drops a tenant's
entries eagerly at swap time so a swapped tenant's capacity is not
squatted by unreachable payloads.

What is cached is the **score payload only** (``predict`` / ``score``
/ ``anchor`` / ``bank_version``): a hit rebuilds the response dict
with a fresh ``status``/``latency_ms``, so the score fields are
bitwise-identical to what a cold cache would have served while the
bookkeeping fields stay truthful.  A hit counts ``serve.served`` (the
request WAS served — the exact-counter invariant
``served + shed + errors == requests`` must keep summing) plus
``cache.hits``; the per-request token count recorded at store time
feeds ``cache.tokens_saved``, the real-token ledger of device work the
cache avoided.

MV102 applies (``*Cache`` is a selection-only class family): a lookup
is a dict probe under a lock — never an encode, a score, or a sleep.
The ``cache.lookup`` fault point (resilience/faults.py) is the chaos
hook; an armed fault degrades the lookup to a miss (one counted
``cache.errors``) so a broken cache costs a device call, never a
request.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

from ..resilience import faults
from ..telemetry import get_registry

__all__ = ["AdmissionCache", "text_digest"]

# the public score fields a hit replays; everything else (status,
# latency_ms, trace bookkeeping) is rebuilt fresh per response
PAYLOAD_FIELDS = ("predict", "score", "anchor", "bank_version")

_CacheKey = Tuple[str, str, int, str, str]


def text_digest(text: str) -> str:
    """sha256 of the raw utf-8 text — raw, not normalized, because the
    serve path encodes raw text (identical bytes ⇒ identical tokens ⇒
    identical scores; a normalizer here would alias texts the encoder
    distinguishes)."""
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


class AdmissionCache:
    """Bounded LRU of exact-duplicate score payloads, keyed on
    (tenant, text digest, bank version, impl, precision).

    Thread-safe: lookups run on submitter threads, stores on the
    batcher/device threads, invalidations on the control plane — one
    lock guards the ordered map, and all metric emission happens
    outside it."""

    def __init__(self, capacity: int, registry=None) -> None:
        if int(capacity) <= 0:
            raise ValueError(f"cache capacity must be > 0, got {capacity!r}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[_CacheKey, Dict[str, Any]]" = OrderedDict()
        self._tel = registry if registry is not None else get_registry()

    @staticmethod
    def _key(
        tenant: str, text: str, bank_version: int, impl: str, precision: str
    ) -> _CacheKey:
        return (
            str(tenant), text_digest(text), int(bank_version),
            str(impl), str(precision),
        )

    def lookup(
        self,
        tenant: str,
        text: str,
        bank_version: int,
        impl: str,
        precision: str,
    ) -> Optional[Dict[str, Any]]:
        """The score payload for an exact repeat, or ``None`` (miss).
        A hit returns a fresh dict (callers mutate responses); an armed
        ``cache.lookup`` fault degrades to a miss — the request falls
        through to the device instead of failing."""
        try:
            faults.fault_point("cache.lookup")
        except BaseException:
            self._tel.counter("cache.errors").inc()
            return None
        key = self._key(tenant, text, bank_version, impl, precision)
        with self._lock:
            payload = self._entries.get(key)
            if payload is not None:
                self._entries.move_to_end(key)
        if payload is None:
            self._tel.counter("cache.misses").inc()
            return None
        self._tel.counter("cache.hits").inc()
        tokens = int(payload.get("n_tokens", 0))
        if tokens:
            self._tel.counter("cache.tokens_saved").inc(tokens)
        return {
            "predict": dict(payload["predict"]),
            "score": payload["score"],
            "anchor": payload["anchor"],
            "bank_version": payload["bank_version"],
        }

    def store(
        self,
        tenant: str,
        text: str,
        bank_version: int,
        impl: str,
        precision: str,
        response: Dict[str, Any],
        n_tokens: int = 0,
    ) -> None:
        """Remember a served response's score payload.  Only the
        :data:`PAYLOAD_FIELDS` are copied out of ``response``; the
        request's real token count rides along so a later hit can
        credit ``cache.tokens_saved``."""
        payload = {field: response[field] for field in PAYLOAD_FIELDS}
        payload["predict"] = dict(payload["predict"])
        payload["n_tokens"] = int(n_tokens)
        key = self._key(tenant, text, bank_version, impl, precision)
        evicted = 0
        with self._lock:
            self._entries[key] = payload
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                evicted += 1
            size = len(self._entries)
        if evicted:
            self._tel.counter("cache.evictions").inc(evicted)
        self._tel.gauge("cache.size").set(size)

    def invalidate(self, tenant: str) -> int:
        """Drop every entry of one tenant (called at bank-swap time).
        The version-in-key already makes stale entries unreachable;
        this reclaims their LRU capacity eagerly.  Returns the count
        dropped."""
        tenant = str(tenant)
        with self._lock:
            doomed = [k for k in self._entries if k[0] == tenant]
            for key in doomed:
                del self._entries[key]
            size = len(self._entries)
        if doomed:
            self._tel.counter("cache.invalidations").inc(len(doomed))
        self._tel.gauge("cache.size").set(size)
        return len(doomed)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, int]:
        """Point-in-time size/capacity (counters live in telemetry)."""
        with self._lock:
            return {"size": len(self._entries), "capacity": self.capacity}
