"""Cross-host fleet supervision: an HTTP-level balancer over per-host
router fleets.

PR 6's :class:`~memvul_tpu.serving.router.ReplicaRouter` ends its blast
radius at one process: every replica lives in the host that dies with
it.  This module lifts PR 13's coordinator pattern (heartbeat-age stall
detection, exponential-backoff restarts through the shared
:class:`~memvul_tpu.resilience.retry.RetryPolicy`, quarantine with a
machine-readable refusal) from shard workers to whole serving hosts:

* :class:`HostBalancer` — spreads load across hosts (least queued,
  round-robin ties), merges ``/healthz`` / ``/metrics`` / ``/tracez`` /
  ``/programz`` across them, and routes around dead or stalled hosts.
  Owed requests re-enqueue onto surviving hosts with their **original
  absolute deadlines** (a reroute never grants fresh budget), so PR 6's
  per-cause invariant extends across hosts:
  ``Σ served + shed + errors == Σ requests`` summed over every host's
  replicas, live and retired.
* :class:`LocalHost` — an in-process host: wraps a serving target
  (router or bare service) built by a factory, so chaos tests and the
  bench drive whole-host death/stall/restart without sockets.  Its
  submit path carries the ``host.kill`` / ``host.stall`` fault points
  (docs/fault_tolerance.md).
* :class:`ProcessHost` — a subprocess host driven over HTTP
  (``memvul_tpu serve`` on the other end, health/queue sampled from
  ``/healthz``), for the slow multi-host chaos variants and real
  ``serve --hosts`` deployments.

Host enumeration (:func:`enumerate_hosts`) accepts an explicit
``host[:port]`` list, the ``MEMVUL_FLEET_HOSTS`` environment variable,
or — on a TPU pod already initialized through
``parallel/multihost.py`` — a ``MEMVUL_FLEET_HOST_TEMPLATE`` URL
pattern expanded to one host per participating process.

Balancer classes fall under checker MV102's selection-only discipline
(tools/lint_no_blocking_in_handler.py): routing methods read cached
state and pick; every blocking operation (kills, restart backoff,
drains) lives in module-level recovery workers on their own threads.

Metrics (``fleet.*``, docs/observability.md): ``fleet.hosts`` /
``fleet.hosts_alive`` gauges, per-host ``fleet.heartbeat_age_s.<host>``
gauges, and the request-path counters mirroring ``router.*`` one level
up (``fleet.requests`` … ``fleet.quarantined``).
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import logging
import os
import subprocess
import threading
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..parallel import multihost
from ..resilience import faults
from ..telemetry import get_registry
from .client import HTTPClient
from .service import (
    STATUS_DEADLINE,
    STATUS_DRAIN,
    STATUS_ERROR,
    STATUS_OK,
    ScoreFuture,
)

logger = logging.getLogger(__name__)

HOST_STARTING = "starting"
HOST_HEALTHY = "healthy"
HOST_UNHEALTHY = "unhealthy"
HOST_DEAD = "dead"
HOST_QUARANTINED = "quarantined"  # terminal: out of restart budget


class HostDead(RuntimeError):
    """Raised by a host's submit when the host cannot take requests."""


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Host-supervision knobs; the ``fleet_*`` keys of
    ``config.SERVING_DEFAULTS`` are the JSON-facing view."""

    heartbeat_timeout_s: float = 10.0  # stall eviction threshold
    monitor_interval_s: float = 0.25   # health-check cadence
    max_reroutes: int = 2              # re-enqueue attempts per request
    auto_restart: bool = True
    max_restarts: int = 2              # per host, then quarantine
    restart_backoff_s: float = 0.5     # exponential base between attempts


@dataclasses.dataclass
class _FleetRequest:
    """The balancer's record of one client request — it outlives any
    single host, so a host death can re-enqueue it with the original
    absolute deadline."""

    rid: int
    text: str
    deadline_ms: Optional[float]
    deadline_monotonic: Optional[float]
    future: ScoreFuture
    attempts: int = 0


class LocalHost:
    """One in-process serving host.

    ``target_factory()`` builds the host's serving target — a
    :class:`ReplicaRouter` or bare :class:`ScoringService` — and is
    re-invoked on :meth:`restart`, so a restarted host comes back the
    same way a fresh one starts (AOT warmup and all).  The submit path
    carries the ``host.kill``/``host.stall`` chaos points: a kill takes
    every replica down with SIGKILL semantics (nothing resolves; the
    balancer must sweep + re-route), a stall wedges the host alive —
    accepting, no progress, futures parked, heartbeat frozen — so the
    balancer's heartbeat-age detector is the only thing that can catch
    it.
    """

    def __init__(self, index: int, target_factory: Callable[[], Any]) -> None:
        self.index = int(index)
        self.name = f"host-{self.index}"
        self._factory = target_factory
        self.state = HOST_STARTING
        self.accepting = threading.Event()
        self.restart_count = 0
        self._state_lock = threading.Lock()
        self._stalled_at: Optional[float] = None
        # futures accepted while stalled: parked, never resolved by the
        # target (they never reach it) — the balancer re-routes from its
        # own records once the stall detector fires
        self._wedged: List[ScoreFuture] = []
        self.target = target_factory()
        self.state = HOST_HEALTHY
        self.accepting.set()

    # -- request path ----------------------------------------------------------

    def submit(
        self,
        text: str,
        deadline_ms: Optional[float] = None,
    ) -> ScoreFuture:
        if self.state in (HOST_DEAD, HOST_QUARANTINED):
            raise HostDead(f"{self.name} is {self.state}")
        try:
            faults.fault_point(f"host.kill.{self.name}")
            faults.fault_point("host.kill")
        except Exception as e:
            self.kill(reason=f"injected: {e}")
            raise HostDead(f"{self.name} killed by fault injection") from e
        try:
            faults.fault_point(f"host.stall.{self.name}")
            faults.fault_point("host.stall")
        except Exception:
            self._stall()
        if self._stalled_at is not None:
            future = ScoreFuture()
            self._wedged.append(future)
            return future
        return self.target.submit(text, deadline_ms=deadline_ms)

    def _stall(self) -> None:
        """Wedge: stay alive and accepting, make no progress.  The
        heartbeat freezes here, so its age grows until the balancer's
        stall detector trips."""
        if self._stalled_at is None:
            self._stalled_at = time.monotonic()
            logger.warning("%s stalled (injected)", self.name)

    @property
    def alive(self) -> bool:
        return self.state in (HOST_STARTING, HOST_HEALTHY, HOST_UNHEALTHY)

    @property
    def queue_depth(self) -> int:
        if not self.alive:
            return 0
        return self.target.queue_depth

    @property
    def default_deadline_ms(self) -> float:
        return self.target.default_deadline_ms

    def heartbeat_age_s(self) -> float:
        """The host-level stall clock: a stalled host's age grows from
        the stall instant; a live router host reports its freshest
        replica (one live replica means the host process breathes)."""
        if self._stalled_at is not None:
            return max(0.0, time.monotonic() - self._stalled_at)
        replicas = getattr(self.target, "replicas", None)
        if replicas:
            return min(r.heartbeat_age_s() for r in replicas)
        return 0.0

    def check_health(self, heartbeat_timeout_s: float) -> bool:
        """Monitor-loop probe: False once the host is dead or its
        heartbeat age crosses the stall threshold."""
        if not self.alive:
            return False
        return self.heartbeat_age_s() <= heartbeat_timeout_s

    # -- lifecycle -------------------------------------------------------------

    def kill(self, reason: str = "killed") -> None:
        """Whole-host SIGKILL semantics: every replica dies mid-flight
        and their unresolved requests are swept into ``serve.errors`` —
        the per-replica counters stay summable, so the cross-host
        invariant still balances after the host is gone."""
        with self._state_lock:
            if self.state in (HOST_DEAD, HOST_QUARANTINED):
                return
            self.state = HOST_DEAD
        self.accepting.clear()
        replicas = getattr(self.target, "replicas", None)
        if replicas is not None:
            for replica in list(replicas):
                replica.kill(reason=f"{self.name}: {reason}")
                replica.sweep_unresolved()
        else:
            self.target.hard_kill()
            self.target.take_unresolved()
        logger.error("%s dead: %s", self.name, reason)

    def restart(self) -> None:
        """Rebuild the target through the factory — the same cold-start
        path as construction.  Raises whatever the factory raises (the
        balancer's RetryPolicy owns the retries)."""
        self.restart_count += 1
        self._stalled_at = None
        self._wedged = []
        self.target = self._factory()
        with self._state_lock:
            self.state = HOST_HEALTHY
        self.accepting.set()
        logger.info("%s restarted (attempt %d)", self.name, self.restart_count)

    def quarantine(self) -> None:
        with self._state_lock:
            self.state = HOST_QUARANTINED
        self.accepting.clear()

    def request_drain(self) -> None:
        if self.alive:
            self.target.request_drain()

    def drain(self, timeout: Optional[float] = 30.0) -> None:
        if self.alive:
            self.target.drain(timeout=timeout)

    # -- merged-endpoint fan-in ------------------------------------------------

    def health_summary(self) -> Dict[str, Any]:
        row: Dict[str, Any] = {
            "host": self.name,
            "state": self.state,
            "restarts": self.restart_count,
            "heartbeat_age_s": round(self.heartbeat_age_s(), 3),
        }
        if self.alive and self._stalled_at is None:
            row["target"] = self.target.health_summary()
        return row

    def metrics_snapshots(self) -> List:
        """The target's snapshot parts, each stamped with this host's
        label — a fleet scrape separates hosts the way a router scrape
        separates replicas."""
        if not self.alive:
            return []
        parts = []
        for labels, snap in self.target.metrics_snapshots():
            parts.append(({"host": self.name, **dict(labels)}, snap))
        return parts

    def recent_traces(self) -> List[Dict[str, Any]]:
        if not self.alive or self._stalled_at is not None:
            return []
        return self.target.recent_traces()

    def programs_snapshot(self) -> List[Dict[str, Any]]:
        if not self.alive or self._stalled_at is not None:
            return []
        rows = []
        for row in self.target.programs_snapshot():
            row = dict(row)
            row["host"] = self.name
            rows.append(row)
        return rows

    def members(self) -> List:
        """Every replica this host has ever admitted (live + retired) —
        the unit of the cross-host counter invariant."""
        replicas = list(getattr(self.target, "replicas", ()) or ())
        replicas.extend(getattr(self.target, "retired_replicas", ()) or ())
        return replicas


class ProcessHost:
    """A serving host in its own process, driven over HTTP.

    ``argv`` launches ``memvul_tpu serve`` (or any program printing the
    same one-line ``{"serving": url, ...}`` JSON banner on stdout); the
    health/queue view is sampled from ``/healthz`` by
    :meth:`check_health` (monitor cadence), so the balancer's routing
    methods read only the cached sample — never a socket.  Used by the
    slow multi-host chaos tests (a real SIGKILL of a real process) and
    by ``serve --hosts`` against already-running hosts (``url=``)."""

    def __init__(
        self,
        index: int,
        argv: Optional[Sequence[str]] = None,
        url: Optional[str] = None,
        startup_timeout_s: float = 120.0,
        request_timeout_s: float = 60.0,
    ) -> None:
        if (argv is None) == (url is None):
            raise ValueError("ProcessHost needs exactly one of argv= or url=")
        self.index = int(index)
        self.name = f"host-{self.index}"
        self.argv = list(argv) if argv is not None else None
        self.proc: Optional[subprocess.Popen] = None
        self.state = HOST_STARTING
        self.accepting = threading.Event()
        self.restart_count = 0
        self._state_lock = threading.Lock()
        self._request_timeout_s = request_timeout_s
        self._startup_timeout_s = startup_timeout_s
        self._last_progress = time.monotonic()
        self._cached_health: Dict[str, Any] = {}
        if url is not None:
            self.base_url = url.rstrip("/")
            self.client = HTTPClient(self.base_url, timeout_s=request_timeout_s)
            self.state = HOST_HEALTHY
            self.accepting.set()
        else:
            self._launch()

    def _launch(self) -> None:
        assert self.argv is not None
        self.proc = subprocess.Popen(
            self.argv,
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
            start_new_session=True,  # one killpg takes the whole host
        )
        deadline = time.monotonic() + self._startup_timeout_s
        banner = None
        assert self.proc.stdout is not None
        while time.monotonic() < deadline:
            line = self.proc.stdout.readline()
            if not line:
                break
            try:
                payload = json.loads(line)
            except ValueError:
                continue
            if "serving" in payload:
                banner = payload
                break
        if banner is None:
            raise HostDead(f"{self.name} never printed its serving banner")
        self.base_url = str(banner["serving"]).rstrip("/")
        self.client = HTTPClient(self.base_url, timeout_s=self._request_timeout_s)
        self._last_progress = time.monotonic()
        with self._state_lock:
            self.state = HOST_HEALTHY
        self.accepting.set()

    # -- request path ----------------------------------------------------------

    def submit(
        self, text: str, deadline_ms: Optional[float] = None
    ) -> ScoreFuture:
        if self.state in (HOST_DEAD, HOST_QUARANTINED):
            raise HostDead(f"{self.name} is {self.state}")
        future = ScoreFuture()

        def relay() -> None:
            try:
                future.resolve(self.client.score(text, deadline_ms=deadline_ms))
            except Exception as e:  # noqa: BLE001 - connection refusals
                # resolve as an error; the balancer re-routes on it
                future.resolve({
                    "status": STATUS_ERROR,
                    "reason": f"host_unreachable: {type(e).__name__}: {e}",
                })

        threading.Thread(
            target=relay, name=f"memvul-{self.name}-relay", daemon=True
        ).start()
        return future

    @property
    def alive(self) -> bool:
        if self.proc is not None and self.proc.poll() is not None:
            return False
        return self.state in (HOST_STARTING, HOST_HEALTHY, HOST_UNHEALTHY)

    @property
    def queue_depth(self) -> int:
        return int(self._cached_health.get("queue_depth", 0) or 0)

    @property
    def default_deadline_ms(self) -> float:
        return float(self._cached_health.get("default_deadline_ms", 0.0) or 0.0)

    def heartbeat_age_s(self) -> float:
        return max(0.0, time.monotonic() - self._last_progress)

    def check_health(self, heartbeat_timeout_s: float) -> bool:
        """Poll ``/healthz`` (monitor thread only) and refresh the
        cached sample the routing methods read.  A reachable, responsive
        host is progress; a dead socket or wedged server lets the
        heartbeat age grow until the stall threshold trips."""
        if not self.alive:
            return False
        try:
            body = self.client._request(
                urllib.request.Request(self.base_url + "/healthz", method="GET"),
                timeout_s=min(heartbeat_timeout_s, 5.0),
            )
        except Exception:  # noqa: BLE001 - connection refused == no progress
            body = None
        if body and "status" in body and body.get("status") != "error":
            self._cached_health = body
            self._last_progress = time.monotonic()
        return self.heartbeat_age_s() <= heartbeat_timeout_s

    # -- lifecycle -------------------------------------------------------------

    def kill(self, reason: str = "killed") -> None:
        with self._state_lock:
            if self.state in (HOST_DEAD, HOST_QUARANTINED):
                return
            self.state = HOST_DEAD
        self.accepting.clear()
        if self.proc is not None and self.proc.poll() is None:
            from ..distributed.coordinator import _kill_process_group

            _kill_process_group(self.proc)
        logger.error("%s dead: %s", self.name, reason)

    def restart(self) -> None:
        if self.argv is None:
            raise HostDead(f"{self.name} is attach-only (url=): cannot relaunch")
        self.restart_count += 1
        self._launch()
        logger.info("%s restarted (attempt %d)", self.name, self.restart_count)

    def quarantine(self) -> None:
        with self._state_lock:
            self.state = HOST_QUARANTINED
        self.accepting.clear()

    def request_drain(self) -> None:
        return None  # the host process owns its own drain (SIGTERM path)

    def drain(self, timeout: Optional[float] = 30.0) -> None:
        return None

    # -- merged-endpoint fan-in ------------------------------------------------

    def health_summary(self) -> Dict[str, Any]:
        row: Dict[str, Any] = {
            "host": self.name,
            "state": self.state,
            "restarts": self.restart_count,
            "heartbeat_age_s": round(self.heartbeat_age_s(), 3),
            "url": getattr(self, "base_url", None),
        }
        if self._cached_health:
            row["target"] = self._cached_health
        return row

    def metrics_snapshots(self) -> List:
        """A coarse host-label part from the cached ``/healthz`` sample
        (queue depth + liveness) — the full per-replica parts live on
        the host's own ``/metrics``, which a scraper reaches directly;
        the merged view answers "is the fleet moving", not "what is
        replica 3 doing"."""
        return [(
            {"host": self.name},
            {"counters": {}, "gauges": {
                "host.up": 1.0 if self.alive else 0.0,
                "host.queue_depth": float(self.queue_depth),
            }, "histograms": {}},
        )]

    def recent_traces(self) -> List[Dict[str, Any]]:
        return []

    def programs_snapshot(self) -> List[Dict[str, Any]]:
        return []

    def members(self) -> List:
        return []


class HostBalancer:
    """Load-balancing dispatch over a fleet of hosts.

    The public surface mirrors :class:`ScoringService` — ``submit`` /
    ``queue_depth`` / ``draining`` / ``health_summary`` /
    ``metrics_snapshots`` / ``recent_traces`` / ``programs_snapshot`` /
    ``request_drain`` / ``drain`` — so serving/frontend.py serves a
    whole fleet through the same handlers that serve one replica.
    """

    def __init__(
        self,
        hosts: Sequence,
        config: Optional[FleetConfig] = None,
        retry_policy=None,
        registry=None,
    ) -> None:
        if not hosts:
            raise ValueError("a balancer needs at least one host")
        self.hosts = list(hosts)
        self.config = config or FleetConfig()
        self.retry_policy = retry_policy
        self._tel = registry if registry is not None else get_registry()
        self._lock = threading.Lock()
        self._rid = itertools.count(1)
        self._rr = itertools.count()
        self._outstanding: Dict[str, Dict[int, _FleetRequest]] = {
            h.name: {} for h in self.hosts
        }
        self._draining = threading.Event()
        self._recovering: Dict[str, bool] = {}
        self._default_deadline_ms = self.hosts[0].default_deadline_ms
        self._stop = threading.Event()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="memvul-fleet-monitor", daemon=True
        )
        self._monitor.start()
        self._tel.gauge("fleet.hosts").set(len(self.hosts))
        self._tel.gauge("fleet.hosts_alive").set(
            sum(1 for h in self.hosts if h.alive)
        )
        self._tel.event("fleet_start", hosts=len(self.hosts))

    # -- ScoringService-compatible surface ------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    @property
    def queue_depth(self) -> int:
        return sum(h.queue_depth for h in self.hosts if h.alive)

    @property
    def default_deadline_ms(self) -> float:
        return self._default_deadline_ms

    def health_summary(self) -> Dict[str, Any]:
        """The merged fleet ``/healthz``: per-host rows plus the
        roll-up an external probe routes on — ``ok`` / ``degraded`` /
        ``unavailable`` with the quarantined hosts named, so a refusal
        is explicable from the probe body alone."""
        draining = self._draining.is_set()
        members = [h.health_summary() for h in self.hosts]
        alive = sum(1 for h in self.hosts if h.alive)
        quarantined = [
            h.name for h in self.hosts if h.state == HOST_QUARANTINED
        ]
        if draining:
            status = "draining"
        elif alive == len(self.hosts):
            status = "ok"
        elif alive > 0:
            status = "degraded"
        else:
            status = "unavailable"
        return {
            "status": status,
            "draining": draining,
            "queue_depth": self.queue_depth,
            "hosts": {
                "total": len(self.hosts),
                "alive": alive,
                "quarantined": quarantined,
                "members": members,
            },
        }

    def metrics_snapshots(self) -> List:
        """Fleet ``/metrics``: the balancer's own registry (``fleet.*``)
        unlabeled, plus every live host's parts under its ``host``
        label — snapshot reads only (the balancer lint)."""
        parts: List = [({}, self._tel.snapshot())]
        for host in self.hosts:
            parts.extend(host.metrics_snapshots())
        return parts

    def recent_traces(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        records: List[Dict[str, Any]] = []
        for host in self.hosts:
            records.extend(host.recent_traces())
        records.sort(
            key=lambda r: -(r.get("waypoints", {}).get("resolved") or 0.0)
        )
        return records[: int(limit)] if limit else records

    def programs_snapshot(self) -> List[Dict[str, Any]]:
        rows: List[Dict[str, Any]] = []
        for host in self.hosts:
            rows.extend(host.programs_snapshot())
        rows.sort(key=lambda r: -(r.get("compiled_wall") or 0.0))
        return rows

    def members(self) -> List:
        """Every replica across every host, live and retired — what the
        cross-host invariant sums over (loadgen.fleet_snapshot)."""
        out: List = []
        for host in self.hosts:
            out.extend(host.members())
        return out

    # -- dispatch --------------------------------------------------------------

    def submit(
        self, text: str, deadline_ms: Optional[float] = None
    ) -> ScoreFuture:
        """Route one request to the least-loaded live host and relay its
        response.  The returned future ALWAYS resolves — via the host,
        via a re-route after a host death, or via the balancer's own
        deadline/drain/exhaustion terminal statuses."""
        future = ScoreFuture()
        self._tel.counter("fleet.requests").inc()
        if self._draining.is_set():
            self._tel.counter("fleet.shed_drain").inc()
            future.resolve({"status": STATUS_DRAIN})
            return future
        now = time.monotonic()
        effective_ms = (
            self._default_deadline_ms if deadline_ms is None else deadline_ms
        )
        request = _FleetRequest(
            rid=next(self._rid),
            text=text,
            deadline_ms=deadline_ms,
            deadline_monotonic=(
                now + effective_ms / 1000.0 if effective_ms > 0 else None
            ),
            future=future,
        )
        self._route(request)
        return future

    def _pick(self, request: _FleetRequest):
        """The host-routing decision: among alive, accepting hosts, the
        smallest combined load (host queue + this balancer's in-flight
        charges), round-robin on ties.  Selection only — nothing here
        may block, poll, or score (the balancer lint)."""
        candidates = [
            h for h in self.hosts if h.alive and h.accepting.is_set()
        ]
        if not candidates:
            return None
        with self._lock:
            charged = {
                h.name: len(self._outstanding.get(h.name, {}))
                for h in candidates
            }
        offset = next(self._rr)
        return min(
            enumerate(candidates),
            key=lambda ih: (
                ih[1].queue_depth + charged[ih[1].name],
                (ih[0] + offset) % len(candidates),
            ),
        )[1]

    def _route(self, request: _FleetRequest) -> None:
        host = self._pick(request)
        if host is None:
            self._tel.counter("fleet.unroutable").inc()
            request.future.resolve(self._refusal("no live host to route to"))
            return
        with self._lock:
            self._outstanding.setdefault(host.name, {})[request.rid] = request
        try:
            inner = host.submit(
                request.text, deadline_ms=self._remaining_ms(request)
            )
        except HostDead:
            with self._lock:
                self._outstanding.get(host.name, {}).pop(request.rid, None)
            self._reroute(request, reason=f"{host.name} died at submit")
            return
        self._tel.counter("fleet.routed").inc()
        inner.add_done_callback(
            lambda response, request=request, host=host: self._on_inner(
                request, host, response
            )
        )

    def _remaining_ms(self, request: _FleetRequest) -> Optional[float]:
        """Deadline budget left for a (re-)submission: the original
        absolute deadline, never a fresh window (the router's
        ``_remaining_ms`` discipline, one level up)."""
        if request.deadline_monotonic is None:
            return request.deadline_ms if request.deadline_ms is not None else None
        return max(
            1e-3, (request.deadline_monotonic - time.monotonic()) * 1000.0
        )

    def _on_inner(self, request: _FleetRequest, host, response: Dict[str, Any]) -> None:
        with self._lock:
            self._outstanding.get(host.name, {}).pop(request.rid, None)
        status = response.get("status")
        reason = str(response.get("reason", ""))
        if status == STATUS_DRAIN and not self._draining.is_set():
            # the host is restarting/draining, the fleet is not — the
            # client keeps its budget on a survivor
            self._reroute(request, reason=f"{host.name} drained")
            return
        if status == STATUS_ERROR and reason.startswith("host_unreachable"):
            self._reroute(request, reason=f"{host.name} unreachable")
            return
        out = dict(response)
        out["host"] = host.name
        if request.attempts:
            out["host_reroutes"] = request.attempts
        if request.future.resolve(out) and status == STATUS_OK:
            self._tel.counter("fleet.served").inc()

    def _reroute(self, request: _FleetRequest, reason: str) -> None:
        """Re-enqueue a request its host never answered.  Terminal
        statuses when re-routing is pointless: past its original
        deadline → ``"deadline"``; out of attempts / fleet draining →
        a machine-readable refusal.  Counted per cause."""
        if request.future.done():
            return
        if (
            request.deadline_monotonic is not None
            and time.monotonic() > request.deadline_monotonic
        ):
            self._tel.counter("fleet.reroute_deadline").inc()
            request.future.resolve({
                "status": STATUS_DEADLINE,
                "reason": f"deadline expired after {reason}",
            })
            return
        if request.attempts >= self.config.max_reroutes or self._draining.is_set():
            self._tel.counter("fleet.reroute_exhausted").inc()
            request.future.resolve(
                self._refusal(f"reroutes exhausted after {reason}")
            )
            return
        request.attempts += 1
        self._tel.counter("fleet.reroutes").inc()
        self._route(request)

    def _refusal(self, reason: str) -> Dict[str, Any]:
        """The machine-readable refusal body (PR 13's quarantine
        payload, lifted to serving): which hosts are quarantined, which
        are alive, why this request could not be placed."""
        return {
            "status": STATUS_ERROR,
            "reason": reason,
            "refusal": {
                "error": "fleet_unavailable",
                "hosts_alive": sum(1 for h in self.hosts if h.alive),
                "hosts_total": len(self.hosts),
                "quarantined": [
                    h.name for h in self.hosts
                    if h.state == HOST_QUARANTINED
                ],
            },
        }

    # -- supervision -----------------------------------------------------------

    def _monitor_loop(self) -> None:
        interval = max(0.05, self.config.monitor_interval_s)
        while not self._stop.wait(interval):
            if self._draining.is_set():
                return
            alive = 0
            for host in self.hosts:
                if host.state == HOST_QUARANTINED:
                    continue
                self._tel.gauge(
                    f"fleet.heartbeat_age_s.{host.name}"
                ).set(round(host.heartbeat_age_s(), 3))
                healthy = host.check_health(self.config.heartbeat_timeout_s)
                if host.alive:
                    alive += 1
                if not healthy:
                    self._spawn_recovery(host)
            self._tel.gauge("fleet.hosts_alive").set(alive)

    def _spawn_recovery(self, host) -> None:
        """One recovery incident per host at a time — the kill/reclaim/
        backoff/restart sequence blocks, so it runs on its own thread
        (the router's ``_recover`` split, one level up)."""
        with self._lock:
            if self._recovering.get(host.name):
                return
            self._recovering[host.name] = True
        threading.Thread(
            target=_recover_host, args=(self, host),
            name=f"memvul-fleet-recover-{host.name}", daemon=True,
        ).start()

    def _reclaim(self, host, reason: str) -> None:
        """Pull every request charged to a lost host and re-enqueue it
        onto survivors — original absolute deadlines intact."""
        with self._lock:
            taken = self._outstanding.get(host.name, {})
            requests, taken_ids = list(taken.values()), list(taken.keys())
            for rid in taken_ids:
                taken.pop(rid, None)
        for request in requests:
            if not request.future.done():
                self._reroute(request, reason=reason)

    # -- shutdown --------------------------------------------------------------

    def request_drain(self) -> None:
        self._draining.set()
        for host in self.hosts:
            if host.alive:
                host.request_drain()
        self._tel.event("fleet_drain_requested")

    def drain(self, timeout: Optional[float] = 30.0) -> None:
        self._draining.set()
        self._stop.set()
        for host in self.hosts:
            if host.alive:
                host.drain(timeout=timeout)
        self._reap_all("fleet drained")
        self._tel.event("fleet_drained")

    def _reap_all(self, reason: str) -> None:
        with self._lock:
            requests = [
                r for owed in self._outstanding.values() for r in owed.values()
            ]
            for owed in self._outstanding.values():
                owed.clear()
        for request in requests:
            if not request.future.done():
                self._tel.counter("fleet.reroute_exhausted").inc()
                request.future.resolve(self._refusal(reason))


def _recover_host(balancer: HostBalancer, host) -> None:
    """Per-incident recovery worker: confirm the kill (sweeping every
    replica's unresolved requests into the counters), re-enqueue owed
    requests onto survivors, then buy the host back through the shared
    RetryPolicy's exponential backoff — or quarantine it with a
    machine-readable event once the restart budget is spent."""
    tel = balancer._tel
    cfg = balancer.config
    try:
        # the host may already be dead (a fault on its own submit path
        # killed it before the monitor noticed) — the incident still
        # counts exactly once: the _recovering guard serializes it
        host.kill(reason="fleet monitor: dead or stalled")
        tel.counter("fleet.host_deaths").inc()
        tel.event("fleet_host_dead", host=host.name)
        recorder = getattr(balancer, "incident_recorder", None)
        if recorder is not None:  # non-blocking bounded-queue put
            recorder.trigger("host_dead", {"host": host.name})
        balancer._reclaim(host, reason=f"{host.name} lost")
        if (
            not cfg.auto_restart
            or host.restart_count >= cfg.max_restarts
        ):
            _quarantine_host(balancer, host, "restart budget exhausted")
            return
        try:
            if balancer.retry_policy is not None:
                balancer.retry_policy.call(
                    host.restart, description=f"restart {host.name}"
                )
            else:
                host.restart()
        except Exception as e:  # noqa: BLE001 - a host that cannot come
            # back is quarantined, never retried forever
            tel.counter("fleet.restart_failures").inc()
            _quarantine_host(
                balancer, host, f"restart failed: {type(e).__name__}: {e}"
            )
            return
        tel.counter("fleet.host_restarts").inc()
        tel.event("fleet_host_restarted", host=host.name)
    finally:
        with balancer._lock:
            balancer._recovering[host.name] = False


def _quarantine_host(balancer: HostBalancer, host, reason: str) -> None:
    host.quarantine()
    balancer._tel.counter("fleet.quarantined").inc()
    balancer._tel.event(
        "fleet_host_quarantined",
        host=host.name, restarts=host.restart_count, reason=reason[:200],
    )
    logger.error("%s quarantined: %s", host.name, reason)
    recorder = getattr(balancer, "incident_recorder", None)
    if recorder is not None:  # non-blocking bounded-queue put
        recorder.trigger(
            "host_quarantined", {"host": host.name, "reason": reason[:200]}
        )


def enumerate_hosts(
    spec: Optional[str] = None, default_port: int = 8341
) -> List[str]:
    """Resolve the fleet's host URLs.

    Precedence: an explicit ``spec`` (comma-separated ``host[:port]``
    or full ``http://`` URLs — the ``serve --hosts`` argument) beats the
    ``MEMVUL_FLEET_HOSTS`` environment variable, which beats pod-derived
    enumeration.  The pod path needs both ``MEMVUL_FLEET_HOST_TEMPLATE``
    (a ``{i}``-indexed URL pattern, the stateful-set naming idiom, e.g.
    ``http://serve-{i}.svc:8341``) and an initialized
    ``parallel/multihost.py`` runtime — the template expands to one
    serving host per participating process
    (``multihost.process_count()``).  An uninitialized runtime is never
    probed (that would initialize the jax backend as a side effect), so
    with no spec, no env list, and no joined pod this returns ``[]``.
    """
    raw = spec if spec else os.environ.get("MEMVUL_FLEET_HOSTS", "")
    if not raw:
        template = os.environ.get("MEMVUL_FLEET_HOST_TEMPLATE", "")
        if template and multihost._initialized:
            raw = ",".join(
                template.replace("{i}", str(i))
                for i in range(multihost.process_count())
            )
    out: List[str] = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if "://" not in part:
            if ":" not in part:
                part = f"{part}:{default_port}"
            part = f"http://{part}"
        out.append(part.rstrip("/"))
    return out
