"""One serving replica: a :class:`ScoringService` plus its own health.

The scale-out tier (docs/serving.md, "Replica tier") runs N scoring
services — one per assigned local device — behind a
:class:`~memvul_tpu.serving.router.ReplicaRouter`.  A replica owns
everything that makes one service individually observable and
individually replaceable:

* **its own telemetry registry** — each replica's counters, events,
  and ``HEARTBEAT.json`` land in ``<run_dir>/replica-<i>/`` (the PR 3
  sinks, one set per replica), so the router's health checks and the
  fleet-wide counter invariant read per-replica state instead of a
  process-wide blur;
* **a service factory** — a zero-argument-but-registry closure that
  rebuilds the service (predictor placement, anchor encode, AOT
  warmup) so a failed replica can be *restarted*, not just evicted.
  The registry survives restarts: counters accumulate across a
  replica's lives, which is what keeps the fleet-wide
  ``served + shed + errors == requests`` invariant exact through a
  death;
* **health self-diagnosis** — :meth:`check_health` classifies the
  replica from its registry's liveness clock (the batcher ticks it
  even when idle) and counter deltas: a dead batcher thread is
  ``DEAD``, a stalled heartbeat or a run of dead-lettered batches
  with no successes is ``UNHEALTHY``, anything else ``HEALTHY``;
* **the ``replica.kill`` chaos point** — fired on the submit path, it
  hard-kills this replica exactly the way a SIGKILLed worker process
  dies: the service stops resolving anything, queued and in-flight
  requests are left dangling, and only the supervisor's sweep
  (:meth:`sweep_unresolved`) accounts them (``serve.errors`` +
  ``serve.errors_lost``) so the invariant still sums.

The heavy operations (restart's re-encode/warmup, bank installs) run
on whatever thread calls them — the router deliberately calls them
from its monitor/control paths, never from request dispatch
(tools/lint_no_blocking_in_handler.py enforces that split).
"""

from __future__ import annotations

import logging
import threading
from pathlib import Path
from typing import Any, Callable, Dict, Iterable, List, Optional, Union

from ..resilience import faults
from ..telemetry.registry import TelemetryRegistry
from .service import ScoreFuture, ScoringService, _Request

logger = logging.getLogger(__name__)

# replica lifecycle states (strings so they serialize straight into
# telemetry events and the /healthz body)
REPLICA_STARTING = "starting"
REPLICA_HEALTHY = "healthy"
REPLICA_UNHEALTHY = "unhealthy"
REPLICA_SWAPPING = "swapping"   # readmission-gated during a rolling swap
REPLICA_DEAD = "dead"
REPLICA_RETIRED = "retired"     # scale-down terminal: drained + closed,
                                # counters kept for the fleet invariant


class ReplicaDead(RuntimeError):
    """Raised by :meth:`Replica.submit` when the replica cannot accept —
    the router's signal to pick another queue immediately."""


class Replica:
    """One scoring service + its registry, factory, and health state.

    ``service_factory(registry)`` must return a started
    :class:`ScoringService` reporting into ``registry``; it is called at
    construction and again on every restart.
    """

    def __init__(
        self,
        index: int,
        service_factory: Callable[[TelemetryRegistry], ScoringService],
        run_dir: Optional[Union[str, Path]] = None,
        device: Any = None,
        telemetry_enabled: bool = True,
        heartbeat_every_s: float = 5.0,
    ) -> None:
        self.index = int(index)
        self.name = f"replica-{self.index}"
        self.device = device
        self._factory = service_factory
        self.restart_count = 0
        self.state = REPLICA_STARTING
        self._state_lock = threading.Lock()
        self._restart_lock = threading.Lock()
        # router readmission gate: cleared while a rolling swap drains
        # this replica; the router routes only to set+healthy replicas
        self.accepting = threading.Event()
        # counter snapshots for the consecutive-batch-error streak
        self._last_dead_letters = 0
        self._last_batches = 0
        self._err_streak = 0
        self.registry = TelemetryRegistry(
            run_dir=Path(run_dir) / self.name if run_dir else None,
            enabled=telemetry_enabled,
            heartbeat_every_s=heartbeat_every_s,
        )
        # shadow tap (bankops/shadow.py): kept here so a restart's fresh
        # service re-attaches it — a replica death must not silently end
        # a shadow evaluation
        self._shadow_tap = None
        self.service = service_factory(self.registry)
        self.state = REPLICA_HEALTHY
        self.accepting.set()
        self.registry.event("replica_start", replica=self.name)

    # -- request path ----------------------------------------------------------

    def submit(
        self,
        text: str,
        deadline_ms: Optional[float] = None,
        trace_id: Optional[str] = None,
        hops: int = 0,
        tenant: Optional[str] = None,
    ) -> ScoreFuture:
        """Enqueue on this replica's service.  Raises :class:`ReplicaDead`
        when the replica is dead — including the moment the
        ``replica.kill`` chaos point fires, which hard-kills this
        replica first so the caller re-routes against a genuinely dead
        worker, not a healthy one wearing a costume.

        ``trace_id``/``hops`` carry a router-assigned request journey
        across re-routes (serving/service.py tracing)."""
        if self.state == REPLICA_DEAD:
            raise ReplicaDead(f"{self.name} is dead")
        try:
            faults.fault_point(f"replica.kill.{self.name}")
            faults.fault_point("replica.kill")
        except Exception as e:
            self.kill(reason=f"injected: {e}")
            raise ReplicaDead(f"{self.name} killed by fault injection") from e
        return self.service.submit(
            text, deadline_ms=deadline_ms, trace_id=trace_id, hops=hops,
            tenant=tenant,
        )

    @property
    def queue_depth(self) -> int:
        if self.state == REPLICA_DEAD:
            return 0
        return self.service.queue_depth

    @property
    def bank_version(self) -> int:
        return self.service.bank_version

    def heartbeat_age_s(self) -> float:
        return self.registry.heartbeat_age_s()

    # -- shadow tap ------------------------------------------------------------

    def set_shadow_tap(self, tap) -> None:
        self._shadow_tap = tap
        self.service.set_shadow_tap(tap)

    def clear_shadow_tap(self) -> None:
        self._shadow_tap = None
        self.service.clear_shadow_tap()

    # -- death / sweep ---------------------------------------------------------

    def kill(self, reason: str = "killed") -> None:
        """Hard-kill (SIGKILL semantics): the service stops resolving,
        nothing is drained, the state flips to DEAD.  Idempotent."""
        with self._state_lock:
            if self.state == REPLICA_DEAD:
                return
            self.state = REPLICA_DEAD
        self.accepting.clear()
        self.service.hard_kill()
        self.registry.counter("replica.kills").inc()
        self.registry.event("replica_killed", replica=self.name, reason=reason)
        logger.warning("%s hard-killed: %s", self.name, reason)

    def sweep_unresolved(self) -> List[_Request]:
        """Collect the killed service's dangling requests and account
        them: each was counted into ``serve.requests`` at submit but
        will never resolve here, so the sweep books them as
        ``serve.errors`` (+ ``serve.errors_lost`` for the cause split)
        — the fleet-wide counter invariant survives the death.  Returns
        the swept service-level requests (the router re-enqueues its
        own routed-request records, not these)."""
        pending = self.service.take_unresolved()
        if pending:
            self.registry.counter("serve.errors").inc(len(pending))
            self.registry.counter("serve.errors_lost").inc(len(pending))
            for request in pending:
                # per-tenant error ledger (no-op single-tenant): the
                # per-tenant counter sums must survive a death too
                self.service._tenant_count(request.tenant, "errors")
            self.registry.event(
                "replica_swept", replica=self.name, lost=len(pending)
            )
        return pending

    # -- health ----------------------------------------------------------------

    def check_health(
        self, heartbeat_timeout_s: float, max_batch_errors: int
    ) -> str:
        """Classify this replica from its own telemetry (the router's
        monitor calls this every interval):

        * batcher thread gone without a drain → ``DEAD``;
        * heartbeat age over ``heartbeat_timeout_s`` (the batcher ticks
          even when idle, so age only grows when it is wedged) →
          ``UNHEALTHY``;
        * ≥ ``max_batch_errors`` dead-lettered batches since the last
          successful one → ``UNHEALTHY``;
        * otherwise (and on recovery of the transient causes) →
          ``HEALTHY``.
        """
        with self._state_lock:
            if self.state == REPLICA_DEAD:
                return self.state
            if self.state == REPLICA_SWAPPING:
                return self.state  # the swap owns this replica right now
            if not self.service.batcher_alive and not self.service.draining:
                self.state = REPLICA_DEAD
                self.accepting.clear()
                self.registry.event(
                    "replica_dead", replica=self.name, reason="batcher exited"
                )
                return self.state
            batches = self.registry.counter("serve.batches").value
            dead_letters = self.registry.counter("serve.dead_letters").value
            if batches > self._last_batches:
                self._err_streak = 0
            self._err_streak += dead_letters - self._last_dead_letters
            self._last_batches = batches
            self._last_dead_letters = dead_letters
            stalled = self.heartbeat_age_s() > heartbeat_timeout_s
            erroring = self._err_streak >= max(1, max_batch_errors)
            new_state = (
                REPLICA_UNHEALTHY if (stalled or erroring) else REPLICA_HEALTHY
            )
            if new_state != self.state:
                self.registry.event(
                    "replica_state", replica=self.name,
                    state=new_state, was=self.state,
                    heartbeat_age_s=round(self.heartbeat_age_s(), 3),
                    err_streak=self._err_streak,
                )
                self.state = new_state
            return self.state

    # -- restart / bank install ------------------------------------------------

    def restart(self, drain_timeout_s: float = 5.0) -> None:
        """Replace the service with a freshly built one (drain → build →
        readmit).  An unhealthy replica is drained first — its queued
        requests resolve ``"drain"`` and flow back through the router's
        re-enqueue; a drain that cannot finish (wedged batcher) falls
        back to a hard kill + sweep so nothing dangles.  The registry —
        and therefore every counter — carries over."""
        with self._restart_lock:
            old = self.service
            if not old.killed:
                old.drain(timeout=drain_timeout_s)
                if old.batcher_alive:
                    old.hard_kill()
            if old.killed:
                # account anything the dead/wedged batcher abandoned
                self.sweep_unresolved()
            self.service = self._factory(self.registry)
            if self._shadow_tap is not None:
                self.service.set_shadow_tap(self._shadow_tap)
            self.restart_count += 1
            self._err_streak = 0
            self._last_batches = self.registry.counter("serve.batches").value
            self._last_dead_letters = self.registry.counter(
                "serve.dead_letters"
            ).value
            with self._state_lock:
                self.state = REPLICA_HEALTHY
            self.accepting.set()
            self.registry.counter("replica.restarts").inc()
            self.registry.event(
                "replica_restart", replica=self.name, n=self.restart_count
            )
            logger.info("%s restarted (restart #%d)", self.name, self.restart_count)

    def install_bank(
        self,
        anchor_instances: Iterable[Dict],
        version: Optional[int] = None,
        source: str = "rolling_swap",
        store_version: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> int:
        """Encode + pre-warm + install a bank on this replica's service
        at an explicit fleet version (the rolling-swap step; see
        ``ScoringService.swap_bank`` for the no-torn-snapshot story and
        the provenance fields).  ``tenant`` targets a named tenant's
        bank slot (serving/tenancy.py)."""
        return self.service.swap_bank(
            anchor_instances, version=version,
            source=source, store_version=store_version, tenant=tenant,
        )

    # -- shutdown --------------------------------------------------------------

    def retire(self, timeout: float = 30.0) -> None:
        """Scale-down terminal state (serving/autoscaler.py): the caller
        has already stopped routing (``accepting`` cleared) and waited
        for the private queue to empty, so the drain here is normally
        instant — anything unexpectedly still queued resolves
        ``"drain"`` and flows back through the router's re-enqueue
        rather than being lost.  The registry closes but keeps its
        counters readable: the fleet invariant is checked over retired
        members too (``ReplicaRouter.retired_replicas``)."""
        self.accepting.clear()
        if self.state != REPLICA_DEAD:
            self.service.drain(timeout=timeout)
        else:
            # a retire that raced a death still accounts the casualties
            self.sweep_unresolved()
        with self._state_lock:
            self.state = REPLICA_RETIRED
        self.registry.counter("replica.retires").inc()
        self.registry.event("replica_retired", replica=self.name)
        self.registry.close()
        logger.info("%s retired", self.name)

    def close(self, timeout: float = 30.0) -> None:
        """Drain the service (unless already dead) and close this
        replica's telemetry sinks."""
        if self.state != REPLICA_DEAD:
            self.service.drain(timeout=timeout)
        self.registry.close()

    def summary(self) -> Dict[str, Any]:
        """One /healthz row: state, backlog, liveness, lives used, and
        the bank's provenance (source + store version) so fleet state is
        traceable to a bank-store version."""
        bank = self.service.bank_snapshot()
        return {
            "name": self.name,
            "state": self.state,
            "accepting": self.accepting.is_set(),
            "queue_depth": self.queue_depth,
            "heartbeat_age_s": round(self.heartbeat_age_s(), 3),
            "restarts": self.restart_count,
            "bank_version": bank.version,
            "bank_source": bank.source,
            "bank_store_version": bank.store_version,
        }
