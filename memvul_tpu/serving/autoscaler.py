"""Live replica autoscaling — the consumer of the SLO monitor's
``scale_hint``.

PR 10 published a machine-readable autoscaling signal (serving/slo.py:
``"up"`` on budget burn / backlog / shedding / latency breach,
``"down"`` only when both burn windows are quiet and the fleet is
underfilled) and nothing consumed it.  :class:`Autoscaler` closes the
loop: it watches the hint and grows or shrinks a live
:class:`~memvul_tpu.serving.router.ReplicaRouter`'s replica count
without dropping a single request.

* **scale-up** — spawn replica → AOT-warm → admit: a worker thread
  builds a fresh :class:`~memvul_tpu.serving.replica.Replica` through
  the same service-factory path the router's restart recovery uses
  (``build.serve_from_archive``'s per-device factory; encode + AOT
  warmup happen inside the factory, exactly like a restart), syncs the
  fleet's current anchor bank (``router._sync_bank`` — a spawn
  mid-rollout cannot resurrect an old bank), then admits it via
  :meth:`ReplicaRouter.admit_replica`.  A failed spawn is retried
  through the shared :class:`~memvul_tpu.resilience.retry.RetryPolicy`
  and then **refused** with a machine-readable record
  (``scaler.spawn_failures`` + the ``last_refusal`` status field) —
  the fleet keeps serving at its current size.
* **scale-down** — stop-route → drain in-flight → retire: the victim's
  readmission gate closes (``accepting``), the worker waits for its
  private queue to empty, then removes it from routing
  (:meth:`ReplicaRouter.retire_replica` re-enqueues anything still
  charged to it) and retires it (:meth:`Replica.retire`).  No request
  is ever lost to a retirement: the per-cause counter invariant
  ``served + shed + errors == requests`` is checked over retired
  members too.
* **stability** — min/max bounds, per-direction cooldowns, and
  hysteresis (``up_consecutive``/``down_consecutive`` agreeing ticks)
  so burn-rate flapping cannot thrash the fleet; one scale operation
  in flight at a time.

The class itself only *decides*: reading ``status()`` dicts, counting
streaks, and spawning a worker thread.  Every heavy operation (factory
build, bank install, drain waits) lives in the module-level workers —
the same split the router's monitor/``_recover_replica`` uses, enforced
by checker MV102 for ``*Autoscaler`` classes
(tools/lint_no_blocking_in_handler.py).

Metrics (``scaler.*``, docs/observability.md): ``scaler.replicas``
gauge, ``scaler.scale_events`` / ``scaler.scale_ups`` /
``scaler.scale_downs`` / ``scaler.spawn_failures`` counters, and a
``scaler.hint`` gauge mirroring the hint the last tick acted on.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..resilience import faults
from ..telemetry import get_registry
from .replica import Replica
from .router import ReplicaRouter, _sync_bank
from .slo import SCALE_DOWN, SCALE_HOLD, SCALE_UP, _HINT_GAUGE

logger = logging.getLogger(__name__)

# the metric window attached to each decision when the TSDB is on
# (serving/incident.py wires ``metrics_store``): the series that
# justify a hint — burn rates, replica count, queue depth
_DECISION_METRICS = (
    "slo.burn_rate_fast",
    "slo.burn_rate_slow",
    "scaler.replicas",
    "serve.queue_depth",
)
_DECISION_WINDOW_S = 60.0


@dataclasses.dataclass(frozen=True)
class AutoscalerConfig:
    """Bounds + stability knobs; the ``autoscale_*`` keys of
    ``config.SERVING_DEFAULTS`` are the JSON-facing view."""

    min_replicas: int = 1
    max_replicas: int = 4
    interval_s: float = 1.0        # hint-sampling cadence
    up_cooldown_s: float = 5.0     # quiet time after a scale-up (or refusal)
    down_cooldown_s: float = 30.0  # quiet time after a scale-down
    up_consecutive: int = 2        # agreeing "up" ticks before acting
    down_consecutive: int = 4      # agreeing "down" ticks before acting
    drain_timeout_s: float = 10.0  # retire: in-flight completion bound
    history: int = 512             # replica-trajectory ring (bench record)

    def __post_init__(self) -> None:
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {self.min_replicas}"
            )
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                "max_replicas must be >= min_replicas "
                f"({self.max_replicas} < {self.min_replicas})"
            )
        if self.up_consecutive < 1 or self.down_consecutive < 1:
            raise ValueError("hysteresis streaks must be >= 1")


class Autoscaler:
    """Grow/shrink a router's replica count from the SLO scale_hint.

    ``replica_factory(index)`` must return a *service factory* (the
    ``registry -> ScoringService`` closure a :class:`Replica` is built
    over) — ``build.serve_from_archive`` passes its per-device
    ``make_factory``, so a spawned replica takes the identical
    placement/warmup path as a restarted one.  ``slo_monitor`` is the
    hint source (its own thread keeps ``status()`` fresh);
    ``start=False`` skips the control thread so tests and the bench
    drive :meth:`tick` deterministically."""

    def __init__(
        self,
        router: ReplicaRouter,
        replica_factory: Callable[[int], Callable],
        slo_monitor,
        config: Optional[AutoscalerConfig] = None,
        registry=None,
        retry_policy=None,
        run_dir=None,
        start: bool = True,
    ) -> None:
        self.router = router
        self.replica_factory = replica_factory
        self.slo_monitor = slo_monitor
        self.config = config or AutoscalerConfig()
        self.retry_policy = retry_policy
        self.run_dir = run_dir
        self._tel = registry if registry is not None else get_registry()
        self._lock = threading.Lock()
        self._scaling = False          # one scale operation in flight
        self._streak_hint = SCALE_HOLD
        self._streak = 0
        self._last_up = -float("inf")   # monotonic stamps for cooldowns
        self._last_down = -float("inf")
        self._started = time.monotonic()
        self._next_index = itertools.count(
            max(r.index for r in router._members()) + 1
        )
        self.last_refusal: Optional[Dict[str, Any]] = None
        self.history: List[Dict[str, Any]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._tel.gauge("scaler.replicas").set(len(router._members()))
        self._tel.event(
            "scaler_start",
            min=self.config.min_replicas, max=self.config.max_replicas,
        )
        if start:
            self._thread = threading.Thread(
                target=self._loop, name="memvul-autoscaler", daemon=True
            )
            self._thread.start()

    # -- public surface --------------------------------------------------------

    @property
    def replicas(self) -> int:
        return len(self.router._members())

    def status(self) -> Dict[str, Any]:
        """Machine-readable controller state — the ``autoscaler`` block
        ``GET /healthz`` carries (a snapshot read)."""
        now = time.monotonic()
        cfg = self.config
        with self._lock:
            return {
                "replicas": self.replicas,
                "min_replicas": cfg.min_replicas,
                "max_replicas": cfg.max_replicas,
                "hint": self._streak_hint,
                "streak": self._streak,
                "scaling": self._scaling,
                "cooldown_remaining_s": {
                    "up": round(
                        max(0.0, self._last_up + cfg.up_cooldown_s - now), 3
                    ),
                    "down": round(
                        max(
                            0.0, self._last_down + cfg.down_cooldown_s - now
                        ), 3
                    ),
                },
                "last_refusal": self.last_refusal,
            }

    def tick(self, now: Optional[float] = None, sync: bool = False) -> Optional[str]:
        """One control decision: read the hint, update the hysteresis
        streak, and — bounds, cooldowns, and streak permitting — start a
        scale operation.  Returns the action taken (``"up"``/``"down"``)
        or None.  ``now`` overrides the monotonic clock and ``sync``
        runs the worker inline, both for deterministic tests."""
        now = time.monotonic() if now is None else float(now)
        hint = str(self.slo_monitor.status().get("scale_hint", SCALE_HOLD))
        self._tel.gauge("scaler.hint").set(_HINT_GAUGE.get(hint, 0.0))
        action = self._decide(hint, now)
        self._observe(hint, action, now)
        if action == SCALE_UP:
            self._launch(_spawn_replica, sync)
        elif action == SCALE_DOWN:
            self._launch(_retire_replica, sync)
        return action

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    # -- decision --------------------------------------------------------------

    def _decide(self, hint: str, now: float) -> Optional[str]:
        """Pure policy: hysteresis streaks, per-direction cooldowns,
        bounds, and the one-in-flight gate.  Selection only — nothing
        here may block, score, or warm (the autoscaler lint)."""
        cfg = self.config
        with self._lock:
            if hint != self._streak_hint:
                self._streak_hint = hint
                self._streak = 0
            self._streak += 1
            if self._scaling or hint == SCALE_HOLD:
                return None
            count = self.replicas
            if hint == SCALE_UP:
                if self._streak < cfg.up_consecutive:
                    return None
                if count >= cfg.max_replicas:
                    return None
                if now - self._last_up < cfg.up_cooldown_s:
                    return None
                self._last_up = now
                self._scaling = True
                return SCALE_UP
            if hint == SCALE_DOWN:
                if self._streak < cfg.down_consecutive:
                    return None
                if count <= cfg.min_replicas:
                    return None
                if now - self._last_down < cfg.down_cooldown_s:
                    return None
                self._last_down = now
                self._scaling = True
                return SCALE_DOWN
            return None

    def _observe(self, hint: str, action: Optional[str], now: float) -> None:
        """Append one trajectory point (the bench record's
        replica-count-vs-time curve) — bounded ring — and emit it as a
        ``scaler_decision`` event so post-mortems survive the process
        (the in-memory deque dies with it).  When the history plane is
        on (``metrics_store`` set by serving/incident.py), the stored
        point also carries the metric window that justified it."""
        slo = self.slo_monitor.status()
        point = {
            "t_s": round(now - self._started, 3),
            "replicas": self.replicas,
            "hint": hint,
            "action": action,
            "burn_rate_fast": slo.get("burn_rate_fast"),
            "backlog": slo.get("backlog"),
        }
        self._tel.event("scaler_decision", **point)
        store = getattr(self, "metrics_store", None)
        if store is not None:
            try:
                point = dict(point)
                point["window"] = store.window(
                    _DECISION_METRICS, _DECISION_WINDOW_S
                )
            except Exception:  # pragma: no cover - a torn store read
                pass  # must not cost a control decision
        with self._lock:
            self.history.append(point)
            if len(self.history) > self.config.history:
                del self.history[: -self.config.history]

    def _launch(self, worker, sync: bool) -> None:
        """Hand the heavy work to a module-level worker — inline when a
        test/bench asks for determinism, else its own thread (the same
        per-incident split the router's monitor uses)."""
        if sync:
            worker(self)
            return
        threading.Thread(
            target=worker, args=(self,),
            name="memvul-autoscaler-worker", daemon=True,
        ).start()

    # -- worker ----------------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(max(0.05, self.config.interval_s)):
            try:
                self.tick()
            except Exception:  # pragma: no cover - the controller must
                # outlive one bad sample (a replica dying mid-read)
                logger.exception("autoscaler tick failed")


def _spawn_replica(scaler: Autoscaler) -> None:
    """Scale-up worker: build a fresh replica through the factory
    (placement + anchor encode + AOT warmup — the identical path a
    restart takes), sync the fleet's current bank, admit it.  A failure
    burns the shared RetryPolicy's attempts and is then refused with a
    machine-readable record; the fleet keeps serving at its current
    size."""
    tel = scaler._tel
    router = scaler.router
    index = next(scaler._next_index)
    name = f"replica-{index}"
    try:
        def build() -> Replica:
            # the scaler.spawn chaos point (docs/fault_tolerance.md):
            # fires inside the retry window, like serve.batch
            faults.fault_point("scaler.spawn")
            return Replica(
                index,
                scaler.replica_factory(index),
                run_dir=scaler.run_dir,
            )

        try:
            if scaler.retry_policy is not None:
                replica = scaler.retry_policy.call(
                    build, description=f"spawn {name}"
                )
            else:
                replica = build()
        except Exception as e:  # noqa: BLE001 - any predictor/device
            # failure must refuse the spawn, never crash the controller
            refusal = {
                "error": "spawn_failed",
                "replica": name,
                "attempts": (
                    scaler.retry_policy.attempts
                    if scaler.retry_policy is not None else 1
                ),
                "reason": f"{type(e).__name__}: {e}"[:200],
            }
            with scaler._lock:
                scaler.last_refusal = refusal
            tel.counter("scaler.spawn_failures").inc()
            tel.event("scaler_spawn_refused", **refusal)
            logger.error("spawn %s refused: %s", name, refusal["reason"])
            recorder = getattr(scaler, "incident_recorder", None)
            if recorder is not None:  # refusals are incident triggers
                recorder.trigger("scaler_spawn_refused", refusal)
            return
        _sync_bank(router, replica)
        router.admit_replica(replica)
        count = len(router._members())
        tel.counter("scaler.scale_events").inc()
        tel.counter("scaler.scale_ups").inc()
        tel.gauge("scaler.replicas").set(count)
        tel.event("scaler_scale_up", replica=replica.name, replicas=count)
        logger.info("scaled up: %s admitted (%d replicas)", replica.name, count)
    finally:
        with scaler._lock:
            scaler._scaling = False


def _retire_replica(
    scaler: Autoscaler, poll_interval_s: float = 0.01
) -> None:
    """Scale-down worker: stop-route → drain in-flight → retire.  The
    victim is the newest healthy member (LIFO keeps the original fleet
    stable); its gate closes first, the worker waits for its private
    queue to empty (every in-flight request completes normally), then
    membership is dropped (anything still charged re-enqueues onto
    survivors) and the replica retires with its counters intact."""
    tel = scaler._tel
    router = scaler.router
    cfg = scaler.config
    try:
        members = router._members()
        if len(members) <= cfg.min_replicas:
            return
        victim = members[-1]
        victim.accepting.clear()
        tel.event("scaler_retire_begin", replica=victim.name)
        deadline = time.monotonic() + cfg.drain_timeout_s
        while time.monotonic() < deadline:
            with router._lock:
                owed = len(router._outstanding.get(victim.name, {}))
            if owed == 0 and victim.queue_depth == 0:
                break
            time.sleep(poll_interval_s)
        try:
            router.retire_replica(victim)
        except ValueError:
            # raced a concurrent recovery/drain that already removed it
            victim.accepting.set()
            return
        victim.retire(timeout=cfg.drain_timeout_s)
        count = len(router._members())
        tel.counter("scaler.scale_events").inc()
        tel.counter("scaler.scale_downs").inc()
        tel.gauge("scaler.replicas").set(count)
        tel.event("scaler_scale_down", replica=victim.name, replicas=count)
        logger.info(
            "scaled down: %s retired (%d replicas)", victim.name, count
        )
    finally:
        with scaler._lock:
            scaler._scaling = False
