"""Online scoring service: dynamic micro-batching over AOT-warmed
shapes, admission control with per-request deadlines, graceful drain,
and hot anchor-bank swap (docs/serving.md).

Entry points: ``build.serve_from_archive`` constructs a ready
:class:`ScoringService` from a model archive; ``python -m memvul_tpu
serve`` puts the stdlib HTTP front end (serving/frontend.py) on top.
"""

from .service import (  # noqa: F401
    MANIFEST_NAME,
    STATUS_DEADLINE,
    STATUS_DRAIN,
    STATUS_ERROR,
    STATUS_OK,
    STATUS_SHED,
    ScoreFuture,
    ScoringService,
    ServiceConfig,
)
from .client import HTTPClient, InprocessClient  # noqa: F401
