"""Online scoring tier: dynamic micro-batching over AOT-warmed shapes,
admission control with per-request deadlines, graceful drain, hot
anchor-bank swap — and the scale-out layer on top: a health-gated
multi-replica router with rolling bank swaps and a closed-loop SLO
harness (docs/serving.md).

Entry points: ``build.serve_from_archive`` constructs a ready
:class:`ScoringService` (or, with ``serving.replicas > 1``, a
:class:`ReplicaRouter` over N of them); ``python -m memvul_tpu serve
[--replicas N]`` puts the stdlib HTTP front end (serving/frontend.py)
on top of either.  Above the single host: ``serve --hosts`` fronts a
:class:`HostBalancer` over per-host fleets (serving/fleet.py), and
``serving.autoscale_enabled`` closes the ``scale_hint`` loop with a
live :class:`Autoscaler` (serving/autoscaler.py).

Multi-tenancy (docs/multitenancy.md): ``serve --tenants`` resolves
per-org anchor banks from versioned :class:`~memvul_tpu.bankops.store.
BankStore` directories through one warmed encoder
(serving/tenancy.py), and ``serving.cache_capacity`` puts a
content-addressed exact-duplicate :class:`AdmissionCache` in front of
admission (serving/admission_cache.py).
"""

from .service import (  # noqa: F401
    MANIFEST_NAME,
    STATUS_DEADLINE,
    STATUS_DRAIN,
    STATUS_ERROR,
    STATUS_OK,
    STATUS_SHED,
    ScoreFuture,
    ScoringService,
    ServiceConfig,
)
from .client import HTTPClient, InprocessClient  # noqa: F401
from .replica import (  # noqa: F401
    REPLICA_DEAD,
    REPLICA_HEALTHY,
    REPLICA_SWAPPING,
    REPLICA_UNHEALTHY,
    Replica,
    ReplicaDead,
)
from .router import ReplicaRouter, RouterConfig, rolling_swap  # noqa: F401
from .fleet import (  # noqa: F401
    FleetConfig,
    HostBalancer,
    HostDead,
    LocalHost,
    ProcessHost,
    enumerate_hosts,
)
from .autoscaler import Autoscaler, AutoscalerConfig  # noqa: F401
from .admission_cache import AdmissionCache, text_digest  # noqa: F401
from .tenancy import (  # noqa: F401
    DEFAULT_TENANT,
    TenantManager,
    TenantSpecError,
    configure_tenants,
    demote_tenant,
    install_tenant_bank,
    parse_tenant_spec,
    promote_tenant,
    validate_tenant_name,
)
from .loadgen import (  # noqa: F401
    LoadConfig,
    LoadGenerator,
    arrival_offsets,
    fleet_snapshot,
    request_deadlines,
    request_texts,
    run_slo_harness,
)
from .slo import (  # noqa: F401
    SCALE_DOWN,
    SCALE_HOLD,
    SCALE_UP,
    SLOConfig,
    SLOMonitor,
)
