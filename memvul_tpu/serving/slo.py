"""Sliding-window SLO evaluation + the autoscaling signal.

The serving tier's counters say what happened since process start; an
operator (or an autoscaler) needs what is happening *now* against an
objective.  :class:`SLOMonitor` samples a serving target's registries
(via the same ``metrics_snapshots()`` fan-out ``GET /metrics`` uses) on
a cadence, keeps a bounded ring of samples, and evaluates two windows
over the deltas (docs/observability.md, "SLO monitor"):

* **availability** — served / requests over the window (1.0 with no
  traffic: an idle fleet is not failing);
* **latency attainment** — the fraction of window samples whose live
  ``serve.latency_s`` p95 was within the objective;
* **burn rate** — ``(1 - availability) / (1 - objective)`` per window:
  1.0 means the error budget burns exactly as fast as the objective
  allows, >1 means an incident.  Two windows (fast/slow) give the
  classic multi-window burn-rate alert shape: the fast window catches
  a spike, the slow window confirms it is not noise;
* **scale_hint** — the machine-readable autoscaling signal the ROADMAP
  owes ("autoscaling signals from the router's utilization/queue
  metrics"): ``"up"`` on budget burn, backlog, overflow shedding, or a
  latency breach; ``"down"`` only when both windows are quiet, the
  backlog is empty, and batch occupancy says the fleet is underfilled;
  ``"hold"`` otherwise.

Published three ways: ``slo.*`` gauges in the target's registry, the
``slo`` block ``GET /healthz`` carries, and the ``slo`` record
``run_slo_harness`` folds into its JSON output.

The monitor is read-only — it never touches routing or admission — and
its worker thread samples snapshots only, so it costs a handful of
dict reads per tick.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import threading
import time
from typing import Any, Dict, List, Optional

from ..telemetry import get_registry

logger = logging.getLogger(__name__)

# machine-readable hints, and their numeric gauge encoding (the gauge
# lets a scrape-only consumer alert on sign alone)
SCALE_UP = "up"
SCALE_HOLD = "hold"
SCALE_DOWN = "down"
_HINT_GAUGE = {SCALE_DOWN: -1.0, SCALE_HOLD: 0.0, SCALE_UP: 1.0}


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """Objectives + window geometry; the ``slo_*`` keys of
    ``config.SERVING_DEFAULTS`` are the JSON-facing view."""

    availability_objective: float = 0.999  # served/requests target
    latency_p95_ms: float = 1000.0         # p95 objective for serve.latency_s
    fast_window_s: float = 60.0            # spike-catcher window
    window_s: float = 300.0                # confirmation (slow) window
    interval_s: float = 5.0                # sampling cadence
    # scale_hint thresholds (not config-exposed: the objective and the
    # windows are the policy surface; these are the standard shapes)
    up_burn_rate: float = 1.0       # fast burn ≥ this → "up"
    down_burn_rate: float = 0.25    # both burns ≤ this to allow "down"
    up_backlog_frac: float = 0.5    # queue_depth / capacity → "up"
    down_backlog_frac: float = 0.05
    down_utilization: float = 0.25  # windowed batch occupancy ceiling
    up_attainment: float = 0.5      # fast latency attainment < this → "up"

    def __post_init__(self) -> None:
        if not (0.0 < self.availability_objective < 1.0):
            raise ValueError(
                "availability_objective must be in (0, 1), got "
                f"{self.availability_objective!r}"
            )
        if self.fast_window_s > self.window_s:
            raise ValueError(
                "fast_window_s must not exceed window_s "
                f"({self.fast_window_s} > {self.window_s})"
            )


# the counters a sample accumulates fleet-wide (summed over parts)
_SAMPLE_COUNTERS = (
    "serve.requests", "serve.served", "serve.shed", "serve.errors",
    "serve.shed_overflow", "serve.shed_deadline",
)


class SLOMonitor:
    """Watch one serving target (a ``ScoringService`` or a
    ``ReplicaRouter``) against :class:`SLOConfig` objectives.

    ``start=False`` skips the worker thread — tests (and the SLO
    harness) drive :meth:`tick` directly with explicit ``now`` values
    for deterministic windows.  ``registry`` receives the ``slo.*``
    gauges (default: the process-wide registry, which for a router is
    also where ``router.*`` lives)."""

    def __init__(
        self,
        target,
        registry=None,
        config: Optional[SLOConfig] = None,
        capacity: Optional[int] = None,
        start: bool = True,
    ) -> None:
        self.target = target
        self.config = config or SLOConfig()
        self._tel = registry if registry is not None else get_registry()
        self.capacity = int(capacity) if capacity else _infer_capacity(target)
        self._samples: "collections.deque[Dict[str, Any]]" = collections.deque()
        self._lock = threading.Lock()
        self._status: Dict[str, Any] = self._empty_status()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._loop, name="memvul-slo-monitor", daemon=True
            )
            self._thread.start()

    # -- public surface --------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """The latest evaluation (a copy) — the ``/healthz`` ``slo``
        block and the harness record field."""
        with self._lock:
            return dict(self._status)

    def tick(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Take one sample and re-evaluate both windows.  ``now`` is a
        monotonic timestamp override for deterministic tests."""
        now = time.monotonic() if now is None else float(now)
        sample = self._collect(now)
        horizon = now - self.config.window_s - 2 * max(
            self.config.interval_s, 1e-3
        )
        with self._lock:
            self._samples.append(sample)
            while self._samples and self._samples[0]["t"] < horizon:
                self._samples.popleft()
            samples = list(self._samples)
        status = self._evaluate(samples, now)
        self._publish(status)
        with self._lock:
            self._status = status
        return status

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    # -- sampling --------------------------------------------------------------

    def _collect(self, now: float) -> Dict[str, Any]:
        counters = {name: 0 for name in _SAMPLE_COUNTERS}
        p95_s: Optional[float] = None
        occ_count = 0.0
        occ_total = 0.0
        for _labels, snapshot in self.target.metrics_snapshots():
            snap_counters = snapshot.get("counters") or {}
            for name in _SAMPLE_COUNTERS:
                counters[name] += int(snap_counters.get(name, 0))
            hists = snapshot.get("histograms") or {}
            latency = hists.get("serve.latency_s") or {}
            if latency.get("p95") is not None:
                p95_s = max(p95_s or 0.0, float(latency["p95"]))
            occupancy = hists.get("serve.batch_occupancy") or {}
            occ_count += float(occupancy.get("count", 0.0))
            occ_total += float(occupancy.get("total", 0.0))
        return {
            "t": now,
            "counters": counters,
            "p95_s": p95_s,
            "occ_count": occ_count,
            "occ_total": occ_total,
            "queue_depth": int(getattr(self.target, "queue_depth", 0)),
        }

    # -- evaluation ------------------------------------------------------------

    def _empty_status(self) -> Dict[str, Any]:
        cfg = self.config
        return {
            "objectives": {
                "availability": cfg.availability_objective,
                "latency_p95_ms": cfg.latency_p95_ms,
            },
            "window_s": cfg.window_s,
            "fast_window_s": cfg.fast_window_s,
            "samples": 0,
            "availability": 1.0,
            "availability_fast": 1.0,
            "latency_attainment": 1.0,
            "latency_p95_ms": None,
            "burn_rate_fast": 0.0,
            "burn_rate_slow": 0.0,
            "error_budget_remaining": 1.0,
            "backlog": 0,
            "backlog_frac": 0.0,
            "utilization": None,
            "scale_hint": SCALE_HOLD,
        }

    def _window(
        self, samples: List[Dict[str, Any]], now: float, window_s: float
    ) -> Dict[str, Any]:
        """Delta stats between the oldest in-window sample and the
        newest one."""
        inside = [s for s in samples if s["t"] >= now - window_s]
        if len(inside) < 2:
            return {
                "n": len(inside), "requests": 0, "served": 0, "errors": 0,
                "shed_overflow": 0, "availability": 1.0, "attainment": 1.0,
                "occupancy": None,
            }
        base, cur = inside[0], inside[-1]

        def delta(name: str) -> int:
            return max(0, cur["counters"][name] - base["counters"][name])

        requests = delta("serve.requests")
        served = delta("serve.served")
        # a request in flight at the window edge is admitted before the
        # base sample but resolves inside the window, so served_Δ can
        # exceed requests_Δ — that is health, not >100% availability
        availability = min(1.0, served / requests) if requests else 1.0
        objective_s = self.config.latency_p95_ms / 1000.0
        attained = [
            s["p95_s"] is None or s["p95_s"] <= objective_s for s in inside
        ]
        occ_count = cur["occ_count"] - base["occ_count"]
        occ_total = cur["occ_total"] - base["occ_total"]
        return {
            "n": len(inside),
            "requests": requests,
            "served": served,
            "errors": delta("serve.errors"),
            "shed_overflow": delta("serve.shed_overflow"),
            "availability": availability,
            "attainment": sum(attained) / len(attained),
            "occupancy": (occ_total / occ_count) if occ_count > 0 else None,
        }

    def _burn(self, availability: float) -> float:
        budget = max(1e-9, 1.0 - self.config.availability_objective)
        return max(0.0, 1.0 - availability) / budget

    def _evaluate(
        self, samples: List[Dict[str, Any]], now: float
    ) -> Dict[str, Any]:
        cfg = self.config
        fast = self._window(samples, now, cfg.fast_window_s)
        slow = self._window(samples, now, cfg.window_s)
        burn_fast = self._burn(fast["availability"])
        burn_slow = self._burn(slow["availability"])
        latest = samples[-1]
        backlog = latest["queue_depth"]
        backlog_frac = backlog / max(1, self.capacity)
        utilization = fast["occupancy"]
        # a latency breach is judged on the LIVE p95, not the windowed
        # attainment average — the spike should flip the hint the tick
        # it appears, not after it has dragged the average down
        breach = (
            latest["p95_s"] is not None
            and latest["p95_s"] > cfg.latency_p95_ms / 1000.0
            and fast["requests"] > 0
        )
        if (
            burn_fast >= cfg.up_burn_rate
            or backlog_frac >= cfg.up_backlog_frac
            or fast["shed_overflow"] > 0
            or fast["attainment"] < cfg.up_attainment
            or breach
        ):
            hint = SCALE_UP
        elif (
            fast["n"] >= 2
            and burn_fast <= cfg.down_burn_rate
            and burn_slow <= cfg.down_burn_rate
            and backlog_frac <= cfg.down_backlog_frac
            and fast["attainment"] >= 1.0
            and (utilization is None or utilization <= cfg.down_utilization)
        ):
            hint = SCALE_DOWN
        else:
            hint = SCALE_HOLD
        status = self._empty_status()
        status.update({
            "samples": len(samples),
            "availability": slow["availability"],
            "availability_fast": fast["availability"],
            "latency_attainment": slow["attainment"],
            "latency_p95_ms": (
                latest["p95_s"] * 1000.0
                if latest["p95_s"] is not None else None
            ),
            "burn_rate_fast": burn_fast,
            "burn_rate_slow": burn_slow,
            "error_budget_remaining": max(0.0, min(1.0, 1.0 - burn_slow)),
            "backlog": backlog,
            "backlog_frac": backlog_frac,
            "utilization": utilization,
            "scale_hint": hint,
        })
        return status

    def _publish(self, status: Dict[str, Any]) -> None:
        tel = self._tel
        tel.gauge("slo.availability").set(status["availability"])
        tel.gauge("slo.latency_attainment").set(status["latency_attainment"])
        tel.gauge("slo.burn_rate_fast").set(status["burn_rate_fast"])
        tel.gauge("slo.burn_rate_slow").set(status["burn_rate_slow"])
        tel.gauge("slo.error_budget_remaining").set(
            status["error_budget_remaining"]
        )
        tel.gauge("slo.scale_hint").set(_HINT_GAUGE[status["scale_hint"]])

    # -- worker ----------------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(max(0.05, self.config.interval_s)):
            try:
                self.tick()
            except Exception:  # pragma: no cover - the monitor must
                # outlive any one bad sample (a replica dying mid-read)
                logger.exception("slo monitor tick failed")


def _infer_capacity(target) -> int:
    """Fleet queue capacity (the backlog normalizer): Σ max_queue over
    replicas, or the single service's max_queue; 256 when the target
    exposes neither (bare fakes in tests)."""
    replicas = getattr(target, "replicas", None)
    if replicas:
        total = 0
        for replica in replicas:
            service_cfg = getattr(
                getattr(replica, "service", None), "config", None
            )
            total += int(getattr(service_cfg, "max_queue", 0) or 0)
        if total > 0:
            return total
    service_cfg = getattr(target, "config", None)
    capacity = int(getattr(service_cfg, "max_queue", 0) or 0)
    return capacity if capacity > 0 else 256
