"""Stdlib-only HTTP front end for the scoring service.

One hard rule, enforced by ``tools/lint_no_blocking_in_handler.py``:
handler threads may only **enqueue** a request and **wait on its
future**.  Tokenization, batching, and every device dispatch live on
the service's batcher thread — a handler that scored inline would
serialize the whole server behind one connection and reintroduce the
per-request-shape compiles the micro-batcher exists to prevent.

API (JSON over ``http.server``; docs/serving.md lists the endpoint
table):

* ``POST /score`` with ``{"text": "...", "deadline_ms": 500}`` →
  the service response (``status`` "ok" carries the per-anchor
  ``predict`` dict, best ``score``/``anchor``, and ``bank_version``).
  HTTP status: 200 ok, 503 shed/drain, 504 deadline, 500 error.
* ``GET /healthz`` → the target's ``health_summary()``: drain state,
  queue depth, active bank version, and — behind a
  :class:`~memvul_tpu.serving.router.ReplicaRouter` — the per-replica
  health rows, so an external probe distinguishes "degraded fleet"
  from "healthy".  HTTP 200, or 503 once draining (a load balancer's
  eviction signal — that contract is unchanged).  When an
  :class:`~memvul_tpu.serving.slo.SLOMonitor` is attached the body
  carries its ``slo`` block (attainment, burn rates, ``scale_hint``);
  an attached :class:`~memvul_tpu.serving.autoscaler.Autoscaler`
  contributes an ``autoscaler`` block (replica count, hint streak,
  cooldowns, last spawn refusal), and behind a
  :class:`~memvul_tpu.serving.fleet.HostBalancer` the summary is the
  merged per-host view with the quarantined hosts named.
* ``GET /metrics`` → the live registries in Prometheus text format
  (telemetry/exposition.py; a router fans out per-replica parts with
  ``replica`` labels).
* ``GET /tracez[?limit=N]`` → the bounded ring of recent completed
  request traces, newest first (serving/service.py tracing).
* ``GET /programz`` → the compiled-program registry rows, newest
  compile first, plus the aggregate roofline reading
  (telemetry/programs.py; a router target merges every replica's rows
  with ``replica`` stamps).
* ``GET /metricsz[?window=S][&metric=prefix]`` → the in-process metric
  history rings as JSON (telemetry/timeseries.py) when the sampler is
  on (``telemetry.tsdb_cadence_s`` > 0); ``{"enabled": false}``
  otherwise — the endpoint itself never 404s.
* ``GET /alertz`` → the alert engine's rule table + currently-firing
  records (telemetry/alerts.py); ``{"enabled": false}`` when the
  history plane is off.
* ``POST /profilez`` with ``{"seconds": N}`` → starts an on-demand
  ``jax.profiler`` capture into the run dir while traffic keeps
  flowing; 409 while one is already running, 503 when the server was
  started without a run dir.

The read endpoints only read **snapshots** — registry snapshots, the
trace ring, the health summary; checker MV102 (static-analysis engine)
rejects any scoring/encoding/packing call inside a handler class, so a
scrape can never stall the batcher.

The front end serves a single :class:`ScoringService` or a
:class:`ReplicaRouter` interchangeably: both expose ``submit`` /
``health_summary`` / ``metrics_snapshots`` / ``recent_traces`` /
``default_deadline_ms``.

The access log goes through ``logging`` (never print — the bare-print
lint holds for serving code too).
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..telemetry import get_registry
from ..telemetry.exposition import render_target
from ..utils.profiling import CaptureInProgress, ProfilerCapture
from .service import (
    STATUS_DEADLINE,
    STATUS_DRAIN,
    STATUS_ERROR,
    STATUS_OK,
    STATUS_SHED,
    ScoringService,
)

logger = logging.getLogger(__name__)

_HTTP_STATUS = {
    STATUS_OK: 200,
    STATUS_SHED: 503,
    STATUS_DRAIN: 503,
    STATUS_DEADLINE: 504,
    STATUS_ERROR: 500,
}
# client-visible slack past the request deadline before the handler
# gives up waiting on the future (the service resolves deadline sheds
# only at batch-pull time, so the wait must outlive the deadline)
_RESULT_SLACK_S = 30.0


class ScoringHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer carrying the service handle for handlers.

    ``profile_dir`` arms ``POST /profilez`` (on-demand ``jax.profiler``
    captures land in ``<profile_dir>/profile-<n>/``); without it the
    endpoint answers 503."""

    daemon_threads = True

    def __init__(self, address, service: ScoringService, profile_dir=None):
        super().__init__(address, ScoreHandler)
        self.service = service
        self.profiler = (
            ProfilerCapture(profile_dir) if profile_dir is not None else None
        )


class ScoreHandler(BaseHTTPRequestHandler):
    server_version = "memvul-serve/1.0"
    protocol_version = "HTTP/1.1"

    # -- plumbing --------------------------------------------------------------

    def log_message(self, format: str, *args) -> None:  # noqa: A002
        # route the access log through logging: the CLI's stdout is a
        # one-JSON-line contract and stderr belongs to the log handler
        logger.info("%s %s", self.address_string(), format % args)

    def _reply(self, http_status: int, payload: dict) -> None:
        body = json.dumps(payload).encode("utf-8")
        self.send_response(http_status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- routes ----------------------------------------------------------------

    def _reply_text(self, http_status: int, text: str) -> None:
        body = text.encode("utf-8")
        self.send_response(http_status)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- routes ----------------------------------------------------------------

    def do_GET(self) -> None:
        path, _, query = self.path.partition("?")
        service = self.server.service
        if path == "/healthz":
            summary = service.health_summary()
            # the SLO monitor is attached by build.serve_from_archive;
            # its status() is a dict copy — a snapshot read, like
            # everything else a handler may touch
            monitor = getattr(service, "slo_monitor", None)
            if monitor is not None:
                summary["slo"] = monitor.status()
            # same attachment pattern for the autoscaler: its status()
            # (replica count, hint streak, cooldowns, last refusal) is
            # a snapshot read too
            scaler = getattr(service, "autoscaler", None)
            if scaler is not None:
                summary["autoscaler"] = scaler.status()
            # the tenancy plane attaches the same way (a bare service's
            # health_summary already embeds it; a router target gets it
            # added here) — summary() is a dict copy, a snapshot read
            manager = getattr(service, "tenant_manager", None)
            if manager is not None and "tenancy" not in summary:
                summary["tenancy"] = manager.summary()
            self._reply(503 if summary["draining"] else 200, summary)
            return
        if path == "/metrics":
            # registry snapshots rendered as Prometheus text — the live
            # scrape surface (docs/observability.md "Live exposition")
            self._reply_text(200, render_target(service))
            return
        if path == "/tracez":
            params = urllib.parse.parse_qs(query)
            try:
                limit = int(params["limit"][0]) if "limit" in params else None
            except (TypeError, ValueError):
                self._reply(400, {
                    "status": "error", "reason": "limit must be an integer",
                })
                return
            traces = service.recent_traces(limit)
            self._reply(200, {"count": len(traces), "traces": traces})
            return
        if path == "/programz":
            # compiled-program registry rows, newest compile first — a
            # snapshot read like /metrics (a router target fans out per
            # replica, each row stamped with its replica name)
            programs = service.programs_snapshot()
            payload = {"count": len(programs), "programs": programs}
            roofline = getattr(service, "programs_roofline", None)
            if roofline is not None:
                payload["roofline"] = roofline()
            self._reply(200, payload)
            return
        if path == "/metricsz":
            # metric history rings — a snapshot copy under the store
            # lock, same discipline as every other read endpoint.  The
            # sampler is attached by serving/incident.py's
            # attach_flight_recorder; absent (the default-off config)
            # the endpoint answers {"enabled": false} rather than 404
            # so probes can distinguish "off" from "wrong path"
            params = urllib.parse.parse_qs(query)
            try:
                window_s = (
                    float(params["window"][0]) if "window" in params else None
                )
            except (TypeError, ValueError):
                self._reply(400, {
                    "status": "error", "reason": "window must be a number",
                })
                return
            metric = params["metric"][0] if "metric" in params else None
            sampler = getattr(service, "metrics_sampler", None)
            if sampler is None:
                self._reply(200, {"enabled": False, "series": 0, "history": {}})
                return
            payload = sampler.status()
            payload["history"] = sampler.history(window_s, metric)
            self._reply(200, payload)
            return
        if path == "/alertz":
            engine = getattr(service, "alert_engine", None)
            if engine is None:
                self._reply(200, {"enabled": False, "firing": [], "rules": []})
                return
            self._reply(200, engine.status())
            return
        self._reply(404, {"status": "error", "reason": "unknown path"})

    def _do_profilez(self) -> None:
        profiler = self.server.profiler
        if profiler is None:
            self._reply(503, {
                "status": "error",
                "reason": "profiling disabled: serve was started without "
                "a run dir (-o/--out-dir)",
            })
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            payload = json.loads(self.rfile.read(length) or b"{}")
            seconds = float(payload["seconds"])
        except (KeyError, TypeError, ValueError) as e:
            self._reply(400, {
                "status": "error",
                "reason": f"bad request: {type(e).__name__}: {e} "
                '(expected {"seconds": N})',
            })
            return
        try:
            info = profiler.start(seconds)
        except CaptureInProgress as e:
            self._reply(409, {"status": "error", "reason": str(e)})
            return
        except ValueError as e:
            self._reply(400, {"status": "error", "reason": str(e)})
            return
        get_registry().counter("serve.profile_captures").inc()
        self._reply(200, {"status": "ok", **info})

    def do_POST(self) -> None:
        if self.path == "/profilez":
            self._do_profilez()
            return
        if self.path != "/score":
            self._reply(404, {"status": "error", "reason": "unknown path"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            payload = json.loads(self.rfile.read(length) or b"{}")
            text = payload["text"]
            if not isinstance(text, str):
                raise TypeError("'text' must be a string")
            deadline_ms = payload.get("deadline_ms")
            if deadline_ms is not None:
                deadline_ms = float(deadline_ms)
            # tenant resolution (docs/multitenancy.md): JSON field wins,
            # then the X-MemVul-Tenant header; absent = default tenant,
            # so every pre-tenancy client keeps working unchanged
            tenant = payload.get("tenant") or self.headers.get(
                "X-MemVul-Tenant"
            )
            if tenant is not None and not isinstance(tenant, str):
                raise TypeError("'tenant' must be a string")
        except (KeyError, TypeError, ValueError) as e:
            self._reply(400, {
                "status": "error",
                "reason": f"bad request: {type(e).__name__}: {e}",
            })
            return
        service = self.server.service
        # enqueue + wait on the future — the ONLY service interaction a
        # handler is allowed (lint_no_blocking_in_handler)
        future = service.submit(text, deadline_ms=deadline_ms, tenant=tenant)
        wait_s = _RESULT_SLACK_S + (
            deadline_ms / 1000.0
            if deadline_ms and deadline_ms > 0
            else service.default_deadline_ms / 1000.0
        )
        try:
            response = future.result(timeout=wait_s)
        except TimeoutError:
            self._reply(504, {
                "status": "error",
                "reason": "request not resolved within the handler wait",
            })
            return
        self._reply(_HTTP_STATUS.get(response["status"], 500), response)


def run_http_server(
    service: ScoringService,
    host: str = "127.0.0.1",
    port: int = 0,
    in_thread: bool = True,
    profile_dir=None,
) -> ScoringHTTPServer:
    """Bind and start serving (port 0 = ephemeral; read the bound port
    off ``server.server_address``).  With ``in_thread`` the accept loop
    runs on a daemon thread and the server handle is returned
    immediately — call ``server.shutdown()`` then ``service.drain()``
    to stop.  ``profile_dir`` (the serve CLI passes the run dir) arms
    ``POST /profilez``."""
    server = ScoringHTTPServer((host, port), service, profile_dir=profile_dir)
    if in_thread:
        thread = threading.Thread(
            target=server.serve_forever, name="memvul-serve-http", daemon=True
        )
        thread.start()
    logger.info(
        "scoring service listening on http://%s:%d (POST /score, GET "
        "/healthz, GET /metrics, GET /tracez, GET /programz, "
        "GET /metricsz, GET /alertz, POST /profilez)",
        *server.server_address[:2],
    )
    return server
