"""Dispatch strategies for the scoring service (docs/serving.md).

PR 4's micro-batcher and PR 8's ragged path shared one dispatch loop by
copy: the pull/deadline/drain/retry/hard-kill/trace semantics lived in
``ScoringService`` twice over an ``if score_impl`` fork, and a third
copy was the natural-but-wrong way to add continuous batching.  This
module is the extraction: :class:`Dispatcher` owns those semantics ONCE
— deadline expiry at pull, ONE bank snapshot per micro-batch, the
``serve.batch`` fault point inside the retried window, dead-letter on
retry exhaustion, hard-kill abandonment (resolve nothing, stay visible
to the sweep), the trace waypoints, and the padding/occupancy ledger —
and a strategy subclass decides only how accepted requests become
device dispatches:

* :class:`BucketedDispatcher` — PR 4: coalesce up to ``max_batch``
  requests, route each to the smallest warmed (rows, length) bucket,
  pad the block;
* :class:`RaggedDispatcher` — PR 8: the same pull, packed by token
  budget into fixed ``[1, token_budget]`` flat batches for the single
  warmed segment-masked program (docs/ragged_serving.md);
* :class:`ContinuousDispatcher` — this PR: no pull-then-seal at all.
  A persistent admission loop pops requests the moment they arrive and
  writes them straight into an open pack on a reusable
  :class:`~memvul_tpu.data.batching.PackSlotAllocator` page table,
  while a device worker thread scores sealed packs: pack N+1 tops up
  *during* pack N's device round-trip, so ``serve.queue_wait_s``
  decouples from device latency (ROADMAP's ≥3× p50 target).  The
  overlap is measurable: ``serve.pack_topups`` counts admissions that
  happened while the device was busy, ``serve.pack_slots_reused``
  counts page-table slot recycling, and telemetry-report derives
  ``serve.admission_efficiency`` from the pair;
* :class:`CascadeDispatcher` — the quantized two-tier cascade
  (docs/quantized_serving.md): bucketed routing, int8 first dispatch,
  fp32 rescore of only the rows whose max-anchor score lands inside
  the configured uncertainty band.

The admission-path discipline is machine-checked: MV102 extends to
``*Dispatcher`` classes (no ``predict*``/``score_texts``/``time.sleep``
between a pop and a dispatch), and MV301's blocking-under-lock rule
covers the continuous dispatcher's two threads like every other
thread-spawning class.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..data.batching import (
    PackSlotAllocator,
    _pad_block,
    collate_ragged,
    pack_token_budget,
)
from ..resilience import faults
from ..resilience.retry import exception_text
from .service import (
    STATUS_DEADLINE,
    STATUS_DRAIN,
    STATUS_ERROR,
    STATUS_OK,
    _BankVersion,
    _Request,
)
from .tenancy import DEFAULT_TENANT

logger = logging.getLogger(__name__)


class Dispatcher:
    """Strategy interface: the batcher-thread body of one
    :class:`~memvul_tpu.serving.service.ScoringService`.

    The base class IS the PR 4 contract — subclasses override only
    :meth:`_dispatch_live` (how live requests become device chunks) and
    inherit everything else.  :class:`ContinuousDispatcher` replaces
    :meth:`run` wholesale but still scores through the shared
    :meth:`_score_chunk` core, so the failure-routing and trace
    semantics stay written once.
    """

    def __init__(self, service) -> None:
        self.service = service

    @property
    def alive(self) -> bool:
        """Dispatcher-internal liveness, AND-ed into the service's
        ``batcher_alive`` health signal.  Single-threaded strategies run
        entirely on the service's batcher thread (which the service
        watches itself); the continuous strategy overrides this to watch
        its device worker too."""
        return True

    # -- the batcher loop (service thread) -------------------------------------

    def run(self) -> None:
        svc = self.service
        while not svc._draining.is_set():
            pulled = self._pull_batch()
            if not pulled:
                continue
            if svc._trace_enabled:
                # one coalesce stamp + micro-batch id for the whole
                # pull: these requests now share a fate until dispatch
                # splits them into shape chunks
                coalesced = time.monotonic()
                batch = next(svc._batch_seq)
                for request in pulled:
                    if request.trace is not None:
                        request.trace.coalesced = coalesced
                        request.trace.batch = batch
            # the pull is the in-flight work; track it so a hard kill's
            # sweep can find requests that were popped but never resolved
            with svc._cond:
                svc._inflight = list(pulled)
            if svc._killed.is_set():
                return  # killed mid-pull: abandon (sweep will account)
            # a pull that completed before the drain flag was seen is
            # the in-flight work — it finishes (the trainer's
            # finish-the-step contract); everything still queued sheds
            self._dispatch(pulled)
            if svc._killed.is_set():
                return  # keep _inflight visible for take_unresolved
            with svc._cond:
                svc._inflight = []
            svc._maybe_sample_hbm()
            svc._tel.heartbeat()
        if svc._killed.is_set():
            return  # a killed worker resolves nothing
        svc._shed_queue(STATUS_DRAIN)
        svc._tel.event("serve_drained")
        svc._tel.heartbeat(force=True)

    def _pull_batch(self) -> List[_Request]:
        """Coalesce up to ``max_batch`` requests: wait for the first,
        then keep pulling until the flush window (``max_wait_ms`` after
        the pull started) closes or the batch is full.  Waits are short
        so the drain flag — which is set without taking the condition —
        is noticed promptly."""
        svc = self.service
        cfg = svc.config
        pulled: List[_Request] = []
        while True:
            with svc._cond:
                if svc._queue:
                    pulled.append(svc._queue.popleft())
                    break
                if svc._draining.is_set():
                    return pulled
                svc._cond.wait(0.05)
            # idle liveness tick, OUTSIDE the queue lock (heartbeat may
            # write HEARTBEAT.json, rate-limited): an idle-but-polling
            # batcher keeps its heartbeat age near zero, so the router's
            # missed-heartbeat eviction fires only on a genuinely wedged
            # replica, never an unloaded one
            svc._maybe_sample_hbm()
            svc._tel.heartbeat()
        flush_at = time.monotonic() + cfg.max_wait_ms / 1000.0
        while len(pulled) < cfg.max_batch and not svc._draining.is_set():
            remaining = flush_at - time.monotonic()
            if remaining <= 0:
                break
            with svc._cond:
                if not svc._queue:
                    svc._cond.wait(min(remaining, 0.05))
                if svc._queue:
                    pulled.append(svc._queue.popleft())
        with svc._cond:
            svc._tel.gauge("serve.queue_depth").set(len(svc._queue))
        return pulled

    def _dispatch(self, pulled: List[_Request]) -> None:
        """Score one coalesced pull: expire stale requests, snapshot the
        bank ONCE, encode, and hand the live set to the strategy."""
        svc = self.service
        now = time.monotonic()
        live: List[_Request] = []
        for request in pulled:
            if (
                request.deadline_monotonic is not None
                and now > request.deadline_monotonic
            ):
                svc._finish_unserved(request, STATUS_DEADLINE)
            else:
                live.append(request)
        if not live:
            return
        seqs = svc.predictor.encoder.encode_many([r.text for r in live])
        svc._count_truncated(live, seqs)
        # group the pull by tenant: ONE bank snapshot per tenant group
        # (the per-tenant no-torn-mix guarantee, serving/tenancy.py).
        # The single-tenant case degenerates to exactly the old path —
        # one group, one snapshot, one _dispatch_live call.
        groups: Dict[str, List[Tuple[_Request, List[int]]]] = {}
        for request, seq in zip(live, seqs):
            request.n_tokens = len(seq)  # the cache's tokens-saved ledger
            groups.setdefault(request.tenant, []).append((request, seq))
        for tenant, grouped in groups.items():
            try:
                bank = svc._bank_for(tenant)
            except KeyError as e:  # pragma: no cover - submit() validates
                reason = exception_text(e)
                svc._tel.counter("serve.errors").inc(len(grouped))
                svc._tenant_count(tenant, "errors", len(grouped))
                for request, _ in grouped:
                    request.future.resolve(
                        {"status": STATUS_ERROR, "reason": reason}
                    )
                    svc._finish_trace(request, STATUS_ERROR)
                continue
            self._dispatch_live(
                [r for r, _ in grouped], [s for _, s in grouped], bank
            )

    def _dispatch_live(
        self,
        live: List[_Request],
        seqs: List[List[int]],
        bank: _BankVersion,
    ) -> None:
        raise NotImplementedError

    # -- the shared device-dispatch core ---------------------------------------

    def _score_chunk(
        self,
        chunk: Sequence[Tuple[_Request, List[int]]],
        bank: _BankVersion,
        *,
        sample: Dict[str, Any],
        occupancy_rows: int,
        padded_tokens: int,
        real_tokens: int,
        score_fn,
        shape: str,
        program_key,
    ) -> None:
        """One device dispatch at a warmed shape, resolved to clients.
        Composed from the three tier-sized pieces below — the cascade
        strategy reuses them with a device call per tier, everything
        else dispatches exactly once."""
        probs = self._device_call(
            chunk, bank, sample=sample, score_fn=score_fn,
            shape=shape, program_key=program_key,
        )
        if probs is None:
            return  # dead-lettered or killed: nothing left to resolve
        self._finalize_batch(
            len(chunk), occupancy_rows=occupancy_rows,
            padded_tokens=padded_tokens, real_tokens=real_tokens,
        )
        self._resolve_scored(chunk, probs, bank)

    def _device_call(
        self,
        chunk: Sequence[Tuple[_Request, List[int]]],
        bank: _BankVersion,
        *,
        sample: Dict[str, Any],
        score_fn,
        shape: str,
        program_key,
        params=None,
        fault_name: str = "serve.batch",
    ) -> Optional[np.ndarray]:
        """One retried device round-trip.  The fault point
        (``serve.batch``, or ``serve.cascade`` for the cascade's fp32
        rescore) fires inside the retried window; retry exhaustion (or a
        non-transient failure) dead-letters the chunk — every request
        resolves ``"error"`` with the reason — rather than hanging its
        clients.  Returns the ``[len(chunk), n_anchors]`` probabilities,
        or ``None`` when the chunk dead-lettered or the worker was
        killed (nothing left to resolve either way).  ``params``
        defaults to the predictor's fp32 params; the cascade's int8 tier
        passes ``predictor.int8_params``."""
        svc = self.service
        tel = svc._tel

        def once():
            faults.fault_point(fault_name)
            return score_fn(
                svc.predictor.params if params is None else params,
                sample, bank.array,
            )

        if svc._trace_enabled:
            # device_dispatch waypoint: tokenize/pad/pack is done, the
            # device call is next — one stamp + shape label per chunk
            dispatched = time.monotonic()
            for request, _ in chunk:
                if request.trace is not None:
                    request.trace.dispatched = dispatched
                    request.trace.shape = shape
        start = time.perf_counter()
        try:
            if svc.retry_policy is None:
                dev = once()
            else:
                dev = svc.retry_policy.call(once, description="serve batch")
            probs = np.asarray(dev)[: len(chunk), : bank.n_anchors]
        except Exception as e:
            if svc._killed.is_set():
                return None  # a killed worker neither counts nor resolves
            reason = exception_text(e)
            logger.error(
                "serve batch dead-lettered (%d request(s)): %s",
                len(chunk), reason[:300],
            )
            tel.counter("serve.dead_letters").inc()
            tel.counter("serve.errors").inc(len(chunk))
            response = {"status": STATUS_ERROR, "reason": reason}
            for request, _ in chunk:
                svc._tenant_count(request.tenant, "errors")
                request.future.resolve(dict(response))
                svc._finish_trace(request, STATUS_ERROR)
            return None
        if svc._killed.is_set():
            return None  # killed mid-dispatch: the sweep accounts this chunk
        if svc._trace_enabled:
            device_done = time.monotonic()
            for request, _ in chunk:
                if request.trace is not None:
                    request.trace.device_done = device_done
        tel.histogram("serve.batch_latency_s").observe(
            time.perf_counter() - start
        )
        # program attribution: this dispatch ran one registered
        # executable start-to-sync (np.asarray above blocks), so the
        # elapsed window is the per-launch device time the roofline
        # gauges divide by
        # program_key is a thunk: duck-typed test fakes carry no program
        # registry, so the key must not be computed unless one exists
        programs = getattr(svc.predictor, "programs", None)
        if programs is not None:
            programs.record_invocation(
                program_key(), time.perf_counter() - start
            )
        return probs

    def _finalize_batch(
        self,
        n_rows: int,
        *,
        occupancy_rows: int,
        padded_tokens: int,
        real_tokens: int,
    ) -> None:
        """Book one dispatched device batch into the occupancy/padding
        ledger (counted per device round-trip: a cascade's fp32 rescore
        is a second batch and pays a second entry)."""
        tel = self.service._tel
        tel.histogram("serve.batch_occupancy").observe(
            n_rows / occupancy_rows
        )
        # the padding-efficiency ledger (docs/ragged_serving.md):
        # real tokens the requests carried vs token slots the dispatched
        # shape paid for — telemetry-report derives
        # serve.real_token_utilization from the pair, and the serve
        # microbench A/B reads them per path
        tel.counter("serve.tokens_real").inc(real_tokens)
        tel.counter("serve.tokens_padded").inc(padded_tokens)
        tel.counter("serve.batches").inc()

    def _resolve_scored(
        self,
        chunk: Sequence[Tuple[_Request, List[int]]],
        probs: np.ndarray,
        bank: _BankVersion,
    ) -> None:
        """Resolve scored rows to their clients: the served counter, the
        per-request response + anchor attribution + stage histograms,
        and the post-resolution shadow tap.  Every request passes
        through here exactly once on the success path — the exact
        counter invariant's served leg."""
        svc = self.service
        tel = svc._tel
        tel.counter("serve.served").inc(len(chunk))
        tel.progress()
        now = time.monotonic()
        anchor_stats = svc.config.anchor_stats
        cache = svc.admission_cache
        weights = bank.weights
        for (request, _), row in zip(chunk, probs):
            # reweight (bankops phase 3): the *winner selection* uses the
            # per-anchor weighted scores, the reported probabilities stay
            # raw.  A weight-1.0 bank carries weights=None and never
            # enters this branch — bitwise-unchanged by construction
            # (the evaluate_reweight parity gate, bankops/promote.py)
            if weights is not None:
                best = int(np.argmax(row * weights))
            else:
                best = int(np.argmax(row))
            tel.histogram("serve.latency_s").observe(
                now - request.enqueued_monotonic
            )
            if anchor_stats:
                # attribute the decision to its winning anchor — the
                # per-anchor win/drift table's raw data (bankops/drift.py,
                # docs/anchor_bank.md); ~one counter inc + one reservoir
                # observe per response, bounded by the bank size
                label = bank.labels[best]
                tel.counter(f"bank.anchor_wins.{label}").inc()
                tel.histogram(f"bank.anchor_score.{label}").observe(
                    float(row[best])
                )
            response = {
                "status": STATUS_OK,
                "predict": {
                    label: float(p) for label, p in zip(bank.labels, row)
                },
                "score": float(row[best]),
                "anchor": bank.labels[best],
                "bank_version": bank.version,
                "latency_ms": round(
                    (now - request.enqueued_monotonic) * 1e3, 3
                ),
            }
            if cache is not None:
                # before resolve: the client owns the resolved dict, the
                # cache copies its payload fields out of this one
                cache.store(
                    request.tenant, request.text, bank.version,
                    svc._score_impl, svc._precision, response,
                    n_tokens=request.n_tokens,
                )
            svc._tenant_count(request.tenant, "served")
            request.future.resolve(response)
            trace = request.trace
            if trace is not None:
                # the four stage histograms partition enqueued→resolved
                # exactly (docs/observability.md latency decomposition)
                trace.resolved = now
                if trace.coalesced is not None and trace.enqueued is not None:
                    tel.histogram("serve.queue_wait_s").observe(
                        trace.coalesced - trace.enqueued
                    )
                if trace.dispatched is not None and trace.coalesced is not None:
                    tel.histogram("serve.pack_s").observe(
                        trace.dispatched - trace.coalesced
                    )
                if trace.device_done is not None and trace.dispatched is not None:
                    tel.histogram("serve.device_s").observe(
                        trace.device_done - trace.dispatched
                    )
                if trace.device_done is not None:
                    tel.histogram("serve.resolve_s").observe(
                        now - trace.device_done
                    )
                svc._finish_trace(request, STATUS_OK)
        tap = svc._shadow_tap
        if tap is not None:
            # after resolution, so shadow sampling never adds to client
            # latency; the tap only enqueues copies, and a raising tap
            # is counted — never client-visible (bankops/shadow.py)
            try:
                tap([request.text for request, _ in chunk], probs, bank)
            except Exception:
                tel.counter("bank.shadow_errors").inc()
                logger.exception(
                    "shadow tap failed (active path unaffected)"
                )


class BucketedDispatcher(Dispatcher):
    """PR 4's strategy: route each live request to the smallest warmed
    (rows, length) bucket covering its token count and pad every chunk
    to the warmed block shape — a served score is bitwise-identical to
    the offline score of the same text."""

    def _dispatch_live(
        self,
        live: List[_Request],
        seqs: List[List[int]],
        bank: _BankVersion,
    ) -> None:
        svc = self.service
        groups: Dict[int, List[Tuple[_Request, List[int]]]] = {}
        for request, seq in zip(live, seqs):
            groups.setdefault(self._bucket_for(len(seq)), []).append(
                (request, seq)
            )
        for length in sorted(groups):
            rows = svc._rows_by_length[length]
            group = groups[length]
            for start in range(0, len(group), rows):
                if svc._killed.is_set():
                    return  # abandoned — the kill sweep takes over
                self._score_bucket_chunk(
                    group[start : start + rows], bank, rows, length
                )

    def _pad_bucket(
        self,
        chunk: Sequence[Tuple[_Request, List[int]]],
        rows: int,
        length: int,
    ) -> Dict[str, Any]:
        """The warmed (rows, length) block for one chunk — `_pad_block`
        layout, mesh-sharded when the predictor carries a mesh."""
        svc = self.service
        sample = _pad_block(
            [seq for _, seq in chunk], rows,
            svc.predictor.encoder.pad_id, length,
        )
        if svc.predictor.mesh is not None:
            from ..parallel.mesh import shard_batch

            sample = shard_batch(sample, svc.predictor.mesh)
        return sample

    def _score_bucket_chunk(
        self,
        chunk: Sequence[Tuple[_Request, List[int]]],
        bank: _BankVersion,
        rows: int,
        length: int,
    ) -> None:
        svc = self.service
        self._score_chunk(
            chunk, bank,
            sample=self._pad_bucket(chunk, rows, length),
            occupancy_rows=rows,
            padded_tokens=rows * length,
            real_tokens=sum(
                min(len(seq), length) for _, seq in chunk
            ),
            score_fn=svc.predictor._score_fn,
            shape=f"bucket:{rows}x{length} fill={len(chunk)}/{rows}",
            program_key=lambda: (
                svc.predictor.bucket_program_key(rows, length)
            ),
        )

    def _bucket_for(self, n_tokens: int) -> int:
        """Smallest warmed bucket covering the token count (over-long
        texts truncate into the largest bucket, matching the offline
        collator's ``seq[:length]``)."""
        for length in self.service._lengths:
            if length >= n_tokens:
                return length
        return self.service._lengths[-1]


class CascadeDispatcher(BucketedDispatcher):
    """Two-tier quantized cascade (docs/quantized_serving.md): every
    micro-batch scores on the int8 tier first, and only rows whose
    max-anchor probability lands inside the ``[cascade_low,
    cascade_high]`` uncertainty band (inclusive) are re-dispatched — at
    the SAME warmed (rows, length) shape — to the fp32 program.
    Confident negatives and positives short-circuit with their int8
    scores; in-band rows resolve with fp32 scores bitwise-equal to the
    bucketed strategy's.

    Inherits the bucketed pull/coalesce/bucket-routing wholesale and
    every base-class semantic: deadline-at-pull, shed/drain/hard-kill,
    retry/dead-letter per device call (the rescore fires its own
    ``serve.cascade`` fault point, so a failing fp32 tier dead-letters
    only the in-band sub-chunk), ONE bank snapshot spanning both tiers
    of a batch, and the trace waypoints — the ``dispatched`` waypoint's
    shape label carries a tier tag, stamped per device call (an in-band
    row's trace shows the fp32 dispatch that produced its score).

    The tier split is observable: ``serve.cascade_shortcircuit`` /
    ``serve.cascade_rescored`` count rows per exit, telemetry-report
    derives ``serve.cascade_rescore_rate``, and each tier compiles
    under its own program-registry scope (``score_int8`` vs ``score``)
    so per-tier device time and roofline gauges stay separable."""

    def _score_bucket_chunk(
        self,
        chunk: Sequence[Tuple[_Request, List[int]]],
        bank: _BankVersion,
        rows: int,
        length: int,
    ) -> None:
        svc = self.service
        predictor = svc.predictor
        tel = svc._tel
        probs = self._device_call(
            chunk, bank,
            sample=self._pad_bucket(chunk, rows, length),
            score_fn=predictor._int8_score_fn,
            params=predictor.int8_params,
            shape=(
                f"bucket:{rows}x{length} fill={len(chunk)}/{rows} tier=int8"
            ),
            program_key=lambda: predictor.int8_program_key(rows, length),
        )
        if probs is None:
            return  # dead-lettered or killed: nothing left to resolve
        self._finalize_batch(
            len(chunk), occupancy_rows=rows,
            padded_tokens=rows * length,
            real_tokens=sum(min(len(seq), length) for _, seq in chunk),
        )
        low, high = getattr(predictor, "cascade_band", (0.3, 0.7))
        best = probs.max(axis=1) if probs.size else np.zeros(len(chunk))
        in_band = [i for i, b in enumerate(best) if low <= b <= high]
        band_set = set(in_band)
        confident = [i for i in range(len(chunk)) if i not in band_set]
        if confident:
            tel.counter("serve.cascade_shortcircuit").inc(len(confident))
            self._resolve_scored(
                [chunk[i] for i in confident], probs[confident], bank
            )
        if not in_band:
            return
        tel.counter("serve.cascade_rescored").inc(len(in_band))
        sub = [chunk[i] for i in in_band]
        rescored = self._device_call(
            sub, bank,
            sample=self._pad_bucket(sub, rows, length),
            score_fn=predictor._score_fn,
            shape=(
                f"bucket:{rows}x{length} fill={len(sub)}/{rows} tier=fp32"
            ),
            program_key=lambda: predictor.bucket_program_key(rows, length),
            fault_name="serve.cascade",
        )
        if rescored is None:
            return  # the in-band sub-chunk dead-lettered (or killed)
        self._finalize_batch(
            len(sub), occupancy_rows=rows,
            padded_tokens=rows * length,
            real_tokens=sum(min(len(seq), length) for _, seq in sub),
        )
        self._resolve_scored(sub, rescored, bank)


class RaggedDispatcher(Dispatcher):
    """PR 8's strategy: coalesce by token budget, not rows-per-bucket —
    the pull is packed into as few fixed-``[1, token_budget]`` batches
    as the greedy in-order packer allows, and ONE warm segment-masked
    program serves any length mix (docs/ragged_serving.md)."""

    def _dispatch_live(
        self,
        live: List[_Request],
        seqs: List[List[int]],
        bank: _BankVersion,
    ) -> None:
        svc = self.service
        budget, max_rows = svc._token_budget, svc._max_rows
        for pack in pack_token_budget(
            [len(seq) for seq in seqs], budget, max_rows
        ):
            if svc._killed.is_set():
                return  # abandoned — the kill sweep takes over
            chunk = [(live[i], seqs[i]) for i in pack]
            real_tokens = sum(min(len(seq), budget) for _, seq in chunk)
            self._score_chunk(
                chunk, bank,
                sample=collate_ragged(
                    [seq for _, seq in chunk], budget, max_rows,
                    svc.predictor.encoder.pad_id,
                ),
                occupancy_rows=max_rows,
                padded_tokens=budget,
                real_tokens=real_tokens,
                score_fn=svc.predictor._ragged_score_fn,
                shape=f"pack:{real_tokens}/{budget}",
                program_key=lambda: svc.predictor.ragged_program_key(),
            )


class _SealedPack:
    """One sealed pack in the admission→device handoff: the rows, the
    collated sample (already copied off the page table), the padding
    ledger numerator, and the ONE bank snapshot the whole pack serves
    from."""

    __slots__ = ("chunk", "sample", "real_tokens", "bank")

    def __init__(self, chunk, sample, real_tokens, bank) -> None:
        self.chunk = chunk
        self.sample = sample
        self.real_tokens = real_tokens
        self.bank = bank


class ContinuousDispatcher(Dispatcher):
    """Continuous batching: persistent admission into the in-flight
    pack (docs/serving.md, "Continuous admission").

    Two threads replace the pull-then-seal loop:

    * the service's batcher thread runs the **admission loop**: it pops
      each request the moment it arrives (deadline checked at the pop —
      the same expire-at-pull semantics as the other strategies),
      encodes it, and writes it straight into the open pack on the
      reusable :class:`PackSlotAllocator` page table.  The pack seals
      when it is full (budget or rows) or when its oldest row has
      waited ``max_wait_ms``, and is handed to
    * a **device worker thread**, which scores sealed packs through the
      shared :meth:`Dispatcher._score_chunk` core (same fault point,
      retry, dead-letter, trace and ledger semantics).

    While pack N is on device the admission loop keeps filling pack
    N+1 — ``serve.pack_topups`` counts exactly those overlapped
    admissions — so a request's queue wait is the pop latency, not a
    device round-trip.  The handoff queue holds at most one sealed
    pack: one pack on device + one sealed + one filling bounds memory
    and keeps backpressure honest (when all three are full, requests
    age in the service queue and expire at the pop, never inside a
    pack).

    Hard-kill and drain keep the service's contract: a kill abandons
    the open pack, the handoff, and the on-device pack unresolved (all
    still visible to ``take_unresolved`` via the service's in-flight
    list, which this strategy maintains incrementally); a drain seals
    and finishes the open pack — it is pulled work — then sheds the
    queue with ``"drain"``.
    """

    def __init__(self, service) -> None:
        super().__init__(service)
        predictor = service.predictor
        self._token_budget = service._token_budget
        self._max_rows = service._max_rows
        self._alloc = PackSlotAllocator(
            self._token_budget, self._max_rows, predictor.encoder.pad_id,
            share_prefixes=bool(service.config.prefix_share),
        )
        # admission-thread-only state (never touched by the worker)
        self._open: List[Tuple[_Request, List[int]]] = []
        self._open_tenant: str = DEFAULT_TENANT
        self._flush_at: Optional[float] = None
        self._slots_reported = 0
        self._aliased_rows_reported = 0
        self._aliased_tokens_reported = 0
        # cross-thread state: plain objects with their own synchronization
        self._handoff: "queue.Queue[Optional[_SealedPack]]" = queue.Queue(
            maxsize=1
        )
        self._device_busy = threading.Event()
        self._worker: Optional[threading.Thread] = None

    @property
    def alive(self) -> bool:
        worker = self._worker
        if worker is None:
            return True  # not started yet (construction window)
        # a worker that exited outside a drain/kill is a dead replica —
        # the admission loop may still spin, but nothing scores
        return worker.is_alive() or self.service._draining.is_set()

    def run(self) -> None:
        svc = self.service
        worker = threading.Thread(
            target=self._device_loop,
            name="memvul-serve-device",
            daemon=True,
        )
        # start-then-publish: ``alive`` treats a None worker as healthy
        # (construction window), but a published-yet-unstarted thread
        # would read as dead to a concurrent health probe
        worker.start()
        self._worker = worker
        while not svc._draining.is_set():
            request = None
            with svc._cond:
                if svc._queue:
                    request = svc._queue.popleft()
                    svc._tel.gauge("serve.queue_depth").set(len(svc._queue))
                else:
                    timeout = 0.05
                    if self._flush_at is not None:
                        timeout = min(
                            timeout,
                            max(self._flush_at - time.monotonic(), 0.0),
                        )
                    if timeout > 0:
                        svc._cond.wait(timeout)
            if request is not None:
                self._admit(request)
                if svc._killed.is_set():
                    return  # abandon — the kill sweep takes over
            else:
                # idle liveness tick, OUTSIDE the queue lock (heartbeat
                # may write HEARTBEAT.json, rate-limited) — same
                # contract as the pull loop's idle wait
                svc._maybe_sample_hbm()
                svc._tel.heartbeat()
            if (
                self._open
                and self._flush_at is not None
                and time.monotonic() >= self._flush_at
            ):
                self._seal_and_submit()
                if svc._killed.is_set():
                    return
        # drain: the admitted-but-unsealed pack is pulled work — it
        # finishes (the trainer's finish-the-step contract)
        if not svc._killed.is_set() and self._open:
            self._seal_and_submit()
        self._stop_worker(worker)
        if svc._killed.is_set():
            return  # a killed worker resolves nothing
        svc._shed_queue(STATUS_DRAIN)
        svc._tel.event("serve_drained")
        svc._tel.heartbeat(force=True)

    # -- admission loop (service batcher thread) -------------------------------

    def _admit(self, request: _Request) -> None:
        """One pop → one page-table write.  Deadline-at-pull happens
        here: a request that expired while queued resolves
        ``"deadline"`` and never touches the pack."""
        svc = self.service
        now = time.monotonic()
        if (
            request.deadline_monotonic is not None
            and now > request.deadline_monotonic
        ):
            svc._finish_unserved(request, STATUS_DEADLINE)
            return
        seq = svc.predictor.encoder.encode_many([request.text])[0]
        svc._count_truncated([request], [seq])
        request.n_tokens = len(seq)  # the cache's tokens-saved ledger
        # in-flight the moment it leaves the queue: a hard kill's sweep
        # must find popped-but-unresolved requests wherever they sit —
        # open pack, handoff, or on device
        with svc._cond:
            svc._inflight.append(request)
        if request.trace is not None:
            # admission into the pack IS the coalesce waypoint — with
            # continuous admission, enqueued→coalesced (queue_wait) is
            # the pop latency, decoupled from the device round-trip
            request.trace.coalesced = now
        if self._open and request.tenant != self._open_tenant:
            # a pack serves ONE tenant's bank snapshot — a tenant switch
            # seals the open pack rather than mixing snapshots in-flight
            self._seal_and_submit()
            if svc._killed.is_set():
                return
        row = self._alloc.admit(seq)
        if row is None:
            self._seal_and_submit()
            if svc._killed.is_set():
                return
            row = self._alloc.admit(seq)
            assert row is not None, "cap-length request must fit an empty pack"
        if self._device_busy.is_set():
            # the decoupling at work: this request joined pack N+1 while
            # pack N was on device — it never waited a round-trip
            svc._tel.counter("serve.pack_topups").inc()
        if not self._open:
            self._flush_at = (
                time.monotonic() + svc.config.max_wait_ms / 1000.0
            )
            self._open_tenant = request.tenant
        self._open.append((request, seq))
        if self._alloc.rows >= self._max_rows:
            self._seal_and_submit()

    def _seal_and_submit(self) -> None:
        """Seal the open pack: snapshot the bank (ONE per micro-batch —
        the no-torn-mix guarantee), copy the sample off the page table,
        recycle the slots, and hand the pack to the device worker.
        Blocks — in short, kill-aware steps — only when a sealed pack is
        already waiting behind the one on device."""
        if not self._open:
            return
        svc = self.service
        # the open pack is single-tenant by construction (_admit seals on
        # a tenant switch), so ONE per-tenant snapshot covers it
        bank = svc._bank_for(self._open_tenant)
        chunk, self._open = self._open, []
        self._flush_at = None
        sample = self._alloc.sample()
        real_tokens = self._alloc.real_tokens
        self._alloc.reset()
        reused = self._alloc.slots_reused - self._slots_reported
        if reused:
            self._slots_reported = self._alloc.slots_reused
            svc._tel.counter("serve.pack_slots_reused").inc(reused)
        aliased = self._alloc.rows_aliased - self._aliased_rows_reported
        if aliased:
            # prefix-share (serving.prefix_share): rows that reused an
            # already-written identical segment instead of paying tokens
            self._aliased_rows_reported = self._alloc.rows_aliased
            svc._tel.counter("serve.prefix_rows_aliased").inc(aliased)
        saved = self._alloc.tokens_aliased - self._aliased_tokens_reported
        if saved:
            self._aliased_tokens_reported = self._alloc.tokens_aliased
            svc._tel.counter("serve.prefix_tokens_saved").inc(saved)
        if svc._trace_enabled:
            batch = next(svc._batch_seq)
            for request, _ in chunk:
                if request.trace is not None:
                    request.trace.batch = batch
        item = _SealedPack(chunk, sample, real_tokens, bank)
        while True:
            if svc._killed.is_set():
                return  # abandon unresolved; the sweep accounts them
            try:
                self._handoff.put(item, timeout=0.05)
                return
            except queue.Full:
                continue  # backpressure: device + handoff both full

    def _stop_worker(self, worker: threading.Thread) -> None:
        """Deliver the shutdown sentinel behind any still-queued pack,
        then wait for the worker to finish it."""
        svc = self.service
        while worker.is_alive():
            if svc._killed.is_set():
                # a killed worker exits on its own killed checks; if the
                # handoff is full, the queued pack wakes it
                try:
                    self._handoff.put_nowait(None)
                except queue.Full:
                    pass
                break
            try:
                self._handoff.put(None, timeout=0.05)
                break
            except queue.Full:
                continue
        worker.join(timeout=30.0)

    # -- device worker thread --------------------------------------------------

    def _device_loop(self) -> None:
        svc = self.service
        while True:
            try:
                item = self._handoff.get(timeout=0.5)
            except queue.Empty:
                if svc._killed.is_set():
                    return
                continue
            if item is None:
                return  # drain sentinel
            if svc._killed.is_set():
                return  # abandon unresolved (still in the in-flight list)
            self._device_busy.set()
            try:
                self._score_chunk(
                    item.chunk, item.bank,
                    sample=item.sample,
                    occupancy_rows=self._max_rows,
                    padded_tokens=self._token_budget,
                    real_tokens=item.real_tokens,
                    score_fn=svc.predictor._ragged_score_fn,
                    shape=f"pack:{item.real_tokens}/{self._token_budget}",
                    program_key=lambda: svc.predictor.ragged_program_key(),
                )
            finally:
                self._device_busy.clear()
            if svc._killed.is_set():
                return  # keep the in-flight list visible for the sweep
            with svc._cond:
                svc._inflight = [
                    r for r in svc._inflight if not r.future.done()
                ]


_DISPATCHERS = {
    "bucketed": BucketedDispatcher,
    "ragged": RaggedDispatcher,
    "continuous": ContinuousDispatcher,
    "cascade": CascadeDispatcher,
}


def make_dispatcher(service) -> Dispatcher:
    """The strategy for the service's predictor ``score_impl`` —
    ``bucketed`` (PR 4), ``ragged`` (PR 8), ``continuous`` (PR 12) or
    ``cascade`` (docs/quantized_serving.md).  The predictor has already
    validated the knob; this is the belt-and-braces for duck-typed test
    fakes."""
    impl = service._score_impl
    try:
        return _DISPATCHERS[impl](service)
    except KeyError:
        raise ValueError(
            f"unknown score_impl {impl!r} "
            f"(known: {sorted(_DISPATCHERS)})"
        ) from None
