"""Multi-replica request router — the scale-out serving tier.

PR 4's :class:`~memvul_tpu.serving.service.ScoringService` is one
predictor on one device: a hard throughput ceiling and a single point
of failure.  The replica tier runs N services (serving/replica.py, one
per assigned local device; each host of a multi-host job runs its own
fleet over ``jax.local_devices()``) behind this router, which owns the
three fleet problems a single service never had:

* **load balancing** — a routing decision reads live replica queue
  depths and picks the least-loaded healthy, accepting replica
  (preferring ones serving the request's pinned bank version).  That
  is ALL a routing decision may do: the
  ``lint_no_blocking_in_handler`` tool rejects ``predict*``/``sleep``/
  scoring calls inside any ``*Router`` class, the same discipline the
  HTTP handlers live under — dispatch selects a queue, every heavy
  operation happens on a replica's own threads or the control plane;
* **health-gated membership** — a monitor thread runs each replica's
  :meth:`~memvul_tpu.serving.replica.Replica.check_health` (missed
  heartbeats, repeated dead-lettered batches, a dead batcher thread),
  evicts unhealthy replicas from routing, drains and restarts them
  through the shared :class:`~memvul_tpu.resilience.retry.RetryPolicy`,
  and **re-enqueues** every request a dead replica still owed onto a
  surviving one — a client sees a retry, never a hang;
* **rolling bank swaps** — :func:`rolling_swap` extends the single
  service's no-torn-snapshot invariant to the fleet: each request is
  pinned at admission to the fleet's active bank version, replicas are
  swapped one at a time (stop routing → drain its queue → encode +
  pre-warm + install at the NEW fleet version → readmit), and the
  fleet version advances only after every replica serves it.  Every
  response therefore carries exactly one bank version; a restarted
  replica re-installs the fleet's current bank before readmission so a
  death mid-rollout cannot resurrect the old bank.

Router metrics (``router.*``) live in the process-wide registry;
per-replica ``serve.*`` counters live in each replica's own registry —
the fleet-wide invariant ``Σ served + Σ shed + Σ errors == Σ requests``
is a sum over replica registries (docs/serving.md lists the names).
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence

from ..telemetry import get_registry
from .replica import (
    REPLICA_DEAD,
    REPLICA_HEALTHY,
    REPLICA_SWAPPING,
    REPLICA_UNHEALTHY,
    Replica,
    ReplicaDead,
)
from .service import (
    STATUS_DEADLINE,
    STATUS_DRAIN,
    STATUS_ERROR,
    STATUS_OK,
    ScoreFuture,
)
from .tenancy import DEFAULT_TENANT

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Fleet-management knobs; defaults mirror ``config.SERVING_DEFAULTS``
    (the JSON-facing view)."""

    heartbeat_timeout_s: float = 10.0  # missed-heartbeat eviction threshold
    max_batch_errors: int = 3     # consecutive dead-letters before eviction
    monitor_interval_s: float = 0.25  # health-check cadence
    max_reroutes: int = 2         # re-enqueue attempts after replica failures
    auto_restart: bool = True     # restart evicted/dead replicas
    restart_drain_timeout_s: float = 5.0


@dataclasses.dataclass
class _RoutedRequest:
    """The router's own record of one client request — it outlives any
    single replica's ``_Request`` so a death can re-enqueue it."""

    rid: int
    text: str
    deadline_ms: Optional[float]
    deadline_monotonic: Optional[float]
    future: ScoreFuture
    pinned_version: int
    tenant: Optional[str] = None
    attempts: int = 0


class ReplicaRouter:
    """Load-balancing dispatch over a fleet of :class:`Replica`\\ s.

    The public surface mirrors :class:`ScoringService` (``submit`` /
    ``queue_depth`` / ``bank_version`` / ``draining`` /
    ``health_summary`` / ``request_drain`` / ``drain``) so the HTTP
    front end and the clients serve either without knowing which.
    """

    def __init__(
        self,
        replicas: Sequence[Replica],
        config: Optional[RouterConfig] = None,
        retry_policy=None,
        registry=None,
    ) -> None:
        if not replicas:
            raise ValueError("a router needs at least one replica")
        self.replicas: List[Replica] = list(replicas)
        # scale-down keeps retired members here: their registries (and
        # therefore their counters) survive, so the fleet-wide counter
        # invariant still sums over every request ever admitted
        self.retired_replicas: List[Replica] = []
        self.config = config or RouterConfig()
        self.retry_policy = retry_policy
        self._tel = registry if registry is not None else get_registry()
        self._lock = threading.Lock()
        self._rid = itertools.count(1)
        self._rr = itertools.count()  # round-robin tie-break cursor
        # per-replica map of routed requests awaiting their inner future
        self._outstanding: Dict[str, Dict[int, _RoutedRequest]] = {
            r.name: {} for r in self.replicas
        }
        self._draining = threading.Event()
        self._swap_lock = threading.Lock()  # one rolling swap at a time
        self._active_version = max(r.bank_version for r in self.replicas)
        # the fleet's current bank content, for re-install on restart
        # (None = the factory-built bank is still current), plus its
        # provenance so a restart re-stamps the same source/store id
        self._bank_instances: Optional[List[Dict]] = None
        self._bank_source: str = "rolling_swap"
        self._bank_store_version: Optional[str] = None
        # per-tenant fleet bank content + provenance + fleet version,
        # for re-install on restart/spawn (serving/tenancy.py): a fresh
        # replica carries only the factory default bank, so every named
        # tenant's bank must be re-rolled onto it before readmission
        self._tenant_banks: Dict[str, tuple] = {}
        self._shadow_tap = None  # re-attached onto autoscaler-spawned members
        self._default_deadline_ms = self.replicas[0].service.default_deadline_ms
        self._recovering: Dict[str, bool] = {}
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="memvul-router-monitor", daemon=True
        )
        self._monitor.start()
        self._tel.gauge("router.replicas").set(len(self.replicas))
        self._tel.gauge("router.bank_version").set(self._active_version)
        self._tel.event("router_start", replicas=len(self.replicas))

    # -- ScoringService-compatible surface ------------------------------------

    def _members(self) -> List[Replica]:
        """A point-in-time copy of the live membership — every iteration
        uses this so the autoscaler's admit/retire (which mutate
        ``self.replicas`` under the lock) can never corrupt a reader
        mid-walk."""
        with self._lock:
            return list(self.replicas)

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    @property
    def queue_depth(self) -> int:
        return sum(r.queue_depth for r in self._members())

    @property
    def bank_version(self) -> int:
        return self._active_version

    @property
    def default_deadline_ms(self) -> float:
        return self._default_deadline_ms

    # -- shadow tap (bankops/shadow.py) ---------------------------------------

    def set_shadow_tap(self, tap) -> None:
        """Fan one shadow tap out to every replica (each replica
        re-attaches it across its own restarts)."""
        self._shadow_tap = tap
        for replica in self._members():
            replica.set_shadow_tap(tap)

    def clear_shadow_tap(self) -> None:
        self._shadow_tap = None
        for replica in self._members():
            replica.clear_shadow_tap()

    def health_summary(self) -> Dict[str, Any]:
        """The /healthz body for a fleet: drain state, total backlog,
        active bank version, and the per-replica health rows — an
        external probe can tell "degraded fleet" (some unhealthy
        members) from "healthy"."""
        draining = self._draining.is_set()
        members = [r.summary() for r in self._members()]
        healthy = sum(1 for m in members if m["state"] == REPLICA_HEALTHY)
        if draining:
            status = "draining"
        elif healthy == len(members):
            status = "ok"
        elif healthy > 0:
            status = "degraded"
        else:
            status = "unavailable"
        return {
            "status": status,
            "draining": draining,
            "queue_depth": self.queue_depth,
            "bank_version": self._active_version,
            "replicas": {
                "total": len(members),
                "healthy": healthy,
                "members": members,
            },
        }

    # -- live exposition (GET /metrics, /tracez) --------------------------------

    def metrics_snapshots(self) -> List:
        """Snapshot parts for ``telemetry.exposition``: the router's own
        registry (``router.*``) unlabeled, plus every replica's registry
        under a ``replica`` label — the same fan-out shape as
        ``health_summary()``, so a scrape separates members exactly the
        way the on-disk ``replica-<i>/`` sinks do.  Registry reads only
        (the handler/router lint's snapshot discipline)."""
        parts: List = [({}, self._tel.snapshot())]
        for replica in self._members():
            parts.append(({"replica": replica.name}, replica.registry.snapshot()))
            service = replica.service
            if service is not None:
                programs = getattr(service.predictor, "programs", None)
                part = programs.metrics_part() if programs is not None else {}
                if part:
                    parts.append(({"replica": replica.name}, part))
        return parts

    def programs_snapshot(self) -> List[Dict[str, Any]]:
        """Fleet ``/programz``: every replica's registered programs,
        stamped with their replica name, merged newest-compile-first
        (the per-row ``compiled_wall`` orders them globally)."""
        rows: List[Dict[str, Any]] = []
        for replica in self._members():
            service = replica.service
            if service is None:
                continue
            for row in service.programs_snapshot():
                row = dict(row)
                row["replica"] = replica.name
                rows.append(row)
        rows.sort(key=lambda r: -(r.get("compiled_wall") or 0.0))
        return rows

    def recent_traces(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Fleet ``/tracez``: every replica's completed-trace ring,
        merged newest-first (one in-process monotonic clock orders them
        globally)."""
        records: List[Dict[str, Any]] = []
        for replica in self._members():
            records.extend(replica.service.recent_traces())
        records.sort(
            key=lambda r: -(r.get("waypoints", {}).get("resolved") or 0.0)
        )
        return records[: int(limit)] if limit else records

    # -- dispatch --------------------------------------------------------------

    def submit(
        self,
        text: str,
        deadline_ms: Optional[float] = None,
        tenant: Optional[str] = None,
    ) -> ScoreFuture:
        """Route one request: pin it to the fleet's active bank version,
        pick the least-loaded healthy replica, relay its response.  The
        returned future ALWAYS resolves — via the replica, via a
        re-route after a replica death, or via the router's own
        deadline/drain/exhaustion terminal statuses."""
        future = ScoreFuture()
        self._tel.counter("router.requests").inc()
        if self._draining.is_set():
            self._tel.counter("router.shed_drain").inc()
            future.resolve({"status": STATUS_DRAIN})
            return future
        now = time.monotonic()
        effective_ms = (
            self._default_deadline_ms if deadline_ms is None else deadline_ms
        )
        request = _RoutedRequest(
            rid=next(self._rid),
            text=text,
            deadline_ms=deadline_ms,
            deadline_monotonic=(
                now + effective_ms / 1000.0 if effective_ms > 0 else None
            ),
            future=future,
            pinned_version=self._active_version,
            tenant=tenant,
        )
        self._route(request)
        return future

    def _pick(self, request: _RoutedRequest) -> Optional[Replica]:
        """The routing decision: among healthy, accepting replicas —
        preferring ones serving the request's pinned bank version —
        the smallest live queue, round-robin on ties.  Selection only;
        nothing here may block or score (the router lint)."""
        candidates = [
            r for r in self._members()
            if r.state == REPLICA_HEALTHY and r.accepting.is_set()
        ]
        if not candidates:
            return None
        pinned = [
            r for r in candidates if r.bank_version == request.pinned_version
        ]
        pool = pinned or candidates
        offset = next(self._rr)
        return min(
            enumerate(pool),
            key=lambda ir: (ir[1].queue_depth, (ir[0] + offset) % len(pool)),
        )[1]

    def _route(self, request: _RoutedRequest) -> None:
        replica = self._pick(request)
        if replica is None:
            self._tel.counter("router.unroutable").inc()
            request.future.resolve({
                "status": STATUS_ERROR,
                "reason": "no healthy replica to route to",
            })
            return
        with self._lock:
            self._outstanding.setdefault(replica.name, {})[request.rid] = request
        try:
            # the router owns the journey id: a rerouted request keeps
            # its rid-derived trace id with a grown hop count, so the
            # replica-level rtrace records stitch into one story
            # (ignored by replicas whose tracing is off)
            inner = replica.submit(
                request.text, deadline_ms=self._remaining_ms(request),
                trace_id=f"r-{request.rid}", hops=request.attempts,
                tenant=request.tenant,
            )
        except ReplicaDead:
            with self._lock:
                self._outstanding.get(replica.name, {}).pop(request.rid, None)
            self._reroute(request, reason=f"{replica.name} died at submit")
            return
        self._tel.counter("router.routed").inc()
        inner.add_done_callback(
            lambda response, request=request, replica=replica: self._on_inner(
                request, replica, response
            )
        )

    def _remaining_ms(self, request: _RoutedRequest) -> Optional[float]:
        """The deadline budget left for a (re-)submission.  Explicit 0
        and unlimited requests stay unlimited; everything else hands the
        replica the original absolute deadline, not a fresh window."""
        if request.deadline_monotonic is None:
            # deadline_ms was 0/negative (explicitly unlimited) or the
            # default resolved to unlimited — keep it that way
            return request.deadline_ms if request.deadline_ms is not None else None
        return max(
            1e-3, (request.deadline_monotonic - time.monotonic()) * 1000.0
        )

    def _on_inner(
        self, request: _RoutedRequest, replica: Replica, response: Dict[str, Any]
    ) -> None:
        """Relay a replica's resolution to the client future.  A
        ``"drain"`` from a replica that is restarting (fleet not
        draining) is the replica's problem, not the client's — it
        re-routes instead of surfacing."""
        with self._lock:
            self._outstanding.get(replica.name, {}).pop(request.rid, None)
        status = response.get("status")
        if status == STATUS_DRAIN and not self._draining.is_set():
            self._reroute(request, reason=f"{replica.name} drained")
            return
        out = dict(response)
        out["replica"] = replica.name
        if request.attempts:
            # how many replica deaths this journey survived — the SLO
            # harness and the trace records split outcomes on it
            out["reroutes"] = request.attempts
        if request.future.resolve(out) and status == STATUS_OK:
            self._tel.counter("router.served").inc()

    def _reroute(self, request: _RoutedRequest, reason: str) -> None:
        """Re-enqueue a request its replica never answered.  Terminal
        statuses when re-routing is pointless: past its deadline →
        ``"deadline"``; out of attempts / fleet draining → ``"error"``
        with the cause.  Counted per cause so the SLO harness can split
        them."""
        if request.future.done():
            return
        if (
            request.deadline_monotonic is not None
            and time.monotonic() > request.deadline_monotonic
        ):
            self._tel.counter("router.reroute_deadline").inc()
            request.future.resolve({
                "status": STATUS_DEADLINE, "reroutes": request.attempts,
            })
            return
        request.attempts += 1
        if request.attempts > self.config.max_reroutes or self._draining.is_set():
            self._tel.counter("router.reroute_exhausted").inc()
            request.future.resolve({
                "status": STATUS_ERROR,
                "reason": f"re-route attempts exhausted ({reason})",
                "reroutes": request.attempts,
            })
            return
        self._tel.counter("router.reroutes").inc()
        self._route(request)

    # -- fleet health (monitor thread) -----------------------------------------

    def _monitor_loop(self) -> None:
        cfg = self.config
        while not self._draining.wait(cfg.monitor_interval_s):
            for replica in self._members():
                state = replica.check_health(
                    cfg.heartbeat_timeout_s, cfg.max_batch_errors
                )
                if state == REPLICA_SWAPPING:
                    continue  # the rolling swap owns it
                if state == REPLICA_DEAD:
                    self._recover(replica, dead=True)
                elif state == REPLICA_UNHEALTHY and cfg.auto_restart:
                    self._recover(replica, dead=False)

    def _recover(self, replica: Replica, dead: bool) -> None:
        """Evict + re-enqueue + (optionally) restart one failed replica.
        Runs on a dedicated thread per incident so one slow restart
        never blinds the monitor to the rest of the fleet."""
        with self._lock:
            if self._recovering.get(replica.name):
                return
            self._recovering[replica.name] = True
        if dead:
            self._tel.counter("router.replica_deaths").inc()
            self._tel.event("replica_dead", replica=replica.name)
            recorder = getattr(self, "incident_recorder", None)
            if recorder is not None:  # non-blocking bounded-queue put
                recorder.trigger("replica_dead", {"replica": replica.name})
        thread = threading.Thread(
            target=_recover_replica,
            args=(self, replica, dead),
            name=f"memvul-router-recover-{replica.name}",
            daemon=True,
        )
        thread.start()

    def _reclaim(self, replica: Replica, reason: str) -> None:
        """Take every routed request still charged to ``replica`` and
        re-enqueue the unresolved ones (resolved ones were popped by
        their callbacks; ``ScoreFuture``'s first-resolution-wins makes
        the race benign)."""
        with self._lock:
            taken = self._outstanding.get(replica.name, {})
            self._outstanding[replica.name] = {}
        for request in taken.values():
            if not request.future.done():
                self._reroute(request, reason=reason)

    # -- live membership (serving/autoscaler.py) -------------------------------

    def admit_replica(self, replica: Replica) -> None:
        """Add a warmed replica to the routing set.  Membership
        bookkeeping only — the heavy spawn work (factory build, AOT
        warmup, bank sync) already happened on the autoscaler's worker
        thread; nothing here may block (the router lint)."""
        if self._draining.is_set():
            raise RuntimeError("cannot admit a replica into a draining fleet")
        if self._shadow_tap is not None:
            replica.set_shadow_tap(self._shadow_tap)
        with self._lock:
            if any(r.name == replica.name for r in self.replicas):
                raise ValueError(f"{replica.name} is already a member")
            self.replicas.append(replica)
            self._outstanding.setdefault(replica.name, {})
            count = len(self.replicas)
        self._tel.gauge("router.replicas").set(count)
        self._tel.counter("router.replica_admits").inc()
        self._tel.event("replica_admit", replica=replica.name, replicas=count)

    def retire_replica(self, replica: Replica) -> None:
        """Remove a drained replica from the routing set and re-enqueue
        anything still charged to it (a retire must never lose a
        request — the counter invariant is checked over
        ``retired_replicas`` too).  The caller owns stopping routes and
        draining first (serving/autoscaler.py); this is membership
        bookkeeping only."""
        with self._lock:
            if len(self.replicas) <= 1:
                raise ValueError("cannot retire the last replica")
            try:
                self.replicas.remove(replica)
            except ValueError:
                raise ValueError(f"{replica.name} is not a member") from None
            taken = self._outstanding.pop(replica.name, {})
            self.retired_replicas.append(replica)
            count = len(self.replicas)
        for request in taken.values():
            if not request.future.done():
                self._reroute(request, reason=f"{replica.name} retired")
        self._tel.gauge("router.replicas").set(count)
        self._tel.counter("router.replica_retires").inc()
        self._tel.event("replica_retire", replica=replica.name, replicas=count)

    # -- shutdown --------------------------------------------------------------

    def request_drain(self) -> None:
        """Begin fleet drain (async-signal-safe: sets a flag)."""
        self._draining.set()

    def drain(self, timeout: Optional[float] = 30.0) -> None:
        """Graceful fleet shutdown: stop the monitor, drain every
        replica (their queued requests resolve ``"drain"`` and — with
        the fleet draining — surface to clients), close their
        registries, resolve any stragglers.  Idempotent."""
        self.request_drain()
        self._monitor.join(timeout)
        for replica in self._members():
            replica.close(timeout=timeout or 30.0)
        with self._lock:
            leftovers = [
                request
                for per_replica in self._outstanding.values()
                for request in per_replica.values()
            ]
            for per_replica in self._outstanding.values():
                per_replica.clear()
        for request in leftovers:
            request.future.resolve({"status": STATUS_DRAIN})
        self._tel.event("router_drained")

    close = drain


def _recover_replica(router: ReplicaRouter, replica: Replica, dead: bool) -> None:
    """Control-plane recovery for one failed replica: sweep + re-enqueue
    the requests it still owed, then (policy permitting) restart it
    through the shared :class:`RetryPolicy` and re-install the fleet's
    current bank before readmission.  Deliberately OUTSIDE the router
    class: a restart re-encodes and AOT-warms (``install_bank``), which
    routing decisions may never do
    (tools/lint_no_blocking_in_handler.py) — the router's monitor only
    spawns this worker."""
    tel = router._tel
    cfg = router.config
    try:
        if dead:
            # account the abandoned requests on the replica's own
            # registry (serve.errors / serve.errors_lost) so the
            # fleet-wide counter invariant survives the death
            replica.sweep_unresolved()
        router._reclaim(
            replica,
            reason=f"{replica.name} {'died' if dead else 'went unhealthy'}",
        )
        if not cfg.auto_restart or router._draining.is_set():
            return
        try:
            restart = lambda: replica.restart(
                drain_timeout_s=cfg.restart_drain_timeout_s
            )
            if router.retry_policy is not None:
                router.retry_policy.call(
                    restart, description=f"restart {replica.name}"
                )
            else:
                restart()
        except Exception as e:  # noqa: BLE001 - a replica restart may fail
            # for any predictor/device reason; the fleet must keep serving
            replica.kill(reason=f"restart failed: {e}")
            replica.sweep_unresolved()
            tel.counter("router.restart_failures").inc()
            tel.event(
                "replica_restart_failed",
                replica=replica.name,
                reason=str(e)[:200],
            )
            logger.error("%s restart failed: %s", replica.name, e)
            return
        # the rebuilt service carries the factory-built bank; sync it to
        # the fleet's current rollout BEFORE readmission — a death
        # mid-rollout cannot resurrect the old bank
        _sync_bank(router, replica)
        tel.counter("router.replica_restarts").inc()
        tel.event(
            "replica_restart", replica=replica.name, n=replica.restart_count
        )
    finally:
        with router._lock:
            router._recovering[replica.name] = False


def _sync_bank(router: ReplicaRouter, replica: Replica) -> None:
    """Install the fleet's current anchor bank on a freshly built
    replica (a restart's rebuild, or an autoscaler spawn) before it is
    (re)admitted.  Runs under the swap lock so the install serializes
    with a concurrent rolling swap.  Control-plane code — encode + AOT
    warmup happen inside ``install_bank``, which routing decisions may
    never call (tools/lint_no_blocking_in_handler.py)."""
    with router._swap_lock:
        if (
            router._bank_instances is not None
            and replica.bank_version != router._active_version
        ):
            replica.accepting.clear()
            replica.install_bank(
                router._bank_instances, version=router._active_version,
                source=router._bank_source,
                store_version=router._bank_store_version,
            )
            replica.accepting.set()
        # named tenant banks never survive a rebuild (the factory builds
        # only the default bank), so re-roll every one of them — a death
        # mid-tenant-rollout cannot leave this member serving no (or an
        # old) bank for a tenant the fleet serves (serving/tenancy.py)
        for tenant, (instances, source, store_version, version) in (
            router._tenant_banks.items()
        ):
            replica.accepting.clear()
            replica.install_bank(
                instances, version=version,
                source=source, store_version=store_version, tenant=tenant,
            )
            replica.accepting.set()


def rolling_swap(
    router: ReplicaRouter,
    anchor_instances: Iterable[Dict],
    drain_timeout_s: float = 30.0,
    poll_interval_s: float = 0.01,
    source: str = "rolling_swap",
    store_version: Optional[str] = None,
    tenant: Optional[str] = None,
) -> int:
    """Roll a new anchor bank across the fleet, one replica at a time.

    Per replica: **stop routing** to it (readmission gate), **drain**
    its private queue (in-flight work finishes on the old snapshot),
    **install** the new bank at the next fleet version (encode + AOT
    pre-warm happen inside ``swap_bank``, off every other replica's
    request path), then **readmit** it.  The fleet's active version —
    which new admissions pin to — advances only after every live
    replica serves the new bank, so no client ever observes a torn
    rollout: responses during the roll are each stamped with exactly
    one version, and once the fleet version advances, new requests
    prefer new-bank replicas.

    Control-plane code: this runs in the caller's thread (wrap it in a
    background thread to keep a CLI responsive) and deliberately lives
    OUTSIDE the router class — routing decisions may not encode, warm,
    or sleep (tools/lint_no_blocking_in_handler.py).  Returns the new
    fleet version.

    ``tenant`` scopes the roll to one named tenant's bank
    (serving/tenancy.py): the same per-replica stop-drain-install-readmit
    discipline, but the fleet's *default* active version — which new
    admissions pin to — is untouched, so a tenant rollout can never tear
    any other tenant's responses.  The tenant's fleet version advances
    independently, recorded so restarts and autoscaler spawns re-install
    the tenant bank before readmission (``_sync_bank``).
    """
    instances = list(anchor_instances)
    tel = router._tel
    named = tenant is not None and tenant != DEFAULT_TENANT
    with router._swap_lock:
        if named:
            prior = router._tenant_banks.get(tenant)
            target = prior[3] + 1 if prior is not None else 1
        else:
            target = router._active_version + 1
        tel.event(
            "rolling_swap_start", version=target,
            replicas=len(router.replicas),
            tenant=tenant if named else DEFAULT_TENANT,
        )
        with tel.span("router.rolling_swap", version=target):
            for replica in router._members():
                if replica.state == REPLICA_DEAD:
                    # the restart path re-installs the fleet bank before
                    # readmission (_recover_replica), so a dead member
                    # cannot resurrect the old bank later
                    continue
                with replica._state_lock:
                    previous_state = replica.state
                    replica.state = REPLICA_SWAPPING
                replica.accepting.clear()
                tel.event("replica_swap_begin", replica=replica.name)
                deadline = time.monotonic() + drain_timeout_s
                while (
                    replica.service.queue_depth > 0
                    and time.monotonic() < deadline
                ):
                    time.sleep(poll_interval_s)
                replica.install_bank(
                    instances, version=target,
                    source=source, store_version=store_version,
                    tenant=tenant if named else None,
                )
                with replica._state_lock:
                    replica.state = previous_state
                replica.accepting.set()
                tel.event(
                    "replica_swap_done", replica=replica.name, version=target
                )
        if named:
            router._tenant_banks[tenant] = (
                instances, source, store_version, target
            )
        else:
            router._bank_instances = instances
            router._bank_source = source
            router._bank_store_version = store_version
            router._active_version = target
    tel.counter("router.bank_swaps").inc()
    if named:
        tel.gauge(f"bank.{tenant}.version").set(target)
    else:
        tel.gauge("router.bank_version").set(target)
    tel.event(
        "rolling_swap_done", version=target,
        tenant=tenant if named else DEFAULT_TENANT,
    )
    logger.info(
        "rolling swap complete: %s at bank v%d (%d replicas)",
        f"tenant {tenant}" if named else "fleet", target,
        len(router.replicas),
    )
    return target
