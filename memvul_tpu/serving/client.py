"""Clients for the scoring service.

:class:`InprocessClient` is the synchronous wrapper tests and the
``BENCH_MICRO=serve`` microbench drive — submit + block on the future,
no sockets.  :class:`HTTPClient` is its stdlib-``urllib`` twin for the
``http.server`` front end; both return the same response dicts
(docs/serving.md), so a test written against one runs against the
other.
"""

from __future__ import annotations

import json
import socket
import urllib.error
import urllib.request
from typing import Any, Dict, Optional

from .service import ScoringService


class InprocessClient:
    """Synchronous in-process client: one ``score`` call = submit + wait."""

    def __init__(self, service: ScoringService) -> None:
        self.service = service

    def score(
        self,
        text: str,
        deadline_ms: Optional[float] = None,
        timeout_s: Optional[float] = 60.0,
        tenant: Optional[str] = None,
    ) -> Dict[str, Any]:
        return self.service.submit(
            text, deadline_ms=deadline_ms, tenant=tenant
        ).result(timeout=timeout_s)


class HTTPClient:
    """Minimal stdlib client for the JSON front end (serving/frontend.py).

    Non-2xx responses still carry the service's JSON body (shed/
    deadline/error statuses ride HTTP 5xx), so ``score`` parses and
    returns it instead of raising — status handling stays in one place
    for both client types.

    The socket timeout of a deadlined request is **derived from the
    deadline** (``deadline_ms / 1000 + deadline_slack_s``), never the
    flat ``timeout_s``: the server resolves an expired request at batch
    pull, so a correct client needs only a little slack past its own
    deadline — a fixed long timeout would leave the client parked on a
    wedged server long after the request it sent could possibly matter.
    A timed-out socket returns a ``"client_timeout"``-reasoned error
    dict instead of raising, matching the non-2xx convention above.
    """

    def __init__(
        self,
        base_url: str,
        timeout_s: float = 60.0,
        deadline_slack_s: float = 5.0,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self.deadline_slack_s = deadline_slack_s

    def _request(
        self, req: urllib.request.Request, timeout_s: Optional[float] = None
    ) -> Dict[str, Any]:
        timeout = self.timeout_s if timeout_s is None else timeout_s
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            return json.loads(e.read().decode("utf-8"))
        except (TimeoutError, socket.timeout) as e:
            return {
                "status": "error",
                "reason": f"client_timeout after {timeout:.3f}s: {e}",
            }
        except urllib.error.URLError as e:
            if isinstance(getattr(e, "reason", None), (TimeoutError, socket.timeout)):
                return {
                    "status": "error",
                    "reason": f"client_timeout after {timeout:.3f}s: {e.reason}",
                }
            raise

    def score(
        self,
        text: str,
        deadline_ms: Optional[float] = None,
        tenant: Optional[str] = None,
    ) -> Dict[str, Any]:
        payload: Dict[str, Any] = {"text": text}
        if deadline_ms is not None:
            payload["deadline_ms"] = deadline_ms
        if tenant is not None:
            payload["tenant"] = tenant
        req = urllib.request.Request(
            self.base_url + "/score",
            data=json.dumps(payload).encode("utf-8"),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        timeout = (
            deadline_ms / 1000.0 + self.deadline_slack_s
            if deadline_ms and deadline_ms > 0
            else None  # no deadline: the flat timeout_s still applies
        )
        return self._request(req, timeout_s=timeout)

    def health(self) -> Dict[str, Any]:
        return self._request(
            urllib.request.Request(self.base_url + "/healthz", method="GET")
        )
