"""Multi-tenant anchor-bank serving plane — bankops phase 3.

One warmed encoder, N per-org anchor banks.  The model is
org-agnostic (it embeds report text); what differs per organization is
the *anchor bank* — which weakness memories a report is matched
against, and how they are weighted.  So tenancy lives entirely in the
bank plane: admission resolves a tenant id (request JSON field or
``X-MemVul-Tenant`` header; absent ⇒ the default tenant, so every
pre-tenancy client keeps working unchanged) to a per-tenant
:class:`~memvul_tpu.serving.service._BankVersion` snapshot installed
from that org's PR 7 :class:`~memvul_tpu.bankops.store.BankStore`.
The dispatchers group each micro-batch by tenant and take ONE bank
snapshot per tenant group, so the single-snapshot-per-response
invariant (docs/serving.md) holds per tenant through all four
dispatch strategies.

Division of labor (MV102 — ``*Tenant*`` is a selection-only class
family):

* :class:`TenantManager` only *selects*: it parses the spec, owns the
  per-tenant ``BankStore`` handles, and records which store version is
  live.  It never encodes, warms, or installs.
* The heavy control-plane work — encode + AOT-warm + install, per
  tenant, per replica — lives in the module-level helpers below
  (:func:`configure_tenants`, :func:`install_tenant_bank`,
  :func:`promote_tenant`, :func:`demote_tenant`), the same shape as
  ``router.rolling_swap`` / ``bankops.promote``.  A fleet install goes
  through the existing gated ``rolling_swap`` (drain one replica at a
  time, never a torn version), just scoped to one tenant's bank.

The ``bank.resolve`` fault point (resilience/faults.py) arms the
resolution step itself: a raised fault errors that one request (counted
in ``serve.errors`` — the exact-counter invariant keeps summing) and
touches no other tenant.
"""

from __future__ import annotations

import logging
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..telemetry import get_registry

logger = logging.getLogger(__name__)

__all__ = [
    "DEFAULT_TENANT",
    "TenantManager",
    "TenantSpecError",
    "configure_tenants",
    "install_tenant_bank",
    "parse_tenant_spec",
    "promote_tenant",
    "demote_tenant",
    "validate_tenant_name",
]

DEFAULT_TENANT = "default"

# tenant names become telemetry label segments (serve.<tenant>.*,
# bank.<tenant>.*) and store subdir names, so the charset is strict
_TENANT_NAME_RE = re.compile(r"^[a-z0-9][a-z0-9_-]{0,63}$")


class TenantSpecError(ValueError):
    """A malformed ``--tenants`` spec or unknown tenant id."""


def validate_tenant_name(name: str) -> str:
    """Validate a single tenant name against the telemetry-label
    charset (the ``bank --tenant`` CLI path).  Returns the name."""
    name = str(name)
    if not _TENANT_NAME_RE.match(name):
        raise TenantSpecError(
            f"tenant name {name!r} must match [a-z0-9][a-z0-9_-]* "
            "(it becomes a telemetry label segment)"
        )
    return name


def parse_tenant_spec(spec: str) -> Dict[str, str]:
    """``"orgA=/path/a,orgB=/path/b"`` → ``{name: store_dir}``.

    Names are validated against the telemetry-label charset and must
    be unique; ``default`` is reserved for the archive's own golden
    bank (the back-compat tenant every untagged request maps to)."""
    out: Dict[str, str] = {}
    for clause in str(spec).split(","):
        clause = clause.strip()
        if not clause:
            continue
        name, sep, path = clause.partition("=")
        name, path = name.strip(), path.strip()
        if not sep or not path:
            raise TenantSpecError(
                f"tenant clause {clause!r} is not name=store_dir"
            )
        if not _TENANT_NAME_RE.match(name):
            raise TenantSpecError(
                f"tenant name {name!r} must match [a-z0-9][a-z0-9_-]* "
                "(it becomes a telemetry label segment)"
            )
        if name == DEFAULT_TENANT:
            raise TenantSpecError(
                f"{DEFAULT_TENANT!r} is reserved for the archive's own "
                "bank — untagged requests map to it"
            )
        if name in out:
            raise TenantSpecError(f"tenant {name!r} appears twice")
        out[name] = path
    if not out:
        raise TenantSpecError(f"tenant spec {spec!r} names no tenants")
    return out


class TenantManager:
    """Selection-only tenant registry: name → ``BankStore`` handle plus
    the live store-version bookkeeping.  All methods are dict probes
    under a lock (MV102); installs go through the module helpers."""

    def __init__(self, stores: Dict[str, Any], registry=None) -> None:
        self._stores = dict(stores)
        self._lock = threading.Lock()
        self._live: Dict[str, Optional[str]] = {}
        self._tel = registry if registry is not None else get_registry()

    @property
    def tenants(self) -> Tuple[str, ...]:
        return tuple(sorted(self._stores))

    def store(self, tenant: str):
        try:
            return self._stores[tenant]
        except KeyError:
            raise TenantSpecError(f"unknown tenant {tenant!r}") from None

    def record_live(self, tenant: str, store_version: Optional[str]) -> None:
        """Note which store version is serving for ``tenant`` (set by
        the install helpers after the swap lands)."""
        self.store(tenant)  # validate the name before recording
        with self._lock:
            self._live[tenant] = store_version

    def live_version(self, tenant: str) -> Optional[str]:
        with self._lock:
            return self._live.get(tenant)

    def summary(self) -> Dict[str, Any]:
        """The /healthz-attachable view: per-tenant live store version."""
        with self._lock:
            live = dict(self._live)
        return {
            "tenants": [
                {"tenant": name, "store_version": live.get(name)}
                for name in self.tenants
            ],
        }


def _active_instances(store) -> Tuple[List[Dict[str, Any]], str]:
    """A store's serving candidate: the ACTIVE pointer, else latest."""
    pointer = store.active()
    version = pointer["version"] if pointer else store.latest()
    if version is None:
        raise TenantSpecError(
            f"bank store {store.root} is empty — run `bank build` first"
        )
    return list(store.instances(version)), version


def install_tenant_bank(
    target,
    tenant: str,
    instances: List[Dict[str, Any]],
    source: str = "tenancy",
    store_version: Optional[str] = None,
) -> int:
    """Encode + warm + install one tenant's bank on a single service,
    or roll it across a fleet one drained replica at a time — the
    ``bankops.promote._install`` shape, scoped to one tenant."""
    if hasattr(target, "replicas"):
        from .router import rolling_swap

        return rolling_swap(
            target, instances,
            source=source, store_version=store_version, tenant=tenant,
        )
    return target.swap_bank(
        instances, source=source, store_version=store_version, tenant=tenant
    )


def configure_tenants(target, spec: str, registry=None) -> TenantManager:
    """Build the tenancy plane at serve startup: parse the spec, open
    each org's :class:`~memvul_tpu.bankops.store.BankStore`, install
    every tenant's active bank (encode + AOT-warm, off the request
    path), and attach the manager to ``target`` as ``tenant_manager``
    (the slo_monitor attachment idiom — /healthz picks it up)."""
    from ..bankops.store import BankStore

    stores = {
        name: BankStore(path)
        for name, path in parse_tenant_spec(spec).items()
    }
    manager = TenantManager(stores, registry=registry)
    for tenant in manager.tenants:
        instances, store_version = _active_instances(manager.store(tenant))
        install_tenant_bank(
            target, tenant, instances,
            source="startup", store_version=store_version,
        )
        manager.record_live(tenant, store_version)
        logger.info(
            "tenant %s: installed bank %s (%d anchors)",
            tenant, store_version, len(instances),
        )
    target.tenant_manager = manager
    return manager


def promote_tenant(
    target, manager: TenantManager, tenant: str, decision, registry=None
) -> int:
    """Gated per-tenant promotion: the standard
    :func:`~memvul_tpu.bankops.promote.promote` gate + audit trail,
    installing through the tenant-scoped fleet path.  Returns the new
    serving bank version for that tenant."""
    from ..bankops.promote import promote

    version = promote(
        target, manager.store(tenant), decision,
        registry=registry, tenant=tenant,
    )
    if decision.approved:
        manager.record_live(tenant, decision.candidate)
    return version


def demote_tenant(
    target, manager: TenantManager, tenant: str, registry=None
) -> Dict[str, Any]:
    """Per-tenant rollback to the active store version's parent."""
    from ..bankops.promote import demote

    out = demote(
        target, manager.store(tenant), registry=registry, tenant=tenant
    )
    manager.record_live(tenant, out["version"])
    return out
