"""Load generation + the SLO regression harness for the serving tier.

A serving stack is only as good as the traffic it was proven under.
This module generates **deterministic, realistic arrival processes**
(seeded; two runs of the same config submit the same schedule) and
turns one run into a parseable SLO record — the thing
``BENCH_MICRO=serve``'s router mode emits and the regression tests pin
(docs/serving.md, "SLO harness"):

* ``closed`` — N client threads in submit→wait lockstep (the classic
  closed loop: measures the service at its own pace);
* ``poisson`` — open-loop steady state: exponential inter-arrivals at
  a target rate, the memoryless baseline SLOs are written against;
* ``burst`` — on/off traffic: whole bursts land at once separated by
  idle gaps (the retry-storm / thundering-herd shape);
* ``diurnal`` — the arrival rate ramps sinusoidally between a floor
  and the peak over a configurable period (a day compressed into
  seconds for tests);
* ``slowloris`` — poisson plus a fraction of *deadline abusers*:
  requests carrying near-zero deadlines that are admitted, queue, and
  then shed — capacity held briefly and returned, the admission-
  control pressure a public endpoint actually sees;
* ``dedup`` — poisson arrivals whose texts are seeded Zipf-ish repeats
  over a small unique pool (``dedup_unique``, skew ``dedup_alpha``),
  optionally sharing a template prefix (``template_prefix``) — the
  duplicate-heavy shape vulnerability-report traffic actually has
  (boilerplate templates, resubmitted advisories), which is what the
  admission cache (serving/admission_cache.py) and the pack prefix-
  share path (``serving.prefix_share``) monetize.

The report sums outcomes **per cause** (ok / shed / deadline / drain /
error / hang) and asserts the one number that must always be zero:
``hang`` — a request whose future never resolved inside the collection
timeout.  :func:`run_slo_harness` folds in the fleet view (per-replica
served/shed/errors + utilization from each replica's own registry, the
router's counters, and the fleet-wide counter invariant) so one JSON
record answers both "how fast" and "did anything leak".
"""

from __future__ import annotations

import dataclasses
import logging
import math
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

logger = logging.getLogger(__name__)

PATTERNS = ("closed", "poisson", "burst", "diurnal", "slowloris", "dedup")


@dataclasses.dataclass(frozen=True)
class LoadConfig:
    """One load scenario.  All randomness comes from ``seed``."""

    pattern: str = "closed"
    requests: int = 256
    rps: float = 200.0            # open-loop target arrival rate
    clients: int = 4              # closed-loop concurrency
    deadline_ms: Optional[float] = None  # per-request deadline (None = default)
    seed: int = 0
    burst_size: int = 32          # burst: requests landing together
    burst_idle_s: float = 0.05    # burst: gap between bursts
    diurnal_period_s: float = 2.0  # diurnal: one full rate cycle
    diurnal_floor: float = 0.25   # diurnal: trough rate as a peak fraction
    abuser_frac: float = 0.1      # slowloris: deadline-abuser fraction
    abuser_deadline_ms: float = 1.0  # slowloris: the abusive deadline
    dedup_unique: int = 16        # dedup: distinct texts in the pool
    dedup_alpha: float = 1.1      # dedup: Zipf skew (higher = more repeats)
    template_prefix: str = ""     # dedup: shared boilerplate prepended to all
    result_timeout_s: float = 60.0  # future-collection bound (hang detector)

    def __post_init__(self) -> None:
        if self.pattern not in PATTERNS:
            raise ValueError(
                f"unknown load pattern {self.pattern!r} (known: {PATTERNS})"
            )
        if self.requests < 1:
            raise ValueError("requests must be >= 1")


def arrival_offsets(config: LoadConfig) -> List[float]:
    """Submission times in seconds from load start — deterministic in
    ``config`` (the regression property: a re-run replays the exact
    schedule).  ``closed`` has no schedule (clients self-pace)."""
    rng = random.Random(config.seed)
    n = config.requests
    if config.pattern == "closed":
        return [0.0] * n
    if config.pattern == "burst":
        offsets: List[float] = []
        t = 0.0
        while len(offsets) < n:
            offsets.extend([t] * min(config.burst_size, n - len(offsets)))
            t += config.burst_idle_s
        return offsets
    if config.pattern == "diurnal":
        # thinning-free construction: integrate a sinusoidal rate —
        # each unit-mean exponential gap is divided by the instantaneous
        # rate, so troughs stretch gaps and peaks compress them
        offsets = []
        t = 0.0
        floor = max(0.0, min(1.0, config.diurnal_floor))
        for _ in range(n):
            phase = 2.0 * math.pi * (t / config.diurnal_period_s)
            scale = floor + (1.0 - floor) * 0.5 * (1.0 - math.cos(phase))
            rate = max(config.rps * scale, 1e-6)
            t += rng.expovariate(1.0) / rate
            offsets.append(t)
        return offsets
    # poisson, slowloris and dedup share the steady-state arrival process
    offsets = []
    t = 0.0
    for _ in range(n):
        t += rng.expovariate(max(config.rps, 1e-6))
        offsets.append(t)
    return offsets


def request_texts(config: LoadConfig, texts: Sequence[str]) -> List[str]:
    """Per-request text schedule, deterministic in ``config``.  Every
    pattern but ``dedup`` cycles round-robin (maximal text diversity —
    the pre-dedup behaviour, byte-identical).  ``dedup`` draws Zipf-ish
    repeats from a ``dedup_unique``-sized pool (rank-``r`` text gets
    weight ``1/(r+1)^dedup_alpha``) and prepends ``template_prefix`` to
    every draw, so a run has a knowable exact-duplicate rate the cache
    hit-rate assertions can be written against."""
    if not texts:
        raise ValueError("load generation needs at least one text")
    n = config.requests
    if config.pattern != "dedup":
        return [texts[i % len(texts)] for i in range(n)]
    rng = random.Random(config.seed ^ 0xDED0)
    pool = [str(t) for t in texts[: max(1, min(config.dedup_unique, len(texts)))]]
    weights = [
        1.0 / float(rank + 1) ** config.dedup_alpha
        for rank in range(len(pool))
    ]
    prefix = config.template_prefix or ""
    return [
        prefix + rng.choices(pool, weights=weights)[0] for _ in range(n)
    ]


def request_deadlines(config: LoadConfig) -> List[Optional[float]]:
    """Per-request deadlines.  Only ``slowloris`` mixes in abusers —
    drawn from a seed derived from (but distinct from) the arrival
    seed, so schedules and abuser picks vary independently."""
    if config.pattern != "slowloris":
        return [config.deadline_ms] * config.requests
    rng = random.Random(config.seed ^ 0x5105)
    return [
        config.abuser_deadline_ms
        if rng.random() < config.abuser_frac
        else config.deadline_ms
        for _ in range(config.requests)
    ]


def _percentile(ordered: Sequence[float], q: float) -> Optional[float]:
    if not ordered:
        return None
    idx = int(round((len(ordered) - 1) * (q / 100.0)))
    return ordered[max(0, min(idx, len(ordered) - 1))]


class LoadGenerator:
    """Drive a ``submit(text, deadline_ms) -> ScoreFuture`` target —
    a :class:`ScoringService` or a :class:`ReplicaRouter` — through one
    :class:`LoadConfig` scenario and measure it."""

    def __init__(
        self,
        submit: Callable[..., Any],
        config: Optional[LoadConfig] = None,
    ) -> None:
        self.submit = submit
        self.config = config or LoadConfig()

    def run(self, texts: Sequence[str]) -> Dict[str, Any]:
        """Submit the scenario's requests (cycling over ``texts``) and
        collect every outcome.  Returns the load-side SLO report."""
        cfg = self.config
        if not texts:
            raise ValueError("load generation needs at least one text")
        deadlines = request_deadlines(cfg)
        schedule = request_texts(cfg, texts)
        entries: List[Dict[str, Any]] = []
        entries_lock = threading.Lock()

        def _record(i: int, t0: float, future) -> None:
            with entries_lock:
                entries.append({"i": i, "t0": t0, "future": future})

        start = time.perf_counter()
        if cfg.pattern == "closed":
            cursor = iter(range(cfg.requests))
            cursor_lock = threading.Lock()

            def _client() -> None:
                while True:
                    with cursor_lock:
                        i = next(cursor, None)
                    if i is None:
                        return
                    t0 = time.perf_counter()
                    future = self.submit(
                        schedule[i], deadline_ms=deadlines[i]
                    )
                    # closed loop: wait before taking the next request
                    try:
                        future.result(timeout=cfg.result_timeout_s)
                    except TimeoutError:
                        pass  # scored as a hang at collection below
                    _record(i, t0, future)

            threads = [
                threading.Thread(target=_client, daemon=True)
                for _ in range(max(1, cfg.clients))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        else:
            offsets = arrival_offsets(cfg)
            for i, offset in enumerate(offsets):
                delay = start + offset - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                t0 = time.perf_counter()
                _record(
                    i, t0,
                    self.submit(schedule[i], deadline_ms=deadlines[i]),
                )
        submitted_span = time.perf_counter() - start

        outcomes = {
            "ok": 0, "shed": 0, "deadline": 0, "drain": 0, "error": 0,
            "hang": 0,
        }
        latencies: List[float] = []
        last_done = start
        for entry in entries:
            try:
                response = entry["future"].result(timeout=cfg.result_timeout_s)
            except TimeoutError:
                # the one outcome that must never happen: an unresolved
                # client — surfaces as hang > 0 in the record
                outcomes["hang"] += 1
                continue
            status = response.get("status", "error")
            outcomes[status] = outcomes.get(status, 0) + 1
            now = time.perf_counter()
            last_done = max(last_done, now)
            if status == "ok":
                latencies.append(
                    response.get("latency_ms", (now - entry["t0"]) * 1e3)
                )
        duration = max(last_done - start, submitted_span, 1e-9)
        latencies.sort()
        report: Dict[str, Any] = {
            "pattern": cfg.pattern,
            "requests": cfg.requests,
            "seed": cfg.seed,
            "duration_s": round(duration, 4),
            "offered_rps": (
                round(cfg.requests / max(submitted_span, 1e-9), 2)
                if cfg.pattern != "closed" else None
            ),
            "achieved_rps": round(outcomes["ok"] / duration, 2),
            "latency_ms": {
                "p50": _percentile(latencies, 50),
                "p95": _percentile(latencies, 95),
                "p99": _percentile(latencies, 99),
                "mean": (
                    round(sum(latencies) / len(latencies), 3)
                    if latencies else None
                ),
                "max": latencies[-1] if latencies else None,
            },
            "outcomes": outcomes,
        }
        return report


def fleet_snapshot(replicas) -> Dict[str, Any]:
    """Per-replica counters + the fleet-wide invariant, read from each
    replica's own registry (serving/replica.py).  The invariant —
    ``served + shed + errors == requests`` per replica, and therefore
    fleet-wide — is the leak detector: any request a death dropped on
    the floor breaks the sum."""
    members = []
    total_served = 0
    invariant_ok = True
    for replica in replicas:
        snapshot = replica.registry.snapshot()["counters"]
        served = snapshot.get("serve.served", 0)
        shed = snapshot.get("serve.shed", 0)
        errors = snapshot.get("serve.errors", 0)
        requests = snapshot.get("serve.requests", 0)
        invariant_ok &= served + shed + errors == requests
        total_served += served
        members.append({
            "name": replica.name,
            "state": replica.state,
            "restarts": replica.restart_count,
            "bank_version": replica.bank_version,
            "heartbeat_age_s": round(replica.heartbeat_age_s(), 3),
            "requests": requests,
            "served": served,
            "shed": shed,
            "shed_overflow": snapshot.get("serve.shed_overflow", 0),
            "shed_deadline": snapshot.get("serve.shed_deadline", 0),
            "shed_drain": snapshot.get("serve.shed_drain", 0),
            "errors": errors,
            "errors_lost": snapshot.get("serve.errors_lost", 0),
        })
    for member in members:
        member["utilization"] = (
            round(member["served"] / total_served, 4) if total_served else 0.0
        )
    return {
        "replicas": members,
        "served_total": total_served,
        "invariant_ok": bool(invariant_ok),
    }


def run_slo_harness(
    target,
    texts: Sequence[str],
    config: Optional[LoadConfig] = None,
    replicas=None,
    router_registry=None,
    slo_monitor=None,
) -> Dict[str, Any]:
    """One SLO measurement: drive ``target`` (service or router) with a
    load scenario and merge the client-side report with the fleet view.
    The record is a plain JSON-able dict — ``BENCH_MICRO=serve``'s
    router mode prints it verbatim, and the regression tests assert on
    its fields rather than its prose.

    With an :class:`~memvul_tpu.serving.slo.SLOMonitor` attached to the
    target (``build.serve_from_archive`` does this) or passed
    explicitly, the record gains its ``slo`` block — availability +
    latency attainment vs the configured objectives, the multi-window
    burn rates, and the machine-readable ``scale_hint`` — evaluated
    once more after the load so the record reflects the run it sits
    in."""
    report = LoadGenerator(target.submit, config).run(texts)
    record: Dict[str, Any] = {"load": report}
    if replicas is None:
        hosts = getattr(target, "hosts", None)
        if hosts is not None:
            # cross-host target (serving/fleet.py): the invariant sums
            # over every replica of every host, live and retired
            replicas = target.members()
            record["hosts"] = {
                "total": len(hosts),
                "alive": sum(1 for h in hosts if h.alive),
                "members": [
                    {
                        "host": h.name,
                        "state": h.state,
                        "restarts": h.restart_count,
                        "heartbeat_age_s": round(h.heartbeat_age_s(), 3),
                    }
                    for h in hosts
                ],
            }
        else:
            replicas = getattr(target, "replicas", None)
            if replicas is not None:
                # a scale-down retires members but their counters still
                # belong in the invariant: every request ever admitted
                replicas = list(replicas) + list(
                    getattr(target, "retired_replicas", ())
                )
    if replicas:
        record["fleet"] = fleet_snapshot(replicas)
    registry = router_registry or getattr(target, "_tel", None)
    if registry is not None and hasattr(registry, "snapshot"):
        counters = registry.snapshot()["counters"]
        record["router"] = {
            name.split(".", 1)[1]: value
            for name, value in counters.items()
            if name.startswith("router.")
        }
        balancer = {
            name.split(".", 1)[1]: value
            for name, value in counters.items()
            if name.startswith("fleet.")
        }
        if balancer:
            record.setdefault("hosts", {})["counters"] = balancer
    # admission-cache view (serving/admission_cache.py): one cache per
    # service, so a fleet sums the per-replica registries; a bare
    # service's counters live in its own registry.  ``hits`` IS the
    # device-calls-avoided number — a hit resolves without a dispatch.
    cache_sources = (
        [r.registry for r in replicas] if replicas
        else [registry] if registry is not None else []
    )
    cache: Dict[str, Any] = {}
    for source in cache_sources:
        if not hasattr(source, "snapshot"):
            continue
        for name, value in source.snapshot()["counters"].items():
            if name.startswith("cache."):
                key = name.split(".", 1)[1]
                cache[key] = cache.get(key, 0) + value
    if cache:
        hits = cache.get("hits", 0)
        lookups = hits + cache.get("misses", 0)
        cache["hit_rate"] = round(hits / lookups, 4) if lookups else 0.0
        cache["device_calls_avoided"] = hits
        record["cache"] = cache
    scaler = getattr(target, "autoscaler", None)
    if scaler is not None:
        record["autoscaler"] = scaler.status()
    monitor = slo_monitor or getattr(target, "slo_monitor", None)
    if monitor is not None:
        monitor.tick()
        record["slo"] = monitor.status()
    return record
