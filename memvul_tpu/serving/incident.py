"""Incident flight recorder — snapshot the crash, off the request path.

When something goes wrong mid-serve — an alert fires, a replica dies,
a host is quarantined, the autoscaler refuses a spawn — the state that
explains it is spread across volatile surfaces: the TSDB window, the
``/tracez`` ring, the program registry, the autoscaler's decision
deque.  All of it evaporates with the process.  The
:class:`IncidentRecorder` freezes that state into an atomic, bounded
``<run_dir>/incidents/<ts>-<trigger>/`` bundle:

* ``manifest.json`` — trigger, detail, active alerts, health summary,
  autoscaler status + recent decisions (each with the metric window
  that justified it);
* ``metrics.json`` — the TSDB history window around the event;
* ``traces.json`` — the request trace ring;
* ``programs.json`` — the compiled-program registry snapshot.

Triggers are **non-blocking**: :meth:`IncidentRecorder.trigger` is a
bounded-queue put from whatever thread noticed the problem (router
sweep, fleet monitor, alert engine, autoscaler worker); a dedicated
worker thread does the dumping.  A full queue or a rate-limited window
drops the trigger (``incident.suppressed``) — losing a duplicate bundle
is fine, delaying a request resolution is not.  The ``incident.dump``
fault point sits in the worker so chaos tests prove a failing or hung
dump never touches the serving path.  Retention keeps the newest
``max_bundles`` bundle dirs; every file is written via
``resilience.io.atomic_write_text`` so a mid-dump kill leaves no torn
JSON.

:func:`attach_flight_recorder` is the one wiring gate (build.py and the
fleet serve path call it): with ``tsdb_cadence_s <= 0`` it constructs
NOTHING — no sampler, no alert engine, no recorder, no new metrics —
preserving the byte-identical disabled baseline.
"""

from __future__ import annotations

import json
import logging
import queue
import re
import shutil
import threading
import time
from pathlib import Path
from typing import Any, Dict, Optional, Sequence, Union

from .. import telemetry
from ..telemetry.alerts import AlertEngine, AlertRule
from ..telemetry.timeseries import MetricsSampler, TimeSeriesStore

logger = logging.getLogger(__name__)

_TRIGGER_SAFE_RE = re.compile(r"[^A-Za-z0-9_-]+")

BUNDLE_FILES = ("manifest.json", "metrics.json", "traces.json", "programs.json")


def _collect(out: Dict[str, Any], key: str, fn) -> None:
    # a half-dead target mid-incident must still yield a bundle: every
    # section degrades to an error string instead of aborting the dump
    try:
        out[key] = fn()
    except Exception as exc:
        out[key] = {"error": f"{type(exc).__name__}: {exc}"}


class IncidentRecorder:
    """Bounded, rate-limited, off-path bundle dumper.

    ``target`` is the serving object (service / router / balancer) the
    bundle snapshots; ``store``/``engine``/``autoscaler`` enrich the
    bundle when present.  ``start=False`` skips the worker thread so
    tests drive :meth:`drain` deterministically."""

    def __init__(
        self,
        target: Any,
        run_dir: Union[str, Path],
        store: Optional[TimeSeriesStore] = None,
        engine: Optional[AlertEngine] = None,
        autoscaler: Any = None,
        registry=None,
        min_interval_s: float = 30.0,
        max_bundles: int = 8,
        window_s: float = 120.0,
        queue_size: int = 8,
        start: bool = True,
    ) -> None:
        if max_bundles < 1:
            raise ValueError(f"max_bundles must be >= 1, got {max_bundles!r}")
        self.target = target
        self.incidents_dir = Path(run_dir) / "incidents"
        self.store = store
        self.engine = engine
        self.autoscaler = autoscaler
        self.min_interval_s = float(min_interval_s)
        self.max_bundles = int(max_bundles)
        self.window_s = float(window_s)
        self._tel = registry if registry is not None else telemetry.get_registry()
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, int(queue_size)))
        self._lock = threading.Lock()
        self._last_dump_wall: Optional[float] = None
        self._seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._loop, name="memvul-incident-recorder", daemon=True
            )
            self._thread.start()

    # -- trigger side (hot path) -----------------------------------------------

    def trigger(self, kind: str, detail: Optional[Dict[str, Any]] = None) -> bool:
        """Request a bundle.  Never blocks, never raises: a full queue
        increments ``incident.suppressed`` and returns False."""
        try:
            self._queue.put_nowait((str(kind), dict(detail or {}), time.time()))
            return True
        except queue.Full:
            self._tel.counter("incident.suppressed").inc()
            return False

    def on_alert(self, record: Dict[str, Any]) -> None:
        """AlertEngine listener adapter: an alert FIRE edge is a trigger."""
        self.trigger(f"alert-{record.get('rule', 'unknown')}", record)

    # -- worker side -----------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            self._handle(*item)

    def drain(self) -> int:
        """Process every queued trigger synchronously (tests; shutdown)."""
        handled = 0
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                return handled
            self._handle(*item)
            handled += 1

    def _handle(self, kind: str, detail: Dict[str, Any], wall: float) -> None:
        with self._lock:
            last = self._last_dump_wall
            if last is not None and wall - last < self.min_interval_s:
                self._tel.counter("incident.suppressed").inc()
                return
            self._last_dump_wall = wall
        try:
            from ..resilience import faults

            faults.fault_point("incident.dump")
            bundle = self._dump(kind, detail, wall)
        except Exception:
            self._tel.counter("incident.dump_errors").inc()
            logger.exception("incident dump failed (trigger=%s)", kind)
            return
        self._tel.counter("incident.dumps").inc()
        self._tel.event("incident", trigger=kind, bundle=bundle.name)
        logger.warning("incident bundle written: %s (trigger=%s)", bundle, kind)

    def _dump(self, kind: str, detail: Dict[str, Any], wall: float) -> Path:
        from ..resilience.io import atomic_write_text

        safe = _TRIGGER_SAFE_RE.sub("-", kind).strip("-") or "incident"
        with self._lock:
            self._seq += 1
            seq = self._seq
        bundle = self.incidents_dir / f"{int(wall)}-{safe}"
        if bundle.exists():
            bundle = self.incidents_dir / f"{int(wall)}-{safe}.{seq}"
        bundle.mkdir(parents=True, exist_ok=True)

        manifest: Dict[str, Any] = {
            "schema": 1,
            "trigger": kind,
            "detail": detail,
            "wall": wall,
            "window_s": self.window_s,
        }
        if self.engine is not None:
            _collect(manifest, "alerts", self.engine.status)
        health = getattr(self.target, "health_summary", None)
        if health is not None:
            _collect(manifest, "health", health)
        if self.autoscaler is not None:
            _collect(manifest, "autoscaler", self.autoscaler.status)
            _collect(
                manifest,
                "autoscaler_decisions",
                lambda: list(self.autoscaler.history)[-16:],
            )
        atomic_write_text(
            bundle / "manifest.json",
            json.dumps(manifest, indent=2, sort_keys=True, default=str),
        )

        metrics: Dict[str, Any] = {}
        if self.store is not None:
            _collect(metrics, "history", lambda: self.store.history(self.window_s))
            _collect(metrics, "stats", self.store.stats)
        atomic_write_text(
            bundle / "metrics.json",
            json.dumps(metrics, sort_keys=True, default=str),
        )

        traces: Any = []
        recent = getattr(self.target, "recent_traces", None)
        if recent is not None:
            holder: Dict[str, Any] = {}
            _collect(holder, "traces", recent)
            traces = holder["traces"]
        atomic_write_text(
            bundle / "traces.json", json.dumps(traces, default=str)
        )

        programs: Any = []
        progs = getattr(self.target, "programs_snapshot", None)
        if progs is not None:
            holder = {}
            _collect(holder, "programs", progs)
            programs = holder["programs"]
        atomic_write_text(
            bundle / "programs.json", json.dumps(programs, default=str)
        )

        self._prune()
        return bundle

    def _prune(self) -> None:
        try:
            bundles = sorted(
                (p for p in self.incidents_dir.iterdir() if p.is_dir()),
                key=lambda p: p.name,
            )
        except OSError:
            return
        for stale in bundles[: max(0, len(bundles) - self.max_bundles)]:
            shutil.rmtree(stale, ignore_errors=True)

    # -- read surface ----------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        bundles = []
        if self.incidents_dir.is_dir():
            bundles = sorted(
                p.name for p in self.incidents_dir.iterdir() if p.is_dir()
            )
        return {
            "enabled": True,
            "dir": str(self.incidents_dir),
            "min_interval_s": self.min_interval_s,
            "max_bundles": self.max_bundles,
            "window_s": self.window_s,
            "bundles": bundles,
        }

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)


def attach_flight_recorder(
    target: Any,
    run_dir: Optional[Union[str, Path]] = None,
    registry=None,
    cadence_s: float = 0.0,
    resolution_s: float = 1.0,
    retention_s: float = 600.0,
    alert_interval_s: float = 5.0,
    min_interval_s: float = 30.0,
    max_bundles: int = 8,
    window_s: float = 120.0,
    rules: Optional[Sequence[AlertRule]] = None,
) -> Any:
    """Wire sampler + alert engine (+ recorder when ``run_dir`` is set)
    onto a serving target.  The single on/off gate for the whole
    history plane: ``cadence_s <= 0`` returns the target untouched —
    nothing constructed, nothing emitted (the ``metrics_port``
    default-off discipline).  Sets ``target.metrics_sampler``,
    ``target.alert_engine``, ``target.incident_recorder`` attributes
    the frontend, report, and shutdown paths discover via getattr."""
    if cadence_s is None or float(cadence_s) <= 0:
        return target
    registry = registry if registry is not None else telemetry.get_registry()
    store = TimeSeriesStore(resolution_s=resolution_s, retention_s=retention_s)
    sampler = MetricsSampler(
        target, store=store, cadence_s=float(cadence_s), registry=registry
    )
    engine = AlertEngine(
        store, registry=registry, rules=rules, interval_s=alert_interval_s
    )
    target.metrics_sampler = sampler
    target.alert_engine = engine
    autoscaler = getattr(target, "autoscaler", None)
    if autoscaler is not None:
        # decisions now carry the metric window that justified them
        autoscaler.metrics_store = store
    if run_dir is not None:
        recorder = IncidentRecorder(
            target,
            run_dir,
            store=store,
            engine=engine,
            autoscaler=autoscaler,
            registry=registry,
            min_interval_s=min_interval_s,
            max_bundles=max_bundles,
            window_s=window_s,
        )
        target.incident_recorder = recorder
        engine.add_listener(recorder.on_alert)
        if autoscaler is not None:
            autoscaler.incident_recorder = recorder
    return target
