"""Model archives — the reference's ``model.tar.gz`` contract.

The reference trains with ``allennlp train``, which leaves a
``model.tar.gz`` (config + weights + vocabulary) in the serialization
dir; evaluation loads it back with partial config overrides
(reference: predict_memory.py:60-67).  This module keeps that contract:
an archive is a tar.gz holding

* ``config.json``     — the fully-resolved training config,
* ``weights.msgpack`` — flax-serialized parameters,
* ``tokenizer.json``  — the tokenizer state (when file-backed), OR
* ``vocab.txt``       — a bert-style wordpiece vocabulary (when the
  tokenizer was built from one; the name tells load_archive which
  constructor path to use).

``load_archive(path, overrides)`` deep-merges overrides onto the stored
config (the reference's with_fallback semantics) and reconstructs the
model + params + tokenizer.
"""

from __future__ import annotations

import dataclasses
import json
import tarfile
import tempfile
from pathlib import Path
from typing import Any, Dict, Optional, Union

from flax import serialization

from .config import loads_config, merge_overrides

ARCHIVE_NAME = "model.tar.gz"


@dataclasses.dataclass
class Archive:
    config: Dict[str, Any]
    model: Any
    params: Any
    tokenizer: Any


def save_archive(
    out_path: Union[str, Path],
    config: Dict[str, Any],
    params,
    tokenizer_file: Optional[Union[str, Path]] = None,
) -> Path:
    """Package config + params (+ tokenizer file) into ``out_path``."""
    out_path = Path(out_path)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    with tempfile.TemporaryDirectory() as tmp:
        tmp = Path(tmp)
        (tmp / "config.json").write_text(json.dumps(config, indent=2))
        (tmp / "weights.msgpack").write_bytes(serialization.to_bytes(params))
        members = ["config.json", "weights.msgpack"]
        if tokenizer_file is not None and Path(tokenizer_file).exists():
            # a bert-style vocab.txt keeps its name so load_archive knows
            # which constructor path to use; everything else is a
            # tokenizers-library (or word-vocab) JSON file
            arc = (
                "vocab.txt"
                if str(tokenizer_file).endswith(".txt")
                else "tokenizer.json"
            )
            (tmp / arc).write_text(Path(tokenizer_file).read_text())
            members.append(arc)
        with tarfile.open(out_path, "w:gz") as tar:
            for name in members:
                tar.add(tmp / name, arcname=name)
    return out_path


def load_archive(
    archive_path: Union[str, Path],
    overrides: Optional[Union[str, Dict[str, Any]]] = None,
) -> Archive:
    """Load an archive (or a serialization dir containing one), merging
    config ``overrides`` (reference: predict_memory.py:60-67)."""
    from .build import build_model, build_tokenizer  # lazy: avoids cycle

    archive_path = Path(archive_path)
    if archive_path.is_dir():
        archive_path = archive_path / ARCHIVE_NAME
    with tempfile.TemporaryDirectory() as tmp:
        tmp = Path(tmp)
        with tarfile.open(archive_path, "r:gz") as tar:
            tar.extractall(tmp, filter="data")
        config = json.loads((tmp / "config.json").read_text())
        if overrides:
            if isinstance(overrides, str):
                # the Jsonnet-subset parser, not bare json.loads: override
                # strings are often the shipped test_config_*.json files
                # verbatim (`--overrides "$(cat configs/...)"`) and those
                # carry // comments and trailing commas
                overrides = loads_config(overrides)
            config = merge_overrides(config, overrides)
        vocab_file = tmp / "vocab.txt"
        tok_file = tmp / "tokenizer.json"
        tok_cfg = dict(config.get("tokenizer") or {})
        if vocab_file.exists():
            # archived bert-style vocab — must win over any path the stored
            # config happens to mention (which may not exist on this host)
            tok_cfg.pop("tokenizer_path", None)
            tok_cfg["vocab_path"] = str(vocab_file)
        elif tok_file.exists():
            # word-level tokenizers store a plain vocab dict, wordpiece a
            # full tokenizers-library file — different constructor params
            key = "vocab_path" if tok_cfg.get("type") == "word" else "tokenizer_path"
            tok_cfg[key] = str(tok_file)
            if key == "tokenizer_path":
                tok_cfg.pop("vocab_path", None)
        tokenizer = build_tokenizer(tok_cfg)
        model = build_model(config.get("model") or {}, tokenizer.vocab_size)
        params = serialization.msgpack_restore(
            (tmp / "weights.msgpack").read_bytes()
        )
    return Archive(config=config, model=model, params=params, tokenizer=tokenizer)
