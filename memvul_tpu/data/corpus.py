"""Offline corpus pipeline: cleaning, leak guards, project-level splits.

Mirrors the reference's offline stage (utils.py:66-152) that turns the raw
issue-report dump into train/validation/test JSON artifacts:

1. drop reports missing both title and body;
2. drop positives created *after* their CVE's public disclosure — the
   temporal leak guard (reference: utils.py:85-88);
3. drop projects left without any positive (reference: utils.py:90-94);
4. normalize title/body text;
5. split 90/10 **by project**, not by report (reference: utils.py:115-152).

Operates on plain lists of dicts (one per issue report) so it has no
DataFrame dependency and streams fine at the 1.2M-report scale.
"""

from __future__ import annotations

import json
import random
from collections import defaultdict
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

from .normalize import normalize_text

POSITIVE = "1"


def extract_project(issue_url: str) -> str:
    """``https://github.com/<owner>/<repo>/issues/<n>`` → ``owner/repo``."""
    parts = issue_url.split("/")
    if len(parts) != 7:
        return "ERROR"
    return f"{parts[3]}/{parts[4]}"


def _is_positive(sample: Dict, target: str) -> bool:
    return str(sample.get(target, "0")) in ("1", "1.0")


def preprocess(
    samples: Iterable[Dict],
    target: str = "Security_Issue_Full",
    normalize: bool = True,
) -> List[Dict]:
    """Clean the raw corpus (steps 1-4 above). Returns new record dicts."""
    kept: List[Dict] = []
    for s in samples:
        title, body = s.get("Issue_Title"), s.get("Issue_Body")
        if not title and not body:
            continue
        if _is_positive(s, target):
            created = s.get("Issue_Created_At") or ""
            published = s.get("Published_Date") or ""
            if created and published and str(created) >= str(published):
                # temporal leak guard: CIR filed after CVE disclosure
                continue
        rec = dict(s)
        rec["project"] = extract_project(s.get("Issue_Url", ""))
        kept.append(rec)

    by_project: Dict[str, int] = defaultdict(int)
    for rec in kept:
        by_project[rec["project"]] += _is_positive(rec, target)
    kept = [rec for rec in kept if by_project[rec["project"]] > 0]

    if normalize:
        # batch through the parity-validated native normalizer when built
        # (thread pool over documents); falls back to the Python pass table
        from .native import normalize_batch

        titles = normalize_batch([rec.get("Issue_Title") or "" for rec in kept])
        bodies = normalize_batch([rec.get("Issue_Body") or "" for rec in kept])
        for rec, title, body in zip(kept, titles, bodies):
            rec["Issue_Title"] = title
            rec["Issue_Body"] = body
    return kept


def split_by_project(
    samples: Sequence[Dict],
    held_out_frac: float = 0.1,
    seed: Optional[int] = None,
) -> Tuple[List[Dict], List[Dict]]:
    """Project-level split: sample a fraction of *projects* (sorted for
    determinism, reference: utils.py:121-126) as the held-out set."""
    rng = random.Random(seed)
    keys = [
        s.get("project") or extract_project(s.get("Issue_Url", "")) for s in samples
    ]
    projects = sorted(set(keys))
    held = set(rng.sample(projects, k=int(len(projects) * held_out_frac)))
    train = [s for s, k in zip(samples, keys) if k not in held]
    test = [s for s, k in zip(samples, keys) if k in held]
    return train, test


def write_json(samples: Sequence[Dict], path: Union[str, Path]) -> None:
    Path(path).parent.mkdir(parents=True, exist_ok=True)
    Path(path).write_text(json.dumps(list(samples), indent=1))


def load_json(path: Union[str, Path]) -> List[Dict]:
    return json.loads(Path(path).read_text())


def write_mlm_corpus(samples: Iterable[Dict], path: Union[str, Path]) -> int:
    """One report per line ("title. body") for MLM further pretraining
    (reference: utils.py:30-37)."""
    n = 0
    with open(path, "w", encoding="utf-8") as f:
        for s in samples:
            line = f"{s.get('Issue_Title') or ''}. {s.get('Issue_Body') or ''}".strip()
            if line != ".":
                f.write(line.replace("\n", " ") + "\n")
                n += 1
    return n
