"""Offline WordPiece tokenization producing fixed-shape arrays.

The reference tokenizes through AllenNLP's PretrainedTransformerTokenizer
(bert-base-uncased wordpieces, reference: MemVul/config_memory.json:16-20).
This module provides the same wordpiece scheme via the ``tokenizers``
library, but fully offline: a vocabulary is either loaded from a local
bert-style ``vocab.txt`` or trained from the corpus itself — there is no
network dependency.

TPU-first detail: ``encode_batch`` returns *fixed-shape* padded numpy
arrays (ids / attention mask / type ids), optionally bucketed, so that the
number of distinct shapes reaching XLA stays small and compile caches hit.
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Union

import numpy as np

from ..registry import Registrable

PAD, UNK, CLS, SEP, MASK = "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"
SPECIAL_TOKENS = [PAD, UNK, CLS, SEP, MASK]

# placeholder tags produced by normalize.py — kept as atomic tokens
_TAG_TOKENS = [
    "APITAG", "CODETAG", "ERRORTAG", "FILETAG", "URLTAG", "CVETAG",
    "EMAILTAG", "MENTIONTAG", "PATHTAG", "NUMBERTAG",
]


class TextTokenizer(Registrable):
    """Base tokenizer interface: text → token ids (no padding)."""

    default_implementation = "wordpiece"

    def encode(self, text: str, max_length: Optional[int] = None) -> List[int]:
        raise NotImplementedError

    def encode_many(
        self, texts: Sequence[str], max_length: Optional[int] = None
    ) -> List[List[int]]:
        """Batch encode.  Subclasses override when they have a parallel
        batch path; the contract is exact per-text equality with
        :meth:`encode`."""
        return [self.encode(t, max_length=max_length) for t in texts]

    @property
    def vocab_size(self) -> int:
        raise NotImplementedError

    @property
    def pad_id(self) -> int:
        raise NotImplementedError

    def encode_batch(
        self,
        texts: Sequence[str],
        max_length: int,
        buckets: Optional[Sequence[int]] = None,
        pad_to: Optional[int] = None,
    ) -> Dict[str, np.ndarray]:
        """Encode and pad to a fixed shape.

        ``buckets``: allowed padded lengths (ascending); the smallest bucket
        covering the longest sequence is chosen (the last bucket caps the
        length).  ``pad_to`` forces an exact length.  Returns ``input_ids``,
        ``attention_mask``, ``token_type_ids`` of shape [B, L].
        """
        from .batching import _bucket_length, _pad_block

        encoded = self.encode_many(texts, max_length=max_length)
        if pad_to is not None:
            length = pad_to
        else:
            length = _bucket_length(encoded, buckets, max_length)
        block = _pad_block(encoded, len(encoded), self.pad_id, length)
        block["token_type_ids"] = np.zeros_like(block["input_ids"])
        return block


@TextTokenizer.register("wordpiece")
class WordPieceTokenizer(TextTokenizer):
    """BERT-style wordpiece tokenizer backed by the ``tokenizers`` library."""

    def __init__(
        self,
        vocab_path: Optional[Union[str, Path]] = None,
        tokenizer_path: Optional[Union[str, Path]] = None,
        lowercase: bool = True,
    ) -> None:
        from tokenizers import Tokenizer as _FastTokenizer

        # A real bert-style ``vocab.txt`` (e.g. bert-base-uncased's — the
        # reference's vocabulary, MemVul/config_memory.json:16-20) wins when
        # it exists on disk; otherwise fall back to a trained tokenizer.json.
        # The vocab.txt loading path is id-level parity-tested against HF's
        # BertTokenizer (tests/test_tokenizer_hf_parity.py), so dropping the
        # genuine vocab file in gives reference tokenization exactly.
        if vocab_path is not None and Path(vocab_path).exists():
            if tokenizer_path is not None:
                logging.getLogger(__name__).info(
                    "tokenizer: using bert vocab %s (tokenizer file %s ignored)",
                    vocab_path,
                    tokenizer_path,
                )
            self._tok = _bert_tokenizer_from_vocab(str(vocab_path), lowercase)
        elif tokenizer_path is not None:
            if vocab_path is not None:
                # the config NAMES the real BERT vocabulary but the file is
                # absent — the trained tokenizer is a functional substitute
                # but tokenizes differently from bert-base-uncased, so F1
                # parity with reference checkpoints is structurally
                # impossible until the genuine vocab.txt is dropped in
                logging.getLogger(__name__).warning(
                    "tokenizer: config names vocab_path=%s but that file "
                    "does NOT exist — falling back to the locally-trained "
                    "tokenizer %s. Tokenization will NOT match "
                    "bert-base-uncased; reference-checkpoint parity needs "
                    "the real vocab file (see README: 'Using the real BERT "
                    "vocabulary').",
                    vocab_path,
                    tokenizer_path,
                )
            self._tok = _FastTokenizer.from_file(str(tokenizer_path))
        elif vocab_path is not None:
            self._tok = _bert_tokenizer_from_vocab(str(vocab_path), lowercase)
        else:
            raise ValueError("need vocab_path or tokenizer_path")
        self._cls = self._tok.token_to_id(CLS)
        self._sep = self._tok.token_to_id(SEP)
        self._pad = self._tok.token_to_id(PAD)

    # -- construction helpers ------------------------------------------------

    @classmethod
    def train_from_corpus(
        cls,
        texts: Iterable[str],
        vocab_size: int = 8192,
        save_path: Optional[Union[str, Path]] = None,
        lowercase: bool = True,
    ) -> "WordPieceTokenizer":
        """Train a wordpiece vocab from raw text — the offline substitute
        for downloading bert-base-uncased's vocabulary."""
        from tokenizers import Tokenizer as _FastTokenizer
        from tokenizers.models import WordPiece as _WordPiece
        from tokenizers.trainers import WordPieceTrainer

        tok = _FastTokenizer(_WordPiece(unk_token=UNK))
        _apply_bert_pretokenization(tok, lowercase)
        trainer = WordPieceTrainer(
            vocab_size=vocab_size,
            special_tokens=SPECIAL_TOKENS + _TAG_TOKENS,
            continuing_subword_prefix="##",
        )
        tok.train_from_iterator(texts, trainer)
        _attach_bert_postprocessor(tok)
        return cls._from_fast_tokenizer(tok, save_path)

    @classmethod
    def _from_vocab_dict(
        cls,
        vocab: Dict[str, int],
        lowercase: bool,
        save_path: Optional[Union[str, Path]],
    ) -> "WordPieceTokenizer":
        from tokenizers import Tokenizer as _FastTokenizer
        from tokenizers.models import WordPiece as _WordPiece

        tok = _FastTokenizer(_WordPiece(vocab, unk_token=UNK))
        _apply_bert_pretokenization(tok, lowercase)
        _attach_bert_postprocessor(tok)
        return cls._from_fast_tokenizer(tok, save_path)

    @classmethod
    def _from_fast_tokenizer(
        cls, tok, save_path: Optional[Union[str, Path]]
    ) -> "WordPieceTokenizer":
        """Shared construction tail for every non-``__init__`` builder."""
        if save_path is not None:
            Path(save_path).parent.mkdir(parents=True, exist_ok=True)
            tok.save(str(save_path))
        self = cls.__new__(cls)
        self._tok = tok
        self._cls = tok.token_to_id(CLS)
        self._sep = tok.token_to_id(SEP)
        self._pad = tok.token_to_id(PAD)
        return self

    @classmethod
    def build_deterministic(
        cls,
        texts: Iterable[str],
        vocab_size: int = 8192,
        save_path: Optional[Union[str, Path]] = None,
        lowercase: bool = True,
    ) -> "WordPieceTokenizer":
        """Deterministic vocabulary with exact tie-breaking, for
        reproducible test/selfcheck/bench artifacts.

        The rust ``WordPieceTrainer`` counts candidates in hashmaps whose
        iteration order is randomized per process, so frequency ties
        resolve differently run to run — even the resulting vocab SIZE
        can differ — making any pipeline seeded through a freshly-trained
        tokenizer non-reproducible.  Production corpora load a fixed
        artifact instead (vocab.txt / tokenizer.json), so this only
        matters where the vocabulary is built on the fly.

        Vocabulary = specials + tag tokens + every seen character (plus
        its ``##`` continuation form, so greedy WordPiece can always
        decompose a word — no UNK fallout) + whole words ranked by
        (count desc, token asc).  Words and characters are counted
        through the SAME Bert normalizer + pre-tokenizer the runtime
        uses (NFD, accent stripping, punctuation splits), so nothing the
        encoder will ever see is missing from the vocabulary.  Same
        wordpiece runtime as the trained path; only vocabulary
        construction differs."""
        from collections import Counter

        from tokenizers import normalizers, pre_tokenizers

        norm = normalizers.BertNormalizer(lowercase=lowercase)
        pre = pre_tokenizers.BertPreTokenizer()
        counts: Counter = Counter()
        for text in texts:
            counts.update(
                w for w, _ in pre.pre_tokenize_str(norm.normalize_str(text))
            )

        vocab: Dict[str, int] = {}
        tags = [t.lower() for t in _TAG_TOKENS] if lowercase else _TAG_TOKENS
        for tok in SPECIAL_TOKENS + tags:
            vocab.setdefault(tok, len(vocab))
        chars = sorted({c for w in counts for c in w})
        for c in chars:
            vocab.setdefault(c, len(vocab))
        for c in chars:
            vocab.setdefault(f"##{c}", len(vocab))
        ranked = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
        for word, _ in ranked:
            if len(vocab) >= vocab_size:
                break
            vocab.setdefault(word, len(vocab))
        return cls._from_vocab_dict(vocab, lowercase, save_path)

    # -- interface -----------------------------------------------------------

    def encode(self, text: str, max_length: Optional[int] = None) -> List[int]:
        return self._frame(self._tok.encode(text).ids, max_length)

    def encode_many(
        self, texts: Sequence[str], max_length: Optional[int] = None
    ) -> List[List[int]]:
        """Parallel batch encode: the rust tokenizer's ``encode_batch``
        fans work across native threads (rayon, one per core), so the
        cold-pass host tokenization that caps corpus throughput on
        few-core rigs (docs/full_corpus.md) scales with the host's core
        count instead of pinning one Python thread.  Per-text output is
        byte-identical to :meth:`encode`
        (tests/test_parallel_tokenize.py)."""
        encodings = self._tok.encode_batch(list(texts))
        return [self._frame(e.ids, max_length) for e in encodings]

    def _frame(self, ids: List[int], max_length: Optional[int]) -> List[int]:
        if not ids or ids[0] != self._cls:
            ids = [self._cls] + ids + [self._sep]
        if max_length is not None and len(ids) > max_length:
            # keep [CLS] ... [SEP] framing after truncation
            ids = ids[: max_length - 1] + [self._sep]
        return ids

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()

    @property
    def pad_id(self) -> int:
        return self._pad

    @property
    def cls_id(self) -> int:
        return self._cls

    @property
    def sep_id(self) -> int:
        return self._sep

    @property
    def mask_id(self) -> int:
        return self._tok.token_to_id(MASK)

    def token_to_id(self, token: str) -> Optional[int]:
        return self._tok.token_to_id(token)

    def save(self, path: Union[str, Path]) -> None:
        self._tok.save(str(path))

    def save_vocab_txt(self, path: Union[str, Path]) -> None:
        """Write the vocabulary as a bert-style ``vocab.txt`` (one token
        per line, in id order) — the file HF's ``BertTokenizer`` and the
        reference's configs consume (MemVul/config_memory.json:16-20)."""
        ordered = sorted(self._tok.get_vocab().items(), key=lambda kv: kv[1])
        ids = [i for _, i in ordered]
        if ids != list(range(len(ordered))):
            raise ValueError(f"vocab ids are not contiguous 0..{len(ordered)-1}")
        Path(path).write_text(
            "\n".join(t for t, _ in ordered) + "\n", encoding="utf-8"
        )


@TextTokenizer.register("word")
class WordTokenizer(TextTokenizer):
    """Word-level tokenizer for the TextCNN baseline (the reference uses
    SpaCy word tokens + a GloVe vocabulary, TextCNN/config_cnn.json:31-41).
    Vocabulary is built from the corpus: index 0 = [PAD], 1 = [UNK]."""

    def __init__(
        self,
        vocab: Optional[Dict[str, int]] = None,
        vocab_path: Optional[Union[str, Path]] = None,
        lowercase: bool = True,
    ) -> None:
        if vocab is None:
            if vocab_path is None:
                raise ValueError("need vocab or vocab_path")
            vocab = json.loads(Path(vocab_path).read_text())
        self._vocab = vocab
        self._lowercase = lowercase

    @classmethod
    def train_from_corpus(
        cls,
        texts: Iterable[str],
        max_vocab: int = 50_000,
        min_count: int = 1,
        lowercase: bool = True,
        save_path: Optional[Union[str, Path]] = None,
    ) -> "WordTokenizer":
        from collections import Counter

        counts: Counter = Counter()
        for text in texts:
            counts.update(cls._split(text, lowercase))
        vocab = {PAD: 0, UNK: 1}
        for word, c in counts.most_common(max_vocab - 2):
            if c < min_count:
                break
            vocab[word] = len(vocab)
        if save_path is not None:
            Path(save_path).write_text(json.dumps(vocab))
        return cls(vocab=vocab, lowercase=lowercase)

    @staticmethod
    def _split(text: str, lowercase: bool) -> List[str]:
        import re

        if lowercase:
            text = text.lower()
        return re.findall(r"[a-zA-Z]+|[0-9]+|[^\sa-zA-Z0-9]", text)

    def encode(self, text: str, max_length: Optional[int] = None) -> List[int]:
        unk = self._vocab[UNK]
        ids = [self._vocab.get(w, unk) for w in self._split(text, self._lowercase)]
        if max_length is not None:
            ids = ids[:max_length]
        return ids or [unk]

    @property
    def vocab_size(self) -> int:
        return len(self._vocab)

    @property
    def pad_id(self) -> int:
        return self._vocab[PAD]

    @property
    def vocab_words(self) -> List[str]:
        ordered = sorted(self._vocab.items(), key=lambda kv: kv[1])
        return [w for w, _ in ordered]


def _apply_bert_pretokenization(tok, lowercase: bool) -> None:
    from tokenizers import normalizers, pre_tokenizers

    tok.normalizer = normalizers.BertNormalizer(lowercase=lowercase)
    tok.pre_tokenizer = pre_tokenizers.BertPreTokenizer()


def _attach_bert_postprocessor(tok) -> None:
    from tokenizers.processors import TemplateProcessing

    tok.post_processor = TemplateProcessing(
        single=f"{CLS} $A {SEP}",
        pair=f"{CLS} $A {SEP} $B:1 {SEP}:1",
        special_tokens=[
            (CLS, tok.token_to_id(CLS)),
            (SEP, tok.token_to_id(SEP)),
        ],
    )


def _bert_tokenizer_from_vocab(vocab_path: str, lowercase: bool):
    from tokenizers import Tokenizer as _FastTokenizer
    from tokenizers.models import WordPiece as _WordPiece

    if vocab_path.endswith(".json"):
        vocab = json.loads(Path(vocab_path).read_text(encoding="utf-8"))
    else:
        vocab = {
            line.rstrip("\n"): i
            for i, line in enumerate(
                Path(vocab_path).read_text(encoding="utf-8").splitlines()
            )
        }
    tok = _FastTokenizer(_WordPiece(vocab, unk_token=UNK))
    _apply_bert_pretokenization(tok, lowercase)
    _attach_bert_postprocessor(tok)
    return tok
