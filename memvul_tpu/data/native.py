"""ctypes binding for the native (C++) normalizer.

Design contract (see memvul_tpu/native/normalizer.cpp):

* the Python pass table in :mod:`memvul_tpu.data.normalize` is the
  *specification*; the native library is an accelerator;
* the native path is enabled only after a runtime **parity self-check**
  — a battery of representative documents run through both
  implementations must agree byte-for-byte;
* every batch additionally cross-checks a **random ~1% sample** of its
  native outputs against the Python implementation; any mismatch
  disables the native path for the rest of the process and recomputes
  the batch in Python;
* any per-document native failure (NULL return) silently falls back to
  the Python implementation.

Together these make the contract "parity-sampled": a divergence outside
the self-check battery is caught probabilistically at runtime and turns
into a slowdown, not a silent wrong result.

The shared library is built on demand with g++ (toolchain is part of the
environment); set ``MEMVUL_NATIVE=0`` to disable the native path
entirely.

Performance note: per-document cost is comparable to CPython's ``re``
(both are C regex engines); the native win is the **GIL-free thread
pool** in ``mv_normalize_batch`` — on an N-core preprocessing host the
corpus normalizes ~N× faster, which Python threads cannot do under the
GIL.  Size cutoffs (std::regex recursion safety): single-document calls
fall back to Python above 16KB; batch calls run on 64MB-stack pool
threads and fall back above 256KB — so only pathological multi-hundred-KB
bodies leave the fast path.  Non-ASCII documents always use Python (the
byte-oriented engine disagrees with unicode ``\\s``/``\\w``).
"""

from __future__ import annotations

import ctypes
import logging
import os
import random
import subprocess
import threading
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from .normalize import normalize_text

logger = logging.getLogger(__name__)

_NATIVE_DIR = Path(__file__).resolve().parent.parent / "native"
_SOURCE = _NATIVE_DIR / "normalizer.cpp"
_LIB = _NATIVE_DIR / "libmemvul_native.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_state: Optional[str] = None  # None=unknown, "ok", "disabled"
_reason: Optional[str] = None  # human text: why disabled
_kind: Optional[str] = None  # structured: env_optout | load_failed |
#   parity_failed | runtime_parity_failed (diagnosis, not control flow)

# documents exercising every pass family; native must agree with Python on
# all of them before it is trusted
_SELF_CHECK_DOCS = [
    "",
    "plain words only here",
    "Fix CVE-2021-44228 and CWE-79 please",
    "see https://cve.mitre.org/cgi-bin/cvename.cgi?name=CVE-2021-44228 now",
    "download https://example.com/file.zip or https://example.com/page",
    "```\nTraceback error: something exploded\n```",
    "run `pip install foo` then `x = compute_thing()` done",
    "[readme](docs/readme.md) and [site](https://example.com)",
    "email me at someone@example.com or ping @username now",
    "path /usr/local/lib/python3.8/site-packages/foo.py crashed",
    "NullPointerException at line 404",
    "version 1.2.3-beta4 released on 2021-06-01",
    "camelCaseIdentifier and some_function() and obj.attr.method",
    "a-very-long-hyphenated-chain-of-words",
    "<div class=\"x\"> <<tags>> <b>bold</b>",
    "*emphasis* **strong** ## heading",
    "files: report.pdf data.csv script.sh archive.zip",
    "x" * 40 + " short",
    "multi\nline\ttext\rwith\\n escapes \\r\\n here",
    "yaml\nkey: value",
]


def _build_library() -> bool:
    """Compile normalizer.cpp → libmemvul_native.so (cached by mtime)."""
    if not _SOURCE.exists():
        return False
    if _LIB.exists() and _LIB.stat().st_mtime >= _SOURCE.stat().st_mtime:
        return True
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
        str(_SOURCE), "-o", str(_LIB),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        return True
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        logger.warning("native normalizer build failed: %s", e)
        return False


def _load() -> Optional[ctypes.CDLL]:
    if not _build_library():
        return None
    try:
        lib = ctypes.CDLL(str(_LIB))
        lib.mv_normalize.restype = ctypes.c_void_p
        lib.mv_normalize.argtypes = [ctypes.c_char_p]
        lib.mv_free.argtypes = [ctypes.c_void_p]
        lib.mv_normalize_batch.restype = None
        lib.mv_normalize_batch.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_int,
        ]
        lib.mv_abi_version.restype = ctypes.c_int
        if lib.mv_abi_version() != 1:
            logger.warning("native normalizer ABI mismatch — disabled")
            return None
    except (OSError, AttributeError) as e:
        # wrong-arch / corrupt / stale .so — fall back, never crash
        logger.warning("native normalizer unusable (%s) — disabled", e)
        return None
    return lib


def _native_one(lib: ctypes.CDLL, text: str) -> Optional[str]:
    if "\x00" in text:
        return None  # the C string boundary would truncate at the NUL
    ptr = lib.mv_normalize(text.encode("utf-8", errors="replace"))
    if not ptr:
        return None
    try:
        return ctypes.cast(ptr, ctypes.c_char_p).value.decode("utf-8", "replace")
    finally:
        lib.mv_free(ptr)


def _self_check(lib: ctypes.CDLL) -> bool:
    for doc in _SELF_CHECK_DOCS:
        native = _native_one(lib, doc)
        expected = normalize_text(doc)
        if native != expected:
            logger.warning(
                "native normalizer parity self-check FAILED on %r: native=%r "
                "python=%r — native path disabled", doc[:60], native, expected,
            )
            return False
    return True


def get_native_normalizer() -> Optional[ctypes.CDLL]:
    """The parity-validated native library, or None."""
    global _lib, _state, _reason, _kind
    with _lock:
        if _state is not None:
            return _lib if _state == "ok" else None
        if os.environ.get("MEMVUL_NATIVE", "1") == "0":
            _state, _reason = "disabled", "MEMVUL_NATIVE=0 (env opt-out)"
            _kind = "env_optout"
            return None
        lib = _load()
        if lib is None:
            _state, _reason = "disabled", "library build/load failed"
            _kind = "load_failed"
            return None
        if not _self_check(lib):
            _state, _reason = "disabled", "parity self-check FAILED"
            _kind = "parity_failed"
            return None
        _lib = lib
        _state = "ok"
        logger.info("native normalizer enabled (parity self-check passed)")
        return _lib


def native_available() -> bool:
    return get_native_normalizer() is not None


def native_status() -> Dict[str, Optional[str]]:
    """Diagnostic state: ``{"state", "reason", "kind"}`` — ``kind`` is the
    STRUCTURED disable cause (env_optout | load_failed | parity_failed |
    runtime_parity_failed) so consumers branch on it, never on the
    human-readable ``reason`` text; both are None when enabled."""
    get_native_normalizer()
    return {"state": _state, "reason": _reason, "kind": _kind}


def normalize_batch(
    texts: Sequence[str],
    n_threads: Optional[int] = None,
    force_python: bool = False,
) -> List[str]:
    """Normalize many documents — native thread pool when available,
    Python fallback per document otherwise."""
    texts = list(texts)
    lib = None if force_python else get_native_normalizer()
    if lib is None or not texts:
        return [normalize_text(t) for t in texts]
    n = len(texts)
    n_threads = n_threads or min(32, os.cpu_count() or 1)
    # NUL bytes would truncate at the C-string boundary — those documents
    # are handled by the Python fallback regardless of the native result
    encoded = []
    fallback_indices = set()
    for i, t in enumerate(texts):
        if not isinstance(t, str) or "\x00" in t:
            fallback_indices.add(i)
            encoded.append(b"")
        else:
            encoded.append(t.encode("utf-8", errors="replace"))
    arr_in = (ctypes.c_char_p * n)(*encoded)
    arr_out = (ctypes.c_void_p * n)()
    lib.mv_normalize_batch(
        ctypes.cast(arr_in, ctypes.POINTER(ctypes.c_char_p)), n,
        ctypes.cast(arr_out, ctypes.POINTER(ctypes.c_void_p)), n_threads,
    )
    out: List[str] = []
    native_indices: List[int] = []
    for i, ptr in enumerate(arr_out):
        if ptr and i not in fallback_indices:
            try:
                out.append(
                    ctypes.cast(ptr, ctypes.c_char_p).value.decode("utf-8", "replace")
                )
            finally:
                lib.mv_free(ptr)
            native_indices.append(i)
        else:
            if ptr:
                lib.mv_free(ptr)
            # native refused (size/encoding limits) or the document needed
            # the NUL-safe path — authoritative Python fallback
            out.append(normalize_text(texts[i]))
    if native_indices and not _sampled_parity_ok(texts, out, native_indices):
        # drift between the native library and the Python specification —
        # disable native for the rest of the process and recompute this
        # batch authoritatively
        _disable_native(
            "sampled runtime parity check failed",
            kind="runtime_parity_failed",
        )
        return [normalize_text(t) for t in texts]
    return out


def _sampled_parity_ok(
    texts: Sequence[str], out: List[str], native_indices: List[int]
) -> bool:
    """Cross-check ~1% (min 1) of the batch's native outputs against the
    Python specification."""
    k = max(1, len(native_indices) // 100)
    sample = random.sample(native_indices, min(k, len(native_indices)))
    for i in sample:
        expected = normalize_text(texts[i])
        if out[i] != expected:
            logger.error(
                "native normalizer runtime parity FAILED on %r: native=%r "
                "python=%r", texts[i][:80], out[i][:120], expected[:120],
            )
            return False
    return True


def _disable_native(reason: str, kind: str = "runtime_parity_failed") -> None:
    global _lib, _state, _reason, _kind
    with _lock:
        _state = "disabled"
        _reason = reason
        _kind = kind
        _lib = None
    logger.warning("native normalizer disabled: %s", reason)
