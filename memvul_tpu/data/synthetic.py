"""Synthetic corpus generation for tests and benchmarks.

The reference's real corpus (1.2M issue reports + CVE/CWE databases,
README.md:8) ships via external drive links and is not part of the repo,
so the framework carries a deterministic generator producing structurally
identical artifacts: issue-report records, a CVE dict, a CWE Research-View
table, and anchors.  Every test and the benchmark harness builds on this.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

_VULN_PHRASES = [
    "buffer overflow in the parser allows remote attackers to execute code",
    "improper neutralization of input during web page generation",
    "sql injection vulnerability in the login form",
    "use after free in the renderer leads to memory corruption",
    "path traversal lets attackers read arbitrary files",
    "cross site scripting in the comment field",
    "integer overflow when decoding the length header",
    "improper authentication allows session hijacking",
]

_BENIGN_PHRASES = [
    "the build fails on windows with a linker warning",
    "documentation typo in the install guide",
    "feature request add dark mode to the settings page",
    "tests are flaky on slow machines please increase the timeout",
    "the cli prints a confusing message when the config file is missing",
    "performance regression after upgrading the compiler",
    "crash on startup when the cache directory is empty",
    "please support python three point twelve",
]

_CWE_NAMES = {
    "79": ("Cross-site Scripting", "Class"),
    "89": ("SQL Injection", "Base"),
    "119": ("Improper Restriction of Operations within the Bounds of a Memory Buffer", "Class"),
    "416": ("Use After Free", "Variant"),
    "22": ("Path Traversal", "Base"),
    "190": ("Integer Overflow or Wraparound", "Base"),
    "287": ("Improper Authentication", "Class"),
    "787": ("Out-of-bounds Write", "Base"),
}


def research_view_records() -> List[Dict[str, str]]:
    """A miniature CWE Research View table (shape of 1000.csv)."""
    ids = list(_CWE_NAMES)
    records = []
    for i, (cwe_id, (name, abstraction)) in enumerate(_CWE_NAMES.items()):
        parent = ids[0] if i else ""
        related = f"::NATURE:ChildOf:CWE ID:{parent}:VIEW ID:1000:ORDINAL:Primary::" if parent else ""
        records.append(
            {
                "CWE-ID": cwe_id,
                "Name": name,
                "Weakness Abstraction": abstraction,
                "Description": f"The product mishandles {name.lower()} conditions.",
                "Extended Description": f"Extended notes about {name.lower()}.",
                "Common Consequences": "::SCOPE:Integrity:IMPACT:Execute Unauthorized Code or Commands::",
                "Related Weaknesses": related,
            }
        )
    return records


def _body_with_length(rng: random.Random, phrases: List[str], base: str) -> str:
    """Compose an issue body with a long-tailed word count, mimicking real
    GitHub issues: lognormal with median ~100 words (≈130 wordpieces),
    ~10-15% of reports exceeding the 512-wordpiece eval cap — so the
    bucketed batcher sees a realistic mix rather than uniform shorts."""
    target = int(rng.lognormvariate(4.6, 1.0))  # median e^4.6 ≈ 100 words
    target = max(5, min(target, 2000))
    parts = [base]
    words = len(base.split())
    while words < target:
        p = rng.choice(phrases)
        parts.append(p)
        words += len(p.split())
    return " ".join(parts)


def generate_corpus(
    num_projects: int = 8,
    reports_per_project: int = 24,
    positive_rate: float = 0.25,
    seed: int = 0,
    realistic_lengths: bool = False,
) -> Tuple[List[Dict], Dict[str, Dict]]:
    """Build (issue_reports, cve_dict)."""
    rng = random.Random(seed)
    cwe_ids = list(_CWE_NAMES)
    reports: List[Dict] = []
    cve_dict: Dict[str, Dict] = {}
    cve_counter = 0
    for p in range(num_projects):
        project = f"org{p}/repo{p}"
        for i in range(reports_per_project):
            url = f"https://github.com/{project}/issues/{i}"
            positive = rng.random() < positive_rate or i == 0  # ≥1 CIR per project
            if positive:
                cve_counter += 1
                cve_id = f"CVE-2021-{10000 + cve_counter}"
                cwe = rng.choice(cwe_ids)
                phrase = rng.choice(_VULN_PHRASES)
                cve_dict[cve_id] = {
                    "CVE_ID": cve_id,
                    "CWE_ID": f"CWE-{cwe}",
                    "CVE_Description": f"{phrase} in project {project}",
                }
                body = f"{phrase} affecting version NUMBERTAG"
                if realistic_lengths:
                    body = _body_with_length(rng, _VULN_PHRASES, body)
                reports.append(
                    {
                        "Issue_Url": url,
                        "Issue_Title": f"security report {i}",
                        "Issue_Body": body,
                        "Security_Issue_Full": "1",
                        "CVE_ID": cve_id,
                        "Issue_Created_At": "2021-01-01T00:00:00Z",
                        "Published_Date": "2021-06-01T00:00:00Z",
                    }
                )
            else:
                body = rng.choice(_BENIGN_PHRASES)
                if realistic_lengths:
                    body = _body_with_length(rng, _BENIGN_PHRASES, body)
                reports.append(
                    {
                        "Issue_Url": url,
                        "Issue_Title": f"issue {i}",
                        "Issue_Body": body,
                        "Security_Issue_Full": "0",
                        "CVE_ID": "",
                        "Issue_Created_At": "2021-01-01T00:00:00Z",
                        "Published_Date": "",
                    }
                )
    return reports, cve_dict


def corpus_texts(reports: List[Dict]) -> List[str]:
    return [f"{r['Issue_Title']}. {r['Issue_Body']}" for r in reports]


def selfcheck_config(ws, **trainer_overrides):
    """A tiny reference-shaped train config over a :func:`build_workspace`
    artifact set — the geometry the CLI ``selfcheck`` command (and the
    test suite) trains in seconds on CPU while exercising every layer:
    reader pair-sampling, Siamese train step, threshold-swept validation,
    archiving."""
    trainer = {
        "num_epochs": 1,
        "patience": 2,
        "batch_size": 4,
        "grad_accum": 2,
        "max_length": 48,
        "eval_batch_size": 8,
        "eval_max_length": 48,
        "warmup_steps": 2,
        "steps_per_epoch": 3,
    }
    trainer.update(trainer_overrides)
    return {
        "random_seed": 2021,
        "tokenizer": {"type": "wordpiece", "tokenizer_path": ws["paths"]["tokenizer"]},
        "dataset_reader": {
            "type": "reader_memory",
            "sample_neg": 1.0,
            "same_diff_ratio": {"same": 2, "diff": 2},
            "cve_path": ws["paths"]["cve"],
            "anchor_path": ws["paths"]["anchors"],
        },
        "train_data_path": ws["paths"]["train"],
        "validation_data_path": ws["paths"]["validation"],
        "model": {
            "type": "model_memory",
            "encoder": {"preset": "tiny", "vocab_size": 4096},
            "use_header": True,
            "header_dim": 32,
            "temperature": 0.1,
        },
        "trainer": trainer,
        "evaluation": {"batch_size": 8, "max_length": 48},
    }


def build_workspace(tmp_dir, seed: int = 0, **corpus_kwargs):
    """Materialize a full artifact set under ``tmp_dir``: train/validation/
    test JSON splits, CVE dict, anchors, and a trained tokenizer.  Returns a
    dict of paths plus in-memory objects."""
    import json
    from pathlib import Path

    from .corpus import preprocess, split_by_project, write_json
    from .cwe import build_anchors, build_cwe_tree, cwe_distribution
    from .tokenizer import WordPieceTokenizer

    tmp = Path(tmp_dir)
    tmp.mkdir(parents=True, exist_ok=True)
    reports, cve_dict = generate_corpus(seed=seed, **corpus_kwargs)
    clean = preprocess(reports)
    train, test = split_by_project(clean, held_out_frac=0.25, seed=seed)
    train, validation = split_by_project(train, held_out_frac=0.25, seed=seed + 1)

    tree = build_cwe_tree(research_view_records())
    positives = [r for r in train if r["Security_Issue_Full"] == "1"]
    for r in positives:
        r["CWE_ID"] = cve_dict[r["CVE_ID"]]["CWE_ID"]
    dist = cwe_distribution(positives, cve_dict)
    anchors = build_anchors(dist, tree, cve_dict, seed=seed)

    paths = {
        "train": tmp / "train_project.json",
        "validation": tmp / "validation_project.json",
        "test": tmp / "test_project.json",
        "cve": tmp / "CVE_dict.json",
        "anchors": tmp / "CWE_anchor_golden_project.json",
        "tokenizer": tmp / "tokenizer.json",
    }
    write_json(train, paths["train"])
    write_json(validation, paths["validation"])
    write_json(test, paths["test"])
    paths["cve"].write_text(json.dumps(cve_dict))
    paths["anchors"].write_text(json.dumps(anchors))

    texts = corpus_texts(reports) + [a for a in anchors.values()]
    # deterministic vocabulary, not the rust trainer: the trainer's
    # hashmap tie-breaking is per-process random (even vocab size can
    # differ run to run), which would make selfcheck/bench artifacts
    # non-reproducible despite every seed being pinned
    tokenizer = WordPieceTokenizer.build_deterministic(
        texts, vocab_size=2048, save_path=paths["tokenizer"]
    )
    return {
        "paths": {k: str(v) for k, v in paths.items()},
        "tokenizer": tokenizer,
        "anchors": anchors,
        "cve_dict": cve_dict,
        "splits": {"train": train, "validation": validation, "test": test},
    }
