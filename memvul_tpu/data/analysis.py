"""Paper-analysis utilities over the corpus (pure functions, no I/O).

The reference's ``utils.py`` carries a set of analysis scripts used in
the FSE'22 paper: the security-keyword preliminary study
(utils.py:442-466), the IR→CVE-disclosure delay histogram
(utils.py:470-512), positive-sample/CVE joins and the per-CWE
distribution (utils.py:186-235), its cumulative form (utils.py:515-541),
the attack-steps (PoC) count (utils.py:544-572), and repo star/fork
stats (utils.py:415-439).  Those scripts print/plot; here each analysis
returns plain data so callers (tests, notebooks, reports) decide the
presentation.
"""

from __future__ import annotations

import re
from datetime import datetime
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .corpus import extract_project

# the paper's security-keyword lexicon (reference: utils.py:443); a match
# in title or body marks a report as "security-flagged" for the
# keyword-baseline comparison
SECURITY_KEYWORD_RE = re.compile(
    r"(?i)(denial.of.service|\bxxe\b|remote.code.execution|\bopen.redirect"
    r"|osvdb|\bvuln|\bcve\b|\bxss\b|\bredos\b|\bnvd\b|malicious"
    r"|x−frame−options|attack|cross.site|exploit|directory.traversal"
    r"|\brce\b|\bdos\b|\bxsrf\b|clickjack|session.fixation|hijack|advisory"
    r"|insecure|security|\bcross−origin\b|unauthori[z|s]ed|infinite.loop"
    r"|authenticat(e|ion)|bruteforce|bypass|constant.time|crack|credential"
    r"|expos(e|ing)|hack|harden|injection|lockout|overflow|password"
    r"|\bpoc\b|proof.of.concept|poison|privelage|\b(in)?secur(e|ity)"
    r"|(de)?serializ|spoof|timing|traversal)"
)

# PoC / reproduction-steps markers (reference: utils.py:560 — no right \b
# so "PoCs" matches; leading (n)? because of literal "\nPoC" artifacts)
ATTACK_STEPS_RE = re.compile(
    r"(?i)(\b(n)?poc|proof-of-concept|proof\sof\sconcept"
    r"|steps\sto\sreproduce|steps\sto\sreplicate)"
)

DELTA_DAY_BINS = ((None, 0.0), (0.0, 7.0), (7.0, 30.0), (30.0, 180.0), (180.0, None))
DELTA_DAY_LABELS = ["(-inf,0]", "(0,7]", "(7,30]", "(30,180]", "(180,+inf)"]


def _is_positive(sample: Dict, target: str) -> bool:
    return str(sample.get(target, "0")) in ("1", "1.0", "pos")


def matches_security_keyword(text: Optional[str]) -> bool:
    return bool(SECURITY_KEYWORD_RE.search(text or ""))


def keyword_match_study(
    samples: Iterable[Dict], target: str = "Security_Issue_Full"
) -> Dict[str, int]:
    """The preliminary study: how well does naive keyword matching separate
    dangerous reports?  Counts the 2×2 of (positive?, keyword in title or
    body?) (reference: utils.py:450-466)."""
    counts = {"pos_match": 0, "pos_not_match": 0, "neg_match": 0, "neg_not_match": 0}
    for s in samples:
        matched = matches_security_keyword(
            s.get("Issue_Title")
        ) or matches_security_keyword(s.get("Issue_Body"))
        key = ("pos" if _is_positive(s, target) else "neg") + (
            "_match" if matched else "_not_match"
        )
        counts[key] += 1
    return counts


def fix_timestamp(t: str) -> str:
    """Normalize ``"2018-10-30 16:26:01 UTC"``-style stamps to ISO-Z
    (reference: utils.py:41-46)."""
    t = t.strip()
    t = re.sub(r"\sUTC", "Z", t)
    return re.sub(r"\s", "T", t)


def _parse_time(t: str) -> datetime:
    t = fix_timestamp(t)
    for fmt in ("%Y-%m-%dT%H:%M:%SZ", "%Y-%m-%dT%H:%MZ", "%Y-%m-%d"):
        try:
            return datetime.strptime(t, fmt)
        except ValueError:
            continue
    raise ValueError(f"unparseable timestamp {t!r}")


def delta_days_histogram(
    positives: Iterable[Dict],
    cve_dict: Optional[Dict[str, Dict]] = None,
) -> Dict[str, object]:
    """Histogram of (CVE disclosure − IR creation) in days over the bins
    (-inf,0], (0,7], (7,30], (30,180], (180,+inf)
    (reference: utils.py:470-512).  ``Published_Date`` is read off the
    record, falling back to the CVE dict."""
    counts = [0] * len(DELTA_DAY_BINS)
    total = 0
    for s in positives:
        created = s.get("Issue_Created_At") or ""
        published = s.get("Published_Date") or ""
        if not published and cve_dict:
            published = (cve_dict.get(s.get("CVE_ID")) or {}).get("Published_Date", "")
        if not created or not published:
            continue
        delta = _parse_time(published) - _parse_time(created)
        delta_days = delta.days + delta.seconds / 86400.0
        for i, (lo, hi) in enumerate(DELTA_DAY_BINS):
            if (lo is None or delta_days > lo) and (hi is None or delta_days <= hi):
                counts[i] += 1
                break
        total += 1
    fractions = [c / total if total else 0.0 for c in counts]
    return {"labels": list(DELTA_DAY_LABELS), "counts": counts,
            "fractions": fractions, "total": total}


def join_positives_with_cve(
    samples: Iterable[Dict],
    cve_dict: Dict[str, Dict],
    target: str = "Security_Issue_Full",
) -> List[Dict]:
    """All positive reports with their CWE id + CVE description attached
    (the reference's ``pos_info.json``, utils.py:186-205)."""
    out = []
    for s in samples:
        if not _is_positive(s, target):
            continue
        rec = dict(s)
        cve = cve_dict.get(s.get("CVE_ID")) or {}
        rec["CWE_ID"] = cve.get("CWE_ID")
        rec["CVE_Description"] = cve.get("CVE_Description")
        out.append(rec)
    return out


def cwe_report_distribution(
    pos_info: Iterable[Dict],
    cwe_tree: Optional[Dict[str, Dict]] = None,
) -> Dict[str, Dict]:
    """Per-CWE-category report/CVE counts — the reference's
    ``CWE_distribution.json`` shape (utils.py:208-235): each entry carries
    ``abstraction`` (from the Research View when resolvable),
    ``#issue report``, ``#CVE`` and a per-CVE report count.  The special
    categories NVD-CWE-noinfo / NVD-CWE-Other / null stay unresolved."""
    dist: Dict[str, Dict] = {}
    for pos in pos_info:
        cve_id = pos.get("CVE_ID")
        cwe_id = pos.get("CWE_ID") or "null"
        entry = dist.get(cwe_id)
        if entry is None:
            entry = dist[cwe_id] = {
                "abstraction": None,
                "#issue report": 0,
                "#CVE": 0,
                "CVE_distribution": {},
            }
            if cwe_id not in ("NVD-CWE-noinfo", "NVD-CWE-Other", "null") and cwe_tree:
                bare = cwe_id.split("-")[-1]
                node = cwe_tree.get(bare)
                if node is not None:
                    entry["abstraction"] = node.get("Weakness Abstraction")
        entry["#issue report"] += 1
        if cve_id not in entry["CVE_distribution"]:
            entry["CVE_distribution"][cve_id] = 0
            entry["#CVE"] += 1
        entry["CVE_distribution"][cve_id] += 1
    return dist


def cumulative_cwe_distribution(
    cwe_distribution: Dict[str, Dict]
) -> List[Tuple[int, float]]:
    """ECDF of category size: (reports-per-CWE, fraction of CWE categories
    with at most that many reports) (reference: utils.py:515-541)."""
    sizes = sorted(v["#issue report"] for v in cwe_distribution.values())
    if not sizes:
        return []
    points: List[Tuple[int, float]] = []
    n = len(sizes)
    for i, size in enumerate(sizes):
        if i + 1 == n or sizes[i + 1] != size:
            points.append((size, (i + 1) / n))
    return points


def count_attack_steps(
    positives: Iterable[Dict], field: str = "Issue_Body"
) -> Dict[str, int]:
    """How many dangerous reports include reproduction/PoC steps
    (reference: utils.py:544-572; paper rebuttal: 1,570 of 3,937)."""
    total = 0
    with_steps = 0
    for s in positives:
        total += 1
        if ATTACK_STEPS_RE.search(s.get(field) or ""):
            with_steps += 1
    return {"total": total, "with_attack_steps": with_steps}


def repo_stats(
    samples: Iterable[Dict], repo_info: Dict[str, Dict]
) -> Dict[str, object]:
    """Median/mean star/watch/fork/subscriber counts over the corpus's
    projects (reference: utils.py:415-439).  Projects missing from
    ``repo_info`` are reported, not dropped silently."""
    import numpy as np

    projects = {
        s.get("project") or extract_project(s.get("Issue_Url", "")) for s in samples
    }
    projects.discard("ERROR")
    missing = sorted(projects - set(repo_info))
    found = sorted(projects & set(repo_info))
    out: Dict[str, object] = {
        "num_projects": len(projects),
        "missing_projects": missing,
    }
    for key, name in (
        ("stargazers_count", "star"),
        ("watchers_count", "watch"),
        ("forks_count", "fork"),
        ("subscribers_count", "subscribe"),
    ):
        values = [repo_info[p].get(key, 0) for p in found]
        out[name] = {
            "median": float(np.median(values)) if values else 0.0,
            "mean": float(np.mean(values)) if values else 0.0,
        }
    return out
