"""Issue-report text normalization.

Replaces code blocks, links, identifiers, versions etc. with stable
placeholder tags (APITAG, CODETAG, ERRORTAG, FILETAG, URLTAG, CVETAG,
EMAILTAG, MENTIONTAG, PATHTAG, NUMBERTAG) so the encoder sees a bounded
vocabulary.  Behavior-equivalent to the reference normalizer
(reference: MemVul/util.py:39-142) including the leak guard that maps
CVE-/CWE-identifiers and mitre/bugzilla links to CVETAG
(reference: MemVul/util.py:85-90,102-104).

The implementation here is pass-table driven: fenced/inline code spans
share one classifier, and the ordered tag passes are listed explicitly.
Order is load-bearing — e.g. paths must be tagged before generic API
tokens, and CVE ids before the number pass.
"""

from __future__ import annotations

import re

# -- span classifiers --------------------------------------------------------

# error-ish text inside a code span ⇒ ERRORTAG
_ERRORISH = re.compile(
    r"exception|error|warning|404|can't|can\s?not|could\s?not|un[a-z]{3,}", re.I
)
# prose-like span (plain words, or yaml front-matter) ⇒ keep the inner text
_PROSE = re.compile(r"^yaml|^\s*([a-z]+[,\.\?]?\s+)*?[a-z]+[,\.\?]?\s*$", re.I)
# a single whitespace-free token ⇒ APITAG
_ONE_TOKEN = re.compile(r"^\s*\S+\s*$")

_MAX_API_SPAN = 150


def _classify_code_span(inner: str) -> str | None:
    """Decide the replacement for the *inner* text of a code span.

    Returns the replacement string (with surrounding spaces), or None when
    the whole span was empty and should collapse to a single space.
    """
    if inner == "":
        return None
    if _ERRORISH.search(inner):
        return " ERRORTAG "
    if _PROSE.search(inner):
        return f" {inner} "
    if _ONE_TOKEN.search(inner) or len(inner) <= _MAX_API_SPAN:
        return " APITAG "
    return " CODETAG "


def _rewrite_code_spans(content: str, fence: str) -> str:
    """Rewrite each ``fence``-delimited code span, one occurrence at a time."""
    n = len(fence)
    pattern = re.compile(re.escape(fence) + r".*?" + re.escape(fence), re.S)
    for match in pattern.finditer(content):
        span = match.group()
        replacement = _classify_code_span(span[n:-n]) or " "
        content = content.replace(span, replacement, 1)
    return content


# -- link / url handling -----------------------------------------------------

_MD_LINK = re.compile(r"[!]?\[(.+?)\]\((\S+)\)", re.S)
_URL = re.compile(
    r"http[s]?://(?:[a-zA-Z]|[0-9]|[$-_@.&+#]|[!*\(\),]|(?:%[0-9a-fA-F][0-9a-fA-F]))+"
)
_VULN_TRACKER = re.compile(r"bugzilla|mitre|bugs", re.I)


def _looks_like_file(s: str) -> bool:
    """A dot near the tail (chars -5..-2) suggests a file extension."""
    return bool(re.search(r"\.", s[-5:-1]))


def _rewrite_md_links(content: str) -> str:
    for match in _MD_LINK.finditer(content):
        whole, text, target = match.group(), match.group(1), match.group(2)
        if _looks_like_file(text) or _looks_like_file(target):
            content = content.replace(whole, " FILETAG ", 1)
        else:
            content = content.replace(whole, f" {text} {target} ", 1)
    return content


def _rewrite_urls(content: str) -> str:
    for match in _URL.finditer(content):
        url = match.group()
        if _VULN_TRACKER.search(url):
            # cve.mitre.org / cwe.mitre.org / bugzilla — vulnerability leak guard
            replacement = " CVETAG "
        elif _looks_like_file(url):
            replacement = " FILETAG "
        else:
            replacement = " URLTAG "
        content = content.replace(url, replacement, 1)
    return content


# -- filename pass -----------------------------------------------------------

_FILE_EXT = re.compile(
    r"\s(\S+?\.(ml|xml|png|csv|jar|sh|sbt|zip|exe|md|txt|js|yml|yaml|json|sql|"
    r"html|pdf|jsp|php|prod|scss|ts|jpg|png|bmp|gif))[?,\.]{0,1}\s",
    re.I,
)


def _rewrite_filenames(content: str) -> str:
    for match in _FILE_EXT.finditer(content):
        content = content.replace(match.group(1), " FILETAG ", 1)
    return content


# -- ordered regex passes ----------------------------------------------------

_SUB_PASSES = [
    # angle-bracket runs and attribute-ish html tags
    (re.compile(r"<[^>]*>{2,}"), " APITAG "),
    (re.compile(r"<[^>]*?[!;=/$%][^>]*>"), " APITAG "),
]

_POST_URL_PASSES = [
    # escaped-newline pairs and markdown emphasis/heading markers
    (re.compile(r"(\\r\\n)|(\\n\\n)|(\\r\\r)|(\\t\\t)|(\\\")|(\\\')"), " "),
    (re.compile(r"\*{1,}"), " "),
    (re.compile(r"#{1,}"), " "),
    # vulnerability identifiers — leak guard
    (re.compile(r"CVE-[0-9]+-[0-9]+"), " CVETAG "),
    (re.compile(r"CWE-[0-9]+"), " CVETAG "),
    (re.compile(r"[0-9a-zA-Z_]{0,19}@[0-9a-zA-Z]{1,13}\.[com,cn,net]{1,3}"), " EMAILTAG "),
    (re.compile(r"@[a-zA-Z0-9_\-]+[,\.]?\s"), " MENTIONTAG "),
    (re.compile(r"\S+?(Error|Exception)([^A-Za-z\s]\S*|\s|$)|404"), " ERRORTAG "),
    # multi-segment paths (2+ separators)
    (re.compile(r"([^\s\(\)]+?[/\\]){2,}[^\s\(\)]*"), " PATHTAG "),
]

_FINAL_PASSES = [
    (re.compile(r"-"), " "),
    (re.compile(r"\S{30,}"), " APITAG "),
    # call-sites, dotted identifiers, camelCase, mentions, generic tags
    (
        re.compile(
            r"\S+?((\(\))|(\[\]))\S*|[^,;\.\s]{3,}?\.\S{4,}|"
            r"\S+?([a-z][A-Z]|[A-Z][a-z]{2,}?)\S*|@\S+|<\S*?>"
        ),
        " APITAG ",
    ),
    (
        re.compile(r"[^a-uwyz]+?\d[^a-uwyz]*(beta[0-9]+){0,1}|beta[0-9]+", re.I),
        " NUMBERTAG ",
    ),
    (re.compile(r"[\r\n\t]"), " "),
    (re.compile(r"(\\r)|(\\n)|(\\t)|(\\\")|(\\\')"), " "),
]


def normalize_text(content) -> str:
    """Normalize one issue-report field (title or body) to tagged text."""
    if not isinstance(content, str):
        return ""

    content = re.sub(r"<!---.*?-->", " ", content)
    content = _rewrite_code_spans(content, "```")
    content = _rewrite_code_spans(content, "`")
    content = _rewrite_md_links(content)
    for pattern, repl in _SUB_PASSES:
        content = pattern.sub(repl, content)
    content = _rewrite_urls(content)
    for pattern, repl in _POST_URL_PASSES:
        content = pattern.sub(repl, content)
    content = _rewrite_filenames(content)
    for pattern, repl in _FINAL_PASSES:
        content = pattern.sub(repl, content)

    return " ".join(tok for tok in content.split(" ") if tok)


# reference-compatible alias (reference: MemVul/util.py:39)
replace_tokens_simple = normalize_text
