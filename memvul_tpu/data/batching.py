"""Instance streams → fixed-shape device-ready batches.

XLA compiles one program per input shape, so batches must arrive in a
small closed set of shapes.  This module pads every batch to a fixed
``batch_size`` (partial tails are padded with dead rows, marked by a
``weight`` vector) and pads sequences to bucketed lengths — single-text
streams through :func:`bucketed_batches_from_instances` (the
corpus-scoring path), Siamese pair streams through
:func:`bucketed_pair_batches_from_instances` (the training path:
per-side bucket grid + in-batch side-2 dedup,
docs/training_throughput.md).  The ragged serve path replaces bucket
padding entirely: :func:`pack_token_budget` packs variable-length
requests into fixed ``[1, token_budget]`` flat batches and
:func:`collate_ragged` emits the segment/position/row tables one warm
program serves (docs/ragged_serving.md); both ride
:class:`PackSlotAllocator`, the reusable token-budget page table the
continuous dispatcher admits into incrementally (serving/dispatch.py).  It also memoizes text→ids (CVE
descriptions and anchors repeat heavily in the pair stream; hit/miss
telemetry makes the memo auditable) and can prefetch batches on a
background thread — optionally committing them to device there too (the
double-buffered feed) — so host-side tokenization and H2D transfer stay
off the TPU critical path.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

LABELS_SIAMESE = {"same": 0, "diff": 1}
LABELS_BINARY = {"pos": 0, "neg": 1}


class CachedEncoder:
    """Memoizing wrapper around ``tokenizer.encode``.

    Hit/miss totals feed the ``data.encode_cache_hits`` /
    ``data.encode_cache_misses`` telemetry counters (one batched ``inc``
    per call, not per text) so host-side tokenization cost shows up in
    ``telemetry-report`` instead of hiding inside wall-clock."""

    def __init__(self, tokenizer, max_length: int, cache_size: int = 200_000):
        self._tokenizer = tokenizer
        self._max_length = max_length
        self._cache: Dict[str, List[int]] = {}
        self._cache_size = cache_size
        self._beyond: Dict[Tuple[int, str], bool] = {}  # encodes_beyond memo

    @property
    def pad_id(self) -> int:
        return self._tokenizer.pad_id

    @property
    def max_length(self) -> int:
        return self._max_length

    def __call__(self, text: str) -> List[int]:
        from ..telemetry import get_registry

        ids = self._cache.get(text)
        if ids is None:
            get_registry().counter("data.encode_cache_misses").inc()
            ids = self._tokenizer.encode(text, max_length=self._max_length)
            if len(self._cache) < self._cache_size:
                self._cache[text] = ids
        else:
            get_registry().counter("data.encode_cache_hits").inc()
        return ids

    def encodes_beyond(self, text: str, cap: int) -> bool:
        """True when ``text`` tokenizes to MORE than ``cap`` tokens — the
        serving truncation probe (``serve.truncated``).  The capped
        ``encode`` output is indistinguishable between "exactly cap
        tokens" and "clamped", so this re-encodes at ``cap + 1``; callers
        only probe sequences already sitting at the cap, and the verdict
        is memoized, which keeps the extra tokenizer call off the
        steady-state path."""
        key = (cap, text)
        hit = self._beyond.get(key)
        if hit is None:
            hit = len(self._tokenizer.encode(text, max_length=cap + 1)) > cap
            if len(self._beyond) < self._cache_size:
                self._beyond[key] = hit
        return hit

    def encode_many(self, texts: Sequence[str]) -> List[List[int]]:
        """Batch lookup: cache misses go through the tokenizer's parallel
        ``encode_many`` in ONE call (rust/rayon threads — the cold-pass
        scaling path for multi-core hosts), so repeated texts (anchors,
        CVE descriptions) still hit the memo and only unique misses pay
        tokenization."""
        from ..telemetry import get_registry

        fresh: Dict[str, List[int]] = {}
        misses = [t for t in dict.fromkeys(texts) if t not in self._cache]
        if misses:
            for t, ids in zip(
                misses,
                self._tokenizer.encode_many(misses, max_length=self._max_length),
            ):
                fresh[t] = ids
                if len(self._cache) < self._cache_size:
                    self._cache[t] = ids
        tel = get_registry()
        tel.counter("data.encode_cache_misses").inc(len(misses))
        tel.counter("data.encode_cache_hits").inc(len(texts) - len(misses))
        return [
            self._cache[t] if t in self._cache else fresh[t] for t in texts
        ]


def _encode_many(encoder, texts: Sequence[str]) -> List[List[int]]:
    """Batch path when the encoder has one (CachedEncoder → rust thread
    pool), scalar loop otherwise (duck-typed stub encoders in tests)."""
    many = getattr(encoder, "encode_many", None)
    return many(texts) if many is not None else [encoder(t) for t in texts]


def _pad_block(
    seqs: Sequence[List[int]],
    batch_size: int,
    pad_id: int,
    length: int,
) -> Dict[str, np.ndarray]:
    ids = np.full((batch_size, length), pad_id, dtype=np.int32)
    mask = np.zeros((batch_size, length), dtype=np.int32)
    for i, seq in enumerate(seqs):
        seq = seq[:length]
        ids[i, : len(seq)] = seq
        mask[i, : len(seq)] = 1
    return {"input_ids": ids, "attention_mask": mask}


def _bucket_for(length: int, buckets: Sequence[int]) -> int:
    """Smallest bucket covering ``length``.  A sequence longer than the
    largest bucket is clamped to it EXPLICITLY and counted
    (``data.truncated_sequences``) — the old behavior relied on the
    downstream ``seq[:length]`` slice in :func:`_pad_block` silently
    dropping the tail, which :func:`validate_buckets` exists to prevent
    but nothing measured when it happened anyway (an unvalidated caller,
    or a tokenizer whose cap disagrees with the bucket grid)."""
    for b in buckets:
        if b >= length:
            return b
    from ..telemetry import get_registry

    get_registry().counter("data.truncated_sequences").inc()
    return buckets[-1]


def _bucket_length(
    seqs: Iterable[List[int]], buckets: Optional[Sequence[int]], max_length: int
) -> int:
    longest = max((len(s) for s in seqs), default=1)
    longest = min(longest, max_length)
    if buckets:
        return _bucket_for(longest, buckets)
    return max_length


def batches_from_instances(
    instances: Iterable[Dict],
    encoder: CachedEncoder,
    batch_size: int,
    label_map: Optional[Dict[str, int]] = None,
    buckets: Optional[Sequence[int]] = None,
    pad_to_max: bool = False,
) -> Iterator[Dict]:
    """Group instances into fixed-shape batches.

    Yields dicts with ``sample1`` (= {input_ids, attention_mask}), and when
    pairs are present ``sample2``; plus ``label`` [B] int32, ``weight`` [B]
    float32 (0 for padding rows), and ``meta`` (list, real rows only).
    """
    label_map = label_map or LABELS_SIAMESE
    for chunk in _blocks(instances, batch_size):
        yield _collate(chunk, encoder, batch_size, label_map, buckets, pad_to_max)


def _collate(
    chunk: List[Dict],
    encoder: CachedEncoder,
    batch_size: int,
    label_map: Dict[str, int],
    buckets: Optional[Sequence[int]],
    pad_to_max: bool,
) -> Dict:
    seqs1 = _encode_many(encoder, [inst["text1"] for inst in chunk])
    length1 = (
        encoder.max_length
        if pad_to_max
        else _bucket_length(seqs1, buckets, encoder.max_length)
    )
    labels = []
    for inst in chunk:
        label = inst.get("label")
        if label not in label_map:
            raise ValueError(
                f"label {label!r} not in label map {sorted(label_map)}; "
                "pass the matching label_map for this reader"
            )
        labels.append(label_map[label])
    batch: Dict = {
        "sample1": _pad_block(seqs1, batch_size, encoder.pad_id, length1),
        "label": np.array(
            labels + [0] * (batch_size - len(chunk)), dtype=np.int32
        ),
        "weight": np.array(
            [1.0] * len(chunk) + [0.0] * (batch_size - len(chunk)), dtype=np.float32
        ),
        "meta": [inst.get("meta", {}) for inst in chunk],
    }
    if chunk and chunk[0].get("text2") is not None:
        seqs2 = _encode_many(encoder, [inst["text2"] for inst in chunk])
        length2 = (
            encoder.max_length
            if pad_to_max
            else _bucket_length(seqs2, buckets, encoder.max_length)
        )
        batch["sample2"] = _pad_block(seqs2, batch_size, encoder.pad_id, length2)
    return batch


def bucketed_batches_from_instances(
    instances: Iterable[Dict],
    encoder: CachedEncoder,
    batch_size: Union[int, Dict[int, int]],
    label_map: Optional[Dict[str, int]] = None,
    buckets: Sequence[int] = (64, 128, 256, 512),
) -> Iterator[Dict]:
    """Length-binned batching: each instance is routed to the smallest
    bucket covering its token length and a batch is emitted whenever a
    bucket fills, so short reports never pay long-report padding.  This is
    where the corpus-scoring throughput win lives: per-batch pad-to-longest
    (the reference's AllenNLP collation) pads nearly every 512-report batch
    to the cap under a long-tailed length distribution, while binning keeps
    the padded-token count within ~2x of the true token count.

    Instances are re-ordered across buckets (metas travel with their rows,
    so downstream metrics are order-independent).  Tails are flushed as
    dead-row-padded batches when the stream ends.  Only single-text
    instances are supported (the eval paths); pair streams use
    :func:`batches_from_instances`.

    ``batch_size`` may be a per-bucket mapping — short buckets can then run
    much larger batches at a constant token budget, keeping the MXU busy on
    sequences the reference would drown in padding.
    """
    label_map = label_map or LABELS_SIAMESE
    buckets = tuple(sorted(buckets))
    if isinstance(batch_size, dict):
        sizes = {b: int(batch_size[b]) for b in buckets}
    else:
        sizes = {b: int(batch_size) for b in buckets}
    pending: Dict[int, List[Dict]] = {b: [] for b in buckets}
    # tokenize in blocks, not per-instance: one encode_many call hands the
    # whole block to the rust tokenizer's thread pool (cold-pass host
    # tokenization is the few-core bottleneck, docs/full_corpus.md)
    for block in _blocks(instances, 512):
        texts = []
        for inst in block:
            if inst.get("text2") is not None:
                raise ValueError(
                    "bucketed batching supports single-text instances only"
                )
            texts.append(inst["text1"])
        for inst, seq in zip(block, _encode_many(encoder, texts)):
            bucket = _bucket_for(len(seq), buckets)
            slot = dict(inst)
            slot["_ids"] = seq
            pending[bucket].append(slot)
            if len(pending[bucket]) == sizes[bucket]:
                yield _collate_bucket(
                    pending[bucket], encoder, sizes[bucket], label_map, bucket
                )
                pending[bucket] = []
    for bucket in buckets:
        if pending[bucket]:
            yield _collate_bucket(pending[bucket], encoder, sizes[bucket], label_map, bucket)


def _blocks(it: Iterable[Dict], size: int) -> Iterator[List[Dict]]:
    block: List[Dict] = []
    for x in it:
        block.append(x)
        if len(block) == size:
            yield block
            block = []
    if block:
        yield block


def bucket_batch_sizes(
    buckets: Sequence[int],
    tokens_per_batch: int,
    multiple_of: int = 8,
    cap: Optional[int] = None,
) -> Dict[int, int]:
    """Per-bucket batch sizes at a constant token budget, rounded down to a
    hardware-friendly multiple (and to the data-mesh axis size when
    sharded)."""
    sizes = {}
    for b in sorted(buckets):
        n = max(multiple_of, (tokens_per_batch // int(b)) // multiple_of * multiple_of)
        if cap is not None:
            n = min(n, cap)
        sizes[int(b)] = n
    return sizes


def _collate_bucket(
    chunk: List[Dict],
    encoder: CachedEncoder,
    batch_size: int,
    label_map: Dict[str, int],
    length: int,
) -> Dict:
    seqs = [inst["_ids"] for inst in chunk]
    labels = []
    for inst in chunk:
        label = inst.get("label")
        if label not in label_map:
            raise ValueError(
                f"label {label!r} not in label map {sorted(label_map)}; "
                "pass the matching label_map for this reader"
            )
        labels.append(label_map[label])
    return {
        "sample1": _pad_block(seqs, batch_size, encoder.pad_id, length),
        "label": np.array(labels + [0] * (batch_size - len(chunk)), dtype=np.int32),
        "weight": np.array(
            [1.0] * len(chunk) + [0.0] * (batch_size - len(chunk)), dtype=np.float32
        ),
        "meta": [inst.get("meta", {}) for inst in chunk],
    }


def dedup_capacities(batch_size: int, floor: int = 8) -> Tuple[int, ...]:
    """The CLOSED set of unique-row capacities a deduped side-2 block may
    take for a given row count: powers of two from ``floor`` up, plus the
    row count itself.  A per-batch capacity (the exact unique count) would
    compile one program per distinct U — this ladder caps the program
    count at ~log2(B/8) per bucket cell while still cutting tower-2 rows
    to the nearest power of two above U."""
    caps: List[int] = []
    c = floor
    while c < batch_size:
        caps.append(c)
        c *= 2
    caps.append(int(batch_size))
    return tuple(caps)


def _dedup_side2(
    seqs: Sequence[List[int]], batch_size: int, cap_floor: int = 8
) -> Tuple[List[List[int]], np.ndarray, int]:
    """Order-preserving unique rows + per-row gather indices.

    Returns ``(unique_seqs, index[batch_size], capacity)`` where
    ``capacity`` is the smallest value in :func:`dedup_capacities`
    covering the unique count.  Rows beyond ``len(seqs)`` (dead rows) map
    to index 0 — they carry zero weight, so what they gather is inert.
    """
    unique: Dict[Tuple[int, ...], int] = {}
    index = np.zeros(batch_size, dtype=np.int32)
    seq_list: List[List[int]] = []
    for i, seq in enumerate(seqs):
        key = tuple(seq)
        slot = unique.get(key)
        if slot is None:
            slot = unique[key] = len(seq_list)
            seq_list.append(seq)
        index[i] = slot
    cap = next(
        c for c in dedup_capacities(batch_size, floor=cap_floor)
        if c >= len(seq_list)
    )
    return seq_list, index, cap


def bucketed_pair_batches_from_instances(
    instances: Iterable[Dict],
    encoder: CachedEncoder,
    batch_size: Union[int, Dict[int, int]],
    label_map: Optional[Dict[str, int]] = None,
    buckets: Sequence[int] = (64, 128, 256, 512),
    dedup_side2: bool = True,
    dedup_cap_floor: int = 8,
) -> Iterator[Dict]:
    """Length-binned batching for Siamese PAIR streams — the training-side
    twin of :func:`bucketed_batches_from_instances`.

    Each pair is routed to the grid cell ``(b1, b2)`` of the smallest
    buckets covering its two sides independently (the report side and the
    anchor/CVE side have very different length distributions — anchors
    are short, reports are long-tailed — so one shared bucket would pad
    the short side to the long side's length).  A batch is emitted when a
    cell fills; tails flush as dead-row-padded batches when the stream
    ends.  The compiled-program count is bounded by the grid:
    ``|buckets|²`` cells times the dedup capacity ladder.

    ``batch_size`` may map the SIDE-1 bucket to a row count (per-bucket
    batch sizes, cf. :func:`bucket_batch_sizes`) — note that for
    *training* a varying row count also varies the optimizer's effective
    batch, so the trainers default to a constant int.

    With ``dedup_side2`` the second side is emitted as its UNIQUE rows
    (``sample2`` [cap, L2], capacity from :func:`dedup_capacities`) plus
    a ``sample2_index`` [B] gather map: the pair stream repeats the ~129
    anchor texts and the same-CWE CVE descriptions heavily, so tower-2
    forward/backward FLOPs drop from B rows to U ≤ unique texts while
    gradients scatter-add through the gather automatically
    (docs/training_throughput.md).  ``dedup_cap_floor`` raises the
    capacity ladder's floor — a data-sharded trainer passes its mesh
    axis size so every unique block stays divisible across the mesh.
    """
    label_map = label_map or LABELS_SIAMESE
    buckets = tuple(sorted(int(b) for b in buckets))
    if isinstance(batch_size, dict):
        sizes = {b: int(batch_size[b]) for b in buckets}
    else:
        sizes = {b: int(batch_size) for b in buckets}
    pending: Dict[Tuple[int, int], List[Dict]] = {}
    for block in _blocks(instances, 512):
        for inst in block:
            if inst.get("text2") is None:
                raise ValueError(
                    "bucketed pair batching needs text2 on every instance; "
                    "single-text streams use bucketed_batches_from_instances"
                )
        seqs1 = _encode_many(encoder, [inst["text1"] for inst in block])
        seqs2 = _encode_many(encoder, [inst["text2"] for inst in block])
        for inst, s1, s2 in zip(block, seqs1, seqs2):
            cell = (_bucket_for(len(s1), buckets), _bucket_for(len(s2), buckets))
            slot = dict(inst)
            slot["_ids1"], slot["_ids2"] = s1, s2
            rows = pending.setdefault(cell, [])
            rows.append(slot)
            if len(rows) == sizes[cell[0]]:
                yield _collate_pair_cell(
                    rows, encoder, sizes[cell[0]], label_map, cell,
                    dedup_side2, dedup_cap_floor,
                )
                pending[cell] = []
    for cell in sorted(pending):
        if pending[cell]:
            yield _collate_pair_cell(
                pending[cell], encoder, sizes[cell[0]], label_map, cell,
                dedup_side2, dedup_cap_floor,
            )


def _collate_pair_cell(
    chunk: List[Dict],
    encoder: CachedEncoder,
    batch_size: int,
    label_map: Dict[str, int],
    cell: Tuple[int, int],
    dedup: bool,
    dedup_cap_floor: int = 8,
) -> Dict:
    length1, length2 = cell
    labels = []
    for inst in chunk:
        label = inst.get("label")
        if label not in label_map:
            raise ValueError(
                f"label {label!r} not in label map {sorted(label_map)}; "
                "pass the matching label_map for this reader"
            )
        labels.append(label_map[label])
    batch: Dict = {
        "sample1": _pad_block(
            [inst["_ids1"] for inst in chunk], batch_size, encoder.pad_id, length1
        ),
        "label": np.array(labels + [0] * (batch_size - len(chunk)), dtype=np.int32),
        "weight": np.array(
            [1.0] * len(chunk) + [0.0] * (batch_size - len(chunk)), dtype=np.float32
        ),
        "meta": [inst.get("meta", {}) for inst in chunk],
    }
    seqs2 = [inst["_ids2"] for inst in chunk]
    if dedup:
        unique, index, cap = _dedup_side2(seqs2, batch_size, dedup_cap_floor)
        batch["sample2"] = _pad_block(unique, cap, encoder.pad_id, length2)
        batch["sample2_index"] = index
    else:
        batch["sample2"] = _pad_block(seqs2, batch_size, encoder.pad_id, length2)
    return batch


def pack_token_budget(
    lengths: Sequence[int],
    token_budget: int,
    max_rows: int,
) -> List[List[int]]:
    """Pack row lengths into fixed-budget flat batches (the ragged serve
    path, docs/ragged_serving.md).

    Greedy, strictly in input order: row ``i`` joins the open pack
    unless its tokens would overflow ``token_budget`` or the pack
    already holds ``max_rows`` rows, in which case the open pack is
    sealed and a new one starts.  The final partial pack is flushed as
    the tail.  Emission is therefore a PURE function of the input order
    — the same multiset of lengths in the same order always produces
    the same packs, and the packs covering a prefix of the input never
    depend on what follows it (the property the hypothesis suite pins).

    Returns a list of index lists; every input index appears in exactly
    one pack.  Lengths are clamped to ``token_budget`` defensively —
    callers size the budget to cover ``max_length``, which the
    tokenizer already caps sequences at.
    """
    if token_budget < 1:
        raise ValueError(f"token_budget must be >= 1, got {token_budget}")
    if max_rows < 1:
        raise ValueError(f"max_rows must be >= 1, got {max_rows}")
    packs: List[List[int]] = []
    open_pack: List[int] = []
    used = 0
    for i, length in enumerate(lengths):
        n = max(1, min(int(length), token_budget))
        if open_pack and (used + n > token_budget or len(open_pack) == max_rows):
            packs.append(open_pack)
            open_pack, used = [], 0
        open_pack.append(i)
        used += n
    if open_pack:
        packs.append(open_pack)
    return packs


class PackSlotAllocator:
    """A reusable token-budget page table of live segments — the
    ``row_starts``/``segment_ids`` bookkeeping promoted out of
    :func:`collate_ragged` so it can run *incrementally*.

    :func:`collate_ragged` rebuilds the whole flat pack from scratch,
    which is fine when a pull is sealed before collation.  The
    continuous dispatcher (serving/dispatch.py) instead keeps a pack
    *open* and admits requests one at a time while the previous pack is
    on device, so the bookkeeping must support admission into a
    half-built page: :meth:`admit` writes one segment in place (tokens,
    mask, segment id, restarted positions, row start) and returns its
    row index, or ``None`` when the segment does not fit the remaining
    budget/rows — the caller's cue to seal the pack (:meth:`sample`)
    and :meth:`reset` the pages for the next one.

    The page arrays are allocated once and recycled across packs;
    ``slots_reused`` counts admissions into a row slot a previous pack
    already used (the ``serve.pack_slots_reused`` counter's source).
    :meth:`sample` returns fresh copies with :func:`collate_ragged`'s
    exact layout, so a sealed pack is safe to hand to the device while
    the pages fill with the next pack's segments.

    ``share_prefixes`` turns on segment-table aliasing (the Ragged
    Paged Attention idea applied to the one sharing case a
    *bidirectional* encoder permits): when an admitted sequence's
    cap-truncated tokens EXACTLY equal a segment already written into
    the open pack, the new row writes no tokens at all — its
    ``row_starts`` entry points at the existing segment's CLS offset,
    so the pooling gather reads the shared embedding.  (A strict-prefix
    share would change the shared tokens' attention — every token
    attends bidirectionally to the suffix — so only whole-segment
    identity keeps served scores within the ≤1e-6 parity gate; the
    template-heavy duplicate streams this targets are exactly
    whole-text repeats.)  Aliased rows add zero real tokens —
    ``rows_aliased``/``tokens_aliased`` are the
    ``serve.prefix_rows_aliased``/``serve.prefix_tokens_saved``
    counters' source — and can be admitted even when the token budget
    is exhausted, since they only consume a row slot.
    """

    def __init__(
        self,
        token_budget: int,
        max_rows: int,
        pad_id: int,
        share_prefixes: bool = False,
    ) -> None:
        if token_budget < 1:
            raise ValueError(f"token_budget must be >= 1, got {token_budget}")
        if max_rows < 1:
            raise ValueError(f"max_rows must be >= 1, got {max_rows}")
        self.token_budget = int(token_budget)
        self.max_rows = int(max_rows)
        self.pad_id = pad_id
        self.share_prefixes = bool(share_prefixes)
        # open-pack segment table: cap-truncated tokens -> row index
        # (only maintained when sharing is on; cleared at reset)
        self._segment_index: Dict[Tuple[int, ...], int] = {}
        self.rows_aliased = 0
        self.tokens_aliased = 0
        self._ids = np.full((1, self.token_budget), pad_id, dtype=np.int32)
        self._mask = np.zeros((1, self.token_budget), dtype=np.int32)
        self._segments = np.zeros((1, self.token_budget), dtype=np.int32)
        self._positions = np.zeros((1, self.token_budget), dtype=np.int32)
        self._row_starts = np.zeros(self.max_rows, dtype=np.int32)
        self._rows = 0
        self._offset = 0
        self._real_tokens = 0
        self._high_water = 0   # deepest row slot any sealed pack used
        self._generation = 0   # completed reset() count
        self.slots_reused = 0

    @property
    def rows(self) -> int:
        """Live segments in the open pack."""
        return self._rows

    @property
    def used_tokens(self) -> int:
        """Token positions the open pack has written."""
        return self._offset

    @property
    def real_tokens(self) -> int:
        """Real (non-pad) tokens the open pack carries — the padding
        ledger's numerator for this pack."""
        return self._real_tokens

    def fits(self, seq: Sequence[int]) -> bool:
        """Whether :meth:`admit` would accept ``seq`` right now.  An
        alias candidate (sharing on, identical segment already in the
        open pack) needs only a free row slot — no token budget."""
        if self._rows >= self.max_rows:
            return False
        n = min(len(seq), self.token_budget)
        if (
            self.share_prefixes
            and tuple(seq[: self.token_budget]) in self._segment_index
        ):
            return True
        return self._offset + n <= self.token_budget

    def admit(self, seq: Sequence[int]) -> Optional[int]:
        """Write one segment into the open pack; returns its row index,
        or ``None`` when it does not fit (seal + reset, then retry).
        With ``share_prefixes``, an exact duplicate of an already-open
        segment aliases it instead of writing tokens."""
        if not self.fits(seq):
            return None
        seq = seq[: self.token_budget]
        n = len(seq)
        row = self._rows
        if self.share_prefixes:
            key = tuple(seq)
            orig = self._segment_index.get(key)
            if orig is not None:
                # alias: point this row's pooling gather at the
                # original segment's CLS token; no tokens written, no
                # real-token cost — the measured prefix-share win
                self._row_starts[row] = self._row_starts[orig]
                self._rows = row + 1
                self.rows_aliased += 1
                self.tokens_aliased += n
                if self._generation and row < self._high_water:
                    self.slots_reused += 1
                return row
            self._segment_index[key] = row
        offset = self._offset
        self._ids[0, offset : offset + n] = seq
        self._mask[0, offset : offset + n] = 1
        self._segments[0, offset : offset + n] = row + 1
        self._positions[0, offset : offset + n] = np.arange(n, dtype=np.int32)
        self._row_starts[row] = offset
        self._rows = row + 1
        self._offset = offset + n
        self._real_tokens += n
        if self._generation and row < self._high_water:
            self.slots_reused += 1
        return row

    def sample(self) -> Dict[str, np.ndarray]:
        """The open pack as the fixed-shape flat sample the ragged score
        program consumes — fresh copies, so the pages can be recycled
        while the device still reads the sealed pack."""
        return {
            "input_ids": self._ids.copy(),
            "attention_mask": self._mask.copy(),
            "segment_ids": self._segments.copy(),
            "position_ids": self._positions.copy(),
            "row_starts": self._row_starts.copy(),
        }

    def reset(self) -> None:
        """Recycle the pages for the next pack: clear only the written
        prefix (the untouched tail is already pad/zero)."""
        offset, rows = self._offset, self._rows
        if offset:
            self._ids[0, :offset] = self.pad_id
            self._mask[0, :offset] = 0
            self._segments[0, :offset] = 0
            self._positions[0, :offset] = 0
        if rows:
            self._row_starts[:rows] = 0
        self._high_water = max(self._high_water, rows)
        self._rows = 0
        self._offset = 0
        self._real_tokens = 0
        self._segment_index.clear()
        self._generation += 1


def collate_ragged(
    seqs: Sequence[List[int]],
    token_budget: int,
    max_rows: int,
    pad_id: int,
) -> Dict[str, np.ndarray]:
    """One pack of sequences → the fixed-shape flat sample the ragged
    score program consumes (docs/ragged_serving.md).

    Layout: the sequences are laid end-to-end in a single ``[1,
    token_budget]`` token row; the row table says where each request
    lives —

    * ``input_ids``/``attention_mask`` [1, budget]: the flat tokens,
      ``pad_id``/0 past the packed tail;
    * ``segment_ids`` [1, budget] int32: row ``i``'s positions carry
      ``i + 1``; dead positions carry 0 (attention masks on equality
      with non-zero, ops/pallas/ragged_attention.py);
    * ``position_ids`` [1, budget] int32: restart at 0 on every row
      boundary, so each request sees exactly the position embeddings
      the padded path gives it;
    * ``row_starts`` [max_rows] int32: offset of each row's first
      (CLS) token — the segment-aware pooling gather; dead rows point
      at 0 and are sliced off host-side by the real row count.

    Every array has a shape that depends only on ``(token_budget,
    max_rows)`` — ONE compiled program serves any length mix — and the
    populated prefix depends only on the sequences themselves, so
    growing ``max_rows`` (more trailing dead rows) changes nothing a
    real row's score can see (pinned by the hypothesis suite).

    The in-place bookkeeping lives in :class:`PackSlotAllocator`; this
    is the one-shot wrapper: fill a fresh page table, return its sample.
    """
    if len(seqs) > max_rows:
        raise ValueError(f"{len(seqs)} rows exceed max_rows={max_rows}")
    alloc = PackSlotAllocator(token_budget, max_rows, pad_id)
    for i, seq in enumerate(seqs):
        if alloc.admit(seq) is None:
            n = len(seq[:token_budget])
            raise ValueError(
                f"pack overflows token_budget={token_budget} at row {i} "
                f"(offset {alloc.used_tokens} + {n} tokens) — pack with "
                "pack_token_budget first"
            )
    return alloc.sample()


def inflight_pipeline(
    batches: Iterable[Dict],
    dispatch,
    inflight: int = 2,
) -> Iterator:
    """Asynchronous device dispatch: calls ``dispatch(batch)`` (which must
    return without blocking — JAX dispatch is async) and yields
    ``(result, batch)`` pairs, keeping up to ``inflight`` results queued on
    the accelerator before the oldest is yielded for host-side syncing.
    The host-side ``np.asarray`` of a yielded result then never leaves the
    chip idle between steps.  Shared by both predictors."""
    from collections import deque

    pending: deque = deque()
    for batch in batches:
        pending.append((dispatch(batch), batch))
        if len(pending) > inflight:
            yield pending.popleft()
    while pending:
        yield pending.popleft()


def auto_buckets(
    lengths: Sequence[int],
    max_length: int,
    n_buckets: int = 4,
    align: int = 8,
) -> Tuple[int, ...]:
    """Choose bucket boundaries that MINIMIZE total padded tokens over a
    sample of sequence lengths (exact interval-partition DP, O(k·m²)).

    Hand-picked powers of two are fine for a uniform mix, but issue-report
    corpora are long-tailed (SURVEY §6: ~12% at the 512 cap, most far
    shorter); boundaries at the distribution's natural knees cut padding
    further at zero runtime cost — the bucket count (compiled program
    count) stays the same.  The final boundary is always ``max_length`` so
    unseen longer sequences stay covered (see :func:`validate_buckets`).
    """
    import numpy as np

    if not len(lengths):
        return (max_length,)
    ls = np.minimum(np.asarray(lengths, np.int64), max_length)
    # compress to aligned candidate boundaries with (count, length-sum)
    # per candidate: the DP is over ≤ max_length/align values, so sample
    # size never matters
    aligned = np.minimum(max_length, -(-ls // align) * align)
    values, inverse = np.unique(aligned, return_inverse=True)
    counts = np.bincount(inverse)
    sums = np.bincount(inverse, weights=ls.astype(np.float64))
    if int(values[-1]) < max_length:
        # the cap is a mandatory boundary (coverage contract) — model it
        # as a zero-count top candidate so the DP can also USE it as a
        # covering bucket (padding stragglers up to the cap can beat
        # spending an interior boundary on them) while it still counts
        # against the n_buckets budget
        values = np.concatenate([values, [max_length]])
        counts = np.concatenate([counts, [0]])
        sums = np.concatenate([sums, [0.0]])
    m = len(values)
    n_pre = np.concatenate([[0], np.cumsum(counts)])
    s_pre = np.concatenate([[0.0], np.cumsum(sums)])

    # cost of one bucket covering candidate values (i, j]: the boundary
    # is values[j-1], every covered sequence pads up to it
    def cost(i: int, j: int) -> float:
        return float(values[j - 1]) * (n_pre[j] - n_pre[i]) - (
            s_pre[j] - s_pre[i]
        )

    INF = float("inf")
    # values[-1] == max_length always holds here (appended above when the
    # sample stays short), so every k-interval partition ends at the cap
    # and the total bucket count (= compiled program count) is exactly
    # the DP's k ≤ n_buckets.  Floor of 1: a non-positive budget degrades
    # to the single mandatory cap bucket rather than crashing
    k_max = max(1, n_buckets)
    f = [[INF] * (m + 1) for _ in range(k_max + 1)]
    arg = [[0] * (m + 1) for _ in range(k_max + 1)]
    f[0][0] = 0.0
    for k in range(1, k_max + 1):
        for j in range(1, m + 1):
            best, best_i = INF, 0
            for i in range(j):
                if f[k - 1][i] == INF:
                    continue
                c = f[k - 1][i] + cost(i, j)
                if c < best:
                    best, best_i = c, i
            f[k][j] = best
            arg[k][j] = best_i
    k_best = min(range(1, k_max + 1), key=lambda k: f[k][m])
    bounds = []
    j = m
    for k in range(k_best, 0, -1):
        bounds.append(int(values[j - 1]))
        j = arg[k][j]
    return tuple(sorted(set(bounds) | {max_length}))


def pow2_buckets(max_length: int, floor: int = 64) -> Tuple[int, ...]:
    """Powers of two from ``floor`` up, capped by (and always including)
    ``max_length`` — the default training bucket grid.  Hand powers of
    two, not the corpus-sampled DP of :func:`auto_buckets`: the training
    pair stream is resampled every epoch, so there is no stable length
    sample to optimize against at trainer-construction time."""
    out: List[int] = []
    b = int(floor)
    while b < max_length:
        out.append(b)
        b *= 2
    out.append(int(max_length))
    return tuple(out)


def resolve_train_buckets(
    spec, max_length: int
) -> Optional[Tuple[int, ...]]:
    """The trainer configs' ``train_buckets`` knob → a validated bucket
    tuple: ``"pow2"`` (the default) derives :func:`pow2_buckets`,
    ``None`` means pad-to-max (the pre-bucketing collation, kept as the
    microbench baseline), and an explicit list is checked for
    ``max_length`` coverage via :func:`validate_buckets`."""
    if spec is None:
        return None
    if spec == "pow2":
        return pow2_buckets(max_length)
    if isinstance(spec, str):
        raise ValueError(
            f"train_buckets {spec!r} not understood: use 'pow2', null "
            "(pad-to-max), or an explicit bucket list"
        )
    return validate_buckets([int(b) for b in spec], max_length)


def validate_buckets(buckets: Sequence[int], max_length: int) -> Tuple[int, ...]:
    """Buckets must cover ``max_length`` — otherwise every sequence longer
    than the largest bucket would be silently truncated below the
    configured limit, changing scores relative to the pad-to-max path."""
    out = tuple(sorted(int(b) for b in buckets))
    if not out:
        raise ValueError("buckets must be non-empty")
    if out[-1] < max_length:
        raise ValueError(
            f"largest bucket {out[-1]} < max_length {max_length}: sequences "
            f"between them would be silently truncated; include "
            f"{max_length} as the final bucket (or lower max_length)"
        )
    return out


def prefetch(
    iterator: Iterator,
    depth: int = 4,
    commit=None,
    occupancy=None,
) -> Iterator:
    """Run ``iterator`` on a background thread with a bounded queue.

    With ``commit`` (e.g. ``jax.device_put`` or a sharded put) the worker
    applies it to every item BEFORE enqueueing, so host collation *and*
    the H2D transfer overlap the consumer's running device step — the
    double-buffered feed: while step N runs, batch N+1 is already
    committed on device and batch N+2 is being collated.  The consumer
    never pays a transfer on its critical path; JAX dispatch being async,
    ``commit`` only enqueues the copy.  (``depth`` then also bounds how
    many committed batches sit in device memory ahead of the step.)

    ``occupancy`` (a telemetry gauge) tracks the queue fill after every
    put/get: a gauge pinned at 0 means the feed is the bottleneck (the
    step waits on collation/transfer), pinned at ``depth`` means the
    device is (docs/training_throughput.md).

    Safe against early consumer exit: closing/abandoning the generator
    unblocks and stops the worker rather than leaking a thread pinned on a
    full queue.
    """
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    _END = object()
    stop = threading.Event()
    error: List[BaseException] = []

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                if occupancy is not None:
                    occupancy.set(q.qsize())
                return True
            except queue.Full:
                continue
        return False

    def worker() -> None:
        try:
            for item in iterator:
                if commit is not None:
                    item = commit(item)
                if not _put(item):
                    return
        except BaseException as e:  # propagate into the consumer
            error.append(e)
        finally:
            _put(_END)

    thread = threading.Thread(target=worker, daemon=True)
    thread.start()
    try:
        while True:
            item = q.get()
            if occupancy is not None:
                occupancy.set(q.qsize())
            if item is _END:
                if error:
                    raise error[0]
                return
            yield item
    finally:
        stop.set()
