"""Instance streams → fixed-shape device-ready batches.

XLA compiles one program per input shape, so batches must arrive in a
small closed set of shapes.  This module pads every batch to a fixed
``batch_size`` (partial tails are padded with dead rows, marked by a
``weight`` vector) and pads sequences to bucketed lengths.  It also
memoizes text→ids (CVE descriptions and anchors repeat heavily in the
pair stream) and can prefetch batches on a background thread so host-side
tokenization stays off the TPU critical path.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

LABELS_SIAMESE = {"same": 0, "diff": 1}
LABELS_BINARY = {"pos": 0, "neg": 1}


class CachedEncoder:
    """Memoizing wrapper around ``tokenizer.encode``."""

    def __init__(self, tokenizer, max_length: int, cache_size: int = 200_000):
        self._tokenizer = tokenizer
        self._max_length = max_length
        self._cache: Dict[str, List[int]] = {}
        self._cache_size = cache_size

    @property
    def pad_id(self) -> int:
        return self._tokenizer.pad_id

    @property
    def max_length(self) -> int:
        return self._max_length

    def __call__(self, text: str) -> List[int]:
        ids = self._cache.get(text)
        if ids is None:
            ids = self._tokenizer.encode(text, max_length=self._max_length)
            if len(self._cache) < self._cache_size:
                self._cache[text] = ids
        return ids

    def encode_many(self, texts: Sequence[str]) -> List[List[int]]:
        """Batch lookup: cache misses go through the tokenizer's parallel
        ``encode_many`` in ONE call (rust/rayon threads — the cold-pass
        scaling path for multi-core hosts), so repeated texts (anchors,
        CVE descriptions) still hit the memo and only unique misses pay
        tokenization."""
        fresh: Dict[str, List[int]] = {}
        misses = [t for t in dict.fromkeys(texts) if t not in self._cache]
        if misses:
            for t, ids in zip(
                misses,
                self._tokenizer.encode_many(misses, max_length=self._max_length),
            ):
                fresh[t] = ids
                if len(self._cache) < self._cache_size:
                    self._cache[t] = ids
        return [
            self._cache[t] if t in self._cache else fresh[t] for t in texts
        ]


def _encode_many(encoder, texts: Sequence[str]) -> List[List[int]]:
    """Batch path when the encoder has one (CachedEncoder → rust thread
    pool), scalar loop otherwise (duck-typed stub encoders in tests)."""
    many = getattr(encoder, "encode_many", None)
    return many(texts) if many is not None else [encoder(t) for t in texts]


def _pad_block(
    seqs: Sequence[List[int]],
    batch_size: int,
    pad_id: int,
    length: int,
) -> Dict[str, np.ndarray]:
    ids = np.full((batch_size, length), pad_id, dtype=np.int32)
    mask = np.zeros((batch_size, length), dtype=np.int32)
    for i, seq in enumerate(seqs):
        seq = seq[:length]
        ids[i, : len(seq)] = seq
        mask[i, : len(seq)] = 1
    return {"input_ids": ids, "attention_mask": mask}


def _bucket_length(
    seqs: Iterable[List[int]], buckets: Optional[Sequence[int]], max_length: int
) -> int:
    longest = max((len(s) for s in seqs), default=1)
    longest = min(longest, max_length)
    if buckets:
        return next((b for b in buckets if b >= longest), buckets[-1])
    return max_length


def batches_from_instances(
    instances: Iterable[Dict],
    encoder: CachedEncoder,
    batch_size: int,
    label_map: Optional[Dict[str, int]] = None,
    buckets: Optional[Sequence[int]] = None,
    pad_to_max: bool = False,
) -> Iterator[Dict]:
    """Group instances into fixed-shape batches.

    Yields dicts with ``sample1`` (= {input_ids, attention_mask}), and when
    pairs are present ``sample2``; plus ``label`` [B] int32, ``weight`` [B]
    float32 (0 for padding rows), and ``meta`` (list, real rows only).
    """
    label_map = label_map or LABELS_SIAMESE
    for chunk in _blocks(instances, batch_size):
        yield _collate(chunk, encoder, batch_size, label_map, buckets, pad_to_max)


def _collate(
    chunk: List[Dict],
    encoder: CachedEncoder,
    batch_size: int,
    label_map: Dict[str, int],
    buckets: Optional[Sequence[int]],
    pad_to_max: bool,
) -> Dict:
    seqs1 = _encode_many(encoder, [inst["text1"] for inst in chunk])
    length1 = (
        encoder.max_length
        if pad_to_max
        else _bucket_length(seqs1, buckets, encoder.max_length)
    )
    labels = []
    for inst in chunk:
        label = inst.get("label")
        if label not in label_map:
            raise ValueError(
                f"label {label!r} not in label map {sorted(label_map)}; "
                "pass the matching label_map for this reader"
            )
        labels.append(label_map[label])
    batch: Dict = {
        "sample1": _pad_block(seqs1, batch_size, encoder.pad_id, length1),
        "label": np.array(
            labels + [0] * (batch_size - len(chunk)), dtype=np.int32
        ),
        "weight": np.array(
            [1.0] * len(chunk) + [0.0] * (batch_size - len(chunk)), dtype=np.float32
        ),
        "meta": [inst.get("meta", {}) for inst in chunk],
    }
    if chunk and chunk[0].get("text2") is not None:
        seqs2 = _encode_many(encoder, [inst["text2"] for inst in chunk])
        length2 = (
            encoder.max_length
            if pad_to_max
            else _bucket_length(seqs2, buckets, encoder.max_length)
        )
        batch["sample2"] = _pad_block(seqs2, batch_size, encoder.pad_id, length2)
    return batch


def bucketed_batches_from_instances(
    instances: Iterable[Dict],
    encoder: CachedEncoder,
    batch_size: Union[int, Dict[int, int]],
    label_map: Optional[Dict[str, int]] = None,
    buckets: Sequence[int] = (64, 128, 256, 512),
) -> Iterator[Dict]:
    """Length-binned batching: each instance is routed to the smallest
    bucket covering its token length and a batch is emitted whenever a
    bucket fills, so short reports never pay long-report padding.  This is
    where the corpus-scoring throughput win lives: per-batch pad-to-longest
    (the reference's AllenNLP collation) pads nearly every 512-report batch
    to the cap under a long-tailed length distribution, while binning keeps
    the padded-token count within ~2x of the true token count.

    Instances are re-ordered across buckets (metas travel with their rows,
    so downstream metrics are order-independent).  Tails are flushed as
    dead-row-padded batches when the stream ends.  Only single-text
    instances are supported (the eval paths); pair streams use
    :func:`batches_from_instances`.

    ``batch_size`` may be a per-bucket mapping — short buckets can then run
    much larger batches at a constant token budget, keeping the MXU busy on
    sequences the reference would drown in padding.
    """
    label_map = label_map or LABELS_SIAMESE
    buckets = tuple(sorted(buckets))
    if isinstance(batch_size, dict):
        sizes = {b: int(batch_size[b]) for b in buckets}
    else:
        sizes = {b: int(batch_size) for b in buckets}
    pending: Dict[int, List[Dict]] = {b: [] for b in buckets}
    # tokenize in blocks, not per-instance: one encode_many call hands the
    # whole block to the rust tokenizer's thread pool (cold-pass host
    # tokenization is the few-core bottleneck, docs/full_corpus.md)
    for block in _blocks(instances, 512):
        texts = []
        for inst in block:
            if inst.get("text2") is not None:
                raise ValueError(
                    "bucketed batching supports single-text instances only"
                )
            texts.append(inst["text1"])
        for inst, seq in zip(block, _encode_many(encoder, texts)):
            bucket = next((b for b in buckets if b >= len(seq)), buckets[-1])
            slot = dict(inst)
            slot["_ids"] = seq
            pending[bucket].append(slot)
            if len(pending[bucket]) == sizes[bucket]:
                yield _collate_bucket(
                    pending[bucket], encoder, sizes[bucket], label_map, bucket
                )
                pending[bucket] = []
    for bucket in buckets:
        if pending[bucket]:
            yield _collate_bucket(pending[bucket], encoder, sizes[bucket], label_map, bucket)


def _blocks(it: Iterable[Dict], size: int) -> Iterator[List[Dict]]:
    block: List[Dict] = []
    for x in it:
        block.append(x)
        if len(block) == size:
            yield block
            block = []
    if block:
        yield block


def bucket_batch_sizes(
    buckets: Sequence[int],
    tokens_per_batch: int,
    multiple_of: int = 8,
    cap: Optional[int] = None,
) -> Dict[int, int]:
    """Per-bucket batch sizes at a constant token budget, rounded down to a
    hardware-friendly multiple (and to the data-mesh axis size when
    sharded)."""
    sizes = {}
    for b in sorted(buckets):
        n = max(multiple_of, (tokens_per_batch // int(b)) // multiple_of * multiple_of)
        if cap is not None:
            n = min(n, cap)
        sizes[int(b)] = n
    return sizes


def _collate_bucket(
    chunk: List[Dict],
    encoder: CachedEncoder,
    batch_size: int,
    label_map: Dict[str, int],
    length: int,
) -> Dict:
    seqs = [inst["_ids"] for inst in chunk]
    labels = []
    for inst in chunk:
        label = inst.get("label")
        if label not in label_map:
            raise ValueError(
                f"label {label!r} not in label map {sorted(label_map)}; "
                "pass the matching label_map for this reader"
            )
        labels.append(label_map[label])
    return {
        "sample1": _pad_block(seqs, batch_size, encoder.pad_id, length),
        "label": np.array(labels + [0] * (batch_size - len(chunk)), dtype=np.int32),
        "weight": np.array(
            [1.0] * len(chunk) + [0.0] * (batch_size - len(chunk)), dtype=np.float32
        ),
        "meta": [inst.get("meta", {}) for inst in chunk],
    }


def inflight_pipeline(
    batches: Iterable[Dict],
    dispatch,
    inflight: int = 2,
) -> Iterator:
    """Asynchronous device dispatch: calls ``dispatch(batch)`` (which must
    return without blocking — JAX dispatch is async) and yields
    ``(result, batch)`` pairs, keeping up to ``inflight`` results queued on
    the accelerator before the oldest is yielded for host-side syncing.
    The host-side ``np.asarray`` of a yielded result then never leaves the
    chip idle between steps.  Shared by both predictors."""
    from collections import deque

    pending: deque = deque()
    for batch in batches:
        pending.append((dispatch(batch), batch))
        if len(pending) > inflight:
            yield pending.popleft()
    while pending:
        yield pending.popleft()


def auto_buckets(
    lengths: Sequence[int],
    max_length: int,
    n_buckets: int = 4,
    align: int = 8,
) -> Tuple[int, ...]:
    """Choose bucket boundaries that MINIMIZE total padded tokens over a
    sample of sequence lengths (exact interval-partition DP, O(k·m²)).

    Hand-picked powers of two are fine for a uniform mix, but issue-report
    corpora are long-tailed (SURVEY §6: ~12% at the 512 cap, most far
    shorter); boundaries at the distribution's natural knees cut padding
    further at zero runtime cost — the bucket count (compiled program
    count) stays the same.  The final boundary is always ``max_length`` so
    unseen longer sequences stay covered (see :func:`validate_buckets`).
    """
    import numpy as np

    if not len(lengths):
        return (max_length,)
    ls = np.minimum(np.asarray(lengths, np.int64), max_length)
    # compress to aligned candidate boundaries with (count, length-sum)
    # per candidate: the DP is over ≤ max_length/align values, so sample
    # size never matters
    aligned = np.minimum(max_length, -(-ls // align) * align)
    values, inverse = np.unique(aligned, return_inverse=True)
    counts = np.bincount(inverse)
    sums = np.bincount(inverse, weights=ls.astype(np.float64))
    if int(values[-1]) < max_length:
        # the cap is a mandatory boundary (coverage contract) — model it
        # as a zero-count top candidate so the DP can also USE it as a
        # covering bucket (padding stragglers up to the cap can beat
        # spending an interior boundary on them) while it still counts
        # against the n_buckets budget
        values = np.concatenate([values, [max_length]])
        counts = np.concatenate([counts, [0]])
        sums = np.concatenate([sums, [0.0]])
    m = len(values)
    n_pre = np.concatenate([[0], np.cumsum(counts)])
    s_pre = np.concatenate([[0.0], np.cumsum(sums)])

    # cost of one bucket covering candidate values (i, j]: the boundary
    # is values[j-1], every covered sequence pads up to it
    def cost(i: int, j: int) -> float:
        return float(values[j - 1]) * (n_pre[j] - n_pre[i]) - (
            s_pre[j] - s_pre[i]
        )

    INF = float("inf")
    # values[-1] == max_length always holds here (appended above when the
    # sample stays short), so every k-interval partition ends at the cap
    # and the total bucket count (= compiled program count) is exactly
    # the DP's k ≤ n_buckets.  Floor of 1: a non-positive budget degrades
    # to the single mandatory cap bucket rather than crashing
    k_max = max(1, n_buckets)
    f = [[INF] * (m + 1) for _ in range(k_max + 1)]
    arg = [[0] * (m + 1) for _ in range(k_max + 1)]
    f[0][0] = 0.0
    for k in range(1, k_max + 1):
        for j in range(1, m + 1):
            best, best_i = INF, 0
            for i in range(j):
                if f[k - 1][i] == INF:
                    continue
                c = f[k - 1][i] + cost(i, j)
                if c < best:
                    best, best_i = c, i
            f[k][j] = best
            arg[k][j] = best_i
    k_best = min(range(1, k_max + 1), key=lambda k: f[k][m])
    bounds = []
    j = m
    for k in range(k_best, 0, -1):
        bounds.append(int(values[j - 1]))
        j = arg[k][j]
    return tuple(sorted(set(bounds) | {max_length}))


def validate_buckets(buckets: Sequence[int], max_length: int) -> Tuple[int, ...]:
    """Buckets must cover ``max_length`` — otherwise every sequence longer
    than the largest bucket would be silently truncated below the
    configured limit, changing scores relative to the pad-to-max path."""
    out = tuple(sorted(int(b) for b in buckets))
    if not out:
        raise ValueError("buckets must be non-empty")
    if out[-1] < max_length:
        raise ValueError(
            f"largest bucket {out[-1]} < max_length {max_length}: sequences "
            f"between them would be silently truncated; include "
            f"{max_length} as the final bucket (or lower max_length)"
        )
    return out


def prefetch(iterator: Iterator, depth: int = 4) -> Iterator:
    """Run ``iterator`` on a background thread with a bounded queue.

    Safe against early consumer exit: closing/abandoning the generator
    unblocks and stops the worker rather than leaking a thread pinned on a
    full queue.
    """
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    _END = object()
    stop = threading.Event()
    error: List[BaseException] = []

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker() -> None:
        try:
            for item in iterator:
                if not _put(item):
                    return
        except BaseException as e:  # propagate into the consumer
            error.append(e)
        finally:
            _put(_END)

    thread = threading.Thread(target=worker, daemon=True)
    thread.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                if error:
                    raise error[0]
                return
            yield item
    finally:
        stop.set()
