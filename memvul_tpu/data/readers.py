"""Dataset readers: corpus JSON → instance streams.

Instance = a plain dict:
  ``text1``        first text (issue report, or anchor description)
  ``text2``        pair partner text (train mode only)
  ``label``        classification label string
  ``meta``         {"type", "label", "Issue_Url"} carried to metric/output

Two readers mirror the reference's:

* :class:`MemoryReader` — Siamese pairs with **online sampling**
  (reference: MemVul/reader_memory.py).  Each epoch re-rolls: every
  positive yields one pair with its own CVE description plus ``same-1``
  pairs with same-CWE partners (partner text: 70% partner's CVE
  description / 15% anchor / 15% partner report — reference:
  reader_memory.py:212-224); each negative survives with probability
  ``sample_neg`` and yields ``diff`` pairs against random anchors.

* :class:`SingleReader` — one instance per report, negatives subsampled
  during training (reference: MemVul/reader_single.py:106-112).

Mode selection: explicit ``split=`` argument, with the reference's
path-substring sniffing ("golden"/"test_"/"validation_",
reference: reader_memory.py:138-162) as fallback.
"""

from __future__ import annotations

import json
import logging
import random
from pathlib import Path
from typing import Dict, Iterator, List, Optional

from ..registry import Registrable
from ..resilience import faults
from .normalize import normalize_text

logger = logging.getLogger(__name__)

TRAIN, VALIDATION, TEST, GOLDEN, UNLABEL = (
    "train", "validation", "test", "golden", "unlabel",
)


def detect_split(file_path: str) -> str:
    name = str(file_path)
    if "golden" in name:
        return GOLDEN
    if "test_" in name:
        return TEST
    if "validation_" in name:
        return VALIDATION
    return TRAIN


def _iter_corpus(file_path: str, quarantine=None) -> Iterator[Dict]:
    """Stream raw sample dicts from a corpus file.

    ``.jsonl`` files (one record per line) stream without ever holding
    the corpus in memory — the format for the full 1.2M-report scoring
    job; plain ``.json`` arrays (the reference's artifact format,
    utils.py:353-381) load at once.

    With a ``quarantine`` (:class:`..resilience.journal.DeadLetter`),
    a record that fails to parse is dead-lettered with its reason and
    the stream continues — one corrupt line at report 900k must not
    kill an hours-long scoring pass.  Without one, the error propagates
    (training keeps its fail-fast contract).  The ``data.read`` fault
    point fires per record, inside the quarantined window."""
    if str(file_path).endswith(".jsonl"):
        with open(file_path, encoding="utf-8") as f:
            for lineno, line in enumerate(f):
                if not line.strip():
                    continue
                try:
                    faults.fault_point("data.read")
                    record = json.loads(line)
                except Exception as e:
                    if quarantine is None:
                        raise
                    quarantine.record(
                        f"line {lineno}: {type(e).__name__}: {e}", raw=line
                    )
                    continue
                yield record
    else:
        for i, record in enumerate(json.loads(Path(file_path).read_text())):
            try:
                faults.fault_point("data.read")
            except Exception as e:
                if quarantine is None:
                    raise
                quarantine.record(
                    f"record {i}: {type(e).__name__}: {e}",
                    meta={"Issue_Url": record.get("Issue_Url")}
                    if isinstance(record, dict) else None,
                )
                continue
            yield record


class DatasetReader(Registrable):
    def read(self, file_path: str, split: Optional[str] = None) -> Iterator[Dict]:
        raise NotImplementedError


@DatasetReader.register("reader_memory")
class MemoryReader(DatasetReader):
    def __init__(
        self,
        cve_path: Optional[str] = None,
        anchor_path: Optional[str] = None,
        same_diff_ratio: Optional[Dict[str, int]] = None,
        sample_neg: float = 0.1,
        train_iter: int = 1,
        target: str = "Security_Issue_Full",
        seed: Optional[int] = None,
    ) -> None:
        self._target = target
        self._ratio = same_diff_ratio or {"same": 2, "diff": 6}
        self._sample_neg = sample_neg
        self._train_iter = train_iter
        self._rng = random.Random(seed)
        self._cve: Dict[str, Dict] = {}
        self._anchors: Dict[str, str] = {}
        if cve_path:
            self._cve = json.loads(Path(cve_path).read_text())
        if anchor_path:
            self._anchors = json.loads(Path(anchor_path).read_text())
        self._grouped_cache: Dict[str, Dict[str, List[Dict]]] = {}

    def reseed(self, seed: int) -> None:
        """Re-seed the pair-sampling RNG.  The trainer calls this at
        every epoch start so each epoch's pair stream is a pure function
        of (trainer seed, epoch index) — the property that lets a
        preempted run replay the interrupted epoch's stream exactly
        (training/trainer.py:_epoch_seed)."""
        self._rng.seed(seed)

    # -- corpus handling -----------------------------------------------------

    def _prepare_sample(self, s: Dict) -> Optional[Dict]:
        """Normalize one raw corpus record in place: concatenated text,
        pos/neg target, CWE resolution via the CVE record.  Returns None
        for dirty positives lacking a CWE (reference drops those,
        reader_memory.py:103-105)."""
        s["text"] = f"{s.get('Issue_Title') or ''}. {s.get('Issue_Body') or ''}"
        if str(s.get(self._target)) in ("1", "1.0"):
            cwe_id = s.get("CWE_ID") or self._cve.get(s.get("CVE_ID"), {}).get("CWE_ID")
            if cwe_id is None:
                return None
            s[self._target] = "pos"
            s["CWE_ID"] = cwe_id
        else:
            s[self._target] = "neg"
        return s

    def _cve_description(self, cve_id: str) -> str:
        """CVE descriptions need tag replacement exactly once
        (reference: reader_memory.py:96-99)."""
        rec = self._cve[cve_id]
        if not rec.get("_normalized"):
            rec["CVE_Description"] = normalize_text(rec.get("CVE_Description") or "")
            rec["_normalized"] = True
        return rec["CVE_Description"]

    def group_by_cwe(self, file_path: str) -> Dict[str, List[Dict]]:
        """Load a corpus file and bucket samples: negatives under "neg",
        positives under their CWE category (via the CVE record)."""
        if file_path in self._grouped_cache:
            return self._grouped_cache[file_path]
        grouped: Dict[str, List[Dict]] = {"neg": []}
        for s in _iter_corpus(file_path):
            s = self._prepare_sample(s)
            if s is None:
                continue  # positives lacking a CWE are dirty data
            if s[self._target] == "pos":
                grouped.setdefault(s["CWE_ID"], []).append(s)
            else:
                grouped["neg"].append(s)
        self._grouped_cache[file_path] = grouped
        return grouped

    # -- instance generation -------------------------------------------------

    def read(
        self,
        file_path: str,
        split: Optional[str] = None,
        quarantine=None,
    ) -> Iterator[Dict]:
        split = split or detect_split(file_path)
        if split == GOLDEN:
            yield from self.read_anchors(file_path)
            return
        if split in (TEST, VALIDATION, UNLABEL):
            # reference semantics: test corpora stream as unlabeled scoring
            # instances, validation as labeled "test" instances
            # (reference: reader_memory.py:146-162).  Evaluation is
            # one-pass, so the corpus streams sample-by-sample — a .jsonl
            # file never materializes in host RAM (the 1.2M-report job);
            # a cached grouped corpus is reused when one exists.
            # ``quarantine`` (a resilience.DeadLetter) makes the stream
            # survive malformed/over-long records by dead-lettering them.
            mode = "test" if split == VALIDATION else UNLABEL
            count = 0
            if file_path in self._grouped_cache:
                samples = (
                    s
                    for bucket in self._grouped_cache[file_path].values()
                    for s in bucket
                )
            else:
                samples = self._prepared_stream(file_path, quarantine)
            for s in samples:
                if (
                    quarantine is not None
                    and len(s.get("text") or "") > quarantine.max_text_chars
                ):
                    quarantine.record(
                        f"over-long text ({len(s['text'])} chars > "
                        f"{quarantine.max_text_chars} cap)",
                        meta={"Issue_Url": s.get("Issue_Url")},
                    )
                    continue
                count += 1
                yield self._eval_instance(s, mode)
            logger.info("%s: %d evaluation instances", file_path, count)
        else:
            # pair generation needs same-CWE partner lookup: grouped corpus
            # (training keeps its fail-fast contract: no quarantine here)
            yield from self._train_pairs(self.group_by_cwe(file_path))

    def _prepared_stream(self, file_path: str, quarantine) -> Iterator[Dict]:
        for s in _iter_corpus(file_path, quarantine=quarantine):
            try:
                prepared = self._prepare_sample(s)
            except Exception as e:
                if quarantine is None:
                    raise
                quarantine.record(
                    f"prepare failed: {type(e).__name__}: {e}",
                    meta={"Issue_Url": s.get("Issue_Url")}
                    if isinstance(s, dict) else None,
                )
                continue
            if prepared is not None:
                yield prepared

    def read_anchors(self, anchor_path: Optional[str] = None) -> Iterator[Dict]:
        anchors = (
            json.loads(Path(anchor_path).read_text()) if anchor_path else self._anchors
        )
        for category, description in anchors.items():
            yield {
                "text1": description,
                "label": "same",
                "meta": {"type": GOLDEN, "label": category},
            }

    def _eval_instance(self, s: Dict, mode: str) -> Dict:
        positive = s[self._target] == "pos"
        return {
            "text1": s["text"],
            "label": "same" if positive else "diff",
            "meta": {
                "type": mode,
                "label": s.get("CWE_ID") if positive else "neg",
                "Issue_Url": s.get("Issue_Url"),
            },
        }

    def _train_pairs(self, grouped: Dict[str, List[Dict]]) -> Iterator[Dict]:
        all_data = [s for bucket in grouped.values() for s in bucket]
        self._rng.shuffle(all_data)
        anchor_ids = list(self._anchors.keys())
        same_k, diff_k = self._ratio["same"], self._ratio["diff"]
        rng = self._rng
        same_num = diff_num = 0

        for _ in range(self._train_iter):
            for s in all_data:
                if s[self._target] == "pos":
                    yield self._pair_instance(s, s)
                    partners = grouped[s["CWE_ID"]]
                    for partner in rng.choices(partners, k=same_k - 1):
                        yield self._pair_instance(s, partner)
                    same_num += same_k
                elif rng.random() < self._sample_neg:
                    for category in rng.choices(anchor_ids, k=diff_k):
                        yield self._anchor_pair_instance(s, category)
                    diff_num += diff_k
        logger.info("pair counts: same=%d diff=%d", same_num, diff_num)

    def _partner_text(self, s: Dict, partner: Dict) -> str:
        """Choose the matched pair's second text
        (reference: reader_memory.py:205-224)."""
        rng = self._rng
        if s["Issue_Url"] == partner["Issue_Url"]:
            return self._cve_description(partner["CVE_ID"])
        if rng.random() < 0.7:
            return self._cve_description(partner["CVE_ID"])
        if rng.random() < 0.5:
            category = partner.get("CWE_ID")
            if category is not None and category in self._anchors:
                return self._anchors[category]
            return partner["text"]
        return partner["text"]

    def _pair_instance(self, s: Dict, partner: Dict) -> Dict:
        return {
            "text1": s["text"],
            "text2": self._partner_text(s, partner),
            "label": "same",
            "meta": {"type": TRAIN, "label": s["CWE_ID"], "Issue_Url": s["Issue_Url"]},
        }

    def _anchor_pair_instance(self, s: Dict, category: str) -> Dict:
        return {
            "text1": s["text"],
            "text2": self._anchors[category],
            "label": "diff",
            "meta": {"type": TRAIN, "label": "neg", "Issue_Url": s.get("Issue_Url")},
        }


@DatasetReader.register("reader_single")
class SingleReader(DatasetReader):
    def __init__(
        self,
        sample_neg: Optional[float] = None,
        target: str = "Security_Issue_Full",
        seed: Optional[int] = None,
    ) -> None:
        self._target = target
        self._sample_neg = sample_neg
        self._rng = random.Random(seed)

    def read(self, file_path: str, split: Optional[str] = None) -> Iterator[Dict]:
        split = split or detect_split(file_path)
        for s in _iter_corpus(file_path):
            positive = str(s.get(self._target)) in ("1", "1.0", "pos")
            if (
                split == TRAIN
                and not positive
                and self._sample_neg is not None
                and self._rng.random() >= self._sample_neg
            ):
                continue
            yield {
                "text1": f"{s.get('Issue_Title') or ''}. {s.get('Issue_Body') or ''}",
                "label": "pos" if positive else "neg",
                "meta": {
                    "type": split,
                    "label": "pos" if positive else "neg",
                    "Issue_Url": s.get("Issue_Url"),
                },
            }
