from .normalize import normalize_text, replace_tokens_simple  # noqa: F401
