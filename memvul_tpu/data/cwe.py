"""CWE Research-View graph + external-memory anchor construction.

Builds the "external memory" of the Siamese matcher: one natural-language
anchor description per CWE category observed in the training split
(reference builds 129 of them — utils.py:310-350).  An anchor description
concatenates, over a BFS subtree of the Research View rooted at the CWE
(level-1 by default, abstraction-sorted), each member's name, description,
consequence impacts and extended description, then appends a few sampled
member-CVE descriptions.  CWEs outside the Research View fall back to CVE
descriptions alone (reference: utils.py:328-332).

Graph semantics (reference: utils.py:155-183): edges come from the
``Related Weaknesses`` field restricted to VIEW 1000 — ChildOf/ParentOf
become father/children, PeerOf/CanAlsoBe become peer, CanPrecede/Requires
become relate.
"""

from __future__ import annotations

import csv
import json
import random
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Union

from .normalize import normalize_text

# ordering used to put high-level categories before specific ones
ABSTRACTION_RANK = {"Pillar": 1, "Class": 2, "Base": 2.5, "Variant": 3, "Compound": 3}


def load_research_view_csv(path: Union[str, Path]) -> List[Dict[str, str]]:
    """Read the CWE Research View export (1000.csv) into record dicts."""
    with open(path, newline="", encoding="utf-8") as f:
        return list(csv.DictReader(f))


def build_cwe_tree(records: Iterable[Dict[str, str]]) -> Dict[str, Dict]:
    """Link CWE records into a graph keyed by the bare numeric id (str)."""
    tree: Dict[str, Dict] = {}
    for rec in records:
        node = dict(rec)
        node.update(father=[], children=[], peer=[], relate=[])
        tree[str(rec["CWE-ID"])] = node

    for cwe_id, node in tree.items():
        for rel in (node.get("Related Weaknesses") or "").split("::"):
            if "VIEW ID:1000" not in rel:
                continue
            parts = rel.split(":")
            try:
                target = str(int(parts[3]))
            except (IndexError, ValueError):
                continue
            if target not in tree:
                continue
            if "ChildOf" in parts:
                node["father"].append(target)
                tree[target]["children"].append(cwe_id)
            elif "PeerOf" in parts or "CanAlsoBe" in parts:
                node["peer"].append(target)
                tree[target]["peer"].append(cwe_id)
            elif "CanPrecede" in parts or "Requires" in parts:
                node["relate"].append(target)
                tree[target]["relate"].append(cwe_id)
    return tree


def bfs_subtree(tree: Dict[str, Dict], root: str, level: int = 1) -> List[str]:
    """Collect ids reachable from ``root`` within ``level`` hops (children,
    peers and related nodes all count as neighbors), root first, BFS order,
    deduplicated keeping first occurrence."""
    seen: List[str] = []
    frontier = [str(root)]
    for _ in range(level + 1):
        nxt: List[str] = []
        for node_id in frontier:
            if node_id not in tree:
                continue
            if node_id not in seen:
                seen.append(node_id)
            node = tree[node_id]
            nxt.extend(str(x) for x in node["children"] + node["peer"] + node["relate"])
        if not nxt:
            break
        frontier = nxt
    return seen


def _with_period(s: str) -> str:
    s = (s or "").strip()
    if not s:
        return s
    if not s.endswith("."):
        s += "."
    return s + " "


def _consequence_impacts(common_consequences: str) -> List[str]:
    """Extract IMPACT values from the ``::``-packed Common Consequences
    field (reference: utils.py:288-295)."""
    impacts: List[str] = []
    for item in (common_consequences or "").split("::"):
        if "SCOPE" not in item:
            continue
        grab = False
        for element in item.split(":"):
            if grab and element not in ("IMPACT", "NOTE"):
                impacts.append(element)
            grab = element == "IMPACT"
    return impacts


def describe_cwe(tree: Dict[str, Dict], cwe_id: str) -> str:
    """Natural-language description of one CWE node."""
    node = tree[str(cwe_id)]
    text = _with_period(node.get("Name", ""))
    text += _with_period(node.get("Description", ""))
    for impact in _consequence_impacts(node.get("Common Consequences", "")):
        text += _with_period(impact)
    text += _with_period(node.get("Extended Description", ""))
    return text


def cwe_distribution(
    pos_samples: Iterable[Dict], cve_dict: Dict[str, Dict]
) -> Dict[str, Dict]:
    """Count issue reports and CVEs per CWE category over positives
    (reference: utils.py:207-235).  Keys are full ids like ``CWE-79`` or
    the special NVD categories; samples with a missing CWE land in
    ``null``."""
    dist: Dict[str, Dict] = {}
    for sample in pos_samples:
        cve_id = sample["CVE_ID"]
        cwe_id = sample.get("CWE_ID") or cve_dict.get(cve_id, {}).get("CWE_ID") or "null"
        bucket = dist.setdefault(
            cwe_id, {"#issue report": 0, "#CVE": 0, "CVE_distribution": {}}
        )
        bucket["#issue report"] += 1
        if cve_id not in bucket["CVE_distribution"]:
            bucket["CVE_distribution"][cve_id] = 0
            bucket["#CVE"] += 1
        bucket["CVE_distribution"][cve_id] += 1
    return dist


def _category_description(
    tree: Dict[str, Dict],
    bare_id: str,
    member_cves: List[str],
    cve_dict: Dict[str, Dict],
    rng: "random.Random",
    level: int,
    num_cve_per_anchor: int,
) -> str:
    """One anchor's text (reference recipe, utils.py:310-350): in-view
    nodes get the abstraction-ranked BFS subtree description plus up to
    ``num_cve_per_anchor`` sampled member-CVE descriptions; out-of-view
    categories get CVE descriptions alone, 3× as many.  Shared by the
    train-seen bank and the full-view bank so the two can never drift."""
    description = ""
    if bare_id not in tree:
        k = min(3 * num_cve_per_anchor, len(member_cves))
        for cve_id in rng.sample(member_cves, k=k):
            description += _with_period(
                normalize_text(cve_dict[cve_id]["CVE_Description"])
            )
        return description.strip()
    subtree = bfs_subtree(tree, bare_id, level)
    ranked = sorted(
        subtree,
        key=lambda x: ABSTRACTION_RANK.get(
            tree[x].get("Weakness Abstraction", ""), 4
        ),
    )
    for node_id in ranked:
        description += describe_cwe(tree, node_id)
    k = min(num_cve_per_anchor, len(member_cves))
    for cve_id in rng.sample(member_cves, k=k):
        description += _with_period(
            normalize_text(cve_dict[cve_id]["CVE_Description"])
        )
    return description.strip()


def build_anchors(
    distribution: Dict[str, Dict],
    tree: Dict[str, Dict],
    cve_dict: Dict[str, Dict],
    level: int = 1,
    num_cve_per_anchor: int = 5,
    seed: Optional[int] = None,
) -> Dict[str, str]:
    """Build anchor descriptions for every CWE category in ``distribution``
    (reference: utils.py:310-350).  Returns {category id: description}."""
    rng = random.Random(seed)
    anchors: Dict[str, str] = {}
    for category, info in distribution.items():
        if category == "null":
            continue  # CVE record missing its CWE — dirty data
        member_cves = list(info["CVE_distribution"].keys())
        bare_id = category.split("-", 1)[1] if "-" in category else category
        anchors[category] = _category_description(
            tree, bare_id, member_cves, cve_dict, rng, level, num_cve_per_anchor
        )
    return anchors


def build_full_view_anchors(
    tree: Dict[str, Dict],
    cve_dict: Dict[str, Dict],
    distribution: Optional[Dict[str, Dict]] = None,
    level: int = 1,
    num_cve_per_anchor: int = 5,
    seed: Optional[int] = None,
) -> Dict[str, str]:
    """CWE-1000-scale external memory: one anchor per node of the whole
    Research View, not just the CWEs seen in training.

    The reference's bank is capped at the 129 train-time categories
    (utils.py:347); this is the stretch bank that covers every weakness
    class the view describes (~900+ nodes) PLUS every train-seen
    out-of-view category (NVD-CWE-noinfo etc. — covered via the same
    3×-CVE-description fallback as :func:`build_anchors`), so it is a
    strict superset of the train-seen bank's categories.  Nodes with no
    training CVEs get the subtree description alone.  The resulting bank
    is the size the model-axis anchor sharding in
    evaluate/predict_memory.py exists for."""
    rng = random.Random(seed)
    distribution = distribution or {}
    cves_by_category = {
        cat: list(info["CVE_distribution"].keys())
        for cat, info in distribution.items()
        if cat != "null"
    }
    categories = {f"CWE-{bare_id}": bare_id for bare_id in tree}
    for cat in cves_by_category:  # train-seen out-of-view categories
        categories.setdefault(
            cat, cat.split("-", 1)[1] if "-" in cat else cat
        )
    anchors: Dict[str, str] = {}
    for category, bare_id in categories.items():
        description = _category_description(
            tree,
            bare_id,
            cves_by_category.get(category, []),
            cve_dict,
            rng,
            level,
            num_cve_per_anchor,
        )
        if description:
            anchors[category] = description
    return anchors


def save_anchors(anchors: Dict[str, str], path: Union[str, Path]) -> None:
    """Persist an anchor set.  Atomic (tmp + rename): the anchor JSON is
    the artifact ``bank build`` imports into the versioned store
    (docs/anchor_bank.md), so a killed build must never leave a torn
    file where a digest-verified bank is about to come from."""
    from ..resilience.io import atomic_write_text

    atomic_write_text(Path(path), json.dumps(anchors, indent=2))


def load_anchors(path: Union[str, Path]) -> Dict[str, str]:
    return json.loads(Path(path).read_text())
