"""Sharded map-reduce corpus scoring (docs/full_corpus.md).

The paper's corpus is 1.22M issue reports and ``predict_file`` is a
single-process stream — one wedged host serializes the whole multi-hour
pass.  This package composes the existing resilience ingredients
(``ScoreJournal`` resume, ``DeadLetter`` quarantine, ``RetryPolicy``
backoff, per-run telemetry + live ``/metrics``) into a supervised
multi-process run:

* :func:`partition.partition_rows` — deterministic contiguous row-span
  partition of the corpus (pure in (corpus length, shard count), so a
  restarted coordinator recomputes identical spans);
* ``worker`` — one subprocess per shard, running the resumable
  ``predict_file`` over its span with its own journal, dead-letter
  file, and ``HEARTBEAT.json``;
* :func:`coordinator.score_corpus` — launches and supervises the
  workers (heartbeat-age stall detection, exit-code death detection,
  exponential-backoff restarts, quarantine after ``max_shard_attempts``),
  then merges shard outputs in partition order under an exactly-once
  verification pass before computing corpus metrics byte-identical to a
  single-process run.
"""

from .coordinator import (  # noqa: F401
    MergeVerificationError,
    PartialCompletionError,
    score_corpus,
)
from .partition import partition_rows  # noqa: F401
