"""One shard worker: score a contiguous row span of the corpus.

Launched by the coordinator as ``python -m memvul_tpu.distributed.worker
<spec.json>`` — one subprocess per shard, in its own session (killable
as a process group).  The spec carries everything pre-resolved by the
coordinator (archive path, span, the merged evaluation config, explicit
bucket boundaries) so every attempt of every shard scores under one
identical configuration.

The worker is just the existing resumable single-process machinery
pointed at a slice: ``predict_file(resume=True)`` with the shard's own
journal (``<out>.journal``), dead-letter file, and ``HEARTBEAT.json``.
A SIGKILLed attempt replays nothing it committed — the next attempt's
journal resume skips the verified prefix, which is what makes restarts
free of double-scoring (the merge verifier proves it).

Completion contract: exit 0 **and** an atomically-written
``shard_metrics.json`` marker.  Exit 0 without the marker is treated as
a failure by the supervisor (a worker that died between the last
journal append and the marker write).
"""

from __future__ import annotations

import itertools
import json
import logging
import sys
import time
from pathlib import Path
from typing import Dict, Iterator, Optional

logger = logging.getLogger(__name__)


class SpanReader:
    """Wrap a dataset reader to yield only rows ``[start, end)`` of the
    (post-quarantine) stream, salted with the ``shard.kill`` /
    ``shard.stall`` fault points.

    ``shard.kill`` (or ``shard.kill.<shard>``) fires before a row is
    yielded — with the ``sigkill`` action it dies exactly like an
    OOM-killed host, no handler, no cleanup.  ``shard.stall`` armed with
    a ``raise`` action wedges the worker instead: it stops yielding and
    sleeps forever, so heartbeat age grows and the supervisor's stall
    detector (not an exit code) must catch it.
    """

    def __init__(self, reader, start: int, end: int, shard: str) -> None:
        self._reader = reader
        self.start = int(start)
        self.end = int(end)
        self.shard = shard

    def read(
        self,
        file_path: str,
        split: Optional[str] = None,
        quarantine=None,
    ) -> Iterator[Dict]:
        from ..resilience import faults

        stream = (
            self._reader.read(file_path, split=split, quarantine=quarantine)
            if quarantine is not None
            else self._reader.read(file_path, split=split)
        )
        for inst in itertools.islice(stream, self.start, self.end):
            faults.fault_point("shard.kill")
            faults.fault_point(f"shard.kill.{self.shard}")
            try:
                faults.fault_point("shard.stall")
                faults.fault_point(f"shard.stall.{self.shard}")
            except Exception as e:
                logger.warning("injected stall (%s): worker wedged", e)
                while True:  # simulate a hung device op: alive, no progress
                    time.sleep(60.0)
            yield inst

    def read_anchors(self, anchor_path: Optional[str] = None):
        return self._reader.read_anchors(anchor_path)


def run_worker(spec_path: str) -> int:
    """Score one shard per its spec file; return the process exit code."""
    from ..utils.platform import honor_platform_env

    honor_platform_env()

    from .. import telemetry
    from ..archive import load_archive
    from ..build import build_reader
    from ..evaluate.predict_memory import SiamesePredictor
    from ..resilience.io import atomic_write_text
    from ..resilience.retry import RetryPolicy

    spec = json.loads(Path(spec_path).read_text())
    shard_dir = Path(spec["shard_dir"])
    ev = spec["evaluation"]
    tel = telemetry.configure(
        run_dir=shard_dir,
        heartbeat_every_s=float(spec["heartbeat_every_s"]),
    )
    try:
        arch = load_archive(spec["archive"], overrides=spec.get("overrides"))
        reader = build_reader(arch.config.get("dataset_reader"))
        span_reader = SpanReader(
            reader, spec["start"], spec["end"], spec["name"]
        )
        # no mesh in workers: each shard must score deterministically so
        # merged metrics stay byte-identical to a single-process pass
        # (scale comes from shard parallelism, not an in-worker mesh)
        predictor = SiamesePredictor(
            arch.model,
            arch.params,
            arch.tokenizer,
            batch_size=int(ev["batch_size"]),
            max_length=int(ev["max_length"]),
            buckets=ev["buckets"],
            tokens_per_batch=ev["tokens_per_batch"],
            anchor_match_impl=ev["anchor_match_impl"],
            aot_warmup=bool(ev["aot_warmup"]),
        )
        predictor.encode_anchors(reader.read_anchors(spec["golden_file"]))
        # first liveness snapshot BEFORE scoring: model load + anchor
        # encode can take minutes at real scale, and the supervisor's
        # stall clock should start from real progress, not launch time
        tel.heartbeat(force=True, rows_scored=0)
        score_retries = int(ev["score_retries"])
        metrics = predictor.predict_file(
            span_reader,
            spec["test_path"],
            spec["out_path"],
            split=spec.get("split"),
            inflight=int(ev["inflight"]),
            resume=True,
            quarantine=ev["quarantine"],
            heartbeat_batches=max(1, int(ev["heartbeat_batches"])),
            retry_policy=RetryPolicy(attempts=score_retries)
            if score_retries > 0 else None,
            expected_reports=spec["end"] - spec["start"],
            attribute_anchors=bool(ev["attribute_anchors"]),
        )
        # the completion marker commits atomically AFTER the journal
        # drained: its presence + exit 0 is the shard's "done" claim
        atomic_write_text(
            shard_dir / "shard_metrics.json",
            json.dumps({
                "shard": spec["name"],
                "span": [spec["start"], spec["end"]],
                "rows": metrics.get("num_samples", 0),
                "metrics": metrics,
            }, default=str),
        )
        return 0
    finally:
        telemetry.write_programs(shard_dir)
        tel.close()


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        # CLI usage text belongs on stderr, not in a logger
        print(  # lint: disable=MV101
            "usage: python -m memvul_tpu.distributed.worker <spec.json>",
            file=sys.stderr,
        )
        return 2
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    return run_worker(argv[0])


if __name__ == "__main__":
    sys.exit(main())
