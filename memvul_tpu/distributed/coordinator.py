"""Shard coordinator: supervise N scoring workers, merge exactly once.

``score_corpus`` is the offline analogue of the serving tier's
kill→reroute→restart story (serving/router.py), applied to the
paper-scale corpus pass:

1. **Partition** — ``partition_rows`` splits the corpus into contiguous
   row spans, one supervised worker subprocess per span (each running
   the resumable ``predict_file`` with its own journal, dead-letter
   file, and ``HEARTBEAT.json``).
2. **Supervise** — a poll loop watches exit codes and heartbeat age:
   a dead worker (nonzero exit, or exit 0 without its completion
   marker) restarts with exponential backoff through the shared
   :class:`RetryPolicy`; a stalled worker (heartbeat older than
   ``shard_stall_timeout_s``) is process-group-killed first.  Resume
   picks up from the shard journal, so a SIGKILLed worker replays
   nothing it committed.  After ``max_shard_attempts`` the shard is
   **quarantined** and the run ends in a machine-readable
   :class:`PartialCompletionError` naming the missing spans — never
   silently truncated metrics.
3. **Merge + verify** — shard outputs concatenate in partition order
   under a mandatory verification pass over the merged journals: span
   algebra proving every corpus row appears exactly once (no loss, no
   double-count across restarts) plus the per-line sha256 checksums,
   before ``cal_metrics`` computes corpus metrics byte-identical to a
   single-process run.

Per-shard progress (rows committed, heartbeat age, retries, restarts,
quarantines) is exported through the live ``/metrics`` endpoint when
``telemetry.metrics_port`` is set (docs/observability.md).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal
import subprocess
import sys
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple, Union

from .partition import partition_rows

logger = logging.getLogger(__name__)


class PartialCompletionError(RuntimeError):
    """One or more shards were quarantined: the corpus was NOT fully
    scored and no merged metrics were computed.  ``payload`` is the
    machine-readable refusal (``status: "partial"``, the quarantined
    shards with their failure history, and the missing row spans) — the
    CLI prints it as JSON and exits 3 (docs/full_corpus.md)."""

    def __init__(self, payload: Dict[str, Any]) -> None:
        self.payload = payload
        super().__init__(json.dumps(payload, default=str))


class MergeVerificationError(RuntimeError):
    """The exactly-once verification pass failed: a journal tail did not
    verify, a row is missing, or a row was scored twice.  ``payload``
    names every problem per shard."""

    def __init__(self, payload: Dict[str, Any]) -> None:
        self.payload = payload
        super().__init__(json.dumps(payload, default=str))


@dataclasses.dataclass
class _ShardState:
    name: str
    start: int
    end: int
    dir: Path
    spec_path: Path
    out_path: Path
    proc: Optional[subprocess.Popen] = None
    attempts: int = 0
    status: str = "pending"  # pending|running|waiting|done|quarantined
    restart_at: float = 0.0
    launched_wall: float = 0.0
    failures: List[str] = dataclasses.field(default_factory=list)


def heartbeat_age_s(
    heartbeat: Dict[str, Any], launched_wall: float, now: float
) -> float:
    """Stall clock for one worker attempt: seconds since the later of
    the last ``HEARTBEAT.json`` write and this attempt's launch.  The
    heartbeat file survives restarts, so a fresh attempt must not
    inherit the dead attempt's stale age — the launch wall resets the
    clock (pinned in tests/test_distributed.py)."""
    try:
        written = float(heartbeat.get("written_wall"))
    except (TypeError, ValueError):
        written = 0.0
    base = max(written, launched_wall)
    if base <= 0:
        return 0.0
    return max(0.0, now - base)


def _kill_process_group(proc: subprocess.Popen, grace: float = 5.0) -> None:
    """SIGTERM the worker's whole session, then SIGKILL — same
    discipline as the bench supervisor (a wedged PJRT client can ignore
    SIGTERM forever)."""
    if grace > 0:
        try:
            os.killpg(proc.pid, signal.SIGTERM)
            proc.wait(timeout=grace)
            return
        except (ProcessLookupError, PermissionError, OSError):
            pass
        except subprocess.TimeoutExpired:
            pass
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        pass
    try:
        proc.wait(timeout=10)
    except Exception:
        pass


def _merge_and_verify(
    states: List[_ShardState],
    corpus_rows: int,
    out_results: Path,
    out_metrics: Path,
    thres: float,
    tel,
) -> Tuple[Dict[str, float], float]:
    """Concatenate shard outputs in partition order under the
    exactly-once contract; returns ``(metrics, merge_wall_s)``."""
    from ..evaluate.measure import cal_metrics
    from ..resilience import faults
    from ..resilience.journal import ScoreJournal, to_spans

    faults.fault_point("merge.verify")
    t0 = time.perf_counter()
    covered: set = set()
    merged_lines: List[str] = []
    problems: List[Dict[str, Any]] = []
    for sh in states:
        journal = ScoreJournal(str(sh.out_path) + ".journal")
        entries = journal.read_entries()
        kept_n, completed, kept_lines = journal.verified_prefix(sh.out_path)
        if kept_n != len(entries):
            problems.append({
                "shard": sh.name,
                "reason": "journal tail failed line-checksum verification",
                "unverified_entries": len(entries) - kept_n,
            })
        expected = set(range(sh.end - sh.start))
        missing = expected - completed
        if missing:
            problems.append({
                "shard": sh.name,
                "reason": "rows missing from the verified journal",
                "missing_spans": [
                    [s + sh.start, e + sh.start]
                    for s, e in to_spans(missing)
                ],
            })
        extra = completed - expected
        if extra:
            problems.append({
                "shard": sh.name,
                "reason": "journal claims rows outside the shard span",
                "extra_spans": to_spans(extra),
            })
        global_rows = {r + sh.start for r in completed if r in expected}
        dup = covered & global_rows
        if dup:
            problems.append({
                "shard": sh.name,
                "reason": "rows already covered by an earlier shard",
                "duplicate_spans": to_spans(dup),
            })
        covered |= global_rows
        merged_lines.extend(kept_lines)
    if not problems and covered != set(range(corpus_rows)):
        # backstop: per-shard algebra should have named the gap already
        problems.append({
            "shard": None,
            "reason": "merged coverage does not equal the corpus",
            "missing_spans": to_spans(set(range(corpus_rows)) - covered),
        })
    if problems:
        raise MergeVerificationError({
            "status": "verification_failed",
            "rows_total": corpus_rows,
            "rows_verified": len(covered),
            "problems": problems,
        })
    with open(out_results, "w", encoding="utf-8") as f:
        for line in merged_lines:
            f.write(line + "\n")
    metrics = cal_metrics(out_results, thres=thres, out_file=out_metrics)
    wall = time.perf_counter() - t0
    tel.counter("merge.rows_verified").inc(len(covered))
    tel.gauge("merge.wall_s").set(round(wall, 3))
    tel.event(
        "merge_verified",
        rows=len(covered), shards=len(states), wall_s=round(wall, 3),
    )
    return metrics, wall


def score_corpus(
    archive_path: Union[str, Path],
    test_path: Union[str, Path],
    out_dir: Union[str, Path],
    shards: Optional[int] = None,
    overrides: Optional[Union[str, Dict[str, Any]]] = None,
    golden_file: Optional[Union[str, Path]] = None,
    name: Optional[str] = None,
    thres: float = 0.5,
    split: Optional[str] = None,
) -> Dict[str, Any]:
    """Score ``test_path`` across ``shards`` supervised worker
    subprocesses and return the merged, verification-gated result.

    Writes ``{name}_result.json`` + ``{name}_metric_all.json`` in
    ``out_dir`` (the ``evaluate_from_archive`` artifact contract) plus
    one ``shard-<i>/`` subdir per shard with that worker's spec,
    output, journal, heartbeat, and ``worker.log``.

    Raises :class:`PartialCompletionError` when any shard exhausts
    ``max_shard_attempts`` and :class:`MergeVerificationError` when the
    exactly-once pass fails — silent truncation is not an outcome.
    """
    from .. import telemetry
    from ..archive import load_archive
    from ..build import _auto_buckets_for_corpus, build_reader
    from ..config import evaluation_config, telemetry_config
    from ..resilience.retry import RetryPolicy
    from ..telemetry.sinks import HeartbeatFile

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    arch = load_archive(archive_path, overrides=overrides)
    tel_cfg = telemetry_config(arch.config)
    tel = telemetry.configure(
        run_dir=out_dir,
        enabled=bool(tel_cfg["enabled"]),
        events=bool(tel_cfg["events"]),
        heartbeat_every_s=float(tel_cfg["heartbeat_every_s"]),
        step_events=bool(tel_cfg["step_events"]),
    )
    metrics_port = int(tel_cfg["metrics_port"] or 0)
    metrics_server = (
        telemetry.start_metrics_server(metrics_port) if metrics_port else None
    )
    try:
        model_cfg = arch.config.get("model") or {}
        model_type = model_cfg.get("type", "model_memory")
        if model_type != "model_memory":
            raise ValueError(
                f"score-corpus supports memory-model archives only, "
                f"got model type {model_type!r}"
            )
        name = name or model_type
        golden = golden_file or (
            arch.config.get("dataset_reader") or {}
        ).get("anchor_path")
        if golden is None:
            raise ValueError(
                "memory-model corpus scoring needs a golden anchor file"
            )
        eval_cfg = evaluation_config(arch.config)
        n_shards = int(shards if shards is not None else eval_cfg["shards"])
        if n_shards < 1:
            raise ValueError(f"shards must be >= 1, got {n_shards}")
        max_shard_attempts = max(1, int(eval_cfg["max_shard_attempts"]))
        stall_timeout = float(eval_cfg["shard_stall_timeout_s"])
        poll_interval = float(eval_cfg["shard_poll_interval_s"])
        policy = RetryPolicy(
            attempts=max_shard_attempts,
            backoff=float(eval_cfg["shard_backoff_s"]),
            exponential=True,
        )

        reader = build_reader(arch.config.get("dataset_reader"))
        max_length = int(eval_cfg["max_length"])
        model_positions = getattr(
            getattr(arch.model, "config", None), "max_position_embeddings",
            None,
        )
        if model_positions is not None and max_length > model_positions:
            logger.warning(
                "evaluation max_length %d exceeds the archived model's "
                "max_position_embeddings %d — clamping",
                max_length, model_positions,
            )
            max_length = model_positions
        buckets = eval_cfg["buckets"]
        if buckets == "auto":
            # resolved ONCE here, shipped to every worker as an explicit
            # list: shards sampling their own spans would disagree on
            # boundaries and break batch-shape determinism across
            # restarts
            buckets = _auto_buckets_for_corpus(
                reader, arch.tokenizer, str(test_path), max_length,
                n_buckets=int(eval_cfg["n_buckets"]),
            )
            logger.info("auto buckets for %s: %s", test_path, buckets)
        elif buckets is not None:
            buckets = [int(b) for b in buckets]
        tokens_per_batch = eval_cfg["tokens_per_batch"]
        resolved_eval = {
            "batch_size": int(eval_cfg["batch_size"]),
            "max_length": max_length,
            "buckets": buckets,
            "tokens_per_batch": (
                int(tokens_per_batch) if tokens_per_batch is not None else None
            ),
            "inflight": int(eval_cfg["inflight"]),
            "anchor_match_impl": eval_cfg["anchor_match_impl"],
            "aot_warmup": bool(eval_cfg["aot_warmup"]),
            "quarantine": eval_cfg["quarantine"],
            "heartbeat_batches": int(eval_cfg["heartbeat_batches"]),
            "score_retries": int(eval_cfg["score_retries"]),
            "attribute_anchors": bool(eval_cfg["attribute_anchors"]),
        }

        # one counting pass pins the partition input; the same reader
        # configuration streams in every worker, so the numbering agrees
        corpus_rows = sum(1 for _ in reader.read(str(test_path), split=split))
        spans = partition_rows(corpus_rows, n_shards)
        logger.info(
            "scoring %d corpus rows across %d shards: %s",
            corpus_rows, n_shards, spans,
        )
        worker_heartbeat_s = float(tel_cfg["heartbeat_every_s"])
        if stall_timeout > 0:
            worker_heartbeat_s = min(
                worker_heartbeat_s, max(1.0, stall_timeout / 4.0)
            )

        states: List[_ShardState] = []
        for i, (s, e) in enumerate(spans):
            shard_name = f"shard-{i}"
            shard_dir = out_dir / shard_name
            shard_dir.mkdir(parents=True, exist_ok=True)
            sh = _ShardState(
                name=shard_name, start=s, end=e, dir=shard_dir,
                spec_path=shard_dir / "spec.json",
                out_path=shard_dir / f"{name}_result.json",
            )
            sh.spec_path.write_text(json.dumps({
                "name": shard_name,
                "shard_dir": str(shard_dir),
                "archive": str(archive_path),
                "overrides": overrides,
                "test_path": str(test_path),
                "split": split,
                "golden_file": str(golden),
                "out_path": str(sh.out_path),
                "start": s,
                "end": e,
                "evaluation": resolved_eval,
                "heartbeat_every_s": worker_heartbeat_s,
            }, indent=2))
            states.append(sh)

        def _launch(sh: _ShardState) -> None:
            env = dict(os.environ)
            if sh.attempts > 0:
                # injected faults are first-attempt-only: a restarted
                # worker re-reading MEMVUL_FAULTS would re-arm the same
                # kill and die identically forever
                env.pop("MEMVUL_FAULTS", None)
            sh.attempts += 1
            with open(sh.dir / "worker.log", "ab") as log:
                sh.proc = subprocess.Popen(
                    [
                        sys.executable, "-m",
                        "memvul_tpu.distributed.worker", str(sh.spec_path),
                    ],
                    stdout=log, stderr=subprocess.STDOUT,
                    env=env, start_new_session=True,
                )
            sh.launched_wall = time.time()
            sh.status = "running"
            if sh.attempts == 1:
                tel.event("shard_start", shard=sh.name)
            else:
                tel.counter("shard.restarts").inc()
                tel.event("shard_restart", shard=sh.name, attempt=sh.attempts)
            logger.info(
                "launched %s pid=%d attempt=%d span=[%d,%d)",
                sh.name, sh.proc.pid, sh.attempts, sh.start, sh.end,
            )

        def _fail(sh: _ShardState, reason: str) -> None:
            sh.failures.append(reason)
            if sh.attempts >= max_shard_attempts:
                sh.status = "quarantined"
                tel.counter("shard.quarantined").inc()
                tel.event(
                    "shard_quarantined",
                    shard=sh.name, attempts=sh.attempts, reason=reason,
                )
                logger.error(
                    "%s quarantined after %d attempts: %s",
                    sh.name, sh.attempts, reason,
                )
            else:
                delay = policy.delay(sh.attempts)
                sh.status = "waiting"
                sh.restart_at = time.time() + delay
                logger.warning(
                    "%s failed (%s); restart %d/%d in %.1fs",
                    sh.name, reason, sh.attempts,
                    max_shard_attempts - 1, delay,
                )

        def _publish(now: float) -> None:
            alive = 0
            for sh in states:
                if sh.status == "running":
                    alive += 1
                hb = HeartbeatFile(sh.dir / "HEARTBEAT.json").read()
                counters = hb.get("counters") or {}
                rows = hb.get("rows_scored")
                if rows is None:
                    rows = counters.get("journal.rows_committed", 0)
                tel.gauge(f"shard.rows_committed.{sh.name}").set(
                    float(rows or 0)
                )
                tel.gauge(f"shard.retries.{sh.name}").set(
                    float(counters.get("resilience.retries", 0) or 0)
                )
                tel.gauge(f"shard.heartbeat_age_s.{sh.name}").set(
                    round(heartbeat_age_s(hb, sh.launched_wall, now), 3)
                )
            tel.gauge("shard.alive").set(float(alive))

        for sh in states:
            if sh.end > sh.start:
                _launch(sh)
            else:
                # a shard past the corpus tail owns zero rows — done by
                # construction, no subprocess to pay for
                sh.status = "done"
                tel.event("shard_done", shard=sh.name, rows=0)

        while True:
            now = time.time()
            active = False
            for sh in states:
                if sh.status == "running":
                    rc = sh.proc.poll()
                    if rc is None:
                        hb = HeartbeatFile(
                            sh.dir / "HEARTBEAT.json"
                        ).read()
                        age = heartbeat_age_s(hb, sh.launched_wall, now)
                        if 0 < stall_timeout < age:
                            tel.event(
                                "shard_stalled",
                                shard=sh.name, age_s=round(age, 1),
                            )
                            _kill_process_group(sh.proc, grace=5.0)
                            _fail(
                                sh, f"stalled (heartbeat age {age:.0f}s)"
                            )
                            active = active or sh.status == "waiting"
                        else:
                            active = True
                    elif rc == 0 and (sh.dir / "shard_metrics.json").exists():
                        sh.status = "done"
                        tel.event(
                            "shard_done",
                            shard=sh.name, attempt=sh.attempts,
                        )
                        logger.info("%s done", sh.name)
                    else:
                        reason = (
                            f"exit code {rc}" if rc != 0
                            else "exit 0 without completion marker"
                        )
                        tel.event(
                            "shard_dead", shard=sh.name, exit_code=rc
                        )
                        _fail(sh, reason)
                        active = active or sh.status == "waiting"
                elif sh.status == "waiting":
                    if now >= sh.restart_at:
                        _launch(sh)
                    active = True
            _publish(now)
            tel.heartbeat(
                force=True,
                shards_done=sum(s.status == "done" for s in states),
                shards_running=sum(s.status == "running" for s in states),
                shards_quarantined=sum(
                    s.status == "quarantined" for s in states
                ),
            )
            if not active:
                break
            time.sleep(poll_interval)

        shard_summaries = [
            {
                "shard": sh.name,
                "span": [sh.start, sh.end],
                "rows": sh.end - sh.start,
                "attempts": sh.attempts,
                "restarts": max(0, sh.attempts - 1),
                "status": sh.status,
                "failures": sh.failures,
            }
            for sh in states
        ]
        quarantined = [sh for sh in states if sh.status == "quarantined"]
        if quarantined:
            missing = [[sh.start, sh.end] for sh in quarantined]
            raise PartialCompletionError({
                "status": "partial",
                "rows_total": corpus_rows,
                "rows_missing": sum(e - s for s, e in missing),
                "missing_spans": missing,
                "quarantined": [
                    s for s in shard_summaries
                    if s["status"] == "quarantined"
                ],
                "shards": shard_summaries,
            })

        out_results = out_dir / f"{name}_result.json"
        out_metrics = out_dir / f"{name}_metric_all.json"
        metrics, merge_wall = _merge_and_verify(
            states, corpus_rows, out_results, out_metrics, thres, tel
        )
        return {
            "metrics": metrics,
            "out_results": str(out_results),
            "out_metrics": str(out_metrics),
            "corpus_rows": corpus_rows,
            "verification": {
                "rows": corpus_rows,
                "shards": n_shards,
                "exactly_once": True,
            },
            "merge_wall_s": merge_wall,
            "restarts": sum(max(0, sh.attempts - 1) for sh in states),
            "shards": shard_summaries,
        }
    finally:
        if tel.enabled:
            telemetry.write_programs(out_dir)
        tel.close()
        if metrics_server is not None:
            metrics_server.close()
