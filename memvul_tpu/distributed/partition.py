"""Deterministic corpus partitioning for sharded scoring.

The partition MUST be a pure, stable function of ``(corpus length,
shard count)``: the coordinator recomputes it on every (re)start, the
merge verifier recomputes it to prove exactly-once coverage, and a
resumed worker's journal only makes sense if its span is the same one
it was launched with.  Any randomness or environment dependence here
would make the exactly-once guarantee vacuous — pinned by
``tests/test_distributed.py::test_partition_rows_pure_and_stable``.
"""

from __future__ import annotations

from typing import List, Tuple


def partition_rows(corpus_len: int, n_shards: int) -> List[Tuple[int, int]]:
    """Split ``range(corpus_len)`` into ``n_shards`` contiguous
    ``[start, end)`` spans.

    Spans are maximally even: the first ``corpus_len % n_shards`` shards
    carry one extra row.  Shards beyond the corpus length get empty
    spans (``start == end``) rather than being dropped, so shard *i*
    always exists and always owns the same rows for a given
    ``(corpus_len, n_shards)``.
    """
    if corpus_len < 0:
        raise ValueError(f"corpus_len must be >= 0, got {corpus_len}")
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    base, extra = divmod(corpus_len, n_shards)
    spans: List[Tuple[int, int]] = []
    start = 0
    for i in range(n_shards):
        end = start + base + (1 if i < extra else 0)
        spans.append((start, end))
        start = end
    return spans
