"""memvul_tpu — a TPU-native (JAX/XLA/pjit/Pallas) framework with the
capabilities of the MemVul replication package (FSE 2022, "Automated
Unearthing of Dangerous Issue Reports").

The reference implementation (PyTorch/AllenNLP) is re-designed TPU-first:

- pure-functional Flax BERT encoder with bf16, layer-scan + remat and a
  swappable attention kernel (XLA fused / Pallas flash / ring attention);
- the per-anchor Siamese match loop (reference: model_memory.py:134-147)
  becomes one einsum against a device-resident anchor bank;
- scaling via ``jax.sharding.Mesh`` + NamedSharding (data/model axes) with
  XLA collectives over ICI, instead of torch.distributed/NCCL;
- a small Registrable-style registry reading the same JSON config shapes
  as the reference's AllenNLP FromParams system.

Subpackages
-----------
``data``      tokenization, normalization, CWE anchors, readers, batching
``models``    Flax encoders and classification heads
``ops``       attention kernels (XLA and Pallas)
``parallel``  mesh/sharding helpers, ring attention
``training``  trainer loop, optimizers, metrics, callbacks, checkpointing
``evaluate``  inference pipelines + metric files in the reference format
"""

__version__ = "0.1.0"

from .registry import Registrable  # noqa: F401
