"""Config loading with reference-compatible override merging.

The reference drives everything from JSON/Jsonnet configs and, at test
time, deep-merges a partial override config onto the archived train config
(reference: predict_memory.py:60-67, test_config_memory.json).  This module
reproduces that contract: ``load_config`` reads a JSON file (tolerating
``//`` comments, which the reference's Jsonnet configs use), and
``merge_overrides`` deep-merges dicts, with dotted keys reaching into
nested objects.
"""

from __future__ import annotations

import copy
import json
import re
from pathlib import Path
from typing import Any, Dict, Optional, Union

def _strip_comments(text: str) -> str:
    """Drop ``//`` line comments that are outside JSON strings.

    The reference's configs carry trailing comments, e.g.
    ``"max_length": 512  // different from the data reader``
    (reference: MemVul/config_no_online.json:89), and ``//`` also appears
    inside string values (URLs), so a string-aware scan is required.
    """
    out = []
    i, n = 0, len(text)
    in_string = False
    while i < n:
        c = text[i]
        if in_string:
            out.append(c)
            if c == "\\" and i + 1 < n:
                out.append(text[i + 1])
                i += 2
                continue
            if c == '"':
                in_string = False
        elif c == '"':
            in_string = True
            out.append(c)
        elif c == "/" and i + 1 < n and text[i + 1] == "/":
            while i < n and text[i] != "\n":
                i += 1
            continue
        else:
            out.append(c)
        i += 1
    return "".join(out)


def loads_config(text: str) -> Dict[str, Any]:
    return json.loads(_strip_comments(text))


def load_config(
    path: Union[str, Path],
    overrides: Optional[Union[str, Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    cfg = loads_config(Path(path).read_text())
    if overrides:
        if isinstance(overrides, str):
            overrides = loads_config(overrides)
        cfg = merge_overrides(cfg, overrides)
    return cfg


def merge_overrides(base: Dict[str, Any], overrides: Dict[str, Any]) -> Dict[str, Any]:
    """Deep-merge ``overrides`` onto ``base`` (returns a new dict).

    A *top-level* dotted key like ``"trainer.optimizer.lr"`` addresses a
    nested value, matching AllenNLP's override syntax used by the reference
    eval scripts.  Keys inside nested override dicts are taken literally
    and deep-merged (the reference's with_fallback semantics).
    """
    out = copy.deepcopy(base)
    for key, value in overrides.items():
        _assign(out, key.split("."), value)
    return out


def _assign(node: Dict[str, Any], parts: list, value: Any) -> None:
    key = parts[0]
    if len(parts) > 1:
        child = node.setdefault(key, {})
        if not isinstance(child, dict):
            child = node[key] = {}
        _assign(child, parts[1:], value)
    elif isinstance(value, dict) and isinstance(node.get(key), dict):
        _deep_merge(node[key], value)
    else:
        node[key] = value


def _deep_merge(node: Dict[str, Any], overrides: Dict[str, Any]) -> None:
    for key, value in overrides.items():
        if isinstance(value, dict) and isinstance(node.get(key), dict):
            _deep_merge(node[key], value)
        else:
            node[key] = value


def save_config(cfg: Dict[str, Any], path: Union[str, Path]) -> None:
    Path(path).write_text(json.dumps(cfg, indent=2, sort_keys=False))
