"""Config loading with reference-compatible override merging.

The reference drives everything from JSON/Jsonnet configs and, at test
time, deep-merges a partial override config onto the archived train config
(reference: predict_memory.py:60-67, test_config_memory.json).  This module
reproduces that contract: ``load_config`` reads a JSON file (tolerating
``//`` comments and the Jsonnet subset the reference configs actually use
— top-level ``local name = <literal>;`` bindings referenced by bare
identifier in value position, e.g. config_memory.json:1-3), and
``merge_overrides`` deep-merges dicts, with dotted keys reaching into
nested objects.
"""

from __future__ import annotations

import copy
import json
import re
from pathlib import Path
from typing import Any, Dict, Optional, Union

def _split_strings(text: str) -> list:
    """Split into alternating ``(is_string, chunk)`` segments — the
    string-aware scanner the locals/body passes below share.  String
    chunks include their quotes and honor backslash escapes; an
    unterminated string runs to end-of-text (json.loads reports it).
    Only valid on COMMENT-STRIPPED text: a quote inside a ``//`` comment
    would otherwise open a phantom string (config_memory_large_tp.json's
    header comment quotes axis names).
    """
    segments = []
    i, n = 0, len(text)
    while i < n:
        if text[i] == '"':
            j = i + 1
            while j < n:
                if text[j] == "\\":
                    j += 2
                elif text[j] == '"':
                    j += 1
                    break
                else:
                    j += 1
            else:
                j = n
            segments.append((True, text[i:j]))
            i = j
        else:
            j = text.find('"', i)
            if j == -1:
                j = n
            segments.append((False, text[i:j]))
            i = j
    return segments


_LOCAL_RE = re.compile(r"\s*local\s+([A-Za-z_]\w*)\s*=")
# the lookbehind keeps substitution off identifier-looking tails of
# numeric literals: with a local named ``e5``, the body literal ``1e5``
# must stay a number, not become ``1<value>``
_IDENT_RE = re.compile(r"(?<![\w.])[A-Za-z_]\w*")
_TRAILING_COMMA_RE = re.compile(r",(?=\s*[}\]])")
_JSON_WORDS = frozenset({"true", "false", "null"})


def _strip_comments(text: str) -> str:
    """Drop ``//`` line comments that are outside JSON strings.

    The reference's configs carry trailing comments, e.g.
    ``"max_length": 512  // different from the data reader``
    (reference: MemVul/config_no_online.json:89), and ``//`` also appears
    inside string values (URLs), so the scan must be string-aware.  This
    one pass cannot reuse ``_split_strings``: comments and strings each
    hide the other's delimiter, so quote- and comment-state must advance
    together; every later pass runs on comment-free text and can.
    """
    out = []
    i, n = 0, len(text)
    in_string = False
    while i < n:
        c = text[i]
        if in_string:
            out.append(c)
            if c == "\\" and i + 1 < n:
                out.append(text[i + 1])
                i += 2
                continue
            if c == '"':
                in_string = False
        elif c == '"':
            in_string = True
            out.append(c)
        elif c == "/" and i + 1 < n and text[i + 1] == "/":
            while i < n and text[i] != "\n":
                i += 1
            continue
        else:
            out.append(c)
        i += 1
    return "".join(out)


def _parse_locals(text: str) -> tuple:
    """Consume leading ``local name = <value>;`` bindings.

    Returns ``(bindings, body)``.  Values are JSON literals (the only
    forms the reference's configs use: strings and numbers,
    config_memory.json:1-3) or references to earlier locals.  The
    terminating ``;`` is found outside strings so string values
    containing semicolons parse correctly.
    """
    bindings: Dict[str, Any] = {}
    pos = 0
    while True:
        m = _LOCAL_RE.match(text, pos)
        if not m:
            break
        end = m.end()
        for is_str, chunk in _split_strings(text[end:]):
            if not is_str and ";" in chunk:
                end += chunk.index(";")
                break
            end += len(chunk)
        else:
            raise ValueError(f"unterminated 'local {m.group(1)} = ...' binding")
        raw = text[m.end() : end].strip()
        if _IDENT_RE.fullmatch(raw) and raw in bindings:
            bindings[m.group(1)] = bindings[raw]
        else:
            bindings[m.group(1)] = json.loads(raw)
        pos = end + 1
    return bindings, text[pos:]


def _jsonnetise_body(body: str, bindings: Dict[str, Any]) -> str:
    """Make the Jsonnet body valid JSON: substitute bare identifiers with
    their bound JSON value and drop trailing commas (both Jsonnet-legal,
    both used by the reference configs — config_memory.json:6,69).

    Body keys are always quoted in the reference configs, so any bare
    identifier outside a string is a reference.  Unbound identifiers are
    left for json.loads to reject with its own error position.  A comma
    is trailing only when whitespace separates it from the closing
    bracket, so the per-chunk regex never crosses a string boundary.
    """

    def substitute(m: "re.Match") -> str:
        word = m.group(0)
        if word in bindings and word not in _JSON_WORDS:
            return json.dumps(bindings[word])
        return word

    return "".join(
        chunk
        if is_str
        else _TRAILING_COMMA_RE.sub("", _IDENT_RE.sub(substitute, chunk))
        for is_str, chunk in _split_strings(body)
    )


def loads_config(text: str) -> Dict[str, Any]:
    stripped = _strip_comments(text)
    bindings, body = _parse_locals(stripped)
    return json.loads(_jsonnetise_body(body, bindings))


def load_config(
    path: Union[str, Path],
    overrides: Optional[Union[str, Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    cfg = loads_config(Path(path).read_text())
    if overrides:
        if isinstance(overrides, str):
            overrides = loads_config(overrides)
        cfg = merge_overrides(cfg, overrides)
    return cfg


def merge_overrides(base: Dict[str, Any], overrides: Dict[str, Any]) -> Dict[str, Any]:
    """Deep-merge ``overrides`` onto ``base`` (returns a new dict).

    A *top-level* dotted key like ``"trainer.optimizer.lr"`` addresses a
    nested value, matching AllenNLP's override syntax used by the reference
    eval scripts.  Keys inside nested override dicts are taken literally
    and deep-merged (the reference's with_fallback semantics).
    """
    out = copy.deepcopy(base)
    for key, value in overrides.items():
        _assign(out, key.split("."), value)
    return out


def _assign(node: Dict[str, Any], parts: list, value: Any) -> None:
    key = parts[0]
    if len(parts) > 1:
        child = node.setdefault(key, {})
        if not isinstance(child, dict):
            child = node[key] = {}
        _assign(child, parts[1:], value)
    elif isinstance(value, dict) and isinstance(node.get(key), dict):
        _deep_merge(node[key], value)
    else:
        # deepcopy, never alias: the merged config must not share
        # structure with the caller's overrides dict — a later dotted-key
        # assignment (or any downstream edit of the merged config) would
        # otherwise mutate the overrides object the caller still holds
        node[key] = copy.deepcopy(value)


def _deep_merge(node: Dict[str, Any], overrides: Dict[str, Any]) -> None:
    for key, value in overrides.items():
        if isinstance(value, dict) and isinstance(node.get(key), dict):
            _deep_merge(node[key], value)
        else:
            node[key] = copy.deepcopy(value)  # same no-aliasing contract


def save_config(cfg: Dict[str, Any], path: Union[str, Path]) -> None:
    Path(path).write_text(json.dumps(cfg, indent=2, sort_keys=False))


# The ``evaluation`` config section, with its documented defaults.  The
# eval entry points (build.evaluate_from_archive) read this one merged
# view instead of scattering per-key ``.get`` defaults, so a new knob is
# added exactly once.  ``None`` means "feature off / model default".
EVALUATION_DEFAULTS: Dict[str, Any] = {
    "batch_size": 512,       # rows per batch without a token budget
    "max_length": 512,       # token cap (clamped to the model's positions)
    "buckets": None,         # length-bin boundaries; "auto" derives them
    "n_buckets": 8,          # boundary count for "auto" buckets
    "tokens_per_batch": None,  # constant token budget per batch
    "inflight": 2,           # async device dispatch depth (0 = sync)
    "anchor_match_impl": None,  # None → model config ("auto"|"fused"|"xla")
    "aot_warmup": True,      # precompile every stream shape at startup
    # fault tolerance (docs/fault_tolerance.md) — all off by default so
    # short interactive evals keep their exact historical behavior;
    # docs/full_corpus.md turns the whole block on for the 1.2M job
    "resume": False,         # journal + skip-completed restartable scoring
    "quarantine": False,     # dead-letter malformed/over-long records
    "heartbeat_batches": 0,  # progress log every N batches (0 = off)
    "score_retries": 0,      # transient-failure retries per batch (0 = off)
    # add the winning anchor id/index to every output record
    # (docs/anchor_bank.md) — off so the default output format stays
    # byte-stable with the reference's
    "attribute_anchors": False,
    # sharded corpus scoring (distributed/, docs/full_corpus.md) — the
    # score-corpus CLI reads these; shards=1 keeps the single-worker
    # degenerate case the default
    "shards": 1,               # supervised worker subprocesses
    "max_shard_attempts": 3,   # launches per shard before quarantine
    "shard_stall_timeout_s": 120.0,  # heartbeat age that counts as wedged
    "shard_poll_interval_s": 1.0,    # supervisor poll cadence
    "shard_backoff_s": 2.0,    # restart backoff base (exponential)
}


def _section_over_defaults(
    cfg: Optional[Dict[str, Any]], key: str, defaults: Dict[str, Any]
) -> Dict[str, Any]:
    """``cfg[key]`` merged over its documented defaults.

    Explicit JSON ``null`` values fall back to the default (matching the
    historical null-tolerant handling of ``tokens_per_batch``/
    ``inflight``; 0 and "" are real values and survive).  Unknown keys
    are kept — they may belong to a newer reader — but logged so a typo
    like ``"ancor_match_impl"`` doesn't silently disable a feature.
    """
    import logging

    section = dict((cfg or {}).get(key) or {})
    unknown = sorted(set(section) - set(defaults))
    if unknown:
        logging.getLogger(__name__).warning(
            "%s config: unknown key(s) %s (known: %s)",
            key, unknown, sorted(defaults),
        )
    out = dict(defaults)
    out.update({k: v for k, v in section.items() if v is not None})
    return out


def evaluation_config(cfg: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """``cfg["evaluation"]`` merged over :data:`EVALUATION_DEFAULTS`."""
    return _section_over_defaults(cfg, "evaluation", EVALUATION_DEFAULTS)


# Training-section knobs that the TrainerConfig dataclasses own the
# defaults for but that are worth failing EARLY on — a bad
# prefetch_depth or a non-covering bucket list otherwise surfaces
# minutes into a run (or silently truncates sequences).  Called by
# build.train_from_config before the trainer is constructed.
def validate_training_config(trainer: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Sanity-check the config's ``trainer`` section (returns it).

    * ``prefetch_depth`` must be >= 1 (the feed queue would deadlock at 0);
    * ``train_buckets`` must be "pow2", null, or a list whose largest
      bucket covers ``max_length`` (docs/training_throughput.md) —
      resolved through the same helper the trainers use so the two can't
      drift;
    * ``dedup_anchors`` must be a bool (a truthy string like "false"
      would silently enable it).
    """
    trainer = dict(trainer or {})
    depth = trainer.get("prefetch_depth", 8)
    if int(depth) < 1:
        raise ValueError(
            f"trainer.prefetch_depth must be >= 1, got {depth!r}"
        )
    from .data.batching import resolve_train_buckets

    max_length = int(trainer.get("max_length", 256))
    resolve_train_buckets(trainer.get("train_buckets", "pow2"), max_length)
    dedup = trainer.get("dedup_anchors", True)
    if not isinstance(dedup, bool):
        raise ValueError(
            f"trainer.dedup_anchors must be a bool, got {dedup!r}"
        )
    return trainer


# The ``serving`` config section (docs/serving.md).  Read by
# build.serve_from_archive, which sizes the online predictor (the
# micro-batch IS its batch shape set, so ``max_batch``/``buckets`` here
# decide which programs the AOT warmup precompiles) and the service's
# admission-control envelope.
SERVING_DEFAULTS: Dict[str, Any] = {
    "max_batch": 16,         # requests coalesced per micro-batch flush
    "max_wait_ms": 5.0,      # oldest-request coalescing window
    "max_queue": 256,        # bounded queue depth; overflow sheds oldest
    "default_deadline_ms": 2000.0,  # per-request budget (<=0 disables)
    "retries": 2,            # transient batch retry attempts (0 = off)
    "max_length": 512,       # token cap (clamped to the model's positions)
    "buckets": None,         # explicit length buckets ("auto" needs a
                             # corpus and is an offline-only policy)
    # dispatch strategy (serving/dispatch.py): "ragged" packs each pull
    # into fixed [1, token_budget] flat batches — ONE warm program for
    # any length mix — instead of routing to bucket shapes
    # (docs/ragged_serving.md); "continuous" admits requests into the
    # in-flight pack persistently, decoupling queue wait from device
    # latency (docs/serving.md, "Continuous admission"); "cascade"
    # scores every micro-batch on an int8 tier first and re-dispatches
    # only rows whose max-anchor score lands inside the [cascade_low,
    # cascade_high] uncertainty band to the fp32 program
    # (docs/quantized_serving.md)
    "score_impl": "bucketed",    # "bucketed" | "ragged" | "continuous"
                                 # | "cascade"
    "token_budget": None,        # ragged pack size (None → 4 × max_length)
    "max_rows_per_pack": None,   # ragged rows cap per pack (None → max_batch)
    # cascade uncertainty band (inclusive; only read with
    # score_impl="cascade"): rows with max-anchor probability inside
    # [low, high] rescore in fp32, everything outside short-circuits
    # on the int8 tier
    "cascade_low": 0.3,
    "cascade_high": 0.7,
    "host": "127.0.0.1",     # HTTP front-end bind address
    "port": 8341,            # HTTP front-end port
    # scale-out tier (serving/router.py; docs/serving.md "Replica tier").
    # replicas > 1 puts N ScoringServices — one per assigned local
    # device, round-robin over jax.local_devices() — behind a
    # ReplicaRouter; the knobs below are its health/eviction policy
    "replicas": 1,           # ScoringService instances behind the router
    "heartbeat_timeout_s": 10.0,  # missed-heartbeat eviction threshold
    "max_batch_errors": 3,   # consecutive dead-letters before eviction
    "monitor_interval_s": 0.25,  # router health-check cadence
    "max_reroutes": 2,       # re-enqueue attempts after replica failures
    # request-journey tracing (docs/observability.md, "Request
    # tracing"): 0.0 = off and entirely free; > 0 stamps waypoints on
    # every request, feeds the serve.queue_wait_s/pack_s/device_s/
    # resolve_s stage histograms, and emits sampled `rtrace` events
    # (always-on for non-served outcomes)
    "trace_sample_rate": 0.0,
    "trace_ring": 256,       # completed traces kept for GET /tracez
    # SLO monitor (serving/slo.py): sliding-window availability +
    # p95-latency attainment, multi-window burn rates, and the
    # machine-readable scale_hint — published as slo.* gauges, the
    # /healthz slo block, and the SLO-harness record
    "slo_enabled": True,
    "slo_availability_objective": 0.999,
    "slo_latency_p95_ms": 1000.0,
    "slo_fast_window_s": 60.0,   # spike-catcher burn window
    "slo_window_s": 300.0,       # confirmation (slow) burn window
    "slo_interval_s": 5.0,       # sampling cadence
    # cross-host fleet (serving/fleet.py; docs/serving.md "Cross-host
    # fleet"): ``serve --hosts`` puts a HostBalancer over per-host
    # router fleets; the knobs below are its stall/restart policy
    "hosts": None,                    # "host[:port],..." or None (single host)
    "fleet_heartbeat_timeout_s": 10.0,  # host stall-eviction threshold
    "fleet_monitor_interval_s": 0.25,   # balancer health-check cadence
    "fleet_max_reroutes": 2,          # cross-host re-enqueue attempts
    "fleet_max_restarts": 2,          # per-host budget, then quarantine
    # autoscaler (serving/autoscaler.py; docs/serving.md "Autoscaling"):
    # consumes the SLO monitor's scale_hint and grows/shrinks the local
    # replica count live, inside [min, max], with per-direction
    # cooldowns and consecutive-tick hysteresis
    "autoscale_enabled": False,
    "autoscale_min_replicas": 1,
    "autoscale_max_replicas": 4,
    "autoscale_interval_s": 1.0,      # hint-sampling cadence
    "autoscale_up_cooldown_s": 5.0,
    "autoscale_down_cooldown_s": 30.0,
    "autoscale_up_consecutive": 2,    # agreeing "up" ticks before acting
    "autoscale_down_consecutive": 4,  # agreeing "down" ticks before acting
    "autoscale_drain_timeout_s": 10.0,  # retire: in-flight completion bound
    # incident flight recorder (serving/incident.py; docs/
    # observability.md "Incident bundles").  Only constructed when the
    # history plane is on (telemetry.tsdb_cadence_s > 0): alert firings
    # / replica deaths / host quarantines / autoscaler refusals dump a
    # rate-limited, retention-bounded incidents/<ts>-<trigger>/ bundle
    "alert_interval_s": 5.0,        # alert-rule evaluation cadence
    "incident_min_interval_s": 30.0,  # bundle rate limit (dups dropped)
    "incident_max_bundles": 8,        # newest-N bundle retention
    "incident_window_s": 120.0,       # metric-history span per bundle
    # multi-tenant serving plane (serving/tenancy.py; docs/
    # multitenancy.md): "name=store_dir,..." installs one versioned
    # anchor bank per named tenant from its BankStore; None = the
    # single default tenant only (the pre-tenancy surface, unchanged)
    "tenants": None,
    # content-addressed admission cache (serving/admission_cache.py):
    # LRU entries kept per process; 0 constructs no cache at all (the
    # cache-off path is byte-identical to pre-cache serving)
    "cache_capacity": 0,
    # continuous-path segment-table aliasing (data/batching.py,
    # PackSlotAllocator): exact-duplicate requests in one pack share a
    # written segment instead of paying tokens.  Off by default behind
    # the ≤1e-6 parity gate (docs/multitenancy.md, "Prefix sharing")
    "prefix_share": False,
}


def serving_config(cfg: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """``cfg["serving"]`` merged over :data:`SERVING_DEFAULTS`."""
    return _section_over_defaults(cfg, "serving", SERVING_DEFAULTS)


# The ``bankops`` config section (docs/anchor_bank.md) — the anchor-bank
# lifecycle subsystem: versioned store location, per-anchor win/drift
# attribution, shadow-scoring sampling, and the promotion-gate
# thresholds.  Read by build.serve_from_archive (attribution knob) and
# the ``python -m memvul_tpu bank`` CLI (store/shadow/gate knobs).
BANKOPS_DEFAULTS: Dict[str, Any] = {
    "store_dir": None,         # versioned bank store root (bankops/store.py)
    "anchor_stats": True,      # per-anchor win/score attribution in serving
    "baseline": None,          # pinned anchor_baseline.json path (drift)
    "drift_interval_s": 30.0,  # DriftMonitor gauge refresh cadence
    # shadow scoring (bankops/shadow.py)
    "shadow_sample_stride": 1,   # shadow-score every Nth served request
    "shadow_max_queue": 512,     # bounded sample queue; overflow drops
    "shadow_threshold": 0.5,     # serving decision threshold (flip detect)
    # promotion gate (bankops/promote.py)
    "max_auc_drop": 0.01,        # golden-set AUC tolerance
    "max_f1_drop": 0.01,         # golden-set F1 tolerance
    "max_flip_rate": 0.02,       # shadow decision-flip ceiling
    "min_shadow_samples": 100,   # required shadow evidence volume
}


def bankops_config(cfg: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """``cfg["bankops"]`` merged over :data:`BANKOPS_DEFAULTS`."""
    return _section_over_defaults(cfg, "bankops", BANKOPS_DEFAULTS)


# The ``telemetry`` config section (docs/observability.md).  Read by the
# build entry points, which configure the process-wide registry
# (memvul_tpu.telemetry) with the run's serialization/output dir before
# the trainers/predictors start reporting through it.
TELEMETRY_DEFAULTS: Dict[str, Any] = {
    "enabled": True,         # registry + sinks in the run dir
    "events": True,          # append-only events.jsonl stream
    "step_events": True,     # per-step train_step events (drain cadence)
    "heartbeat_every_s": 30.0,  # HEARTBEAT.json max write rate
    # jax.profiler trace dir for the run's hot section (the named-scope
    # map in docs/observability.md tells xprof time apart); None = off
    "trace_dir": None,
    # serving HBM liveness: sample device_memory_stats into
    # serve.hbm_in_use_bytes / serve.hbm_peak_bytes per replica at
    # heartbeat cadence (no-op on backends without memory stats)
    "hbm_gauges": True,
    # live exposition for non-serving runs (telemetry/live.py): a
    # daemon-thread /metrics + /programz server inside train_from_config
    # and the corpus-eval predict_file flow.  0 (default) = off — the
    # run's emitted metric/event set stays identical to a build without
    # the server; any other value binds that port (0 < p < 65536)
    "metrics_port": 0,
    # in-process metrics history (telemetry/timeseries.py): a sampler
    # thread snapshots the registry (plus per-replica / per-host parts
    # in serving) into bounded (ts, value) rings, served as GET
    # /metricsz and fed to alert rules + incident bundles.  0.0
    # (default) = off — nothing is constructed and the emitted
    # metric/event set stays byte-identical to a build without it
    "tsdb_cadence_s": 0.0,
    "tsdb_resolution_s": 1.0,   # ring bucket width (points coalesce)
    "tsdb_retention_s": 600.0,  # per-series history span
}


def telemetry_config(cfg: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """``cfg["telemetry"]`` merged over :data:`TELEMETRY_DEFAULTS`."""
    return _section_over_defaults(cfg, "telemetry", TELEMETRY_DEFAULTS)


# The ``tuning`` config section (docs/tuning.md) — the offline
# autotuner.  Read in two places: ``python -m memvul_tpu tune`` (the
# sweep knobs) and the build entry points (profile loading:
# ``build.train_from_config`` / ``serve_from_archive`` overlay the
# device class's tuned profile UNDER any explicit trainer/serving
# config — explicit keys always win, and with no profile store
# configured the merged config is byte-identical to pre-tuner builds).
TUNING_DEFAULTS: Dict[str, Any] = {
    "enabled": True,         # load tuned profiles in the build entry points
    # tuned-profile store root (tuning/profile.py layout:
    # <dir>/<device_class>/profile-NNNN.json + MANIFEST.json).  None
    # falls back to $MEMVUL_TUNED_PROFILES, then to no loading at all
    "profile_dir": None,
    # tune for a specific device class instead of the default backend's
    # (normalized device_kind, e.g. "tpu_v5_lite"); None = autodetect
    "device_class": None,
    # cascade band autotuner (tune --cascade): fraction of golden-set
    # rows the chosen [cascade_low, cascade_high] band should send to
    # the fp32 rescue tier
    "target_rescore_rate": 0.1,
    # analytic pruning ceilings (tuning/prune.py): candidates whose
    # worst-case compiled-program count or projected HBM footprint
    # exceed these are refused before any microbench spend
    "max_programs": 64,
    "hbm_fraction": 0.9,     # of the device class's PEAK_SPECS hbm_bytes
    # fixed probe-set size for the parity gate's score evidence
    "parity_probe": 32,
}


def tuning_config(cfg: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """``cfg["tuning"]`` merged over :data:`TUNING_DEFAULTS`."""
    return _section_over_defaults(cfg, "tuning", TUNING_DEFAULTS)
