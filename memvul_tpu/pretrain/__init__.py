from .mlm import (  # noqa: F401
    MLMModel,
    MLMTrainer,
    extract_encoder_params,
    transplant_encoder,
    whole_word_mask,
)
