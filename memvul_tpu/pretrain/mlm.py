"""Whole-word-mask MLM further pretraining.

The reference runs HF's ``run_mlm_wwm.py`` over one-report-per-line text
(50 epochs, mask prob 0.15, ``DataCollatorForWholeWordMask`` —
further_pretrain.json, run_mlm_wwm.py:349-359) and the resulting
checkpoint is loaded by the classifier's embedder
(custom_PTM_embedder.py:95-99).

Here the same subsystem is native: a whole-word-mask collator over
wordpiece ids (a "word" = a token plus its ``##`` continuations), an MLM
head over the in-repo Flax BERT with the decoder tied to the input
embedding table, and a compact jitted training loop.  The pretrained
encoder subtree transplants directly into MemoryModel/SingleModel params
(:func:`transplant_encoder`) — the further-pretrain → fine-tune contract.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from ..models.bert import BertConfig, BertEncoder, _dense_init

logger = logging.getLogger(__name__)

IGNORE = -100


# -- masking -----------------------------------------------------------------


def continuation_flags(tokenizer) -> np.ndarray:
    """[V] bool: True for ``##`` continuation wordpieces."""
    flags = np.zeros(tokenizer.vocab_size, dtype=bool)
    vocab = tokenizer._tok.get_vocab()
    for token, idx in vocab.items():
        if token.startswith("##"):
            flags[idx] = True
    return flags


def whole_word_mask(
    ids: np.ndarray,
    attention_mask: np.ndarray,
    rng: np.random.Generator,
    mask_id: int,
    vocab_size: int,
    continuation: np.ndarray,
    special_ids: Iterable[int],
    mask_prob: float = 0.15,
) -> Tuple[np.ndarray, np.ndarray]:
    """HF DataCollatorForWholeWordMask semantics over a [B, L] batch:
    pick ~15% of *words* (a head wordpiece plus its continuations); of the
    chosen tokens 80% → [MASK], 10% → random id, 10% → unchanged.
    Returns (masked_ids, labels) with labels = IGNORE off the masked set."""
    special = set(int(s) for s in special_ids)
    masked = ids.copy()
    labels = np.full_like(ids, IGNORE)
    B, L = ids.shape
    for b in range(B):
        # word start indices
        words: List[List[int]] = []
        for i in range(L):
            if not attention_mask[b, i] or int(ids[b, i]) in special:
                continue
            if continuation[ids[b, i]] and words:
                words[-1].append(i)
            else:
                words.append([i])
        if not words:
            continue
        n_mask = max(1, int(round(len(words) * mask_prob)))
        chosen = rng.permutation(len(words))[:n_mask]
        for w in chosen:
            for i in words[w]:
                labels[b, i] = ids[b, i]
                roll = rng.random()
                if roll < 0.8:
                    masked[b, i] = mask_id
                elif roll < 0.9:
                    masked[b, i] = rng.integers(0, vocab_size)
    return masked, labels


# -- model -------------------------------------------------------------------


class MLMModel(nn.Module):
    """BERT encoder + transform head + decoder tied to the word-embedding
    table (HF BertForMaskedLM layout)."""

    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask, deterministic: bool = True):
        c = self.config
        encoder = BertEncoder(c, name="bert")
        hidden = encoder(input_ids, attention_mask, deterministic=deterministic)
        x = nn.Dense(c.hidden_size, kernel_init=_dense_init(c), dtype=c.dtype,
                     name="transform")(hidden)
        x = nn.gelu(x, approximate=False)
        x = nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=c.dtype,
                         name="transform_LayerNorm")(x)
        embed_table = encoder.variables["params"]["embeddings"][
            "word_embeddings"
        ]["embedding"]
        logits = x @ embed_table.T.astype(x.dtype)
        bias = self.param("decoder_bias", nn.initializers.zeros, (c.vocab_size,))
        return logits + bias.astype(logits.dtype)


def mlm_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean CE over positions with labels != IGNORE."""
    mask = (labels != IGNORE).astype(jnp.float32)
    safe_labels = jnp.where(labels == IGNORE, 0, labels)
    log_probs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(log_probs, safe_labels[..., None], axis=-1)[..., 0]
    return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)


# -- params plumbing ---------------------------------------------------------


def extract_encoder_params(mlm_params) -> Dict:
    """The ``bert`` subtree of an MLM checkpoint."""
    return jax.device_get(mlm_params)["params"]["bert"]


def transplant_encoder(classifier_params, encoder_subtree) -> Dict:
    """Insert a pretrained encoder into MemoryModel/SingleModel params
    (their encoder also lives under ``params/bert``) — the counterpart of
    the reference's pretrained_model_path loading
    (custom_PTM_embedder.py:95-99)."""
    out = dict(jax.device_get(classifier_params))
    out["params"] = dict(out["params"])
    # guard against a tokenizer/vocab swap between pretrain and fine-tune:
    # a mismatched embedding table would silently clamp out-of-range ids
    # under XLA and produce garbage representations
    def _embed_rows(tree):
        emb = tree.get("embeddings", {}).get("word_embeddings", {})
        table = emb.get("embedding")
        return None if table is None else table.shape[0]

    want = _embed_rows(out["params"].get("bert", {}))
    got = _embed_rows(encoder_subtree)
    if want is not None and got is not None and want != got:
        raise ValueError(
            f"pretrained encoder vocab size {got} != classifier vocab size "
            f"{want}; the tokenizer changed between pretraining and "
            "fine-tuning (did data/vocab.txt appear after the MLM run?)"
        )
    out["params"]["bert"] = encoder_subtree
    return out


# -- trainer -----------------------------------------------------------------


@dataclasses.dataclass
class MLMTrainerConfig:
    batch_size: int = 16
    grad_accum: int = 2
    max_length: int = 256
    mask_prob: float = 0.15
    learning_rate: float = 5e-5
    warmup_steps: int = 50000
    num_epochs: int = 50
    seed: int = 2021
    steps_per_epoch: Optional[int] = None


class MLMTrainer:
    def __init__(
        self,
        config: BertConfig,
        tokenizer,
        trainer_config: Optional[MLMTrainerConfig] = None,
    ) -> None:
        import optax

        self.model = MLMModel(config)
        self.tokenizer = tokenizer
        self.c = trainer_config or MLMTrainerConfig()
        self._continuation = continuation_flags(tokenizer)
        self._special = [tokenizer.pad_id, tokenizer.cls_id, tokenizer.sep_id]
        self._np_rng = np.random.default_rng(self.c.seed)

        dummy = np.zeros((2, 8), np.int32)
        self.params = self.model.init(
            jax.random.PRNGKey(self.c.seed), dummy, np.ones_like(dummy)
        )
        from ..training.optim import linear_with_warmup

        schedule = linear_with_warmup(self.c.warmup_steps)
        self.tx = optax.chain(
            optax.clip_by_global_norm(1.0),
            optax.scale_by_adam(),
            optax.scale_by_schedule(schedule),
            optax.scale(-self.c.learning_rate),
        )
        self.opt_state = self.tx.init(self.params)
        self.step = 0

        def train_step(params, opt_state, ids, mask, labels, rng):
            def loss_fn(p):
                logits = self.model.apply(
                    p, ids, mask, deterministic=False, rngs={"dropout": rng}
                )
                return mlm_loss(logits, labels)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(
                lambda p, u: p + u.astype(p.dtype), params, updates
            )
            return params, opt_state, loss

        self._train_step = jax.jit(train_step)

    def _batches(self, lines: List[str]) -> Iterator[Tuple[np.ndarray, ...]]:
        c = self.c
        order = self._np_rng.permutation(len(lines))
        for start in range(0, len(lines), c.batch_size):
            # the trailing partial batch is padded with empty rows (pad-only
            # rows yield no maskable positions, so they contribute no loss)
            texts = [lines[i] for i in order[start : start + c.batch_size]]
            ids = np.full((c.batch_size, c.max_length), self.tokenizer.pad_id, np.int32)
            mask = np.zeros_like(ids)
            for i, t in enumerate(texts):
                seq = self.tokenizer.encode(t, max_length=c.max_length)
                ids[i, : len(seq)] = seq
                mask[i, : len(seq)] = 1
            masked, labels = whole_word_mask(
                ids, mask, self._np_rng, self.tokenizer.mask_id,
                self.tokenizer.vocab_size, self._continuation, self._special,
                c.mask_prob,
            )
            yield masked, mask, labels

    def train(self, corpus_path: str) -> Dict[str, float]:
        c = self.c
        lines = [
            l.strip() for l in open(corpus_path, encoding="utf-8") if l.strip()
        ]
        if not lines:
            raise ValueError(f"MLM corpus {corpus_path} is empty")
        logger.info("MLM corpus: %d lines", len(lines))
        rng = jax.random.PRNGKey(c.seed)
        history: List[float] = []
        for epoch in range(c.num_epochs):
            losses = []
            started = time.perf_counter()
            for i, (ids, mask, labels) in enumerate(self._batches(lines)):
                if c.steps_per_epoch is not None and i >= c.steps_per_epoch:
                    break
                rng, sub = jax.random.split(rng)
                self.params, self.opt_state, loss = self._train_step(
                    self.params, self.opt_state, ids, mask, labels, sub
                )
                losses.append(float(loss))
                self.step += 1
            mean_loss = float(np.mean(losses)) if losses else 0.0
            history.append(mean_loss)
            logger.info(
                "mlm epoch %d: loss %.4f (%.1fs)",
                epoch, mean_loss, time.perf_counter() - started,
            )
        return {"final_loss": history[-1] if history else 0.0, "history": history}

    def encoder_params(self):
        return extract_encoder_params(self.params)
