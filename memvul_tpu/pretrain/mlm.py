"""Whole-word-mask MLM further pretraining.

The reference runs HF's ``run_mlm_wwm.py`` over one-report-per-line text
(50 epochs, mask prob 0.15, ``DataCollatorForWholeWordMask`` —
further_pretrain.json, run_mlm_wwm.py:349-359) and the resulting
checkpoint is loaded by the classifier's embedder
(custom_PTM_embedder.py:95-99).

Here the same subsystem is native: a whole-word-mask collator over
wordpiece ids (a "word" = a token plus its ``##`` continuations), an MLM
head over the in-repo Flax BERT with the decoder tied to the input
embedding table, and a compact jitted training loop.  The pretrained
encoder subtree transplants directly into MemoryModel/SingleModel params
(:func:`transplant_encoder`) — the further-pretrain → fine-tune contract.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from ..models.bert import BertConfig, BertEncoder, _dense_init
from ..training.metrics import drain_pending

logger = logging.getLogger(__name__)

IGNORE = -100


# -- masking -----------------------------------------------------------------


def continuation_flags(tokenizer) -> np.ndarray:
    """[V] bool: True for ``##`` continuation wordpieces."""
    flags = np.zeros(tokenizer.vocab_size, dtype=bool)
    vocab = tokenizer._tok.get_vocab()
    for token, idx in vocab.items():
        if token.startswith("##"):
            flags[idx] = True
    return flags


def whole_word_mask(
    ids: np.ndarray,
    attention_mask: np.ndarray,
    rng: np.random.Generator,
    mask_id: int,
    vocab_size: int,
    continuation: np.ndarray,
    special_ids: Iterable[int],
    mask_prob: float = 0.15,
) -> Tuple[np.ndarray, np.ndarray]:
    """HF DataCollatorForWholeWordMask semantics over a [B, L] batch:
    pick ~15% of *words* (a head wordpiece plus its continuations); of the
    chosen tokens 80% → [MASK], 10% → random id, 10% → unchanged.
    Returns (masked_ids, labels) with labels = IGNORE off the masked set.

    Fully vectorized over the batch — the masking collator sits on the
    host critical path of a 50-epoch × 1.1M-line run, so a per-token
    Python loop (the round-1 implementation) would be the pipeline
    bottleneck.  Word selection draws one uniform score per word and masks
    the ``n_mask`` smallest, which matches the permutation-prefix
    distribution of the reference collator."""
    B, L = ids.shape
    special = np.asarray(sorted(int(s) for s in special_ids), dtype=ids.dtype)
    maskable = (attention_mask > 0) & ~np.isin(ids, special)
    is_cont = np.zeros((B, L), dtype=bool)
    np.copyto(is_cont, continuation[ids], where=maskable)
    head = maskable & ~is_cont
    # a continuation with no preceding word starts its own word: force the
    # first maskable position of each row to be a head
    first = maskable & (np.cumsum(maskable, axis=1) == 1)
    head |= first
    # word index per position (0-based); positions share their head's index
    word_idx = np.cumsum(head, axis=1) - 1  # [B, L], -1 before any head
    n_words = head.sum(axis=1)  # [B]
    max_words = int(n_words.max()) if B else 0
    masked = ids.copy()
    labels = np.full_like(ids, IGNORE)
    if max_words == 0:
        return masked, labels
    n_mask = np.maximum(1, np.round(n_words * mask_prob).astype(np.int64))
    n_mask = np.where(n_words > 0, np.minimum(n_mask, n_words), 0)
    # rank words by an i.i.d. uniform score; the n_mask smallest are chosen
    scores = rng.random((B, max_words))
    scores[np.arange(max_words)[None, :] >= n_words[:, None]] = np.inf
    ranks = scores.argsort(axis=1).argsort(axis=1)
    chosen_word = ranks < n_mask[:, None]  # [B, max_words]
    safe_idx = np.clip(word_idx, 0, max_words - 1)
    chosen = maskable & (word_idx >= 0) & np.take_along_axis(
        chosen_word, safe_idx, axis=1
    )
    labels[chosen] = ids[chosen]
    # 80% [MASK] / 10% random / 10% unchanged, independently per token
    roll = rng.random((B, L))
    rand_ids = rng.integers(0, vocab_size, size=(B, L), dtype=ids.dtype)
    masked = np.where(chosen & (roll < 0.8), mask_id, masked)
    masked = np.where(chosen & (roll >= 0.8) & (roll < 0.9), rand_ids, masked)
    return masked, labels


# -- model -------------------------------------------------------------------


class MLMModel(nn.Module):
    """BERT encoder + transform head + decoder tied to the word-embedding
    table (HF BertForMaskedLM layout)."""

    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask, deterministic: bool = True):
        c = self.config
        encoder = BertEncoder(c, name="bert")
        hidden = encoder(input_ids, attention_mask, deterministic=deterministic)
        x = nn.Dense(c.hidden_size, kernel_init=_dense_init(c), dtype=c.dtype,
                     name="transform")(hidden)
        x = nn.gelu(x, approximate=False)
        x = nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=c.dtype,
                         name="transform_LayerNorm")(x)
        embed_table = encoder.variables["params"]["embeddings"][
            "word_embeddings"
        ]["embedding"]
        logits = x @ embed_table.T.astype(x.dtype)
        bias = self.param("decoder_bias", nn.initializers.zeros, (c.vocab_size,))
        return logits + bias.astype(logits.dtype)


def mlm_nll_sums(logits: jax.Array, labels: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(summed NLL over supervised positions, supervised-position count)
    — the one implementation of the masked-LM numerics, shared by the
    training loss (mean) and held-out evaluation (corpus-weighted)."""
    mask = (labels != IGNORE).astype(jnp.float32)
    safe_labels = jnp.where(labels == IGNORE, 0, labels)
    log_probs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(log_probs, safe_labels[..., None], axis=-1)[..., 0]
    return (nll * mask).sum(), mask.sum()


def mlm_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean CE over positions with labels != IGNORE."""
    nll_sum, count = mlm_nll_sums(logits, labels)
    return nll_sum / jnp.maximum(count, 1.0)


# -- params plumbing ---------------------------------------------------------


def extract_encoder_params(mlm_params) -> Dict:
    """The ``bert`` subtree of an MLM checkpoint."""
    return jax.device_get(mlm_params)["params"]["bert"]


def transplant_encoder(classifier_params, encoder_subtree) -> Dict:
    """Insert a pretrained encoder into MemoryModel/SingleModel params
    (their encoder also lives under ``params/bert``) — the counterpart of
    the reference's pretrained_model_path loading
    (custom_PTM_embedder.py:95-99)."""
    out = dict(jax.device_get(classifier_params))
    out["params"] = dict(out["params"])
    # guard against a tokenizer/vocab swap between pretrain and fine-tune:
    # a mismatched embedding table would silently clamp out-of-range ids
    # under XLA and produce garbage representations
    def _embed_rows(tree):
        emb = tree.get("embeddings", {}).get("word_embeddings", {})
        table = emb.get("embedding")
        return None if table is None else table.shape[0]

    want = _embed_rows(out["params"].get("bert", {}))
    got = _embed_rows(encoder_subtree)
    if want is not None and got is not None and want != got:
        raise ValueError(
            f"pretrained encoder vocab size {got} != classifier vocab size "
            f"{want}; the tokenizer changed between pretraining and "
            "fine-tuning (did data/vocab.txt appear after the MLM run?)"
        )
    out["params"]["bert"] = encoder_subtree
    return out


# -- trainer -----------------------------------------------------------------


@dataclasses.dataclass
class MLMTrainerConfig:
    batch_size: int = 16
    grad_accum: int = 2          # effective batch 32 (reference schedule)
    max_length: int = 256
    mask_prob: float = 0.15
    learning_rate: float = 5e-5
    warmup_steps: int = 50000
    num_epochs: int = 50
    seed: int = 2021
    steps_per_epoch: Optional[int] = None
    output_dir: Optional[str] = None  # enables checkpoint/resume
    overwrite_output_dir: bool = False  # reference: run_mlm_wwm.py:190-196
    # steps allowed in flight before losses are pulled to the host (the
    # NaN guard fires in the pulled block); 1 = sync per step
    sync_every: int = 32
    # checkify float-checks localizing the first NaN/inf op (debug only;
    # shared mechanism: training/trainer.py jit_step)
    debug_checks: bool = False
    # host batches prepared ahead of the device (masking off critical path)
    prefetch_depth: int = 4


def read_corpus_lines(path) -> List[str]:
    """Non-blank corpus lines; raises on an effectively-empty file.
    Shared by training, held-out evaluation, and the CLI's fail-fast
    validation check so all three agree on what 'empty' means."""
    with open(path, encoding="utf-8") as f:
        lines = [l.strip() for l in f if l.strip()]
    if not lines:
        raise ValueError(f"MLM corpus {path} is empty")
    return lines


class MLMTrainer:
    def __init__(
        self,
        config: BertConfig,
        tokenizer,
        trainer_config: Optional[MLMTrainerConfig] = None,
    ) -> None:
        import optax

        from ..training.trainer import _reject_inference_only_quant

        self.model = MLMModel(config)
        _reject_inference_only_quant(self.model)
        self.tokenizer = tokenizer
        self.c = trainer_config or MLMTrainerConfig()
        self._continuation = continuation_flags(tokenizer)
        self._special = [tokenizer.pad_id, tokenizer.cls_id, tokenizer.sep_id]
        self._np_rng = np.random.default_rng(self.c.seed)

        dummy = np.zeros((2, 8), np.int32)
        self.params = self.model.init(
            jax.random.PRNGKey(self.c.seed), dummy, np.ones_like(dummy)
        )
        from ..training.optim import linear_with_warmup

        schedule = linear_with_warmup(self.c.warmup_steps)
        self.tx = optax.chain(
            optax.clip_by_global_norm(1.0),
            optax.scale_by_adam(),
            optax.scale_by_schedule(schedule),
            optax.scale(-self.c.learning_rate),
        )
        self.opt_state = self.tx.init(self.params)
        self.step = 0
        self.start_epoch = 0
        self.checkpointer = None
        if self.c.output_dir is not None:
            self._init_output_dir()

        def train_step(params, opt_state, rng, stack_ids, stack_mask, stack_labels):
            """One optimizer update over a [K, B, L] microbatch stack —
            the reference's batch 16 × accum 2 schedule made real via the
            same lax.scan pattern as training/trainer.py:make_train_step.
            The RNG advances on device so the host loop is dispatch-only."""

            def loss_fn(p, ids, mask, labels, sub):
                logits = self.model.apply(
                    p, ids, mask, deterministic=False, rngs={"dropout": sub}
                )
                return mlm_loss(logits, labels)

            def accumulate(carry, micro):
                grads_sum, loss_sum, real_sum, rng = carry
                ids, mask, labels = micro
                rng, sub = jax.random.split(rng)
                loss, grads = jax.value_and_grad(loss_fn)(
                    params, ids, mask, labels, sub
                )
                grads_sum = jax.tree_util.tree_map(jnp.add, grads_sum, grads)
                # epoch-tail stacks are padded with all-padding microbatches
                # (zero loss, zero grads) — they must not dilute the mean
                real = (labels != IGNORE).any().astype(jnp.float32)
                return (grads_sum, loss_sum + loss, real_sum + real, rng), None

            zero = jax.tree_util.tree_map(jnp.zeros_like, params)
            (grads, loss_sum, real_k, rng), _ = jax.lax.scan(
                accumulate,
                (zero, 0.0, 0.0, rng),
                (stack_ids, stack_mask, stack_labels),
            )
            real_k = jnp.maximum(real_k, 1.0)
            grads = jax.tree_util.tree_map(lambda g: g / real_k, grads)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(
                lambda p, u: p + u.astype(p.dtype), params, updates
            )
            return params, opt_state, rng, loss_sum / real_k

        from ..training.trainer import jit_step

        self._train_step = jit_step(
            train_step, donate=(0, 1, 2), debug_checks=self.c.debug_checks
        )
        from ..telemetry.programs import get_program_registry

        self._programs = get_program_registry()
        self._step_shapes: set = set()

    def _register_step_program(self, *args) -> str:
        """First occurrence of a stack shape routes through the program
        registry's chokepoint (see MemoryTrainer._register_step_program)."""
        from ..telemetry.programs import shape_key

        key = shape_key("mlm_step", args[3:])
        if key in self._step_shapes:
            return key
        self._step_shapes.add(key)
        lower = getattr(self._train_step, "lower", None)
        if lower is not None:
            self._programs.compile_and_register(
                key, lower(*args), scope="mlm"
            )
        return key

    # -- checkpoint / resume --------------------------------------------------

    def _init_output_dir(self) -> None:
        from pathlib import Path

        from ..training.checkpoint import TrainCheckpointer

        out = Path(self.c.output_dir)
        has_checkpoints = (out / "epochs").exists()
        if (
            out.exists()
            and any(out.iterdir())
            and not has_checkpoints
            and not self.c.overwrite_output_dir
        ):
            # non-empty dir with no checkpoints to resume from — refuse to
            # clobber (reference: run_mlm_wwm.py:190-196)
            raise ValueError(
                f"output dir {out} exists and is not empty; pass "
                "overwrite_output_dir=True to overwrite, or point at a "
                "directory with checkpoints to resume"
            )
        self.checkpointer = TrainCheckpointer(out)

    def _state_dict(self, epoch: int = 0) -> Dict:
        return {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
            "meta": {"step": self.step, "epoch": epoch},
        }

    def maybe_restore(self) -> bool:
        if self.checkpointer is None:
            return False
        restored = self.checkpointer.restore_latest(self._state_dict())
        if restored is None:
            return False
        _, state = restored
        self.params = state["params"]
        self.opt_state = state["opt_state"]
        self.step = int(state["meta"]["step"])
        self.start_epoch = int(state["meta"]["epoch"]) + 1
        logger.info("mlm: resumed after epoch %d", self.start_epoch - 1)
        return True

    # -- data ------------------------------------------------------------------

    def _encode_corpus(self, lines: List[str]) -> None:
        """Tokenize the whole corpus ONCE into a packed (flat ids, offsets)
        int32 pair; every epoch afterwards only shuffles indices and masks.
        The reference gets the same once-only property from datasets.map
        with worker processes (run_mlm_wwm.py:322-333); at 1.1M lines × 50
        epochs, per-epoch re-tokenization would dominate the pipeline."""
        c = self.c
        started = time.perf_counter()
        chunks: List[np.ndarray] = []
        offsets = np.zeros(len(lines) + 1, dtype=np.int64)
        # block-wise encode_many: the rust tokenizer's thread pool does
        # the corpus pass in parallel (1.1M lines would otherwise pin one
        # Python thread — the reference parallelizes the same pass with
        # datasets.map worker processes, run_mlm_wwm.py:322-333)
        i = 0
        for start in range(0, len(lines), 8192):
            block = lines[start : start + 8192]
            for seq in self.tokenizer.encode_many(block, max_length=c.max_length):
                seq = np.asarray(seq, np.int32)
                chunks.append(seq)
                offsets[i + 1] = offsets[i] + len(seq)
                i += 1
        self._flat_ids = (
            np.concatenate(chunks) if chunks else np.zeros(0, np.int32)
        )
        self._offsets = offsets
        logger.info(
            "mlm: tokenized %d lines (%d tokens) in %.1fs — cached for all "
            "epochs", len(lines), len(self._flat_ids),
            time.perf_counter() - started,
        )

    @property
    def corpus_size(self) -> int:
        return len(self._offsets) - 1 if hasattr(self, "_offsets") else 0

    def _batches(
        self, rng: Optional[np.random.Generator] = None
    ) -> Iterator[Tuple[np.ndarray, ...]]:
        """[K, B, L] microbatch stacks (K = grad_accum) from the packed
        token cache.  The trailing partial stack is padded with empty
        rows — pad-only rows yield no maskable positions, so they
        contribute no loss.

        ``rng``: the generator for shuffle + masking.  The training loop
        passes a per-epoch generator spawned on the main thread because
        this iterator runs on a prefetch worker — an abandoned worker
        from a truncated epoch may overlap the next epoch's, and numpy
        Generators are not thread-safe to share."""
        c = self.c
        rng = self._np_rng if rng is None else rng
        n = self.corpus_size
        rows = c.batch_size * max(1, c.grad_accum)
        order = rng.permutation(n)
        for start in range(0, n, rows):
            picked = order[start : start + rows]
            seqs = [
                self._flat_ids[self._offsets[idx] : self._offsets[idx + 1]]
                for idx in picked
            ]
            masked, mask, labels = self._masked_rows(seqs, rows, rng)
            shape = (max(1, c.grad_accum), c.batch_size, c.max_length)
            yield masked.reshape(shape), mask.reshape(shape), labels.reshape(shape)

    def _masked_rows(
        self, seqs: List[np.ndarray], rows: int, rng: np.random.Generator
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(masked ids, attention mask, labels) for up to ``rows`` padded
        sequences — the one batch-construction path shared by training
        (`_batches`) and held-out evaluation, so their losses stay
        comparable."""
        c = self.c
        ids = np.full((rows, c.max_length), self.tokenizer.pad_id, np.int32)
        mask = np.zeros_like(ids)
        for i, seq in enumerate(seqs):
            ids[i, : len(seq)] = seq
            mask[i, : len(seq)] = 1
        masked, labels = whole_word_mask(
            ids, mask, rng, self.tokenizer.mask_id,
            self.tokenizer.vocab_size, self._continuation, self._special,
            c.mask_prob,
        )
        return masked, mask, labels

    def evaluate(
        self, corpus_path: str, params=None, seed: int = 0
    ) -> Dict[str, float]:
        """Held-out masked-LM loss + perplexity (the reference script's
        ``do_eval`` path, run_mlm_wwm.py:386-397).  Masking is drawn from
        a fixed ``seed`` so the metric is reproducible; the mean is
        weighted by masked-token count, not per-batch."""
        import math

        c = self.c
        params = self.params if params is None else params
        lines = read_corpus_lines(corpus_path)

        if not hasattr(self, "_eval_sums"):
            def eval_sums(p, ids, mask, labels):
                logits = self.model.apply(p, ids, mask, deterministic=True)
                return mlm_nll_sums(logits, labels)

            self._eval_sums = jax.jit(eval_sums)  # compiled once per trainer

        rng = np.random.default_rng(seed)
        rows = c.batch_size
        nll_total = 0.0
        masked_total = 0.0
        for start in range(0, len(lines), rows):
            seqs = [
                np.asarray(ids, np.int32)
                for ids in self.tokenizer.encode_many(
                    lines[start : start + rows], max_length=c.max_length
                )
            ]
            masked, mask, labels = self._masked_rows(seqs, rows, rng)
            s, k = self._eval_sums(params, masked, mask, labels)
            nll_total += float(s)
            masked_total += float(k)
        loss = nll_total / max(masked_total, 1.0)
        return {
            "eval_loss": loss,
            "perplexity": math.exp(min(loss, 30.0)),
            "eval_lines": len(lines),
            "masked_tokens": int(masked_total),
        }

    def train(self, corpus_path: str) -> Dict[str, float]:
        from ..data.batching import prefetch

        c = self.c
        lines = read_corpus_lines(corpus_path)
        logger.info("MLM corpus: %d lines", len(lines))
        self._encode_corpus(lines)
        self.maybe_restore()
        rng = jax.random.PRNGKey(c.seed)
        rng = jax.random.fold_in(rng, self.start_epoch)  # distinct post-resume
        history: List[float] = []
        for epoch in range(self.start_epoch, c.num_epochs):
            losses: List[float] = []
            pending: List[jax.Array] = []
            started = time.perf_counter()

            def drain() -> None:
                # the loop's only blocking transfer; NaN guard lives here
                drain_pending(
                    pending, jax.device_get, self.step, losses, what="MLM loss"
                )

            # per-epoch generator spawned on the main thread: the prefetch
            # worker owns it exclusively (no cross-epoch thread sharing)
            epoch_rng = np.random.default_rng(self._np_rng.integers(2**63))
            batches = prefetch(
                self._batches(epoch_rng), depth=max(1, c.prefetch_depth)
            )
            for i, (ids, mask, labels) in enumerate(batches):
                if c.steps_per_epoch is not None and i >= c.steps_per_epoch:
                    break
                program_key = self._register_step_program(
                    self.params, self.opt_state, rng, ids, mask, labels
                )
                self.params, self.opt_state, rng, loss = self._train_step(
                    self.params, self.opt_state, rng, ids, mask, labels
                )
                pending.append(loss)
                self.step += 1
                self._programs.record_invocation(program_key)
                if len(pending) >= max(1, c.sync_every):
                    drain()
            drain()
            mean_loss = float(np.mean(losses)) if losses else 0.0
            history.append(mean_loss)
            logger.info(
                "mlm epoch %d: loss %.4f (%.1fs)",
                epoch, mean_loss, time.perf_counter() - started,
            )
            if self.checkpointer is not None:
                self.checkpointer.save(
                    epoch, self._state_dict(epoch), metadata={"loss": mean_loss}
                )
        if self.checkpointer is not None:
            self.checkpointer.flush()  # final async save must land on disk
        return {"final_loss": history[-1] if history else 0.0, "history": history}

    def encoder_params(self):
        return extract_encoder_params(self.params)
