"""Pallas TPU flash-attention forward kernel.

Blockwise (FlashAttention-style) exact attention: the [Tq, Tk] score
matrix never materializes in HBM — each grid step streams one key/value
block through VMEM and folds it into a running online-softmax
accumulator.  This is the long-context capability the reference lacks
entirely (it *folds* long inputs instead, custom_PTM_embedder.py:244-381);
at 1k-4k tokens the XLA path's [B, H, T, T] score tensor dominates HBM
traffic while this kernel's footprint stays O(T·D).

Scope (by design, documented at the call site in ops/attention.py):

* forward pass only — the backward pass recomputes attention through the
  XLA formulation via ``jax.custom_vjp`` (correct gradients, XLA-sized
  memory; the flash win targets inference/eval where long sequences
  actually occur in this workload);
* key-only additive bias (the encoder's padding mask, broadcastable to
  [B, 1, 1, Tk]); a full [B, H, Tq, Tk] bias falls back to XLA;
* no dropout (callers route dropout through XLA).

Numerics match the XLA path: scores and softmax accumulate in float32
(MXU matmuls via ``preferred_element_type``), output cast back to the
query dtype.  All-masked rows produce the same uniform-average artifact
as XLA softmax — downstream pooling drops padded rows either way.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# renamed TPUCompilerParams -> CompilerParams across jax releases
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

_NEG_INF = float(jnp.finfo(jnp.float32).min)


def _fit_block(block: int, t: int) -> int:
    """Largest block ≤ the requested size whose grid padding stays ≤25%.

    Big blocks win on MXU utilisation (see the sweep in SMOKE.md) but pad
    the sequence up to the next multiple: at T=1100 a 1024 block pads to
    2048 (~86% wasted work) while 256 pads to 1280 (16%).  Halve until the
    padded length is within 1.25×T, floored at 128 (the lane tile)."""
    block = min(block, t)
    while block > 128 and -(-t // block) * block > 1.25 * t:
        block = max(block // 2, 128)
    return block


class UnsupportedBiasError(ValueError):
    """The bias carries real query/head structure the kernel does not
    support — callers catch THIS (not ValueError, which would also swallow
    genuine tracing/lowering failures) to fall back to XLA."""


def _flash_fwd_kernel(
    bias_ref,  # [1, 1, block_k] f32 — key-position additive bias
    q_ref,     # [1, block_q, d]
    k_ref,     # [1, block_k, d]
    v_ref,     # [1, block_k, d]
    out_ref,   # [1, block_q, d]
    m_scratch,    # [block_q, 128] f32 running max (lane-replicated)
    l_scratch,    # [block_q, 128] f32 running denominator
    acc_scratch,  # [block_q, d] f32 output accumulator
    *,
    scale: float,
    num_k_blocks: int,
):
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, _NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    q = q_ref[0]  # [block_q, d]
    k = k_ref[0]  # [block_k, d]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [block_q, block_k]
    s = s * scale + bias_ref[0, 0][None, :]

    m_prev = m_scratch[:, :1]  # [block_q, 1]
    l_prev = l_scratch[:, :1]
    m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
    correction = jnp.exp(m_prev - m_new)  # [block_q, 1]
    p = jnp.exp(s - m_new)  # [block_q, block_k]
    l_new = l_prev * correction + p.sum(axis=-1, keepdims=True)
    m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
    l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)

    pv = jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [block_q, d]
    acc_scratch[:] = acc_scratch[:] * correction + pv

    @pl.when(kj == num_k_blocks - 1)
    def _finalize():
        denom = jnp.maximum(l_scratch[:, :1], 1e-30)
        out_ref[0] = (acc_scratch[:] / denom).astype(out_ref.dtype)


def _flash_forward(
    query: jax.Array,   # [B, Tq, H, D]
    key: jax.Array,     # [B, Tk, H, D]
    value: jax.Array,   # [B, Tk, H, D]
    key_bias: jax.Array,  # [B, Tk] f32 additive
    block_q: int,
    block_k: int,
    interpret: bool,
) -> jax.Array:
    b, t_q, h, d = query.shape
    t_k = key.shape[1]
    scale = 1.0 / (d ** 0.5)

    block_q = _fit_block(block_q, t_q)
    block_k = _fit_block(block_k, t_k)
    pad_q = (-t_q) % block_q
    pad_k = (-t_k) % block_k
    if pad_q:
        query = jnp.pad(query, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
    if pad_k:
        key = jnp.pad(key, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        value = jnp.pad(value, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        # padded keys must never win the softmax
        key_bias = jnp.pad(key_bias, ((0, 0), (0, pad_k)), constant_values=_NEG_INF)
    tq_p, tk_p = t_q + pad_q, t_k + pad_k

    # [B, T, H, D] -> [B*H, T, D]: each (batch, head) pair is one
    # independent attention problem; the grid walks key blocks innermost
    qt = query.transpose(0, 2, 1, 3).reshape(b * h, tq_p, d)
    kt = key.transpose(0, 2, 1, 3).reshape(b * h, tk_p, d)
    vt = value.transpose(0, 2, 1, 3).reshape(b * h, tk_p, d)

    num_q_blocks = tq_p // block_q
    num_k_blocks = tk_p // block_k

    kernel = functools.partial(
        _flash_fwd_kernel, scale=scale, num_k_blocks=num_k_blocks
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, num_q_blocks, num_k_blocks),
        in_specs=[
            # bias is per-batch (shared across heads): row = bh // h —
            # lax.div (truncating) instead of Python // because Mosaic
            # rejects floor-division's negative-operand select in index maps.
            # The bias rides in as [B, 1, Tk]: batch must live in a leading
            # (freely blockable) dim — Mosaic requires the LAST TWO block
            # dims to be (8, 128)-divisible or equal to the array dims, so a
            # [1, block_k] block over [B, Tk] is rejected on real hardware.
            pl.BlockSpec(
                (1, 1, block_k),
                lambda bh, qi, kj: (jax.lax.div(bh, h), 0, kj),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, block_q, d), lambda bh, qi, kj: (bh, qi, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, block_k, d), lambda bh, qi, kj: (bh, kj, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, block_k, d), lambda bh, qi, kj: (bh, kj, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, block_q, d), lambda bh, qi, kj: (bh, qi, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, tq_p, d), query.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(key_bias.astype(jnp.float32)[:, None, :], qt, kt, vt)

    out = out.reshape(b, h, tq_p, d).transpose(0, 2, 1, 3)
    if pad_q:
        out = out[:, :t_q]
    return out


def _squeeze_key_bias(bias: Optional[jax.Array], b: int, t_k: int) -> Optional[jax.Array]:
    """A bias broadcastable to [B, 1, 1, Tk] reduced to [B, Tk]; None when
    the bias carries real query/head structure (caller falls back)."""
    if bias is None:
        return jnp.zeros((b, t_k), jnp.float32)
    if bias.ndim != 4 or bias.shape[1] != 1 or bias.shape[2] != 1:
        return None
    if bias.shape[3] != t_k:
        return None
    out = bias[:, 0, 0, :].astype(jnp.float32)
    if out.shape[0] == 1 and b > 1:
        out = jnp.broadcast_to(out, (b, t_k))
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_attention_vjp(query, key, value, key_bias, block_q, block_k, interpret):
    return _flash_forward(query, key, value, key_bias, block_q, block_k, interpret)


def _flash_vjp_fwd(query, key, value, key_bias, block_q, block_k, interpret):
    out = _flash_forward(query, key, value, key_bias, block_q, block_k, interpret)
    return out, (query, key, value, key_bias)


def _flash_vjp_bwd(block_q, block_k, interpret, residuals, g):
    # backward recomputes attention through the XLA formulation — correct
    # gradients at XLA-sized memory; the flash memory win is forward-only
    query, key, value, key_bias = residuals
    from ..attention import _xla_attention

    bias = key_bias[:, None, None, :]

    def ref(q, k, v):
        return _xla_attention(q, k, v, bias, None, 0.0, True)

    _, vjp = jax.vjp(ref, query, key, value)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


_flash_attention_vjp.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    bias: Optional[jax.Array] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Blockwise exact attention.  [B, T, H, D] in, [B, T, H, D] out.

    ``bias`` must be key-only (broadcastable to [B, 1, 1, Tk]) — raises
    ValueError otherwise so the caller can fall back to XLA explicitly.
    ``interpret`` defaults to True off-TPU so tests exercise the kernel
    logic anywhere.

    Default blocks (512, 1024) come from an on-chip sweep (v5e, bf16,
    B=4 H=12 D=64): 2.2-2.8x over the XLA formulation at 1k-4k tokens,
    vs 0.7x at the naive (256, 256) — see SMOKE.md / TPU_PROOFS.json.
    Blocks clamp to the actual sequence length for shorter inputs.
    """
    if query.ndim != 4:
        raise ValueError(f"expected [B, T, H, D], got {query.shape}")
    b, _, _, _ = query.shape
    t_k = key.shape[1]
    key_bias = _squeeze_key_bias(bias, b, t_k)
    if key_bias is None:
        raise UnsupportedBiasError(
            "flash kernel supports key-only bias (broadcastable to "
            f"[B, 1, 1, Tk]); got shape {None if bias is None else bias.shape}"
        )
    if interpret is None:
        from ...utils.platform import is_tpu_backend

        interpret = not is_tpu_backend()
    return _flash_attention_vjp(
        query, key, value, key_bias, block_q, block_k, interpret
    )
