"""Blockwise (flash) attention via Pallas for long sequences.

At the reference's sequence lengths (256 train / 512 eval) XLA's fused
attention is already near-roofline, so the XLA path is the default; this
kernel exists for the long-context stretch where the [T, T] score matrix
stops fitting in VMEM.  On non-TPU backends it falls back to the einsum
formulation so tests run anywhere.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def flash_attention_or_fallback(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    bias: Optional[jax.Array] = None,
) -> jax.Array:
    if jax.default_backend() == "tpu":
        try:
            return _pallas_flash(query, key, value, bias)
        except (ImportError, NotImplementedError):
            pass  # kernel not built yet — XLA fallback below
    from ..attention import _xla_attention

    return _xla_attention(query, key, value, bias, None, 0.0, True)


def _pallas_flash(query, key, value, bias):
    from .flash_kernel import flash_attention

    return flash_attention(query, key, value, bias)
