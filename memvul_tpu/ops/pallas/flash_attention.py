"""Blockwise (flash) attention dispatch for long sequences.

At the reference's sequence lengths (256 train / 512 eval) XLA's fused
attention is already near-roofline, so the XLA path is the default; the
Pallas kernel (:mod:`.flash_kernel`) exists for the long-context stretch
where the [B, H, T, T] score tensor stops fitting — its footprint stays
O(T·D).  Dispatch rules:

* TPU + key-only bias (the encoder's padding mask): Pallas kernel;
* TPU + structured [B, H, Tq, Tk] bias: XLA (logged once) — the kernel
  deliberately supports only the bias shape the models produce;
* non-TPU backends: XLA (mathematically identical; the kernel itself is
  exercised on CPU via interpret mode in tests/test_flash_kernel.py).
"""

from __future__ import annotations

import logging
from typing import Optional

import jax

logger = logging.getLogger(__name__)
_warned_bias = False


def flash_attention_or_fallback(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    bias: Optional[jax.Array] = None,
) -> jax.Array:
    global _warned_bias
    from ...utils.platform import is_tpu_backend

    if is_tpu_backend():
        from .flash_kernel import UnsupportedBiasError, flash_attention

        try:
            # named scope: the kernel shows up as "flash_attention" in
            # trace_context profiles instead of an anonymous custom call
            with jax.named_scope("flash_attention"):
                return flash_attention(query, key, value, bias)
        except UnsupportedBiasError:
            # only the documented bias-shape rejection falls back; any
            # other kernel failure propagates so regressions surface
            if not _warned_bias:
                _warned_bias = True
                logger.info(
                    "flash kernel: non-key-only bias %s — using XLA attention",
                    None if bias is None else bias.shape,
                )
    from ..attention import _xla_attention

    return _xla_attention(query, key, value, bias, None, 0.0, True)
