"""Ragged (segment-masked) flash attention for the packed serve path.

The ragged serving path (docs/ragged_serving.md) packs many
variable-length requests into ONE flat token row — ``input_ids`` of a
fixed ``[1, token_budget]`` shape with a ``segment_ids`` row table
marking which request each position belongs to (0 = dead padding).
Attention must then be blocked on request boundaries: a token may only
attend to keys carrying its own segment id, never across requests that
merely happen to be neighbours in the pack.

This module extends the blockwise kernel in :mod:`.flash_kernel` from
key-only *padding* masks to full segment masking:

* the Pallas kernel streams key/value blocks through VMEM exactly like
  the flash kernel (O(T·D) footprint, online softmax) and applies the
  ``q_seg == k_seg & k_seg > 0`` mask per score block — the [T, T]
  segment mask never materializes in HBM, which matters because the
  packed budget is the one sequence length in the system that *grows*
  with batching (the bucketed path's [B, H, L, L] bias is per-bucket
  small; the ragged path's would be [1, H, budget, budget]);
* segment ids ride in lane-/sublane-replicated layouts ([B, Tq, 128]
  for the query side, [B, 8, Tk] for the key side — the same
  replication trick the flash kernel's m/l scratch uses) so the
  per-block equality is a 2D broadcast Mosaic can lower;
* non-TPU backends fall back to the XLA formulation over an explicit
  [B, 1, Tq, Tk] segment bias — mathematically identical, and the
  kernel itself is exercised on CPU via interpret mode in
  tests/test_ragged_serving.py.

Forward-only by design: the ragged path serves inference (the packed
program is never differentiated); training keeps the bucketed pair
batches of PR 5.  Numerics match the XLA path: scores and softmax in
float32, output cast back to the query dtype.  Dead positions (segment
0) see an all-masked row and produce the same uniform-average artifact
as XLA softmax under a fully-masked bias — the row-table gather drops
them before anything downstream looks.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_kernel import _CompilerParams, _NEG_INF, _fit_block

# replication widths for the segment-id operands (see module docstring):
# query-side ids replicate across the 128-lane axis, key-side ids across
# the 8-sublane axis, so each block slice is a legal (8,128)-tiled ref
_LANES = 128
_SUBLANES = 8


def segment_bias(segment_ids: jax.Array, dtype=jnp.float32) -> jax.Array:
    """[B, T] segment ids → additive bias [B, 1, Tq, Tk].

    Position q may attend to position k iff they carry the same non-zero
    segment id; everything else (cross-request pairs and dead padding)
    gets the dtype's finite min, which a float32 softmax turns into an
    exact zero weight — the same convention as
    :func:`~memvul_tpu.ops.attention.mask_to_bias`, so the packed scores
    match the bucketed path's padded scores bit-for-bit in the real
    rows."""
    neg = jnp.finfo(dtype).min
    q = segment_ids[:, :, None]  # [B, Tq, 1]
    k = segment_ids[:, None, :]  # [B, 1, Tk]
    allowed = (q == k) & (k > 0)
    return jnp.where(allowed[:, None, :, :], 0.0, neg).astype(dtype)


def _ragged_fwd_kernel(
    q_seg_ref,  # [1, block_q, 128] int32 — lane-replicated query segments
    k_seg_ref,  # [1, 8, block_k] int32 — sublane-replicated key segments
    q_ref,      # [1, block_q, d]
    k_ref,      # [1, block_k, d]
    v_ref,      # [1, block_k, d]
    out_ref,    # [1, block_q, d]
    m_scratch,    # [block_q, 128] f32 running max (lane-replicated)
    l_scratch,    # [block_q, 128] f32 running denominator
    acc_scratch,  # [block_q, d] f32 output accumulator
    *,
    scale: float,
    num_k_blocks: int,
):
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, _NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    q = q_ref[0]  # [block_q, d]
    k = k_ref[0]  # [block_k, d]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [block_q, block_k]
    s = s * scale

    # the segment mask: [block_q, 1] == [1, block_k] broadcasts to the
    # score block's shape without any 1D iota/transpose Mosaic would
    # reject; k_seg > 0 additionally kills dead (padding) keys
    q_seg = q_seg_ref[0, :, :1]   # [block_q, 1]
    k_seg = k_seg_ref[0, :1, :]   # [1, block_k]
    mask = (q_seg == k_seg) & (k_seg > 0)
    s = jnp.where(mask, s, _NEG_INF)

    m_prev = m_scratch[:, :1]  # [block_q, 1]
    l_prev = l_scratch[:, :1]
    m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
    correction = jnp.exp(m_prev - m_new)  # [block_q, 1]
    p = jnp.exp(s - m_new)  # [block_q, block_k]
    # a fully-masked score block is exp(0) = 1 everywhere (NEG_INF is the
    # finite float32 min, so the subtraction stays finite); those uniform
    # weights only ever land on dead rows, whose output no one gathers
    l_new = l_prev * correction + p.sum(axis=-1, keepdims=True)
    m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
    l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)

    pv = jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [block_q, d]
    acc_scratch[:] = acc_scratch[:] * correction + pv

    @pl.when(kj == num_k_blocks - 1)
    def _finalize():
        denom = jnp.maximum(l_scratch[:, :1], 1e-30)
        out_ref[0] = (acc_scratch[:] / denom).astype(out_ref.dtype)


def ragged_flash_attention(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    segment_ids: jax.Array,
    block_q: int = 256,
    block_k: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """Segment-masked blockwise attention.  [B, T, H, D] in/out.

    ``segment_ids`` is [B, T] int32: equal non-zero values attend to each
    other, 0 marks dead padding.  Forward-only (inference path).
    ``interpret`` defaults to True off-TPU so tests exercise the kernel
    logic anywhere; default blocks are smaller than the flash kernel's
    because the packed budget replaces the batch axis (grid parallelism
    comes from q-blocks, not rows).
    """
    if query.ndim != 4:
        raise ValueError(f"expected [B, T, H, D], got {query.shape}")
    if segment_ids.shape != query.shape[:2]:
        raise ValueError(
            f"segment_ids {segment_ids.shape} must match [B, T] "
            f"{query.shape[:2]}"
        )
    if interpret is None:
        from ...utils.platform import is_tpu_backend

        interpret = not is_tpu_backend()
    b, t_q, h, d = query.shape
    t_k = key.shape[1]
    scale = 1.0 / (d ** 0.5)
    segment_ids = segment_ids.astype(jnp.int32)

    block_q = _fit_block(block_q, t_q)
    block_k = _fit_block(block_k, t_k)
    pad_q = (-t_q) % block_q
    pad_k = (-t_k) % block_k
    seg_q, seg_k = segment_ids, segment_ids
    if pad_q:
        query = jnp.pad(query, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        # padded query rows keep segment 0 → fully masked → dropped output
        seg_q = jnp.pad(seg_q, ((0, 0), (0, pad_q)))
    if pad_k:
        key = jnp.pad(key, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        value = jnp.pad(value, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        seg_k = jnp.pad(seg_k, ((0, 0), (0, pad_k)))  # 0 = never attended
    tq_p, tk_p = t_q + pad_q, t_k + pad_k

    # [B, T, H, D] -> [B*H, T, D] (one attention problem per batch-head)
    qt = query.transpose(0, 2, 1, 3).reshape(b * h, tq_p, d)
    kt = key.transpose(0, 2, 1, 3).reshape(b * h, tk_p, d)
    vt = value.transpose(0, 2, 1, 3).reshape(b * h, tk_p, d)

    # replicated segment-id layouts (module docstring): blocks sliced
    # from these are (sublane, lane)-legal without any in-kernel reshape
    q_seg_rep = jax.lax.broadcast_in_dim(
        seg_q, (b, tq_p, _LANES), (0, 1)
    )
    k_seg_rep = jax.lax.broadcast_in_dim(
        seg_k, (b, _SUBLANES, tk_p), (0, 2)
    )

    num_q_blocks = tq_p // block_q
    num_k_blocks = tk_p // block_k

    kernel = functools.partial(
        _ragged_fwd_kernel, scale=scale, num_k_blocks=num_k_blocks
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, num_q_blocks, num_k_blocks),
        in_specs=[
            # segment ids are per-batch (shared across heads): row =
            # bh // h via lax.div, same Mosaic-friendly index map as the
            # flash kernel's bias spec
            pl.BlockSpec(
                (1, block_q, _LANES),
                lambda bh, qi, kj: (jax.lax.div(bh, h), qi, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, _SUBLANES, block_k),
                lambda bh, qi, kj: (jax.lax.div(bh, h), 0, kj),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, block_q, d), lambda bh, qi, kj: (bh, qi, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, block_k, d), lambda bh, qi, kj: (bh, kj, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, block_k, d), lambda bh, qi, kj: (bh, kj, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, block_q, d), lambda bh, qi, kj: (bh, qi, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, tq_p, d), query.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q_seg_rep, k_seg_rep, qt, kt, vt)

    out = out.reshape(b, h, tq_p, d).transpose(0, 2, 1, 3)
    if pad_q:
        out = out[:, :t_q]
    return out


def ragged_attention_or_fallback(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    segment_ids: jax.Array,
) -> jax.Array:
    """Dispatch: Pallas kernel on TPU, XLA over an explicit segment bias
    elsewhere (mathematically identical; the bias materializes
    [B, 1, T, T], which is fine off-TPU where T is test-sized)."""
    from ...utils.platform import is_tpu_backend

    if is_tpu_backend():
        with jax.named_scope("ragged_flash_attention"):
            return ragged_flash_attention(query, key, value, segment_ids)
    from ..attention import _xla_attention

    with jax.named_scope("ragged_xla_attention"):
        return _xla_attention(
            query, key, value, segment_bias(segment_ids, jnp.float32),
            None, 0.0, True,
        )
