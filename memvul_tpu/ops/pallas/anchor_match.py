"""Pallas TPU kernel for the fused anchor-bank match.

The Siamese bank match (models/memory.py:match_anchors) decomposes the
bias-free concat-linear into

    logits[b, a, c] = u[b]·W_u[:, c] + v[a]·W_v[:, c]
                      + Σ_d |u[b, d] − v[a, d]| · W_d[d, c]

Two small matmuls plus one batched abs-diff contraction.  XLA fuses the
matmuls but materializes the ``[B, A, D]`` abs-diff intermediate in HBM
— at the production shape (B=512, A=129, D=512, bf16) that is ~68 MB
written by the subtraction and read back by the einsum, per batch, for
an op whose useful inputs total under 1 MB.  The corpus-scoring path is
the north-star workload (1.2M reports streamed against the bank), so
that round-trip is pure memory-bound overhead — the same pattern the
flash-attention kernel (flash_kernel.py) eliminates for the [Tq, Tk]
score matrix.

:func:`fused_anchor_match` streams the reduction instead: the grid tiles
(B, A) and walks D blockwise, so each ``[block_b, block_a, block_d]``
abs-diff tile lives only in VMEM/registers and HBM traffic drops to the
inputs-once + output (see docs/anchor_match_kernel.md for the math).
The u/v terms are folded into the same D-walk, so the kernel emits the
complete logits — no separate XLA epilogue.

Layout notes (mirroring flash_kernel.py):

* the output is produced as ``[C, B, A]`` — the class dim (C=2) is far
  below the 128-lane tile, so it rides in the leading (freely blockable)
  position while the last two block dims stay (8, 128)-aligned; the
  caller transposes back to ``[B, A, C]``;
* the three weight slices arrive pre-transposed as ``[C, D]`` rows so a
  class's weight vector is a lane-contiguous row inside the kernel;
* scores accumulate in float32 scratch regardless of input dtype
  (bf16-safe), output casts back to the input dtype;
* ``interpret=True`` runs the same kernel logic on CPU — that is the
  path the parity tests exercise (tests/test_anchor_match_kernel.py);
  ``interpret=None`` resolves to interpret-off-TPU like flash_attention.

:func:`anchor_match` is the dispatch used by the model: ``"auto"``
routes to the kernel on TPU hardware and to the jnp decomposition
(:func:`anchor_match_reference`) everywhere else — interpret mode is a
debugging/testing vehicle, not a CPU production path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# renamed TPUCompilerParams -> CompilerParams across jax releases
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams


def anchor_match_reference(
    u: jax.Array, anchors: jax.Array, kernel: jax.Array
) -> jax.Array:
    """[B, D] × [A, D] × [3D, C] → [B, A, C] via the decomposed einsum.

    This is the XLA formulation (the pre-kernel ``match_anchors`` body):
    only the |u−v| term builds a [B, A, D] intermediate.  It is the
    numerical reference for the Pallas kernel and the fallback on
    non-TPU backends and for a model-sharded anchor bank, where XLA's
    SPMD partitioner splits the einsum across the mesh.
    """
    d = u.shape[-1]
    w_u, w_v, w_d = kernel[:d], kernel[d : 2 * d], kernel[2 * d :]
    term_u = u @ w_u  # [B, C]
    term_v = anchors @ w_v  # [A, C]
    diff = jnp.abs(u[:, None, :] - anchors[None, :, :])  # [B, A, D]
    term_d = jnp.einsum("bad,dc->bac", diff, w_d)
    return term_u[:, None, :] + term_v[None, :, :] + term_d


def _fit_block(block: int, t: int, floor: int) -> int:
    """Largest block ≤ the requested size whose grid padding stays ≤25%
    (same policy as flash_kernel._fit_block, with a per-dim floor: 8 for
    sublane-tiled dims, 128 for lane-tiled ones)."""
    block = max(min(block, -(-t // floor) * floor), floor)
    while block > floor and -(-t // block) * block > 1.25 * t:
        block = max(block // 2, floor)
    return block


def _anchor_match_kernel(
    u_ref,    # [block_b, block_d]
    v_ref,    # [block_a, block_d]
    wu_ref,   # [C, block_d]  (pre-transposed weight rows)
    wv_ref,   # [C, block_d]
    wd_ref,   # [C, block_d]
    out_ref,  # [C, block_b, block_a]
    acc_ref,  # [C, block_b, block_a] f32 scratch
    *,
    num_d_blocks: int,
    num_classes: int,
):
    dj = pl.program_id(2)

    @pl.when(dj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    u = u_ref[...].astype(jnp.float32)  # [block_b, block_d]
    v = v_ref[...].astype(jnp.float32)  # [block_a, block_d]
    # the tile that never touches HBM: |u − v| for this (B, A, D) block
    diff = jnp.abs(u[:, None, :] - v[None, :, :])  # [block_b, block_a, block_d]
    for c in range(num_classes):  # static unroll, C == 2
        w_d = wd_ref[c, :].astype(jnp.float32)  # [block_d]
        w_u = wu_ref[c, :].astype(jnp.float32)
        w_v = wv_ref[c, :].astype(jnp.float32)
        # VPU reductions over the lane (d) axis; each is a partial sum
        # over this d-block, so accumulating per grid step stays exact
        term_d = jnp.sum(diff * w_d[None, None, :], axis=-1)  # [block_b, block_a]
        term_u = jnp.sum(u * w_u[None, :], axis=-1)  # [block_b]
        term_v = jnp.sum(v * w_v[None, :], axis=-1)  # [block_a]
        acc_ref[c, :, :] += term_d + term_u[:, None] + term_v[None, :]

    @pl.when(dj == num_d_blocks - 1)
    def _finalize():
        out_ref[...] = acc_ref[...].astype(out_ref.dtype)


def fused_anchor_match(
    u: jax.Array,
    anchors: jax.Array,
    kernel: jax.Array,
    block_b: int = 128,
    block_a: int = 128,
    block_d: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """[B, D] × [A, D] × [3D, C] → [B, A, C] without the HBM intermediate.

    Grid: (B/block_b, A/block_a) parallel tiles × a D-blockwise reduction
    walked innermost ("arbitrary"), flash-attention style.  All three
    operands are zero-padded up to block multiples — zero d-columns
    contribute exactly zero to every term (|0−0| = 0 and the padded
    weight rows are zero), and padded B/A rows are sliced off the output.

    ``interpret`` defaults to True off-TPU so the kernel logic is
    testable anywhere (the dispatch in :func:`anchor_match` routes
    non-TPU *production* calls to the jnp reference instead — interpret
    mode is orders of magnitude slower than XLA on CPU).
    """
    if u.ndim != 2 or anchors.ndim != 2 or kernel.ndim != 2:
        raise ValueError(
            f"expected u[B, D], anchors[A, D], kernel[3D, C]; got "
            f"{u.shape}, {anchors.shape}, {kernel.shape}"
        )
    b, d = u.shape
    a = anchors.shape[0]
    if anchors.shape[1] != d or kernel.shape[0] != 3 * d:
        raise ValueError(
            f"dimension mismatch: u D={d}, anchors D={anchors.shape[1]}, "
            f"kernel rows={kernel.shape[0]} (need 3D={3 * d})"
        )
    c = kernel.shape[1]
    if interpret is None:
        from ...utils.platform import is_tpu_backend

        interpret = not is_tpu_backend()

    # weight slices as [C, D] rows: lane-contiguous per class in-kernel
    w_u = kernel[:d].T
    w_v = kernel[d : 2 * d].T
    w_d = kernel[2 * d :].T

    block_b = _fit_block(block_b, b, floor=8)
    block_a = _fit_block(block_a, a, floor=128)
    block_d = _fit_block(block_d, d, floor=128)
    pad_b, pad_a, pad_d = (-b) % block_b, (-a) % block_a, (-d) % block_d
    if pad_b or pad_d:
        u = jnp.pad(u, ((0, pad_b), (0, pad_d)))
    if pad_a or pad_d:
        anchors = jnp.pad(anchors, ((0, pad_a), (0, pad_d)))
    if pad_d:
        w_u = jnp.pad(w_u, ((0, 0), (0, pad_d)))
        w_v = jnp.pad(w_v, ((0, 0), (0, pad_d)))
        w_d = jnp.pad(w_d, ((0, 0), (0, pad_d)))
    bp, ap, dp = b + pad_b, a + pad_a, d + pad_d
    num_d_blocks = dp // block_d

    kern = functools.partial(
        _anchor_match_kernel, num_d_blocks=num_d_blocks, num_classes=c
    )
    weight_spec = pl.BlockSpec(
        (c, block_d), lambda bi, ai, dj: (0, dj), memory_space=pltpu.VMEM
    )
    out = pl.pallas_call(
        kern,
        grid=(bp // block_b, ap // block_a, num_d_blocks),
        in_specs=[
            pl.BlockSpec(
                (block_b, block_d), lambda bi, ai, dj: (bi, dj),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (block_a, block_d), lambda bi, ai, dj: (ai, dj),
                memory_space=pltpu.VMEM,
            ),
            weight_spec,
            weight_spec,
            weight_spec,
        ],
        out_specs=pl.BlockSpec(
            (c, block_b, block_a), lambda bi, ai, dj: (0, bi, ai),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((c, bp, ap), u.dtype),
        scratch_shapes=[pltpu.VMEM((c, block_b, block_a), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(u, anchors, w_u, w_v, w_d)

    out = out.transpose(1, 2, 0)  # [C, Bp, Ap] -> [Bp, Ap, C]
    if pad_b or pad_a:
        out = out[:b, :a]
    return out


_fallback_warned = False


def _warn_fused_fallback(error: BaseException) -> None:
    """One warning per process — a million-batch scoring run must not
    log the same degradation a million times."""
    global _fallback_warned
    if _fallback_warned:
        return
    _fallback_warned = True
    import logging

    logging.getLogger(__name__).warning(
        "fused anchor-match kernel failed to build (%s: %s) — degrading "
        "to anchor_match_impl='xla' (identical scores, loses the VMEM-"
        "streaming HBM win; see docs/anchor_match_kernel.md)",
        type(error).__name__, error,
    )


def anchor_match(
    u: jax.Array,
    anchors: jax.Array,
    kernel: jax.Array,
    impl: Optional[str] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Bank-match dispatch — the single entry point the model calls.

    * ``"auto"`` (default, also ``None``): the Pallas kernel on real TPU
      hardware, the jnp decomposition everywhere else;
    * ``"fused"``: always the kernel (interpret mode off-TPU — the
      testing path);
    * ``"xla"``: always the jnp decomposition (also the forced choice
      for a model-sharded anchor bank, where the SPMD partitioner must
      split the contraction — see SiamesePredictor).

    When the kernel path fails to *build* (a Pallas/Mosaic trace-time
    failure — e.g. an unsupported shape on a new TPU generation, or the
    injected ``kernel.lower`` fault), the dispatch degrades to the jnp
    decomposition with one warning instead of aborting the run: the two
    formulations are parity-pinned ≤1e-5 (tests/test_anchor_match_kernel
    .py), so the degradation costs HBM bandwidth, never correctness.
    Compile-time Mosaic failures surface later, at the enclosing jit's
    compile — ``SiamesePredictor`` catches those and rebuilds its score
    program on "xla" (evaluate/predict_memory.py).
    """
    if impl is None or impl == "auto":
        from ...utils.platform import is_tpu_backend

        use_fused = is_tpu_backend()
    elif impl == "fused":
        use_fused = True
    elif impl == "xla":
        use_fused = False
    else:
        raise ValueError(
            f"unknown anchor_match impl {impl!r} (want auto | fused | xla)"
        )
    # named scopes tell the two backends apart in profiles/jaxprs — the
    # kernel work stops being an anonymous blob in xprof
    # (docs/observability.md, named-scope map)
    if use_fused:
        from ...resilience import faults

        try:
            faults.fault_point("kernel.lower")
            with jax.named_scope("anchor_match_fused"):
                return fused_anchor_match(u, anchors, kernel, interpret=interpret)
        except Exception as e:
            from ...telemetry import get_registry

            # trace-time-only effect: this branch runs once, when Mosaic
            # lowering fails at trace, never per executed step
            get_registry().counter("kernel.degradations").inc()  # lint: disable=MV201
            _warn_fused_fallback(e)
    with jax.named_scope("anchor_match_xla"):
        return anchor_match_reference(u, anchors, kernel)
