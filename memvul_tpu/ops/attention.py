"""Attention kernels with a swappable implementation.

The encoder calls one function — :func:`dot_product_attention` — and the
``impl`` knob selects the backend:

* ``"xla"``     einsum formulation; XLA fuses softmax+matmul well on the
                MXU and this is the right default at seq-len ≤ 512.
* ``"flash"``   Pallas blockwise (flash) attention for long sequences;
                falls back to ``"xla"`` on non-TPU backends.
* ``"ring"``    sequence-parallel ring attention (memvul_tpu.parallel.ring)
                used under shard_map when the sequence axis is sharded.

Shapes follow the JAX convention [batch, seq, heads, head_dim].
Softmax is computed in float32 regardless of the activation dtype — on
TPU the matmuls run in bf16 on the MXU while the reduction stays
numerically safe.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def dot_product_attention(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    bias: Optional[jax.Array] = None,
    dropout_rng: Optional[jax.Array] = None,
    dropout_rate: float = 0.0,
    deterministic: bool = True,
    impl: str = "xla",
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Scaled dot-product attention.

    query/key/value: [B, T, H, Dh]; bias broadcastable to [B, H, Tq, Tk].
    Returns [B, Tq, H, Dh] in the dtype of ``query``.

    ``segment_ids`` ([B, T] int32, 0 = dead padding) switches to the
    ragged packed-batch path (docs/ragged_serving.md): attention is
    masked on segment boundaries instead of ``bias``, through the
    segment-masked Pallas kernel on TPU and the XLA formulation over an
    explicit segment bias elsewhere.  Inference-only — it overrides
    ``impl`` and supports no dropout (the packed path never trains).
    """
    if segment_ids is not None:
        if not deterministic and dropout_rate > 0.0:
            raise ValueError(
                "ragged segment attention is an inference path — "
                "attention dropout is not supported with segment_ids"
            )
        from .pallas.ragged_attention import ragged_attention_or_fallback

        return ragged_attention_or_fallback(query, key, value, segment_ids)
    if impl == "flash":
        if deterministic or dropout_rate == 0.0:
            from .pallas.flash_attention import flash_attention_or_fallback

            return flash_attention_or_fallback(query, key, value, bias)
        # the flash kernel has no dropout support — training steps with
        # attention dropout route through the XLA formulation instead of
        # silently dropping the dropout
    elif impl == "ring":
        # sequence-parallel: caller must be inside shard_map with the
        # "seq" axis bound to the sharded sequence dim; the bias travels
        # around the ring with its key/value block
        if not deterministic and dropout_rate > 0.0:
            raise ValueError(
                "ring attention has no dropout support — set "
                "attention_dropout=0 for sequence-parallel training"
            )
        from ..parallel.ring import ring_attention

        return ring_attention(query, key, value, key_bias=bias, axis_name="seq")
    elif impl != "xla":
        raise ValueError(f"unknown attention impl {impl!r}")
    return _xla_attention(
        query, key, value, bias, dropout_rng, dropout_rate, deterministic
    )


def _xla_attention(
    query, key, value, bias, dropout_rng, dropout_rate, deterministic
) -> jax.Array:
    depth = query.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", query, key) / jnp.sqrt(
        jnp.asarray(depth, dtype=query.dtype)
    )
    if bias is not None:
        scores = scores + bias
    weights = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(query.dtype)
    if not deterministic and dropout_rate > 0.0:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, weights.shape)
        weights = weights * keep / (1.0 - dropout_rate)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, value)


def mask_to_bias(attention_mask: jax.Array, dtype=jnp.float32) -> jax.Array:
    """[B, T] {0,1} mask → additive bias [B, 1, 1, T] with -inf-ish fill."""
    neg = jnp.finfo(dtype).min
    bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0, neg)
    return bias.astype(dtype)
