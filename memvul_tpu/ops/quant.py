"""Dynamic int8 matmul for inference — the v5e MXU runs int8 at ~2x its
bf16 rate, and the scoring path (SURVEY §3.2: encode 1.2M reports) is
MXU-bound at production batch sizes, so quantizing the encoder's dense
layers buys throughput the reference's fp32/fp16 GPU path has no
equivalent for.

Scheme: per-row (token) dynamic activation scales x per-column weight
scales — symmetric, zero-point-free, computed on the fly inside the
jitted forward (no calibration pass, no separate checkpoint format; the
same f32/bf16 params serve both paths).  The int8 x int8 -> int32
``lax.dot_general`` lowers onto the MXU's native int8 path on TPU; on
CPU it is exercised for numerics only.

Accuracy: symmetric per-row/per-column dynamic quant on BERT-class
encoders is the standard production recipe; the on-chip ``quantdrift``
proof (tools/tpu_proofs.py) bounds the induced best-anchor-probability
drift the same way the bf16 proof does.
"""

from __future__ import annotations

import math
from typing import Any, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax import lax

INT8_MAX = 127.0


def _rowwise_scales(x: jax.Array, eps: float = 1e-8) -> jax.Array:
    """Per-last-axis-row symmetric scale: max|row| / 127."""
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    return jnp.maximum(absmax, eps) / INT8_MAX


def quantize_rowwise(x: jax.Array):
    """float [..., K] -> (int8 [..., K], f32 scales [..., 1])."""
    x32 = x.astype(jnp.float32)
    scales = _rowwise_scales(x32)
    q = jnp.clip(jnp.round(x32 / scales), -INT8_MAX, INT8_MAX).astype(jnp.int8)
    return q, scales


def int8_matmul(x: jax.Array, w: jax.Array, out_dtype=jnp.float32) -> jax.Array:
    """``x [..., K] @ w [K, N]`` via dynamic int8: quantize x per row and
    w per output column, contract in int8 -> int32 on the MXU, dequantize
    with the outer product of scales."""
    xq, xs = quantize_rowwise(x)                      # [..., K], [..., 1]
    wq, ws = quantize_rowwise(w.astype(jnp.float32).T)  # [N, K], [N, 1]
    acc = lax.dot_general(
        xq,
        wq.T,
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )                                                  # [..., N] int32
    return (acc.astype(jnp.float32) * xs * ws[:, 0]).astype(out_dtype)


def quantize_colwise(w: jax.Array):
    """float [K, N] -> (int8 [K, N], f32 scales [N]) — per-output-column
    symmetric scales.  Defined as ``quantize_rowwise`` of ``w.T``
    transposed back, so a weight quantized ONCE here and contracted via
    :func:`int8_matmul_prequant` is bitwise-equal to what
    :func:`int8_matmul` derives dynamically on every call."""
    wq_t, ws = quantize_rowwise(w.astype(jnp.float32).T)  # [N, K], [N, 1]
    return wq_t.T, ws[:, 0]


def int8_matmul_prequant(
    x: jax.Array, wq: jax.Array, ws: jax.Array, out_dtype=jnp.float32
) -> jax.Array:
    """``x [..., K] @ dequant(wq [K, N], ws [N])`` with the weight half
    already quantized (:func:`quantize_colwise`); activations are still
    quantized per row dynamically inside the jitted forward.  The int32
    accumulation is exact, so this is bitwise-equal to
    ``int8_matmul(x, w)`` for ``wq, ws = quantize_colwise(w)``."""
    xq, xs = quantize_rowwise(x)                       # [..., K], [..., 1]
    acc = lax.dot_general(
        xq,
        wq,
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )                                                  # [..., N] int32
    return (acc.astype(jnp.float32) * xs * ws).astype(out_dtype)


# -- flax layers (drop-in for the nn.Dense/DenseGeneral uses in bert.py) ----
#
# Param names and shapes are IDENTICAL to their flax counterparts, so one
# checkpoint serves both the full-precision and the quantized path — the
# quantization is a property of the forward, not of the weights.


class QuantDense(nn.Module):
    """nn.Dense with the contraction in dynamic int8."""

    features: int
    dtype: Any = jnp.float32
    kernel_init: Any = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", self.kernel_init, (x.shape[-1], self.features)
        )
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        y = int8_matmul(x, kernel, out_dtype=self.dtype)
        return y + bias.astype(self.dtype)


class QuantDenseGeneral(nn.Module):
    """nn.DenseGeneral with the contraction in dynamic int8 — supports the
    two shapes bert.py uses: fan-out to (heads, head_dim) and fan-in from
    ``axis=(-2, -1)``."""

    features: Union[int, Sequence[int]]
    axis: Union[int, Tuple[int, ...]] = -1
    dtype: Any = jnp.float32
    kernel_init: Any = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        features = (
            (self.features,) if isinstance(self.features, int) else tuple(self.features)
        )
        axis = (self.axis,) if isinstance(self.axis, int) else tuple(self.axis)
        if sorted(a % x.ndim for a in axis) != list(
            range(x.ndim - len(axis), x.ndim)
        ):
            raise ValueError(f"QuantDenseGeneral needs trailing axes, got {axis}")
        in_shape = x.shape[x.ndim - len(axis):]
        kernel = self.param(
            "kernel", self.kernel_init, (*in_shape, *features)
        )
        bias = self.param("bias", nn.initializers.zeros, features)
        k = math.prod(in_shape)
        n = math.prod(features)
        x2d = x.reshape(*x.shape[: x.ndim - len(axis)], k)
        y = int8_matmul(x2d, kernel.reshape(k, n), out_dtype=self.dtype)
        y = y.reshape(*x.shape[: x.ndim - len(axis)], *features)
        return y + bias.astype(self.dtype)


# -- prequantized layers (quant="int8") -------------------------------------
#
# Same contraction as the Quant* twins above, but the weight half is
# quantized ONCE and cached in the "quant" variable collection instead of
# being re-quantized inside every forward — at serve batch sizes the
# encoder is memory-bound, so re-reading fp32 weights just to re-derive
# the same int8 copy wastes the bandwidth the quantization was meant to
# save.  Materialize the cache with one apply under ``mutable=["quant"]``
# (SiamesePredictor does this at build time); the jitted forward then
# reads it as a plain input.  Param tree stays IDENTICAL to
# nn.Dense/DenseGeneral — the cache is derived state, never checkpointed.


class Int8Dense(nn.Module):
    """nn.Dense with the contraction in int8 and the weight quantized once
    (per-column, cached in the "quant" collection)."""

    features: int
    dtype: Any = jnp.float32
    kernel_init: Any = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", self.kernel_init, (x.shape[-1], self.features)
        )
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        kernel_q = self.variable(
            "quant", "kernel_q", lambda: quantize_colwise(kernel)[0]
        )
        kernel_scale = self.variable(
            "quant", "kernel_scale", lambda: quantize_colwise(kernel)[1]
        )
        y = int8_matmul_prequant(
            x, kernel_q.value, kernel_scale.value, out_dtype=self.dtype
        )
        return y + bias.astype(self.dtype)


class Int8DenseGeneral(nn.Module):
    """nn.DenseGeneral with the contraction in int8 and the weight
    quantized once — supports the two shapes bert.py uses: fan-out to
    (heads, head_dim) and fan-in from ``axis=(-2, -1)``."""

    features: Union[int, Sequence[int]]
    axis: Union[int, Tuple[int, ...]] = -1
    dtype: Any = jnp.float32
    kernel_init: Any = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        features = (
            (self.features,) if isinstance(self.features, int) else tuple(self.features)
        )
        axis = (self.axis,) if isinstance(self.axis, int) else tuple(self.axis)
        if sorted(a % x.ndim for a in axis) != list(
            range(x.ndim - len(axis), x.ndim)
        ):
            raise ValueError(f"Int8DenseGeneral needs trailing axes, got {axis}")
        in_shape = x.shape[x.ndim - len(axis):]
        kernel = self.param(
            "kernel", self.kernel_init, (*in_shape, *features)
        )
        bias = self.param("bias", nn.initializers.zeros, features)
        k = math.prod(in_shape)
        n = math.prod(features)
        kernel_q = self.variable(
            "quant", "kernel_q", lambda: quantize_colwise(kernel.reshape(k, n))[0]
        )
        kernel_scale = self.variable(
            "quant", "kernel_scale", lambda: quantize_colwise(kernel.reshape(k, n))[1]
        )
        x2d = x.reshape(*x.shape[: x.ndim - len(axis)], k)
        y = int8_matmul_prequant(
            x2d, kernel_q.value, kernel_scale.value, out_dtype=self.dtype
        )
        y = y.reshape(*x.shape[: x.ndim - len(axis)], *features)
        return y + bias.astype(self.dtype)
