"""Optimizers — parameter-group AdamW + linear warmup, in optax.

The reference uses HF AdamW with parameter groups (embedder lr 2e-5,
pooler lr 5e-5, everything else lr 1e-4) and a linear-with-warmup
schedule (warmup 10000) plus grad-norm clipping
(reference: MemVul/config_memory.json:60-75, custom_trainer.py:263-277).

Here parameter groups are expressed as path-prefix rules mapped through
``optax.multi_transform``; the warmup/decay schedule is a shared scale so
each group keeps its own base learning rate.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import optax


def linear_with_warmup(
    warmup_steps: int, total_steps: Optional[int] = None
) -> optax.Schedule:
    """0→1 linearly over ``warmup_steps``, then (if ``total_steps``) decay
    linearly to 0 — HF/AllenNLP's ``linear_with_warmup``; without
    ``total_steps`` the scale stays at 1 after warmup."""

    def schedule(step):
        import jax.numpy as jnp

        step = jnp.asarray(step, jnp.float32)
        warm = jnp.minimum(1.0, step / jnp.maximum(1.0, float(warmup_steps)))
        if total_steps is None:
            return warm
        decay = jnp.maximum(
            0.0,
            (total_steps - step) / jnp.maximum(1.0, float(total_steps - warmup_steps)),
        )
        return jnp.where(step < warmup_steps, warm, decay)

    return schedule


def label_params_by_prefix(
    params, rules: Sequence[Tuple[str, str]], default: str = "default"
):
    """Assign each param leaf a group label by first matching path rule.

    ``rules``: (substring, label) pairs checked in order against the
    ``/``-joined parameter path.
    """

    def label(path, _):
        path_str = "/".join(str(getattr(k, "key", k)) for k in path)
        for needle, name in rules:
            if needle in path_str:
                return name
        return default

    return jax.tree_util.tree_map_with_path(label, params)


def make_optimizer(
    params,
    group_lrs: Optional[Dict[str, float]] = None,
    group_rules: Optional[Sequence[Tuple[str, str]]] = None,
    base_lr: float = 1e-4,
    warmup_steps: int = 0,
    total_steps: Optional[int] = None,
    betas: Tuple[float, float] = (0.9, 0.999),
    weight_decay: float = 0.0,
    grad_clip_norm: Optional[float] = 1.0,
) -> Tuple[optax.GradientTransformation, object]:
    """Build the reference's optimizer stack.

    Default groups mirror config_memory.json:60-68: the BERT encoder at
    2e-5, the pooler at 5e-5, heads at ``base_lr``.
    Returns (optimizer, opt_state).
    """
    if group_rules is None:
        group_rules = (("bert/", "embedder"), ("pooler/", "pooler"))
    if group_lrs is None:
        group_lrs = {"embedder": 2e-5, "pooler": 5e-5}
    schedule = (
        linear_with_warmup(warmup_steps, total_steps)
        if (warmup_steps or total_steps is not None)
        else None
    )

    def adamw(lr: float) -> optax.GradientTransformation:
        chain = [optax.scale_by_adam(b1=betas[0], b2=betas[1])]
        if weight_decay:
            chain.append(optax.add_decayed_weights(weight_decay))
        if schedule is not None:
            chain.append(optax.scale_by_schedule(schedule))
        chain.append(optax.scale(-lr))
        return optax.chain(*chain)

    transforms = {name: adamw(lr) for name, lr in group_lrs.items()}
    transforms["default"] = adamw(base_lr)
    labels = label_params_by_prefix(params, group_rules)
    tx = optax.multi_transform(transforms, labels)
    if grad_clip_norm is not None:
        tx = optax.chain(optax.clip_by_global_norm(grad_clip_norm), tx)
    return tx, tx.init(params)
