"""Optimizers — parameter-group AdamW + schedule family, in optax.

The reference uses HF AdamW with parameter groups (embedder lr 2e-5,
pooler lr 5e-5, everything else lr 1e-4) and a linear-with-warmup
schedule (warmup 10000) plus grad-norm clipping
(reference: MemVul/config_memory.json:60-75, custom_trainer.py:263-277).
Its trainer also accepts any AllenNLP LearningRateScheduler /
MomentumScheduler (custom_trainer.py:168-169, stepped at 741-744);
:func:`make_schedule` provides the non-linear members of that family as
pure step→scale functions (jit-friendly, no host-side stepping), and a
momentum schedule drives AdamW's b1 through
``optax.inject_hyperparams`` — no shipped reference config uses either,
they exist for drop-in parity.

Here parameter groups are expressed as path-prefix rules mapped through
``optax.multi_transform``; the warmup/decay schedule is a shared scale so
each group keeps its own base learning rate.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import optax


def linear_with_warmup(
    warmup_steps: int, total_steps: Optional[int] = None
) -> optax.Schedule:
    """0→1 linearly over ``warmup_steps``, then (if ``total_steps``) decay
    linearly to 0 — HF/AllenNLP's ``linear_with_warmup``; without
    ``total_steps`` the scale stays at 1 after warmup."""

    def schedule(step):
        import jax.numpy as jnp

        step = jnp.asarray(step, jnp.float32)
        warm = jnp.minimum(1.0, step / jnp.maximum(1.0, float(warmup_steps)))
        if total_steps is None:
            return warm
        decay = jnp.maximum(
            0.0,
            (total_steps - step) / jnp.maximum(1.0, float(total_steps - warmup_steps)),
        )
        return jnp.where(step < warmup_steps, warm, decay)

    return schedule


def make_schedule(spec: Dict) -> optax.Schedule:
    """``{"type": ..., ...}`` → a step→scale schedule in [0, 1].

    Types (mirroring the AllenNLP scheduler family the reference trainer
    accepts; all step-based and traceable):

    * ``constant`` — 1.0
    * ``linear_with_warmup`` — warmup_steps, total_steps (optional decay)
    * ``slanted_triangular`` — num_steps, cut_frac=0.1, ratio=32
      (Howard & Ruder's STLR: short linear climb, long linear fall,
      floor at 1/ratio)
    * ``cosine_with_warmup`` — warmup_steps, total_steps: half-cosine
      from 1 to 0 after warmup
    * ``polynomial_decay`` — warmup_steps, total_steps, power=1.0,
      end_factor=0.0
    """
    import jax.numpy as jnp

    kind = spec.get("type", "linear_with_warmup")
    warmup = float(spec.get("warmup_steps", 0))
    total = spec.get("total_steps", spec.get("num_steps"))

    if kind == "constant":
        return lambda step: jnp.float32(1.0)

    if kind == "linear_with_warmup":
        return linear_with_warmup(int(warmup), total)

    if kind == "slanted_triangular":
        if total is None:
            raise ValueError("slanted_triangular needs num_steps/total_steps")
        cut_frac = float(spec.get("cut_frac", 0.1))
        ratio = float(spec.get("ratio", 32))
        cut = max(1.0, float(total) * cut_frac)

        def stlr(step):
            t = jnp.asarray(step, jnp.float32)
            frac_up = t / cut
            frac_down = 1.0 - (t - cut) / jnp.maximum(1.0, float(total) - cut)
            p = jnp.clip(jnp.where(t < cut, frac_up, frac_down), 0.0, 1.0)
            return (1.0 + p * (ratio - 1.0)) / ratio

        return stlr

    def warmup_then(decay):
        """Linear warmup to 1, then ``decay(progress)`` where progress
        runs 0→1 (clipped) over the post-warmup steps — the scaffolding
        cosine and polynomial share."""
        if total is None:
            raise ValueError(f"{kind} needs total_steps")

        def schedule(step):
            t = jnp.asarray(step, jnp.float32)
            warm = t / jnp.maximum(1.0, warmup)
            progress = jnp.clip(
                (t - warmup) / jnp.maximum(1.0, float(total) - warmup), 0.0, 1.0
            )
            return jnp.where(t < warmup, warm, decay(progress))

        return schedule

    if kind == "cosine_with_warmup":
        return warmup_then(lambda p: 0.5 * (1.0 + jnp.cos(jnp.pi * p)))

    if kind == "polynomial_decay":
        power = float(spec.get("power", 1.0))
        end = float(spec.get("end_factor", 0.0))
        return warmup_then(lambda p: (1.0 - p) ** power * (1.0 - end) + end)

    raise ValueError(f"unknown schedule type {kind!r}")


def make_momentum_schedule(spec: Dict, base: float = 0.9) -> optax.Schedule:
    """Momentum (AdamW b1) schedule — the reference trainer's
    MomentumScheduler slot (custom_trainer.py:169,743-744).

    ``inverted_triangular`` (the one concrete AllenNLP momentum
    scheduler): ramp from ``base`` down to ``low`` over ``cooldown``
    steps, back up to ``base`` over ``warmup`` steps, then hold.
    ``constant`` holds ``base``.
    """
    import jax.numpy as jnp

    kind = spec.get("type", "inverted_triangular")
    if kind == "constant":
        return lambda step: jnp.float32(base)
    if kind != "inverted_triangular":
        raise ValueError(f"unknown momentum schedule type {kind!r}")
    low = float(spec.get("low", 0.85))
    cooldown = float(spec.get("cooldown_steps", spec.get("cooldown", 1)))
    warmup = float(spec.get("warmup_steps", spec.get("warmup", 1)))

    def schedule(step):
        t = jnp.asarray(step, jnp.float32)
        down = base + (low - base) * t / jnp.maximum(1.0, cooldown)
        up = low + (base - low) * (t - cooldown) / jnp.maximum(1.0, warmup)
        return jnp.where(
            t < cooldown, down, jnp.where(t < cooldown + warmup, up, base)
        )

    return schedule


def label_params_by_prefix(
    params, rules: Sequence[Tuple[str, str]], default: str = "default"
):
    """Assign each param leaf a group label by first matching path rule.

    ``rules``: (substring, label) pairs checked in order against the
    ``/``-joined parameter path.
    """

    def label(path, _):
        path_str = "/".join(str(getattr(k, "key", k)) for k in path)
        for needle, name in rules:
            if needle in path_str:
                return name
        return default

    return jax.tree_util.tree_map_with_path(label, params)


def make_optimizer(
    params,
    group_lrs: Optional[Dict[str, float]] = None,
    group_rules: Optional[Sequence[Tuple[str, str]]] = None,
    base_lr: float = 1e-4,
    warmup_steps: int = 0,
    total_steps: Optional[int] = None,
    betas: Tuple[float, float] = (0.9, 0.999),
    weight_decay: float = 0.0,
    grad_clip_norm: Optional[float] = 1.0,
    lr_schedule: Optional[Dict] = None,
    momentum_schedule: Optional[Dict] = None,
) -> Tuple[optax.GradientTransformation, object]:
    """Build the reference's optimizer stack.

    Default groups mirror config_memory.json:60-68: the BERT encoder at
    2e-5, the pooler at 5e-5, heads at ``base_lr``.  ``lr_schedule``
    (a :func:`make_schedule` spec) replaces the default linear-warmup
    scale; ``momentum_schedule`` (a :func:`make_momentum_schedule` spec)
    drives AdamW's b1 per step.  Returns (optimizer, opt_state).
    """
    if group_rules is None:
        group_rules = (("bert/", "embedder"), ("pooler/", "pooler"))
    if group_lrs is None:
        group_lrs = {"embedder": 2e-5, "pooler": 5e-5}
    if lr_schedule is not None:
        spec = dict(lr_schedule)
        spec.setdefault("warmup_steps", warmup_steps)
        spec.setdefault("total_steps", total_steps)
        schedule = make_schedule(spec)
    else:
        schedule = (
            linear_with_warmup(warmup_steps, total_steps)
            if (warmup_steps or total_steps is not None)
            else None
        )

    def scale_by_adam_tx() -> optax.GradientTransformation:
        if momentum_schedule is not None:
            b1 = make_momentum_schedule(momentum_schedule, base=betas[0])
            return optax.inject_hyperparams(optax.scale_by_adam)(
                b1=b1, b2=betas[1]
            )
        return optax.scale_by_adam(b1=betas[0], b2=betas[1])

    def adamw(lr: float) -> optax.GradientTransformation:
        chain = [scale_by_adam_tx()]
        if weight_decay:
            chain.append(optax.add_decayed_weights(weight_decay))
        if schedule is not None:
            chain.append(optax.scale_by_schedule(schedule))
        chain.append(optax.scale(-lr))
        return optax.chain(*chain)

    transforms = {name: adamw(lr) for name, lr in group_lrs.items()}
    transforms["default"] = adamw(base_lr)
    labels = label_params_by_prefix(params, group_rules)
    tx = optax.multi_transform(transforms, labels)
    if grad_clip_norm is not None:
        tx = optax.chain(optax.clip_by_global_norm(grad_clip_norm), tx)
    return tx, tx.init(params)
