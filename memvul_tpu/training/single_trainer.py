"""Training loop for the single-text classifiers (MemVul-m, TextCNN).

The reference trains these with AllenNLP's stock ``GradientDescentTrainer``
(config_single.json uses the default trainer, metric ``+pos_f1-score``,
batch 64; TextCNN/config_cnn.json uses Adam lr 1e-3).  The loop here is
the TPU shape of the same contract: one jitted CE step, negatives
re-subsampled every epoch by re-reading the reader, per-epoch validation
scored through :class:`SinglePredictor`, patience-based early stopping and
best-model checkpointing.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..data.batching import (
    LABELS_BINARY,
    CachedEncoder,
    batches_from_instances,
    bucketed_batches_from_instances,
    prefetch,
    resolve_train_buckets,
)
from ..data.readers import DatasetReader
from ..models.losses import masked_cross_entropy
from ..parallel.mesh import replicate, shard_batch
from ..telemetry import get_registry
from ..telemetry.programs import get_program_registry, shape_key
from .checkpoint import MetricTracker, TrainCheckpointer
from .metrics import RunningClassification, device_confusion, drain_pending
from .optim import make_optimizer

logger = logging.getLogger(__name__)

# blocking device→host pulls route through this alias so tests can count
# them (same contract as training/trainer.py)
_host_fetch = jax.device_get


def make_classifier_step(model, tx):
    """One CE optimizer step over a single padded batch.  The RNG advances
    on device and per-step metrics come back as a tiny stats dict (mean
    loss + weighted confusion counts) so the epoch loop never blocks on a
    per-step transfer."""

    def loss_fn(params, batch, rng):
        with jax.named_scope("classifier_forward"):
            logits = model.apply(
                params, batch["sample1"], deterministic=False, rngs={"dropout": rng}
            )
        with jax.named_scope("cross_entropy"):
            loss = masked_cross_entropy(
                logits.astype(jnp.float32), batch["label"], batch["weight"]
            )
        return loss, logits

    def step(params, opt_state, rng, batch):
        rng, sub = jax.random.split(rng)
        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch, sub
        )
        with jax.named_scope("optimizer_apply"):
            updates, opt_state = tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(
                lambda p, u: p + u.astype(p.dtype), params, updates
            )
        stats = {
            "loss": loss,
            "grad_norm": optax.global_norm(grads),
            "confusion": device_confusion(
                logits, batch["label"], batch["weight"]
            ),
        }
        return params, opt_state, rng, stats

    return step


@dataclasses.dataclass
class ClassifierTrainerConfig:
    num_epochs: int = 10
    patience: Optional[int] = 10
    validation_metric: str = "+pos_f1-score"
    batch_size: int = 64
    max_length: int = 256
    # length-binned TRAIN collation (same contract as the memory
    # trainer's knob, docs/training_throughput.md): "pow2" derives
    # power-of-two buckets up to max_length, an explicit list is
    # coverage-validated, None = pad-to-max (the pre-bucketing baseline)
    train_buckets: Union[str, Sequence[int], None] = "pow2"
    # feed queue depth: collation + committed H2D run this many batches
    # ahead of the step on the prefetch worker (≥ 1)
    prefetch_depth: int = 8
    eval_batch_size: int = 512
    eval_max_length: int = 512
    # length-binned validation (same mechanism as the memory trainer's
    # eval_buckets); None = pad-to-max
    eval_buckets: Optional[Sequence[int]] = None
    eval_tokens_per_batch: Optional[int] = None
    warmup_steps: int = 0
    total_steps: Optional[int] = None
    base_lr: float = 2e-5
    group_lrs: Optional[Dict[str, float]] = None
    # optim.make_schedule / make_momentum_schedule specs (the reference
    # trainer's scheduler slots); None = linear warmup / constant b1
    learning_rate_scheduler: Optional[Dict] = None
    momentum_scheduler: Optional[Dict] = None
    grad_clip_norm: Optional[float] = 1.0
    weight_decay: float = 0.0
    seed: int = 2021
    serialization_dir: Optional[str] = None
    keep_checkpoints: int = 1
    steps_per_epoch: Optional[int] = None
    # steps allowed in flight before the accumulated stats are pulled to
    # the host (NaN guard fires in the pulled block); 1 = sync per step
    sync_every: int = 32
    # checkify float-checks localizing the first NaN/inf op (debug only)
    debug_checks: bool = False


class ClassifierTrainer:
    """Shared trainer for any model whose forward is
    ``apply(params, sample1) -> [B, num_classes]`` (SingleModel, TextCNN)."""

    def __init__(
        self,
        model,
        params,
        tokenizer,
        reader: DatasetReader,
        train_path: Union[str, Path],
        validation_path: Optional[Union[str, Path]] = None,
        config: Optional[ClassifierTrainerConfig] = None,
        mesh=None,
    ) -> None:
        self.model = model
        self.config = config or ClassifierTrainerConfig()
        self.tokenizer = tokenizer
        self.reader = reader
        self.train_path = str(train_path)
        self.validation_path = str(validation_path) if validation_path else None
        self.mesh = mesh
        from .trainer import _reject_inference_only_quant

        _reject_inference_only_quant(model)

        c = self.config
        self.encoder = CachedEncoder(tokenizer, max_length=c.max_length)
        if int(c.prefetch_depth) < 1:
            raise ValueError(
                f"prefetch_depth must be >= 1, got {c.prefetch_depth} "
                "(1 = no read-ahead; 0 would deadlock the feed queue)"
            )
        self.train_buckets = resolve_train_buckets(c.train_buckets, c.max_length)
        self.tx, opt_state = make_optimizer(
            params,
            group_lrs=c.group_lrs,
            base_lr=c.base_lr,
            warmup_steps=c.warmup_steps,
            total_steps=c.total_steps,
            grad_clip_norm=c.grad_clip_norm,
            weight_decay=c.weight_decay,
            lr_schedule=c.learning_rate_scheduler,
            momentum_schedule=c.momentum_scheduler,
        )
        if mesh is not None:
            params = replicate(params, mesh)
            opt_state = replicate(opt_state, mesh)
        self.params = params
        self.opt_state = opt_state
        self.rng = jax.random.PRNGKey(c.seed)
        self.step = 0
        self.epoch = 0
        self.tracker = MetricTracker(c.validation_metric, c.patience)
        self.checkpointer = (
            TrainCheckpointer(c.serialization_dir, c.keep_checkpoints)
            if c.serialization_dir
            else None
        )
        self.metrics_history: List[Dict[str, Any]] = []
        from .trainer import jit_step

        # recompile probe (same contract as MemoryTrainer): the wrapper
        # body runs only when jit traces
        self.train_trace_count = 0
        # program-registry adoption, same contract as MemoryTrainer
        self._programs = get_program_registry()
        self._step_shapes: set = set()
        self._programs.mark_warm("train", warm=False)
        raw_step = make_classifier_step(self.model, self.tx)

        def traced_step(*args):
            self.train_trace_count += 1
            get_registry().counter("train.recompiles").inc()
            self._programs.note_trace("train", shape_key("train_step", args[-1]))
            return raw_step(*args)

        self._step_fn = jit_step(
            traced_step,
            donate=(0, 1, 2),
            debug_checks=c.debug_checks,
        )

    def _register_step_program(self, *args) -> str:
        """First occurrence of a batch shape routes through the program
        registry's ``lower().compile()`` chokepoint (see
        ``MemoryTrainer._register_step_program``)."""
        key = shape_key("train_step", args[-1])
        if key in self._step_shapes:
            return key
        self._step_shapes.add(key)
        lower = getattr(self._step_fn, "lower", None)
        if lower is not None:
            self._programs.compile_and_register(
                key, lower(*args), scope="train"
            )
        return key

    # -- data ----------------------------------------------------------------

    def _raw_batches(self) -> Iterator[tuple]:
        """(host_batch, token-count info) pairs — the un-prefetched feed.
        Token counts happen here while the arrays are host numpy."""
        c = self.config
        instances = self.reader.read(self.train_path, split="train")
        if self.train_buckets is None:
            batches = batches_from_instances(
                instances,
                self.encoder,
                batch_size=c.batch_size,
                label_map=LABELS_BINARY,
                pad_to_max=True,
            )
        else:
            batches = bucketed_batches_from_instances(
                instances,
                self.encoder,
                batch_size=c.batch_size,
                label_map=LABELS_BINARY,
                buckets=self.train_buckets,
            )
        for batch in batches:
            batch.pop("meta", None)
            info = {
                "padded_tokens": int(batch["sample1"]["input_ids"].size),
                "real_tokens": int(batch["sample1"]["attention_mask"].sum()),
            }
            yield batch, info

    def _commit_batch(self, item: tuple) -> tuple:
        """H2D commit on the prefetch worker (double-buffered feed)."""
        batch, info = item
        if self.mesh is not None:
            return shard_batch(batch, self.mesh), info
        return jax.device_put(batch), info

    def _batches(self) -> Iterator[tuple]:
        c = self.config
        tel = get_registry()
        return prefetch(
            self._raw_batches(),
            depth=int(c.prefetch_depth),
            commit=self._commit_batch,
            occupancy=tel.gauge("train.feed_occupancy") if tel.enabled else None,
        )

    # -- epochs --------------------------------------------------------------

    def train_epoch(self) -> Dict[str, float]:
        c = self.config
        from ..utils.profiling import StepTimer, device_memory_stats

        tel = get_registry()
        running = RunningClassification(2, ["neg", "pos"])
        losses: List[float] = []
        grad_norms: List[float] = []
        pending: List[Dict] = []
        timer = StepTimer()
        padded_tokens = 0  # varies per batch under bucketed collation
        real_tokens = 0
        started = time.perf_counter()

        def drain() -> None:
            # the loop's only blocking transfer; NaN guard lives here.
            # Telemetry events ride the drained window (drain cadence,
            # never per step)
            n_before = len(losses)
            drain_pending(
                pending, _host_fetch, self.step, losses, running,
                extras={"grad_norm": grad_norms},
            )
            new = losses[n_before:]
            if not new:
                return
            tel.counter("train.steps").inc(len(new))
            if tel.step_events:
                first = self.step - len(new)
                new_norms = grad_norms[n_before:]
                for offset, loss in enumerate(new):
                    fields = {"step": first + offset, "loss": round(loss, 6)}
                    if offset < len(new_norms):
                        fields["grad_norm"] = round(new_norms[offset], 6)
                    tel.event("train_step", **fields)
            tel.heartbeat()

        with tel.span("train_epoch", epoch=self.epoch):
            for i, (batch, info) in enumerate(self._batches()):
                if c.steps_per_epoch is not None and i >= c.steps_per_epoch:
                    break
                padded_tokens += info["padded_tokens"]
                real_tokens += info["real_tokens"]
                program_key = self._register_step_program(
                    self.params, self.opt_state, self.rng, batch
                )
                with timer.step():
                    self.params, self.opt_state, self.rng, stats = self._step_fn(
                        self.params, self.opt_state, self.rng, batch
                    )
                    pending.append(stats)
                    self.step += 1
                self._programs.record_invocation(
                    program_key, timer.durations[-1]
                )
                if len(pending) >= max(1, c.sync_every):
                    with timer.distribute_over_last(len(pending)):
                        drain()
            if pending:
                with timer.distribute_over_last(len(pending)):
                    drain()
        self._programs.mark_warm("train")
        metrics = running.compute()
        metrics["loss"] = float(np.mean(losses)) if losses else 0.0
        metrics["epoch_seconds"] = time.perf_counter() - started
        metrics["num_steps"] = len(losses)
        metrics["padded_tokens"] = padded_tokens
        metrics["real_tokens"] = real_tokens
        metrics["tokens_per_sec"] = padded_tokens / max(
            metrics["epoch_seconds"], 1e-9
        )
        metrics["real_tokens_per_sec"] = real_tokens / max(
            metrics["epoch_seconds"], 1e-9
        )
        metrics.update(timer.summary())
        for key, value in device_memory_stats(all_devices=True).items():
            metrics[f"memory_{key}"] = value
        if tel.enabled:
            step_hist = tel.histogram("train.step_s")
            for d in timer.durations:
                step_hist.observe(d)
            tel.counter("train.tokens").inc(padded_tokens)
            tel.counter("train.tokens_real").inc(real_tokens)
            tel.gauge("train.tokens_per_sec").set(metrics["tokens_per_sec"])
            tel.gauge("train.real_tokens_per_sec").set(
                metrics["real_tokens_per_sec"]
            )
            tel.event(
                "train_epoch",
                epoch=self.epoch,
                **{k: v for k, v in metrics.items() if isinstance(v, (int, float))},
            )
        return metrics

    def validate(self) -> Dict[str, float]:
        if not self.validation_path:
            return {}
        c = self.config
        if not hasattr(self, "_val_predictor"):
            from ..evaluate.predict_single import SinglePredictor

            self._val_predictor = SinglePredictor(
                self.model,
                self.params,
                self.tokenizer,
                mesh=self.mesh,
                batch_size=c.eval_batch_size,
                max_length=c.eval_max_length,
                buckets=tuple(c.eval_buckets) if c.eval_buckets else None,
                tokens_per_batch=c.eval_tokens_per_batch,
            )
        predictor = self._val_predictor
        predictor.params = self.params
        import tempfile

        out_dir = (
            Path(c.serialization_dir)
            if c.serialization_dir
            else Path(tempfile.mkdtemp(prefix="memvul_val_"))
        )
        out = out_dir / f"validation_epoch_{self.epoch}.json"
        measured = predictor.predict_file(
            self.reader, self.validation_path, out, split="validation"
        )
        # reference metric names (model_single.py metrics: +pos_f1-score)
        rename = {"f1": "pos_f1-score", "prec": "pos_precision", "pd&recall": "pos_recall"}
        return {rename.get(k, k): v for k, v in measured.items()}

    def train(self) -> Dict[str, Any]:
        c = self.config
        self.maybe_restore()
        while self.epoch < c.num_epochs:
            epoch_metrics: Dict[str, Any] = {"epoch": self.epoch}
            epoch_metrics.update(
                {f"training_{k}": v for k, v in self.train_epoch().items()}
            )
            with get_registry().span("validate", epoch=self.epoch):
                val = self.validate()
            epoch_metrics.update({f"validation_{k}": v for k, v in val.items()})
            self.metrics_history.append(epoch_metrics)
            logger.info("epoch %d: %s", self.epoch, epoch_metrics)
            is_best = True
            if val:
                is_best = self.tracker.update(val, self.epoch)
            if self.checkpointer is not None:
                self.checkpointer.save(
                    self.epoch, self._state_dict(), is_best=is_best,
                    metadata=epoch_metrics,
                )
            self.epoch += 1
            if val and self.tracker.should_stop():
                logger.info("early stopping at epoch %d", self.epoch)
                break
        if self.checkpointer is not None:
            self.checkpointer.flush()  # final async save must land on disk
        return {
            "best_epoch": self.tracker.best_epoch,
            "best_validation": self.tracker.best,
            "history": self.metrics_history,
        }

    # -- state ---------------------------------------------------------------

    def _state_dict(self) -> Dict[str, Any]:
        return {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
            "rng": jax.device_get(self.rng),
            "meta": {
                "step": self.step,
                "epoch": self.epoch,
                "tracker": self.tracker.state_dict(),
            },
        }

    def maybe_restore(self) -> bool:
        if self.checkpointer is None:
            return False
        restored = self.checkpointer.restore_latest(self._state_dict())
        if restored is None:
            return False
        _, state = restored
        self.params = state["params"]
        self.opt_state = state["opt_state"]
        self.rng = jnp.asarray(state["rng"])
        meta = state["meta"]
        self.step = int(meta["step"])
        self.epoch = int(meta["epoch"]) + 1
        self.tracker.load_state_dict(dict(meta["tracker"]))
        if self.mesh is not None:
            self.params = replicate(self.params, self.mesh)
            self.opt_state = replicate(self.opt_state, self.mesh)
        logger.info("restored checkpoint at epoch %d", self.epoch - 1)
        return True

    def best_params(self):
        if self.checkpointer is None:
            return self.params
        state = self.checkpointer.restore_best(self._state_dict())
        return state["params"] if state is not None else self.params
