"""Evaluation metrics matching the reference's arithmetic.

* :func:`binary_confusion` / :func:`model_measure` — TP/FN/TN/FP with
  recall/precision/F1 plus ROC-AUC and average precision
  (reference: predict_memory.py:117-156, custom_metric.py:9-32).
* :func:`find_best_threshold` — decision-threshold sweep 0.50→0.90 step
  0.01 keeping the best F1 (ties go to the *higher* threshold, matching
  the reference's ``>=`` update — custom_metric.py:35-52).
* :class:`SiameseMeasure` — accumulates per-report (label, best-anchor
  probability) during evaluation and computes the swept F1 only when the
  full pass is done (reference: custom_metric.py:56-98); drives model
  selection via ``+s_f1-score``.
* :class:`RunningClassification` — streaming accuracy + per-class and
  weighted P/R/F1 (the reference's CategoricalAccuracy/FBetaMeasure trio,
  model_memory.py:80-84) from a confusion matrix.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np
from sklearn import metrics as _skm


def device_confusion(logits, labels, weights):
    """[C, C] weighted confusion counts (rows = true label), computed ON
    DEVICE inside a jitted train step — the per-step metrics travel back
    to the host as C² ints instead of full logits.  Shared by all three
    trainers so the stats contract can't diverge."""
    import jax.numpy as jnp

    n_classes = logits.shape[-1]
    preds = logits.argmax(axis=-1).reshape(-1)
    labels = labels.reshape(-1)
    keep = (weights.reshape(-1) > 0).astype(jnp.int32)
    return jnp.zeros((n_classes, n_classes), jnp.int32).at[labels, preds].add(keep)


def drain_pending(
    pending: List,
    fetch,
    current_step: int,
    losses: List[float],
    running: Optional["RunningClassification"] = None,
    what: str = "loss",
    extras: Optional[Dict[str, List[float]]] = None,
) -> None:
    """Pull a window of in-flight per-step stats to the host in ONE
    transfer (the epoch loops' only blocking point) and fold them into
    host accumulators.  The NaN guard fires here, attributed to the
    absolute step index.  ``pending`` entries are either stats dicts
    ({"loss", "confusion"}) or bare loss scalars.

    ``extras`` maps additional scalar stat keys (e.g. ``"grad_norm"``)
    to host lists they accumulate into, parallel to ``losses`` — how the
    telemetry layer gets its per-step values out of the same single
    transfer."""
    if not pending:
        return
    first_step = current_step - len(pending)
    for offset, stats in enumerate(fetch(pending)):
        loss = float(stats["loss"]) if isinstance(stats, dict) else float(stats)
        if np.isnan(loss):
            raise FloatingPointError(f"NaN {what} at step {first_step + offset}")
        losses.append(loss)
        if extras is not None and isinstance(stats, dict):
            for key, sink in extras.items():
                if key in stats:
                    sink.append(float(stats[key]))
        if running is not None and isinstance(stats, dict):
            running.update_confusion(stats["confusion"])
    pending.clear()


def binary_confusion(
    labels: Sequence[int], preds: Sequence[int]
) -> Tuple[int, int, int, int]:
    labels = np.asarray(labels)
    preds = np.asarray(preds)
    tp = int(((preds == 1) & (labels == 1)).sum())
    fn = int(((preds == 0) & (labels == 1)).sum())
    tn = int(((preds == 0) & (labels == 0)).sum())
    fp = int(((preds == 1) & (labels == 0)).sum())
    return tp, fn, tn, fp


def _prf(tp: int, fn: int, fp: int) -> Tuple[float, float, float]:
    recall = tp / (tp + fn) if tp + fn else 0.0
    precision = tp / (tp + fp) if tp + fp else 0.0
    f1 = (
        2 * recall * precision / (recall + precision) if recall + precision else 0.0
    )
    return precision, recall, f1


def model_measure(
    labels: Sequence[int], preds: Sequence[int], scores: Sequence[float]
) -> Dict[str, float]:
    """The reference's headline metric dict
    (reference: predict_memory.py:154)."""
    tp, fn, tn, fp = binary_confusion(labels, preds)
    precision, recall, f1 = _prf(tp, fn, fp)
    fpr, tpr, _ = _skm.roc_curve(labels, scores, pos_label=1)
    auc = _skm.auc(fpr, tpr)
    ap = _skm.average_precision_score(labels, scores, pos_label=1)
    return {
        "TP": tp, "FN": fn, "TN": tn, "FP": fp,
        "pd&recall": recall, "prec": precision, "f1": f1,
        "ap": float(ap), "auc": float(auc),
    }


def find_best_threshold(
    labels: Sequence[int],
    scores: Sequence[float],
    interval: Tuple[float, float] = (0.5, 0.9),
    step: float = 0.01,
) -> Dict[str, float]:
    labels = np.asarray(labels)
    scores = np.asarray(scores)
    best: Optional[Dict[str, float]] = None
    best_f1 = 0.0
    for thres in np.arange(interval[0], interval[1], step):
        preds = (scores >= thres).astype(int)
        tp, fn, tn, fp = binary_confusion(labels, preds)
        precision, recall, f1 = _prf(tp, fn, fp)
        if f1 >= best_f1:
            best_f1 = f1
            best = {
                "TP": tp, "FN": fn, "TN": tn, "FP": fp,
                "precision": precision, "recall": recall, "f1": f1,
                "thres": float(thres),
            }
    return best or {
        "TP": 0, "FN": 0, "TN": 0, "FP": 0,
        "precision": 0.0, "recall": 0.0, "f1": 0.0, "thres": interval[0],
    }


class SiameseMeasure:
    """Streaming (label, best-anchor-probability) accumulator."""

    def __init__(self) -> None:
        self._labels: List[int] = []
        self._scores: List[float] = []

    def update(self, scores: Iterable[float], metas: Iterable[Dict]) -> None:
        """``scores``: per-report P(same) already reduced over anchors;
        ``metas``: instance metadata with ``label`` ("neg" or a CWE id)."""
        for score, meta in zip(scores, metas):
            self._labels.append(0 if meta.get("label") == "neg" else 1)
            self._scores.append(float(score))

    def __len__(self) -> int:
        return len(self._labels)

    def compute(self, reset: bool = True) -> Dict[str, float]:
        empty = {
            "precision": 0.0, "recall": 0.0, "f1": 0.0, "thres": 0.0,
            "auc": 0.0, "ave_precision_score": 0.0,
        }
        if not self._scores:
            return empty
        best = find_best_threshold(self._labels, self._scores)
        fpr, tpr, _ = _skm.roc_curve(self._labels, self._scores, pos_label=1)
        out = {
            "precision": best["precision"],
            "recall": best["recall"],
            "f1": best["f1"],
            "thres": best["thres"],
            "auc": float(_skm.auc(fpr, tpr)),
            "ave_precision_score": float(
                _skm.average_precision_score(self._labels, self._scores, pos_label=1)
            ),
        }
        if reset:
            self.reset()
        return out

    def reset(self) -> None:
        self._labels.clear()
        self._scores.clear()


class RunningClassification:
    """Streaming accuracy + per-class / weighted P/R/F1 from a confusion
    matrix (replaces the reference's AllenNLP metric objects)."""

    def __init__(self, num_classes: int, class_names: Sequence[str]) -> None:
        self.num_classes = num_classes
        self.class_names = list(class_names)
        self._cm = np.zeros((num_classes, num_classes), dtype=np.int64)

    def update(
        self,
        preds: Sequence[int],
        labels: Sequence[int],
        weights: Optional[Sequence[float]] = None,
    ) -> None:
        preds = np.asarray(preds)
        labels = np.asarray(labels)
        keep = (
            np.asarray(weights) > 0 if weights is not None else np.ones_like(preds, bool)
        )
        for p, l in zip(preds[keep], labels[keep]):
            self._cm[l, p] += 1

    def update_confusion(self, confusion) -> None:
        """Merge a pre-computed [C, C] count matrix (rows = true label,
        cols = prediction) — the shape the device-side train step emits."""
        self._cm += np.asarray(confusion, dtype=np.int64)

    def compute(self, reset: bool = False) -> Dict[str, float]:
        cm = self._cm
        support = cm.sum(axis=1)
        total = cm.sum()
        out: Dict[str, float] = {
            "accuracy": float(np.trace(cm) / total) if total else 0.0
        }
        per_class = []
        for i, name in enumerate(self.class_names):
            tp = cm[i, i]
            fp = cm[:, i].sum() - tp
            fn = support[i] - tp
            precision, recall, f1 = _prf(int(tp), int(fn), int(fp))
            per_class.append((precision, recall, f1))
            out[f"{name}_precision"] = precision
            out[f"{name}_recall"] = recall
            out[f"{name}_f1-score"] = f1
        if total:
            w = support / total
            out["precision"] = float(sum(w[i] * per_class[i][0] for i in range(self.num_classes)))
            out["recall"] = float(sum(w[i] * per_class[i][1] for i in range(self.num_classes)))
            out["f1-score"] = float(sum(w[i] * per_class[i][2] for i in range(self.num_classes)))
        else:
            out["precision"] = out["recall"] = out["f1-score"] = 0.0
        if reset:
            self._cm[:] = 0
        return out
