"""The training loop for the Siamese memory model.

Reference counterpart: ``CustomGradientDescentTrainer``
(MemVul/custom_trainer.py) driving per-epoch hooks.  The semantics kept:

* **online sampling** — the pair stream is re-rolled every epoch (the
  reference's ``reset_dataloader`` callback, callbacks.py:16-25; here the
  reader is simply re-read, which re-rolls its RNG draws);
* **anchor re-encode before validation** — after each train epoch the
  anchor bank is re-encoded with the *current* weights, then validation
  matches against it (the ``custom_validation`` callback + ordering at
  custom_trainer.py:681-683);
* gradient accumulation, grad-norm clipping, warmup schedule, NaN guard,
  patience-based early stopping on ``+s_f1-score``, best-model selection,
  checkpoint/resume.

TPU redesign: one jitted ``train_step`` takes a *stack* of K microbatches
[K, B, L] and folds gradient accumulation into ``lax.scan`` — a single
device program per optimizer step.  Under a mesh the batch is sharded on
the ``data`` axis and params are replicated; XLA inserts the gradient
all-reduce over ICI (no DDP machinery, no done-flag collectives —
batches are fixed-shape by construction).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import signal
import tempfile
import threading
import time
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..data.batching import (
    LABELS_SIAMESE,
    CachedEncoder,
    batches_from_instances,
    bucketed_pair_batches_from_instances,
    prefetch,
    resolve_train_buckets,
)
from ..data.readers import MemoryReader
from ..models.memory import MemoryModel, pair_loss
from ..parallel.mesh import DATA_AXIS, replicate, shard_batch
from ..resilience import faults
from ..resilience.io import atomic_write_text
from ..telemetry import get_registry
from ..telemetry.programs import get_program_registry, shape_key
from .checkpoint import MetricTracker, TrainCheckpointer
from .metrics import RunningClassification, device_confusion, drain_pending
from .optim import linear_with_warmup, make_optimizer, make_schedule

logger = logging.getLogger(__name__)

# every blocking device→host pull in the epoch loop goes through this
# alias so tests can count transfers (proving the loop runs ahead of the
# device rather than syncing per step)
_host_fetch = jax.device_get


def _reject_inference_only_quant(model) -> None:
    """int8_dynamic rounds/clips inside the forward, so gradients through
    the quantized contractions are zero — a trainer fed such a model would
    silently not learn.  Fail loudly instead; train full-precision and
    enable quant at evaluation time (same checkpoint serves both)."""
    quant = getattr(getattr(model, "config", None), "quant", None)
    if quant is not None:
        raise ValueError(
            f"encoder quant={quant!r} is inference-only (zero gradient "
            "through round/clip); train without quant and enable it on the "
            "evaluation config instead"
        )


def make_train_step(model: MemoryModel, tx, ema_decay: Optional[float] = None):
    """Build the fused optimizer step: grad accumulation over a [K, B, ...]
    microbatch stack via ``lax.scan``, then one parameter-group AdamW
    update.  Shared by :class:`MemoryTrainer` and the driver's multi-chip
    dryrun so both compile the same program.

    Everything the host needs per step is folded into the one program so
    the epoch loop never blocks on a transfer (the reference host-syncs
    every step — custom_trainer.py:398-435): the RNG advances on device,
    the EMA update (when ``ema_decay`` is set) rides the same dispatch,
    and per-step metrics come back as a tiny ``stats`` dict — mean loss
    plus a weighted 2×2 confusion-count matrix — instead of full logits.

    Signature: ``step(params, opt_state, rng, stack) ->
    (params, opt_state, rng, stats)``; with EMA an ``ema`` pytree is
    threaded in before ``stack`` and returned before ``stats``.
    """
    temperature = model.temperature

    def loss_fn(params, microbatch, rng):
        # named scopes: jax.profiler traces (and jaxpr name stacks)
        # attribute time to "siamese_forward"/"pair_loss" instead of an
        # anonymous fused blob (docs/observability.md, named-scope map)
        with jax.named_scope("siamese_forward"):
            logits = model.apply(
                params,
                microbatch["sample1"],
                microbatch["sample2"],
                deterministic=False,
                rngs={"dropout": rng},
                # deduped batches carry the [B] gather map; tower-2 then
                # encodes only the unique sample2 rows (models/memory.py)
                sample2_index=microbatch.get("sample2_index"),
            )
        with jax.named_scope("pair_loss"):
            loss = pair_loss(
                logits, microbatch["label"], microbatch["weight"], temperature
            )
        return loss, logits

    def _core(params, opt_state, rng, stack):
        def accumulate(carry, microbatch):
            grads_sum, loss_sum, rng = carry
            rng, sub = jax.random.split(rng)
            (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, microbatch, sub
            )
            grads_sum = jax.tree_util.tree_map(jnp.add, grads_sum, grads)
            return (grads_sum, loss_sum + loss, rng), logits

        zero_grads = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p), params)
        (grads, loss_sum, rng), logits = jax.lax.scan(
            accumulate, (zero_grads, 0.0, rng), stack
        )
        k = stack["label"].shape[0]
        grads = jax.tree_util.tree_map(lambda g: g / k, grads)
        with jax.named_scope("optimizer_apply"):
            updates, opt_state = tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(
                lambda p, u: p + u.astype(p.dtype), params, updates
            )
        stats = {
            "loss": loss_sum / k,
            # pre-clip global gradient norm — rides back with the stats
            # window (one scalar), surfaced as a per-step telemetry event
            "grad_norm": optax.global_norm(grads),
            "confusion": device_confusion(
                logits, stack["label"], stack["weight"]
            ),
        }
        return params, opt_state, rng, stats

    if ema_decay is None:
        return _core

    decay = float(ema_decay)

    def train_step(params, opt_state, rng, ema, stack):
        params, opt_state, rng, stats = _core(params, opt_state, rng, stack)
        ema = jax.tree_util.tree_map(
            lambda e, x: e * decay + x.astype(e.dtype) * (1.0 - decay),
            ema, params,
        )
        return params, opt_state, rng, ema, stats

    return train_step


def jit_step(raw_step, donate, debug_checks: bool):
    """jit a train step, optionally wrapped in checkify float-checks.

    Debug mode deliberately does NOT donate: when ``err.throw()`` raises,
    the caller's pre-step params/opt-state must stay alive so they can be
    checkpointed or inspected post-mortem (donation would have deleted
    them).  Shared by MemoryTrainer, ClassifierTrainer, and MLMTrainer so
    the checkify mechanism has one implementation and one test."""
    if not debug_checks:
        return jax.jit(raw_step, donate_argnums=donate)
    from jax.experimental import checkify

    checked = jax.jit(checkify.checkify(raw_step, errors=checkify.float_checks))

    def _checked_step(*args):
        err, out = checked(*args)
        err.throw()  # raises with the first NaN/inf producer's location
        return out

    return _checked_step


@dataclasses.dataclass
class TrainerConfig:
    num_epochs: int = 30
    patience: Optional[int] = 10
    validation_metric: str = "+s_f1-score"
    batch_size: int = 32
    grad_accum: int = 2
    max_length: int = 256
    # length-binned TRAIN collation (docs/training_throughput.md):
    # "pow2" (default) derives power-of-two buckets up to max_length;
    # an explicit list is validated for max_length coverage; None keeps
    # the pre-bucketing pad-to-max collation (the microbench baseline).
    # Pairs route to (len1, len2) grid cells, so short sides stop paying
    # max_length BERT FLOPs; the compiled-program count stays bounded by
    # the grid (pinned via the train_trace_count probe)
    train_buckets: Union[str, Sequence[int], None] = "pow2"
    # in-batch anchor deduplication: encode only the UNIQUE sample2 rows
    # of each batch and gather the embeddings back per pair — the ~129
    # anchor texts and same-CWE CVE descriptions repeat heavily, so
    # tower-2 drops from B rows to U ≤ unique texts.  Only applies to
    # the bucketed collation
    dedup_anchors: bool = True
    # host-side feed queue depth: collation AND the committed H2D
    # device_put run this many batches ahead of the step on the prefetch
    # worker (the double-buffered device feed; ≥ 1)
    prefetch_depth: int = 8
    eval_batch_size: int = 512
    eval_max_length: int = 512
    # length-binned validation batching (same mechanism as the evaluation
    # block's buckets/tokens_per_batch): short reports stop paying
    # eval_max_length padding during the per-epoch validation sweep.
    # None = pad-to-max (the reference's collation)
    eval_buckets: Optional[Sequence[int]] = None
    eval_tokens_per_batch: Optional[int] = None
    warmup_steps: int = 10000
    total_steps: Optional[int] = None  # enables linear decay after warmup
    base_lr: float = 1e-4
    group_lrs: Optional[Dict[str, float]] = None
    # non-linear LR / momentum schedules — the reference trainer's
    # scheduler slots (custom_trainer.py:168-169,741-744); specs for
    # optim.make_schedule / make_momentum_schedule.  None = the default
    # linear warmup(+decay) above / constant b1
    learning_rate_scheduler: Optional[Dict] = None
    momentum_scheduler: Optional[Dict] = None
    grad_clip_norm: Optional[float] = 1.0
    weight_decay: float = 0.0
    seed: int = 2021
    serialization_dir: Optional[str] = None
    # 2, not 1: the checksum-verified restore falls back to the previous
    # good checkpoint when the newest is corrupt, so one spare
    # generation must survive GC (docs/fault_tolerance.md)
    keep_checkpoints: int = 2
    # periodic mid-epoch step checkpoint (params/opt/rng/EMA + stream
    # position) every N optimizer steps; None = only on preemption.
    # Synchronous — size it so the save cost amortizes (e.g. 500-2000
    # steps on a pod, where an epoch is hours)
    save_every_steps: Optional[int] = None
    # append {"step", "loss"} JSON lines here as stats drain — the
    # machine-readable loss trajectory the kill/resume parity proof (and
    # any external watchdog) reads
    step_loss_log: Optional[str] = None
    steps_per_epoch: Optional[int] = None  # cap (useful for tests/smoke)
    # MemVul-o ablation: False freezes the first epoch's pair sample and
    # reuses it every epoch (the reference disables its reset_dataloader
    # callback, config_no_online.json:77-79)
    online_resample: bool = True
    # when set, epoch 0 is wrapped in a jax.profiler trace written here
    profile_dir: Optional[str] = None
    # checkify float-checks on the train step: the existing NaN guard in
    # _drain_stats *detects* a non-finite loss after the fact; this mode
    # *localizes* the first NaN/inf-producing op (file:line inside the
    # model) at the step that created it.  Syncs every step — debug only
    debug_checks: bool = False
    # exponential moving average of params; validation/checkpoint use the
    # averaged weights (the reference's moving_average support,
    # custom_trainer.py:437-439,514-516)
    ema_decay: Optional[float] = None
    # how many steps to let run ahead before pulling the accumulated
    # per-step stats (loss + confusion counts) to the host; the NaN guard
    # fires inside the pulled block.  1 restores step-synchronous behavior.
    sync_every: int = 32


class MemoryTrainer:
    def __init__(
        self,
        model: MemoryModel,
        params,
        tokenizer,
        reader: MemoryReader,
        train_path: Union[str, Path],
        validation_path: Optional[Union[str, Path]] = None,
        anchor_path: Optional[Union[str, Path]] = None,
        config: Optional[TrainerConfig] = None,
        mesh=None,
    ) -> None:
        self.model = model
        self.config = config or TrainerConfig()
        self.tokenizer = tokenizer
        self.reader = reader
        self.train_path = str(train_path)
        self.validation_path = str(validation_path) if validation_path else None
        self.anchor_path = str(anchor_path) if anchor_path else None
        self.mesh = mesh
        _reject_inference_only_quant(model)

        c = self.config
        self.encoder = CachedEncoder(tokenizer, max_length=c.max_length)
        if int(c.prefetch_depth) < 1:
            raise ValueError(
                f"prefetch_depth must be >= 1, got {c.prefetch_depth} "
                "(1 = no read-ahead; 0 would deadlock the feed queue)"
            )
        # resolved once: "pow2" → derived grid, list → coverage-validated,
        # None → pad-to-max legacy collation
        self.train_buckets = resolve_train_buckets(c.train_buckets, c.max_length)
        # under a data-sharded mesh every device-fed dimension must divide
        # the axis — raise the dedup capacity ladder's floor to it
        self._dedup_cap_floor = 8
        if mesh is not None and DATA_AXIS in mesh.axis_names:
            self._dedup_cap_floor = max(8, int(mesh.shape[DATA_AXIS]))
        total_steps = c.total_steps
        if total_steps is None and c.steps_per_epoch is not None:
            # the reference wires total steps as epochs × steps-per-epoch so
            # the warmup schedule decays to 0 (custom_trainer.py:949)
            total_steps = c.num_epochs * c.steps_per_epoch
        self.total_steps = total_steps
        self.tx, opt_state = make_optimizer(
            params,
            group_lrs=c.group_lrs,
            base_lr=c.base_lr,
            warmup_steps=c.warmup_steps,
            total_steps=total_steps,
            grad_clip_norm=c.grad_clip_norm,
            weight_decay=c.weight_decay,
            lr_schedule=c.learning_rate_scheduler,
            momentum_schedule=c.momentum_scheduler,
        )
        if mesh is not None:
            params = replicate(params, mesh)
            opt_state = replicate(opt_state, mesh)
        self.params = params
        self.opt_state = opt_state
        self.rng = jax.random.PRNGKey(c.seed)
        self.step = 0
        self.epoch = 0
        # preemption / mid-epoch resume state
        self._stop_signal: Optional[int] = None
        self._resume_skip_stacks = 0  # stacks of the current epoch already trained
        self._epoch_stacks_done = 0
        self.tracker = MetricTracker(c.validation_metric, c.patience)
        self.checkpointer = (
            TrainCheckpointer(c.serialization_dir, c.keep_checkpoints)
            if c.serialization_dir
            else None
        )
        self.metrics_history: List[Dict[str, Any]] = []
        self.ema_params = None
        if c.ema_decay is not None:
            self.ema_params = jax.tree_util.tree_map(jnp.copy, self.params)
        # host-side lr mirror of the optimizer's schedule — per-step lr
        # in the telemetry events without pulling it off the device
        self._lr_scale = (
            make_schedule(c.learning_rate_scheduler)
            if c.learning_rate_scheduler
            else linear_with_warmup(c.warmup_steps, total_steps)
        )
        # recompile probe: the wrapper body runs at TRACE time only, so
        # the counter ticks exactly when jit misses its cache (a new
        # stack shape mid-run = a silent multi-second stall on TPU)
        self.train_trace_count = 0
        # compiled-program registry (telemetry/programs.py): the step
        # program registers lazily per stack shape; a fresh trainer has
        # warmed nothing, so its first-epoch traces are not recompiles
        self._programs = get_program_registry()
        self._step_shapes: set = set()
        self._programs.mark_warm("train", warm=False)
        raw_step = make_train_step(self.model, self.tx, ema_decay=c.ema_decay)

        def traced_step(*args):
            self.train_trace_count += 1
            get_registry().counter("train.recompiles").inc()
            self._programs.note_trace("train", shape_key("train_step", args[-1]))
            return raw_step(*args)

        # EMA rides inside the one jitted step (no second dispatch); input
        # state buffers are donated so base-geometry params/opt-state don't
        # double-buffer in HBM
        self._train_step = jit_step(
            traced_step,
            donate=(0, 1, 2, 3) if c.ema_decay is not None else (0, 1, 2),
            debug_checks=c.debug_checks,
        )

    def _register_step_program(self, *args) -> str:
        """Route the first occurrence of a stack shape through the
        program-registry chokepoint (``lower().compile()`` populates the
        same executable cache the jit call hits, so the step right after
        pays no second compile) and return the shape's registry key.
        Already-seen shapes return their key without touching jit.  The
        checkify debug wrapper exposes no ``.lower`` — those runs skip
        registration and compile lazily, as before."""
        key = shape_key("train_step", args[-1])
        if key in self._step_shapes:
            return key
        self._step_shapes.add(key)
        lower = getattr(self._train_step, "lower", None)
        if lower is not None:
            self._programs.compile_and_register(
                key, lower(*args), scope="train"
            )
        return key

    # -- data ----------------------------------------------------------------

    def _epoch_seed(self, epoch: int) -> int:
        """Deterministic per-epoch pair-sampling seed.  Seeding each
        epoch from (trainer seed, epoch) — instead of letting the
        reader's RNG free-run across epochs — makes every epoch's stream
        a pure function of its index, which is what lets a mid-epoch
        resume replay the interrupted epoch exactly (the prefetch thread
        over-reads the stream, so the RNG's live state at kill time is
        not meaningful)."""
        return (self.config.seed * 1_000_003 + epoch) & 0x7FFFFFFF

    def _reseed_reader(self, epoch: int) -> None:
        reseed = getattr(self.reader, "reseed", None)
        if reseed is not None:
            reseed(self._epoch_seed(epoch))

    def _train_instances(self):
        """The epoch's pair stream.  With ``online_resample`` off the first
        epoch's sampled pairs are frozen and replayed every epoch (instances
        are small host dicts; batches/stacks are still rebuilt per epoch so
        nothing epoch-sized is pinned on device)."""
        if self.config.online_resample:
            self._reseed_reader(self.epoch)
            return self.reader.read(self.train_path, split="train")
        if not hasattr(self, "_frozen_instances"):
            # the frozen sample is always epoch 0's stream, even when the
            # freeze happens on a trainer resumed at a later epoch
            self._reseed_reader(0)
            self._frozen_instances = list(
                self.reader.read(self.train_path, split="train")
            )
        return iter(self._frozen_instances)

    def _microbatch_stacks(self) -> Iterator[tuple]:
        """Group the epoch's pair stream into [K, B, L] stacks.

        Bucketed mode (``train_buckets`` set) collates through the
        (len1, len2) grid; a [K, B, ...] stack needs K identically-shaped
        microbatches, so each shape key accumulates its own pending group
        and epoch-end tails are padded with zero-weight copies (the same
        dead-microbatch trick the pad-to-max path always used).  Emission
        order is a pure function of the epoch's instance stream — what
        keeps PR 2's mid-epoch resume replay exact under bucketing.

        Yields ``(host_stack, info)`` with the stack's padded/real token
        counts, computed HERE while the arrays are still host numpy (the
        feed commits them to device right after — counting later would
        mean a device→host sync on the step path).
        """
        c = self.config
        if self.train_buckets is None:
            batches = batches_from_instances(
                self._train_instances(),
                self.encoder,
                batch_size=c.batch_size,
                label_map=LABELS_SIAMESE,
                pad_to_max=True,  # single shape → single compiled program
            )
        else:
            batches = bucketed_pair_batches_from_instances(
                self._train_instances(),
                self.encoder,
                batch_size=c.batch_size,
                label_map=LABELS_SIAMESE,
                buckets=self.train_buckets,
                dedup_side2=c.dedup_anchors,
                dedup_cap_floor=self._dedup_cap_floor,
            )
        groups: Dict[tuple, List[Dict]] = {}
        for batch in batches:
            batch.pop("meta", None)
            key = (
                batch["sample1"]["input_ids"].shape,
                batch["sample2"]["input_ids"].shape,
            )
            group = groups.setdefault(key, [])
            group.append(batch)
            if len(group) == c.grad_accum:
                yield self._stack(group)
                groups[key] = []
        # flush ragged tails in first-seen key order (dict insertion
        # order — deterministic for a given stream)
        for group in groups.values():
            if not group:
                continue
            while len(group) < c.grad_accum:
                dead = jax.tree_util.tree_map(np.copy, group[-1])
                dead["weight"] = np.zeros_like(dead["weight"])
                group.append(dead)
            yield self._stack(group)

    def _stack(self, group: List[Dict]) -> tuple:
        padded = real = 0
        for b in group:
            for side in ("sample1", "sample2"):
                padded += int(b[side]["input_ids"].size)
                real += int(b[side]["attention_mask"].sum())
        stacked = jax.tree_util.tree_map(
            lambda *xs: np.stack(xs, axis=0), *group
        )
        return stacked, {"padded_tokens": padded, "real_tokens": real}

    def _commit_stack(self, item: tuple) -> tuple:
        """H2D commit, run on the prefetch worker so the transfer of
        stack N+1 overlaps step N (the double-buffered device feed).
        Under a mesh this is the sharded put the step loop used to do
        inline; donation is untouched (the stack argument is never in
        the step's donate_argnums)."""
        stack, info = item
        if self.mesh is not None:
            # shard the batch dim (axis 1 of the [K, B, ...] stack)
            return shard_batch(stack, self.mesh, batch_axis=1), info
        return jax.device_put(stack), info

    # -- epoch orchestration ---------------------------------------------------

    def _lr_at(self, step: int) -> float:
        """Host-side learning rate at a step (base group's rate — the
        schedule scale times ``base_lr``), for the telemetry events."""
        return float(self._lr_scale(step)) * self.config.base_lr

    def _drain_stats(self, pending, running, losses, grad_norms=None) -> None:
        """One blocking transfer per window; NaN guard fires here
        (reference NaN check: custom_trainer.py:403-404).  Telemetry
        rides the same drain: the per-step events are emitted from the
        freshly pulled window, so a disabled registry costs the step
        loop nothing."""
        n_before = len(losses)
        drain_pending(
            pending, _host_fetch, self.step, losses, running,
            extras={"grad_norm": grad_norms} if grad_norms is not None else None,
        )
        new = losses[n_before:]
        if not new:
            return
        first = self.step - len(new)
        log_path = self.config.step_loss_log
        if log_path:
            with open(log_path, "a") as f:
                for offset, loss in enumerate(new):
                    f.write(json.dumps({"step": first + offset, "loss": loss}) + "\n")
        tel = get_registry()
        tel.counter("train.steps").inc(len(new))
        if tel.step_events:
            new_norms = grad_norms[n_before:] if grad_norms is not None else []
            for offset, loss in enumerate(new):
                step = first + offset
                fields = {
                    "step": step,
                    "loss": round(loss, 6),
                    "lr": self._lr_at(step),
                }
                if offset < len(new_norms):
                    fields["grad_norm"] = round(new_norms[offset], 6)
                tel.event("train_step", **fields)
        tel.heartbeat()

    def train_epoch(self) -> Dict[str, float]:
        c = self.config
        from ..utils.profiling import StepTimer, device_memory_stats, trace_context

        tel = get_registry()
        running = RunningClassification(2, ["same", "diff"])
        losses: List[float] = []
        grad_norms: List[float] = []
        pending: List[Dict] = []
        timer = StepTimer()
        padded_tokens = 0  # varies per stack under bucketed collation
        real_tokens = 0
        started = time.perf_counter()
        trace_dir = c.profile_dir if (c.profile_dir and self.epoch == 0) else None
        # mid-epoch resume: the epoch's stream is replayed from its
        # deterministic per-epoch seed and the stacks that were already
        # trained before the preemption are skipped (they are re-collated
        # — cheap host work — but never re-trained)
        skip = self._resume_skip_stacks
        self._resume_skip_stacks = 0
        self._epoch_stacks_done = skip
        # the double-buffered feed: the worker collates AND device-commits
        # up to prefetch_depth stacks ahead of the running step; the gauge
        # makes feed stalls visible (0 = host-bound, depth = device-bound)
        feed = prefetch(
            self._microbatch_stacks(),
            depth=int(c.prefetch_depth),
            commit=self._commit_stack,
            occupancy=tel.gauge("train.feed_occupancy") if tel.enabled else None,
        )
        with tel.span("train_epoch", epoch=self.epoch), trace_context(trace_dir):
            for i, (stack, info) in enumerate(feed):
                if c.steps_per_epoch is not None and i >= c.steps_per_epoch:
                    break
                if i < skip:
                    continue
                padded_tokens += info["padded_tokens"]
                real_tokens += info["real_tokens"]
                # chaos hook: "step.<global step index>" fires at the
                # start of the step (docs/fault_tolerance.md)
                faults.fault_point(f"step.{self.step}")
                step_args = (
                    (self.params, self.opt_state, self.rng, self.ema_params,
                     stack)
                    if self.ema_params is not None
                    else (self.params, self.opt_state, self.rng, stack)
                )
                program_key = self._register_step_program(*step_args)
                with timer.step():
                    if self.ema_params is not None:
                        (
                            self.params, self.opt_state, self.rng,
                            self.ema_params, stats,
                        ) = self._train_step(*step_args)
                    else:
                        self.params, self.opt_state, self.rng, stats = (
                            self._train_step(*step_args)
                        )
                    pending.append(stats)
                    self.step += 1
                self._programs.record_invocation(
                    program_key, timer.durations[-1]
                )
                self._epoch_stacks_done = i + 1
                if len(pending) >= max(1, c.sync_every):
                    with timer.distribute_over_last(len(pending)):
                        self._drain_stats(pending, running, losses, grad_norms)
                if (
                    c.save_every_steps
                    and self.checkpointer is not None
                    and self.step % c.save_every_steps == 0
                ):
                    with timer.distribute_over_last(max(1, len(pending))):
                        self._drain_stats(pending, running, losses, grad_norms)
                    self._save_step_checkpoint()
                if self._stop_signal is not None:
                    # the in-flight step above completed; leave the rest
                    # of the epoch to the resumed run
                    logger.warning(
                        "stop signal %s: halting after step %d "
                        "(%d/%s stacks of epoch %d)",
                        self._stop_signal, self.step - 1,
                        self._epoch_stacks_done,
                        c.steps_per_epoch or "?", self.epoch,
                    )
                    break
            if pending:
                with timer.distribute_over_last(len(pending)):
                    self._drain_stats(pending, running, losses, grad_norms)
        # the epoch's shape set is the warm set: any step-program trace
        # from here on is a recompile regression (rcompile attribution)
        self._programs.mark_warm("train")
        metrics = running.compute()
        metrics["loss"] = float(np.mean(losses)) if losses else 0.0
        metrics["epoch_seconds"] = time.perf_counter() - started
        metrics["num_steps"] = len(losses)
        # padded tokens = what the device computed over (the cost);
        # real tokens = what the corpus contained (the work).  Their gap
        # is the padding waste the bucketed collation exists to cut —
        # both throughputs surface so the microbench and epoch metrics
        # tell the same story (docs/training_throughput.md)
        metrics["padded_tokens"] = padded_tokens
        metrics["real_tokens"] = real_tokens
        metrics["tokens_per_sec"] = padded_tokens / max(
            metrics["epoch_seconds"], 1e-9
        )
        metrics["real_tokens_per_sec"] = real_tokens / max(
            metrics["epoch_seconds"], 1e-9
        )
        metrics.update(timer.summary())
        # peak-memory-in-metrics behavior (reference: custom_trainer.py:
        # 674-679), summed across ALL local devices — a sharded run's
        # footprint lives on every chip, not jax.devices()[0]
        for key, value in device_memory_stats(all_devices=True).items():
            metrics[f"memory_{key}"] = value
        if tel.enabled:
            step_hist = tel.histogram("train.step_s")
            for d in timer.durations:
                step_hist.observe(d)
            tel.counter("train.tokens").inc(padded_tokens)
            tel.counter("train.tokens_real").inc(real_tokens)
            tel.gauge("train.tokens_per_sec").set(metrics["tokens_per_sec"])
            tel.gauge("train.real_tokens_per_sec").set(
                metrics["real_tokens_per_sec"]
            )
            tel.event(
                "train_epoch",
                epoch=self.epoch,
                **{k: v for k, v in metrics.items() if isinstance(v, (int, float))},
            )
        return metrics

    def validate(self) -> Dict[str, float]:
        """Anchor re-encode with current weights, then validation scoring —
        the custom-callbacks-before-validation contract
        (reference: custom_trainer.py:681-683, callbacks.py:28-53)."""
        if not (self.validation_path and self.anchor_path):
            return {}
        c = self.config
        if not hasattr(self, "_val_predictor"):
            # local import: evaluate.predict_memory ↔ training would
            # otherwise form an import cycle
            from ..evaluate.predict_memory import SiamesePredictor

            self._val_predictor = SiamesePredictor(
                self.model,
                self.params,
                self.tokenizer,
                mesh=self.mesh,
                batch_size=c.eval_batch_size,
                max_length=c.eval_max_length,
                buckets=tuple(c.eval_buckets) if c.eval_buckets else None,
                tokens_per_batch=c.eval_tokens_per_batch,
            )
        predictor = self._val_predictor
        # validate with the averaged weights when EMA is on — the
        # reference swaps the moving average in around validation
        # (custom_trainer.py:514-516)
        predictor.params = (
            self.ema_params if self.ema_params is not None else self.params
        )
        predictor.encode_anchors(self.reader.read_anchors(self.anchor_path))
        out_dir = (
            Path(c.serialization_dir)
            if c.serialization_dir
            else Path(tempfile.mkdtemp(prefix="memvul_val_"))
        )
        out = out_dir / f"validation_epoch_{self.epoch}.json"
        metrics = predictor.predict_file(
            self.reader, self.validation_path, out, split="validation"
        )
        # reference metric names (model_memory.py:210-215)
        rename = {"f1": "s_f1-score"}
        return {
            rename.get(k, f"s_{k}"): v for k, v in metrics.items()
        }

    # -- preemption safety -----------------------------------------------------

    def _request_stop(self, signum, frame) -> None:
        """Signal handler: flag only.  The in-flight step finishes, the
        epoch loop drains its stats window, and the trainer exits through
        a step checkpoint — never mid-update."""
        self._stop_signal = signum

    def _install_signal_handlers(self):
        """SIGTERM (the preemption notice on managed pods) and SIGINT
        route through :meth:`_request_stop` while train() runs.  Only
        possible from the main thread — elsewhere (tests driving the
        trainer from a worker thread) training simply runs unguarded."""
        if threading.current_thread() is not threading.main_thread():
            return None
        previous = []
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                previous.append((sig, signal.signal(sig, self._request_stop)))
            except (ValueError, OSError):  # exotic embedding
                pass
        return previous

    @property
    def _preempt_marker(self) -> Optional[Path]:
        if self.config.serialization_dir is None:
            return None
        return Path(self.config.serialization_dir) / "PREEMPTED.json"

    def _save_step_checkpoint(self) -> None:
        """Synchronous mid-epoch checkpoint: full optimizer state plus the
        host stream position (epoch index + stacks consumed), enough to
        replay the rest of the epoch exactly."""
        if self.checkpointer is None:
            return
        self.checkpointer.save_step(
            self.step,
            self._state_dict(),
            metadata={
                "epoch": self.epoch,
                "step": self.step,
                "stacks_done": self._epoch_stacks_done,
                "epoch_seed": self._epoch_seed(self.epoch),
                "signal": self._stop_signal,
            },
        )
        logger.info(
            "step checkpoint: global step %d (epoch %d, %d stacks done)",
            self.step, self.epoch, self._epoch_stacks_done,
        )

    def _save_preemption_state(self) -> None:
        self._save_step_checkpoint()
        marker = self._preempt_marker
        if marker is not None:
            atomic_write_text(
                marker,
                json.dumps(
                    {
                        "signal": self._stop_signal,
                        "epoch": self.epoch,
                        "step": self.step,
                        "stacks_done": self._epoch_stacks_done,
                    },
                    indent=2,
                ),
            )
        logger.warning(
            "preempted by signal %s at step %d — resumable state saved",
            self._stop_signal, self.step,
        )
        tel = get_registry()
        tel.counter("train.preemptions").inc()
        tel.event(
            "preempted",
            signal=self._stop_signal, epoch=self.epoch, step=self.step,
        )
        tel.heartbeat(force=True)

    def train(self) -> Dict[str, Any]:
        c = self.config
        self.maybe_restore()
        handlers = self._install_signal_handlers()
        preempted = False
        try:
            while self.epoch < c.num_epochs:
                if self._stop_signal is not None:  # signal between epochs
                    preempted = True
                    self._save_preemption_state()
                    break
                epoch_metrics = {"epoch": self.epoch}
                train_metrics = self.train_epoch()
                if self._stop_signal is not None:
                    # partial epoch: no validation, no epoch checkpoint,
                    # no tracker update — the resumed run finishes the
                    # epoch and produces the real epoch metrics
                    preempted = True
                    self._save_preemption_state()
                    break
                epoch_metrics.update(
                    {f"training_{k}": v for k, v in train_metrics.items()}
                )
                with get_registry().span("validate", epoch=self.epoch):
                    val = self.validate()
                epoch_metrics.update({f"validation_{k}": v for k, v in val.items()})
                self.metrics_history.append(epoch_metrics)
                logger.info("epoch %d: %s", self.epoch, epoch_metrics)

                is_best = True
                if val:
                    is_best = self.tracker.update(
                        {k.replace("validation_", ""): v for k, v in epoch_metrics.items()
                         if k.startswith("validation_")},
                        self.epoch,
                    )
                if self.checkpointer is not None:
                    with get_registry().span("checkpoint", epoch=self.epoch):
                        self.checkpointer.save(
                            self.epoch,
                            self._state_dict(),
                            is_best=is_best,
                            metadata=epoch_metrics,
                        )
                self.epoch += 1
                self._epoch_stacks_done = 0
                if val and self.tracker.should_stop():
                    logger.info("early stopping at epoch %d", self.epoch)
                    break
        finally:
            if handlers:
                for sig, old in handlers:
                    try:
                        signal.signal(sig, old)
                    except (ValueError, OSError):
                        pass
        if self.checkpointer is not None:
            self.checkpointer.flush()  # final async save must land on disk
        marker = self._preempt_marker
        if not preempted and marker is not None and marker.exists():
            marker.unlink()  # completed cleanly: the resumable marker is stale
        result: Dict[str, Any] = {
            "best_epoch": self.tracker.best_epoch,
            "best_validation": self.tracker.best,
            "history": self.metrics_history,
        }
        if preempted:
            result["preempted"] = True
            result["preempt_signal"] = self._stop_signal
        return result

    # -- state ----------------------------------------------------------------

    def _state_dict(self) -> Dict[str, Any]:
        state = {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
            "rng": jax.device_get(self.rng),
            "meta": {
                "step": self.step,
                "epoch": self.epoch,
                # stream position within the (possibly partial) epoch —
                # meaningful for step checkpoints, full-epoch for epoch ones
                "stacks_done": self._epoch_stacks_done,
                "tracker": self.tracker.state_dict(),
            },
        }
        if self.ema_params is not None:
            state["ema_params"] = jax.device_get(self.ema_params)
        return state

    def _restore_templates(self):
        """The expected checkpoint structure, plus the ema-toggled variant:
        resuming a serialization dir written before/after ``ema_decay`` was
        flipped must degrade gracefully rather than die inside orbax's
        structure match."""
        full = self._state_dict()
        alt = dict(full)
        if "ema_params" in alt:
            del alt["ema_params"]
        else:
            alt["ema_params"] = jax.device_get(self.params)
        return full, alt

    def _try_restore(self, restore_fn):
        """Run a checkpointer restore with the ema-toggle template
        fallback (shared by the epoch and step paths)."""
        full, alt = self._restore_templates()
        try:
            return restore_fn(full)
        except Exception:
            logger.warning(
                "checkpoint structure mismatch (ema_decay toggled?) — "
                "retrying with the alternate template"
            )
            return restore_fn(alt)

    def maybe_restore(self) -> bool:
        if self.checkpointer is None:
            return False
        restored = self._try_restore(self.checkpointer.restore_latest)
        step_restored = self._try_restore(self.checkpointer.restore_latest_step)
        # a step checkpoint belongs to an epoch still in progress when it
        # was written; it wins only if no epoch checkpoint completed that
        # epoch afterwards
        completed_epoch = restored[0] if restored is not None else -1
        mid_epoch = False
        if step_restored is not None:
            step_epoch = int(step_restored[1]["meta"]["epoch"])
            if step_epoch > completed_epoch:
                restored = step_restored
                mid_epoch = True
            else:
                logger.info(
                    "ignoring stale step checkpoint from epoch %d "
                    "(epoch %d completed after it)", step_epoch, completed_epoch,
                )
        if restored is None:
            return False
        _, state = restored
        self.params = state["params"]
        self.opt_state = state["opt_state"]
        self.rng = jnp.asarray(state["rng"])
        if self.ema_params is not None:
            if "ema_params" in state:
                self.ema_params = state["ema_params"]
            else:
                # ema was enabled after this checkpoint was written —
                # seed the average from the restored live params
                self.ema_params = jax.tree_util.tree_map(jnp.copy, self.params)
        meta = state["meta"]
        self.step = int(meta["step"])
        if mid_epoch:
            # resume INSIDE the interrupted epoch: replay its stream and
            # skip the stacks that were already trained
            self.epoch = int(meta["epoch"])
            self._resume_skip_stacks = int(meta.get("stacks_done", 0))
        else:
            self.epoch = int(meta["epoch"]) + 1  # resume after the saved epoch
            self._resume_skip_stacks = 0
        tracker_state = dict(meta["tracker"])
        self.tracker.load_state_dict(tracker_state)
        # reload per-epoch metrics history from the JSON sidecars so
        # result["history"] covers pre-restore epochs too
        if self.checkpointer is not None:
            import json as _json

            self.metrics_history = []
            for i in range(self.epoch):
                f = self.checkpointer.directory / f"metrics_epoch_{i}.json"
                if f.exists():
                    self.metrics_history.append(_json.loads(f.read_text()))
        if self.mesh is not None:
            self.params = replicate(self.params, self.mesh)
            self.opt_state = replicate(self.opt_state, self.mesh)
        if mid_epoch:
            logger.info(
                "restored mid-epoch step checkpoint: resuming epoch %d at "
                "stack %d (global step %d)",
                self.epoch, self._resume_skip_stacks, self.step,
            )
        else:
            logger.info("restored checkpoint at epoch %d", self.epoch - 1)
        return True

    def best_params(self):
        """Reload the best-by-validation params (reference:
        custom_trainer.py:779-784) — the EMA weights when averaging is on,
        since those are what validation selected."""
        live = self.ema_params if self.ema_params is not None else self.params
        if self.checkpointer is None:
            return live
        full, alt = self._restore_templates()
        try:
            state = self.checkpointer.restore_best(full)
        except Exception:
            state = self.checkpointer.restore_best(alt)
        if state is None:
            return live
        if "ema_params" in state:
            return state["ema_params"]
        return state["params"]
