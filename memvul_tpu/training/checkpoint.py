"""Checkpoint/resume via orbax.

The reference checkpoints model+training state every epoch, keeps the
best by validation metric, and restores epoch/optimizer/metric-tracker
state on resume (reference: custom_trainer.py:668-672,746-754,787-867).
Note the anchor-bank embeddings are derived state and are NOT persisted —
they are recomputed from anchor text after every restore, matching the
reference (model_memory.py:76-77, predict_memory.py:78-83).
"""

from __future__ import annotations

import hashlib
import json
import logging
import shutil
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple, Union

import jax
import orbax.checkpoint as ocp

from ..resilience import faults
from ..resilience.io import atomic_write_text

logger = logging.getLogger(__name__)


class TrainCheckpointer:
    """Tracks 'latest' and 'best' training state under one directory.

    Two checkpoint families share it: per-**epoch** state (the original
    contract) and mid-epoch per-**step** state (preemption saves /
    ``save_every_steps``), each with its own orbax manager under
    ``epochs/`` and ``steps/``.  Every committed checkpoint gets a
    checksum manifest (``manifest_<family>_<n>.json`` beside the
    family dir, sha256 per file) that restore verifies — a corrupt
    newest checkpoint falls back to the previous good one instead of
    poisoning the resumed run, which is why ``max_to_keep`` defaults to
    2 (one fallback generation)."""

    def __init__(self, directory: Union[str, Path], max_to_keep: int = 2) -> None:
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        self._manager = ocp.CheckpointManager(
            self.directory / "epochs",
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )
        self._step_manager = ocp.CheckpointManager(
            self.directory / "steps",
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max(2, max_to_keep), create=True
            ),
        )
        self._best_ckptr = ocp.StandardCheckpointer()
        self._best_dir = self.directory / "best"
        # manifests for async epoch saves are deferred until the write
        # commits — flush() drains this
        self._pending_manifests: List[int] = []

    # -- per-epoch state -----------------------------------------------------

    def save(
        self,
        step: int,
        state: Dict[str, Any],
        is_best: bool = False,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Asynchronous: the disk write overlaps the next training epoch.

        The caller passes HOST-resident state (the trainer device_gets
        before calling), so nothing here races device buffer donation;
        in-flight writes from the previous epoch are flushed first, and
        every read path (restore/latest_step) flushes before touching
        disk.  Orbax commits via tmp-dir rename, so a crash mid-write
        leaves the previous checkpoint intact."""
        self.flush()
        self._manager.save(step, args=ocp.args.StandardSave(state))
        self._pending_manifests.append(step)
        if metadata is not None:
            # tmp + os.replace: a kill mid-write must leave the previous
            # metrics file (or none), never a torn JSON half
            atomic_write_text(
                self.directory / f"metrics_epoch_{step}.json",
                json.dumps(metadata, indent=2, default=float),
            )
        if is_best:
            # the best checkpoint swaps via rename-aside: write the
            # replacement beside the old one, wait for it to commit, move
            # the old best aside, rename the new one into place, then
            # delete the old copy — a crash at any point leaves a
            # committed best on disk under ``best``, ``best_tmp`` or
            # ``best_old``, and ``_recover_best`` promotes the newest (the
            # epoch save above stays async; best epochs are the minority)
            tmp = self.directory / "best_tmp"
            old = self.directory / "best_old"
            self._recover_best()
            # glob, not exact paths: a crash mid-write leaves orbax
            # staging litter (best_tmp.orbax-checkpoint-tmp-*) beside the
            # exact names
            for stale in (
                *self.directory.glob("best_tmp*"),
                *self.directory.glob("best_old*"),
            ):
                if stale.exists():
                    shutil.rmtree(stale)
            self._best_ckptr.save(tmp, state)
            self._best_ckptr.wait_until_finished()
            if self._best_dir.exists():
                self._best_dir.rename(old)
            tmp.rename(self._best_dir)
            if old.exists():
                shutil.rmtree(old)

    def _recover_best(self) -> None:
        """Finish an interrupted best-swap, newest copy first.

        Orbax finalizes a save by atomically renaming its own staging dir
        into the target, so an existing ``best_tmp`` is always a fully
        committed checkpoint that is NEWER than any ``best`` beside it
        (the swap writes ``best_tmp`` before touching ``best``) — promote
        it even when ``best`` exists, which covers the crash window after
        ``best_tmp`` commits but before the old best is renamed aside.
        ``best_old`` is only ever the pre-swap copy, so it is promoted
        only when ``best`` is missing.  A half-written save only ever
        leaves ``best_tmp.orbax-*`` litter, which the glob cleanup in
        save() removes."""
        tmp = self.directory / "best_tmp"
        old = self.directory / "best_old"
        if tmp.exists():
            if self._best_dir.exists():
                shutil.rmtree(self._best_dir)
            tmp.rename(self._best_dir)
        elif not self._best_dir.exists() and old.exists():
            old.rename(self._best_dir)

    def flush(self) -> None:
        """Block until all in-flight checkpoint writes are committed,
        then stamp their checksum manifests (a manifest is only valid
        once the directory it hashes is final)."""
        self._manager.wait_until_finished()
        self._step_manager.wait_until_finished()
        self._best_ckptr.wait_until_finished()
        for step in self._pending_manifests:
            self._write_manifest("epochs", step)
        self._pending_manifests.clear()
        self._prune_stale_manifests()

    # -- checksum manifests --------------------------------------------------

    def _manifest_path(self, family: str, step: int) -> Path:
        return self.directory / f"manifest_{family}_{step}.json"

    def _checkpoint_dir(self, family: str, step: int) -> Path:
        return self.directory / family / str(step)

    def _write_manifest(self, family: str, step: int) -> None:
        root = self._checkpoint_dir(family, step)
        if not root.exists():  # GC'd by max_to_keep before the flush
            return
        files = {}
        for p in sorted(root.rglob("*")):
            if p.is_file():
                files[str(p.relative_to(root))] = hashlib.sha256(
                    p.read_bytes()
                ).hexdigest()
        atomic_write_text(
            self._manifest_path(family, step),
            json.dumps({"family": family, "step": step, "files": files}, indent=2),
        )

    def verify_manifest(self, family: str, step: int) -> bool:
        """True when every file the manifest records hashes clean.  A
        missing manifest passes (checkpoints written before manifests
        existed must stay restorable) — corruption detection needs the
        manifest to have landed, which flush() guarantees for every
        committed save."""
        mpath = self._manifest_path(family, step)
        if not mpath.exists():
            return True
        try:
            manifest = json.loads(mpath.read_text())
        except ValueError:
            logger.warning("manifest %s is unreadable — treating %s/%d as "
                           "corrupt", mpath, family, step)
            return False
        root = self._checkpoint_dir(family, step)
        for rel, digest in manifest.get("files", {}).items():
            p = root / rel
            if not p.is_file() or hashlib.sha256(p.read_bytes()).hexdigest() != digest:
                logger.warning(
                    "checkpoint %s/%d failed checksum verification at %s",
                    family, step, rel,
                )
                return False
        return True

    def _prune_stale_manifests(self) -> None:
        """Manifests of checkpoints orbax GC'd under max_to_keep."""
        live = {
            "epochs": set(self._manager.all_steps()),
            "steps": set(self._step_manager.all_steps()),
        }
        for mpath in self.directory.glob("manifest_*_*.json"):
            try:
                _, family, step = mpath.stem.split("_", 2)
                if int(step) not in live.get(family, set()):
                    mpath.unlink()
                    meta = self.directory / f"step_meta_{step}.json"
                    if family == "steps" and meta.exists():
                        meta.unlink()
            except (ValueError, OSError):
                continue

    def latest_step(self) -> Optional[int]:
        self.flush()
        return self._manager.latest_step()

    def _restore_newest_verified(
        self, manager: "ocp.CheckpointManager", family: str, template: Dict[str, Any]
    ) -> Optional[Tuple[int, Dict[str, Any]]]:
        """Newest checkpoint that passes manifest verification; older
        good generations are the fallback.  A restore error on a
        manifest-clean checkpoint is NOT treated as corruption — it means
        the caller's template doesn't match (e.g. ema_decay toggled), and
        the trainer's alternate-template retry needs to see it."""
        for step in sorted(manager.all_steps(), reverse=True):
            if not self.verify_manifest(family, step):
                logger.warning(
                    "skipping corrupt %s checkpoint %d — falling back to "
                    "the previous good one", family, step,
                )
                continue
            return step, manager.restore(
                step, args=ocp.args.StandardRestore(template)
            )
        return None

    def restore_latest(
        self, template: Dict[str, Any]
    ) -> Optional[Tuple[int, Dict[str, Any]]]:
        self.flush()
        return self._restore_newest_verified(self._manager, "epochs", template)

    # -- mid-epoch step checkpoints ------------------------------------------

    def save_step(
        self,
        step: int,
        state: Dict[str, Any],
        metadata: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Synchronous step save: a preemption save must be fully on
        disk (manifest included) before the process exits, and the
        periodic ``save_every_steps`` path reuses the same guarantee so
        a step checkpoint is never half-committed."""
        faults.fault_point("ckpt.write")
        self.flush()
        self._step_manager.save(step, args=ocp.args.StandardSave(state))
        self._step_manager.wait_until_finished()
        self._write_manifest("steps", step)
        if metadata is not None:
            atomic_write_text(
                self.directory / f"step_meta_{step}.json",
                json.dumps(metadata, indent=2, default=float),
            )
        self._prune_stale_manifests()

    def step_metadata(self, step: int) -> Optional[Dict[str, Any]]:
        p = self.directory / f"step_meta_{step}.json"
        if not p.exists():
            return None
        try:
            return json.loads(p.read_text())
        except ValueError:
            logger.warning("step metadata %s is torn/unreadable", p)
            return None

    def latest_step_checkpoint(self) -> Optional[int]:
        self.flush()
        return self._step_manager.latest_step()

    def restore_latest_step(
        self, template: Dict[str, Any]
    ) -> Optional[Tuple[int, Dict[str, Any]]]:
        self.flush()
        return self._restore_newest_verified(
            self._step_manager, "steps", template
        )

    def restore_best(self, template: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        self.flush()
        self._recover_best()
        if not self._best_dir.exists():
            return None
        return self._best_ckptr.restore(self._best_dir, template)

    def close(self) -> None:
        self.flush()
        self._manager.close()
        self._step_manager.close()
        self._best_ckptr.close()


class MetricTracker:
    """Best-metric tracking + patience-based early stopping.

    ``spec`` is the reference's signed-metric string, e.g. ``"+s_f1-score"``
    (higher is better) or ``"-loss"`` (reference: config_memory.json:102,
    custom_trainer.py:207,709-710).
    """

    def __init__(self, spec: str, patience: Optional[int] = None) -> None:
        if spec[0] not in "+-":
            raise ValueError(f"metric spec must start with +/-: {spec!r}")
        self.sign = 1.0 if spec[0] == "+" else -1.0
        self.name = spec[1:]
        self.patience = patience
        self.best: Optional[float] = None
        self.best_epoch: Optional[int] = None
        self.epochs_without_improvement = 0

    def update(self, metrics: Dict[str, float], epoch: int) -> bool:
        """Returns True when this epoch is the new best.  ``best`` stores
        the raw (unsigned) metric value."""
        if self.name not in metrics:
            raise KeyError(
                f"validation metric {self.name!r} missing from {sorted(metrics)}"
            )
        value = float(metrics[self.name])
        if self.best is None or self.sign * value > self.sign * self.best:
            self.best = value
            self.best_epoch = epoch
            self.epochs_without_improvement = 0
            return True
        self.epochs_without_improvement += 1
        return False

    def should_stop(self) -> bool:
        return (
            self.patience is not None
            and self.epochs_without_improvement >= self.patience
        )

    def state_dict(self) -> Dict[str, Any]:
        return {
            "best": self.best,
            "best_epoch": self.best_epoch,
            "epochs_without_improvement": self.epochs_without_improvement,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.best = state["best"]
        self.best_epoch = state["best_epoch"]
        self.epochs_without_improvement = state["epochs_without_improvement"]
