"""Checkpoint/resume via orbax.

The reference checkpoints model+training state every epoch, keeps the
best by validation metric, and restores epoch/optimizer/metric-tracker
state on resume (reference: custom_trainer.py:668-672,746-754,787-867).
Note the anchor-bank embeddings are derived state and are NOT persisted —
they are recomputed from anchor text after every restore, matching the
reference (model_memory.py:76-77, predict_memory.py:78-83).
"""

from __future__ import annotations

import json
import shutil
from pathlib import Path
from typing import Any, Dict, Optional, Tuple, Union

import jax
import orbax.checkpoint as ocp


class TrainCheckpointer:
    """Tracks 'latest' and 'best' training state under one directory."""

    def __init__(self, directory: Union[str, Path], max_to_keep: int = 1) -> None:
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        self._manager = ocp.CheckpointManager(
            self.directory / "epochs",
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )
        self._best_ckptr = ocp.StandardCheckpointer()
        self._best_dir = self.directory / "best"

    # -- per-epoch state -----------------------------------------------------

    def save(
        self,
        step: int,
        state: Dict[str, Any],
        is_best: bool = False,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Asynchronous: the disk write overlaps the next training epoch.

        The caller passes HOST-resident state (the trainer device_gets
        before calling), so nothing here races device buffer donation;
        in-flight writes from the previous epoch are flushed first, and
        every read path (restore/latest_step) flushes before touching
        disk.  Orbax commits via tmp-dir rename, so a crash mid-write
        leaves the previous checkpoint intact."""
        self.flush()
        self._manager.save(step, args=ocp.args.StandardSave(state))
        if metadata is not None:
            (self.directory / f"metrics_epoch_{step}.json").write_text(
                json.dumps(metadata, indent=2, default=float)
            )
        if is_best:
            # the best checkpoint swaps via rename-aside: write the
            # replacement beside the old one, wait for it to commit, move
            # the old best aside, rename the new one into place, then
            # delete the old copy — a crash at any point leaves a
            # committed best on disk under ``best``, ``best_tmp`` or
            # ``best_old``, and ``_recover_best`` promotes the newest (the
            # epoch save above stays async; best epochs are the minority)
            tmp = self.directory / "best_tmp"
            old = self.directory / "best_old"
            self._recover_best()
            # glob, not exact paths: a crash mid-write leaves orbax
            # staging litter (best_tmp.orbax-checkpoint-tmp-*) beside the
            # exact names
            for stale in (
                *self.directory.glob("best_tmp*"),
                *self.directory.glob("best_old*"),
            ):
                if stale.exists():
                    shutil.rmtree(stale)
            self._best_ckptr.save(tmp, state)
            self._best_ckptr.wait_until_finished()
            if self._best_dir.exists():
                self._best_dir.rename(old)
            tmp.rename(self._best_dir)
            if old.exists():
                shutil.rmtree(old)

    def _recover_best(self) -> None:
        """Finish an interrupted best-swap, newest copy first.

        Orbax finalizes a save by atomically renaming its own staging dir
        into the target, so an existing ``best_tmp`` is always a fully
        committed checkpoint that is NEWER than any ``best`` beside it
        (the swap writes ``best_tmp`` before touching ``best``) — promote
        it even when ``best`` exists, which covers the crash window after
        ``best_tmp`` commits but before the old best is renamed aside.
        ``best_old`` is only ever the pre-swap copy, so it is promoted
        only when ``best`` is missing.  A half-written save only ever
        leaves ``best_tmp.orbax-*`` litter, which the glob cleanup in
        save() removes."""
        tmp = self.directory / "best_tmp"
        old = self.directory / "best_old"
        if tmp.exists():
            if self._best_dir.exists():
                shutil.rmtree(self._best_dir)
            tmp.rename(self._best_dir)
        elif not self._best_dir.exists() and old.exists():
            old.rename(self._best_dir)

    def flush(self) -> None:
        """Block until all in-flight checkpoint writes are committed."""
        self._manager.wait_until_finished()
        self._best_ckptr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        self.flush()
        return self._manager.latest_step()

    def restore_latest(
        self, template: Dict[str, Any]
    ) -> Optional[Tuple[int, Dict[str, Any]]]:
        step = self.latest_step()  # flushes in-flight writes
        if step is None:
            return None
        restored = self._manager.restore(
            step, args=ocp.args.StandardRestore(template)
        )
        return step, restored

    def restore_best(self, template: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        self.flush()
        self._recover_best()
        if not self._best_dir.exists():
            return None
        return self._best_ckptr.restore(self._best_dir, template)

    def close(self) -> None:
        self.flush()
        self._manager.close()
        self._best_ckptr.close()


class MetricTracker:
    """Best-metric tracking + patience-based early stopping.

    ``spec`` is the reference's signed-metric string, e.g. ``"+s_f1-score"``
    (higher is better) or ``"-loss"`` (reference: config_memory.json:102,
    custom_trainer.py:207,709-710).
    """

    def __init__(self, spec: str, patience: Optional[int] = None) -> None:
        if spec[0] not in "+-":
            raise ValueError(f"metric spec must start with +/-: {spec!r}")
        self.sign = 1.0 if spec[0] == "+" else -1.0
        self.name = spec[1:]
        self.patience = patience
        self.best: Optional[float] = None
        self.best_epoch: Optional[int] = None
        self.epochs_without_improvement = 0

    def update(self, metrics: Dict[str, float], epoch: int) -> bool:
        """Returns True when this epoch is the new best.  ``best`` stores
        the raw (unsigned) metric value."""
        if self.name not in metrics:
            raise KeyError(
                f"validation metric {self.name!r} missing from {sorted(metrics)}"
            )
        value = float(metrics[self.name])
        if self.best is None or self.sign * value > self.sign * self.best:
            self.best = value
            self.best_epoch = epoch
            self.epochs_without_improvement = 0
            return True
        self.epochs_without_improvement += 1
        return False

    def should_stop(self) -> bool:
        return (
            self.patience is not None
            and self.epochs_without_improvement >= self.patience
        )

    def state_dict(self) -> Dict[str, Any]:
        return {
            "best": self.best,
            "best_epoch": self.best_epoch,
            "epochs_without_improvement": self.epochs_without_improvement,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.best = state["best"]
        self.best_epoch = state["best_epoch"]
        self.epochs_without_improvement = state["epochs_without_improvement"]
