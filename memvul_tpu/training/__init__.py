from .metrics import (  # noqa: F401
    SiameseMeasure,
    binary_confusion,
    find_best_threshold,
    model_measure,
)
