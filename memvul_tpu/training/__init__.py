from .checkpoint import MetricTracker, TrainCheckpointer  # noqa: F401
from .metrics import (  # noqa: F401
    SiameseMeasure,
    binary_confusion,
    find_best_threshold,
    model_measure,
)
from .optim import linear_with_warmup, make_optimizer  # noqa: F401
from .trainer import MemoryTrainer, TrainerConfig  # noqa: F401
