from .profiling import (  # noqa: F401
    StepTimer,
    device_memory_stats,
    trace_context,
)
