"""Environment / artifact self-diagnosis (``python -m memvul_tpu doctor``).

The reference has no operational tooling — a user discovers a missing
``vocab.txt`` or a wedged device only when training crashes hours in
(or worse, silently trains on the fallback vocabulary).  The doctor
front-loads every such check into one JSON report:

* backend + mesh: device presence, a tiny jitted device op, and a
  sharded cross-device reduction — ALL device ops run in one child
  process under a timeout (on a wedged axon tunnel the first device op
  hangs rather than errors, and a hung doctor is worse than no doctor);
  ``--skip-device`` skips the whole child (e.g. while another process
  holds the serialized tunnel);
* vocabulary: whether the config's ``vocab_path`` exists (the
  genuine-vs-fallback distinction that decides reference F1 parity,
  see README "Using the real BERT vocabulary");
* data artifacts: the train/validation/anchor/CVE files the config names;
* native normalizer: library builds/loads AND passes its parity
  self-check;
* compile cache: where persistent XLA executables go.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Dict, Optional, Tuple


_DEVICE_PROBE = """
from memvul_tpu.utils.platform import honor_platform_env
honor_platform_env()
import jax, jax.numpy as jnp
d = jax.devices()
x = jnp.ones((64, 64))
s = float((x @ x).sum())
print("DOCTOR_BACKEND", len(d), d[0].platform, s)
from memvul_tpu.parallel import create_mesh, shard_batch
n = len(d)
mesh = create_mesh({"data": n})
batch = shard_batch({"x": jnp.arange(n * 4.0).reshape(n * 4, 1)}, mesh)
total = float(batch["x"].sum())  # cross-device reduction over the shards
print("DOCTOR_MESH", n, total, float(sum(range(n * 4))))
"""


def _check_device_and_mesh(
    device_timeout_s: float,
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Every device-touching check in ONE timed child process.

    Kill discipline on timeout reuses the bench supervisor's: the child
    runs in its own session and gets SIGTERM + grace before SIGKILL, so
    its PJRT client closes the tunnel connection cleanly instead of
    becoming one more dead client holding the device lease (the wedge
    this timeout exists to diagnose — bench.py:_kill_process_group)."""
    import subprocess
    import sys
    import tempfile

    from ..bench import _kill_process_group

    # spool child output to temp files, not PIPEs: during the SIGTERM
    # grace a full 64KB pipe would block the child's shutdown logging and
    # burn the grace into a SIGKILL — the unclean exit the grace exists
    # to avoid
    with tempfile.TemporaryFile("w+") as out_f, tempfile.TemporaryFile(
        "w+"
    ) as err_f:
        proc = subprocess.Popen(
            [sys.executable, "-c", _DEVICE_PROBE],
            stdout=out_f, stderr=err_f, text=True,
            start_new_session=True,
        )
        try:
            proc.wait(timeout=device_timeout_s)
        except subprocess.TimeoutExpired:
            _kill_process_group(proc, grace=10.0)
            err = {
                "ok": False,
                "error": f"device op hung for {device_timeout_s:.0f}s — "
                "backend wedged or unreachable (axon: see SMOKE.md tunnel "
                "notes)",
            }
            return err, dict(err)
        out_f.seek(0)
        err_f.seek(0)
        stdout, stderr = out_f.read(), err_f.read()
    backend: Dict[str, Any] = {
        "ok": False,
        "error": (stderr.strip().splitlines() or ["no output"])[-1][:300],
    }
    mesh: Dict[str, Any] = dict(backend)
    for line in stdout.splitlines():
        if line.startswith("DOCTOR_BACKEND"):
            _, n, platform, s = line.split()
            backend = {
                "ok": True,
                "devices": int(n),
                "platform": platform,
                "matmul_sum": float(s),
            }
        elif line.startswith("DOCTOR_MESH"):
            _, n, total, expected = line.split()
            mesh = {
                "ok": float(total) == float(expected),
                "devices": int(n),
                "sharded_sum": float(total),
            }
    return backend, mesh


def _load_config_or_error(
    config_path: Path,
) -> Tuple[Optional[Dict[str, Any]], Optional[str]]:
    """Parse once for every config-dependent check; any failure (absent
    file, directory, syntax error) becomes a report entry, never a
    traceback — the CLI promises one JSON report regardless."""
    from ..config import load_config

    try:
        return load_config(config_path), None
    except Exception as e:
        return None, f"{type(e).__name__}: {e}"[:300]


def _check_vocab(cfg: Optional[Dict], error: Optional[str]) -> Dict[str, Any]:
    if cfg is None:
        return {"ok": False, "error": error}
    tok = cfg.get("tokenizer") or {}
    vocab = tok.get("vocab_path")
    trained = tok.get("tokenizer_path")
    out: Dict[str, Any] = {
        "vocab_path": vocab,
        "vocab_exists": bool(vocab and Path(vocab).exists()),
        "tokenizer_path": trained,
        "tokenizer_exists": bool(trained and Path(trained).exists()),
    }
    if out["vocab_exists"]:
        out["ok"] = True
        out["note"] = "genuine vocabulary — reference tokenization exact"
    elif out["tokenizer_exists"]:
        out["ok"] = True
        out["note"] = (
            "FALLBACK trained tokenizer — training works but F1 parity "
            "with reference checkpoints needs the real vocab.txt "
            "(README: 'Using the real BERT vocabulary')"
        )
    else:
        out["ok"] = False
        out["error"] = "neither vocab_path nor tokenizer_path exists"
    return out


def _check_data(cfg: Optional[Dict], error: Optional[str]) -> Dict[str, Any]:
    if cfg is None:
        return {"ok": False, "error": error}
    reader = cfg.get("dataset_reader") or {}
    paths = {
        "train_data_path": cfg.get("train_data_path"),
        "validation_data_path": cfg.get("validation_data_path"),
        "anchor_path": reader.get("anchor_path"),
        "cve_path": reader.get("cve_path"),
    }
    missing = sorted(
        k for k, p in paths.items() if p and not Path(p).exists()
    )
    return {"ok": not missing, "paths": paths, "missing": missing}


def _check_native() -> Dict[str, Any]:
    """Surfaces WHY the native path is off: env opt-out, build failure,
    and parity-self-check failure are different diagnoses (the last one
    means native and Python normalization disagree — a red flag, not a
    preference)."""
    try:
        from ..data.native import native_status

        status = native_status()
        return {
            # a parity FAILURE is a failed check (native and Python
            # normalization disagree); opt-out/build-miss are
            # degraded-but-fine (the Python path is the specification).
            # Branch on the structured kind, never the reason text.
            "ok": status["kind"] not in (
                "parity_failed", "runtime_parity_failed"
            ),
            "state": status["state"],
            "kind": status["kind"],
            "reason": status["reason"],
        }
    except Exception as e:
        return {"ok": False, "error": str(e)[:300]}


def _check_compile_cache() -> Dict[str, Any]:
    try:
        import jax

        from .platform import enable_compilation_cache

        enable_compilation_cache()
        cache_dir = jax.config.jax_compilation_cache_dir
        return {
            "ok": cache_dir is not None,
            "dir": cache_dir,
            "entries": len(list(Path(cache_dir).glob("*")))
            if cache_dir and Path(cache_dir).exists()
            else 0,
        }
    except Exception as e:  # older jax / exotic plugin without the key
        return {"ok": False, "error": str(e)[:300]}


def run_doctor(
    config: str = "configs/config_memory.json",
    device_timeout_s: float = 90.0,
    skip_device: bool = False,
) -> Dict[str, Any]:
    if skip_device:
        backend: Dict[str, Any] = {"ok": True, "skipped": True}
        mesh: Dict[str, Any] = {"ok": True, "skipped": True}
    else:
        backend, mesh = _check_device_and_mesh(device_timeout_s)
    cfg, cfg_error = _load_config_or_error(Path(config))
    report: Dict[str, Any] = {
        "backend": backend,
        "mesh": mesh,
        "vocabulary": _check_vocab(cfg, cfg_error),
        "data_artifacts": _check_data(cfg, cfg_error),
        "native_normalizer": _check_native(),
        "compile_cache": _check_compile_cache(),
    }
    report["ok"] = all(
        section.get("ok", False) for section in report.values()
        if isinstance(section, dict)
    )
    return report
