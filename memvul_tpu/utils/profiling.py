"""Tracing / profiling utilities (SURVEY §5, tracing row).

The reference logs per-epoch wall clock + ETA and folds peak CPU/GPU
memory into the epoch metrics (reference: custom_trainer.py:309-316,
500-503,674-679,759-768).  The TPU equivalents here:

* :class:`StepTimer` — streaming step timings with percentile summary
  (first-step compile time reported separately — on TPU the first step
  includes XLA compilation and would poison a mean);
* :func:`device_memory_stats` — per-device live/peak HBM bytes via the
  device ``memory_stats()`` API (absent on some backends → {});
* :func:`trace_context` — a ``jax.profiler`` trace scope producing a
  TensorBoard-loadable trace directory;
* :class:`ProfilerCapture` — on-demand, one-at-a-time timed captures of
  a LIVE process through the same trace scope (the serving tier's
  ``POST /profilez`` endpoint, docs/serving.md).
"""

from __future__ import annotations

import contextlib
import threading
import time
from pathlib import Path
from typing import Dict, Iterator, List, Optional

import jax
import numpy as np


class StepTimer:
    """Accumulates per-step wall-clock timings.

    Usage::

        timer = StepTimer()
        for batch in data:
            with timer.step():
                run(batch)
        metrics.update(timer.summary())
    """

    def __init__(self) -> None:
        self._durations: List[float] = []

    @contextlib.contextmanager
    def step(self) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            self._durations.append(time.perf_counter() - start)

    @contextlib.contextmanager
    def distribute_over_last(self, n: int) -> Iterator[None]:
        """Spread the block's elapsed time evenly over the last ``n``
        recorded steps instead of counting a new one.

        Used for windowed stats drains: with an async step loop the
        per-step contexts measure dispatch only (microseconds) while the
        drain absorbs the whole window's device time — raw percentiles
        would be bimodal nonsense.  Distributing the drain restores
        per-step timings that sum to wall clock and average to the true
        step cost (the first step still carries its own compile time,
        which happens synchronously at dispatch)."""
        start = time.perf_counter()
        try:
            yield
        finally:
            elapsed = time.perf_counter() - start
            if not self._durations:
                if elapsed > 0:
                    self._durations.append(elapsed)
            else:
                n = max(1, min(n, len(self._durations)))
                share = elapsed / n
                for i in range(len(self._durations) - n, len(self._durations)):
                    self._durations[i] += share

    def __len__(self) -> int:
        return len(self._durations)

    @property
    def durations(self) -> tuple:
        """The recorded per-step durations (copy) — what the telemetry
        layer feeds into its ``train.step_s`` histogram at epoch end."""
        return tuple(self._durations)

    def summary(self, prefix: str = "step_") -> Dict[str, float]:
        """Timing summary; the first (compile-bearing) step is excluded
        from the steady-state stats and reported as ``first_s``."""
        if not self._durations:
            return {}
        first, rest = self._durations[0], self._durations[1:]
        out = {
            f"{prefix}first_s": first,
            f"{prefix}count": float(len(self._durations)),
            f"{prefix}total_s": float(np.sum(self._durations)),
        }
        if rest:
            out.update(
                {
                    f"{prefix}mean_s": float(np.mean(rest)),
                    f"{prefix}p50_s": float(np.percentile(rest, 50)),
                    f"{prefix}p95_s": float(np.percentile(rest, 95)),
                    f"{prefix}max_s": float(np.max(rest)),
                }
            )
        return out

    def reset(self) -> None:
        self._durations.clear()


_MEMORY_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def device_memory_stats(
    device: Optional[jax.Device] = None, all_devices: bool = False
) -> Dict[str, float]:
    """Live/peak memory stats (the reference folds peak memory into epoch
    metrics, custom_trainer.py:674-679).  Returns {} when the backend
    exposes no stats (e.g. CPU).

    Default: one device (``device`` or ``jax.devices()[0]``) — the
    historical behavior.  With ``all_devices=True`` every local device is
    polled: the three byte keys are **summed** across reporting devices
    (a sharded run's true HBM footprint), each device's peak also comes
    back as ``peak_bytes_in_use_device<i>`` (the imbalance view — one
    hot shard OOMs a pod whose *sum* looks fine), and
    ``devices_reporting`` counts how many devices answered.
    """
    if not all_devices:
        device = device or jax.devices()[0]
        stats = getattr(device, "memory_stats", lambda: None)()
        if not stats:
            return {}
        return {k: float(stats[k]) for k in _MEMORY_KEYS if k in stats}
    out: Dict[str, float] = {}
    reporting = 0
    for i, dev in enumerate(jax.local_devices()):
        stats = getattr(dev, "memory_stats", lambda: None)()
        if not stats:
            continue
        reporting += 1
        for key in _MEMORY_KEYS:
            if key in stats:
                out[key] = out.get(key, 0.0) + float(stats[key])
        if "peak_bytes_in_use" in stats:
            out[f"peak_bytes_in_use_device{i}"] = float(stats["peak_bytes_in_use"])
    if reporting:
        out["devices_reporting"] = float(reporting)
    return out


@contextlib.contextmanager
def trace_context(log_dir: Optional[str]) -> Iterator[None]:
    """``jax.profiler`` trace scope; no-op when ``log_dir`` is falsy."""
    if not log_dir:
        yield
        return
    jax.profiler.start_trace(str(log_dir))
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class CaptureInProgress(RuntimeError):
    """A capture is already running — ``jax.profiler`` allows exactly
    one trace at a time, so the caller gets a 409, not a crash."""


class ProfilerCapture:
    """One-at-a-time on-demand profiler captures of a live process.

    ``start(seconds)`` opens a :func:`trace_context` on a background
    thread for the requested duration and returns immediately with the
    capture's trace dir — the serving tier's ``POST /profilez``
    contract (docs/serving.md): the caller never blocks, and a second
    start while one runs raises :class:`CaptureInProgress`.  Each
    capture lands in its own ``profile-<n>/`` subdir of ``base_dir``
    so successive captures never clobber each other.
    """

    def __init__(self, base_dir, max_seconds: float = 300.0) -> None:
        self.base_dir = Path(base_dir)
        self.max_seconds = float(max_seconds)
        self._lock = threading.Lock()
        self._busy = False
        self._captures = 0

    @property
    def busy(self) -> bool:
        with self._lock:
            return self._busy

    @property
    def captures(self) -> int:
        """Completed + in-flight captures this process started."""
        with self._lock:
            return self._captures

    def start(self, seconds: float) -> Dict[str, object]:
        """Begin one timed capture; returns ``{"trace_dir", "seconds"}``.
        Raises ``ValueError`` on a non-positive/over-cap duration and
        :class:`CaptureInProgress` while a capture runs."""
        seconds = float(seconds)
        if not (0.0 < seconds <= self.max_seconds):
            raise ValueError(
                f"seconds must be in (0, {self.max_seconds:g}], got {seconds!r}"
            )
        with self._lock:
            if self._busy:
                raise CaptureInProgress(
                    "a profiler capture is already running (jax.profiler "
                    "supports one trace at a time)"
                )
            self._busy = True
            self._captures += 1
            trace_dir = self.base_dir / f"profile-{self._captures:03d}"
        thread = threading.Thread(
            target=self._run,
            args=(trace_dir, seconds),
            name="memvul-profilez-capture",
            daemon=True,
        )
        thread.start()
        return {"trace_dir": str(trace_dir), "seconds": seconds}

    def _wait(self, seconds: float) -> None:
        """Dwell inside the trace scope for the capture's duration.
        A seam: tests replace it with an event wait so the busy window
        is controlled instead of racing wall clock."""
        time.sleep(seconds)

    def _run(self, trace_dir: Path, seconds: float) -> None:
        try:
            with trace_context(str(trace_dir)):
                self._wait(seconds)
        except Exception:  # pragma: no cover - a failed capture must
            pass           # never take the server with it
        finally:
            with self._lock:
                self._busy = False
