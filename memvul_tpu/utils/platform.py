"""Backend identification.

The Pallas kernels must know whether real TPU hardware is underneath —
but PJRT plugins can register under a platform name other than "tpu"
(e.g. a tunnelled TPU appears as platform "axon" while its devices still
report a TPU ``device_kind``).  Checking ``jax.default_backend() ==
"tpu"`` alone would silently route the flash kernel to its XLA fallback
on such rigs, which is exactly the hardware the kernel exists for.
"""

from __future__ import annotations

import os


def honor_platform_env() -> None:
    """Re-assert the environment's ``JAX_PLATFORMS`` choice.

    A sitecustomize hook may pin jax to the TPU plugin (and hang in its
    tunnel) even when the environment asks for another platform; calling
    this before the first device op makes CPU runs (virtual 8-device
    meshes, tests, tiny benches) work regardless.  Shared by the CLI,
    the bench child, and ``__graft_entry__``."""
    requested = os.environ.get("JAX_PLATFORMS")
    if requested:
        import jax

        jax.config.update("jax_platforms", requested)


def enable_compilation_cache(path: str | None = None) -> None:
    """Point jax at a persistent compilation cache so separate processes
    (bench child, each proof runner, the driver's round-end bench) reuse
    each other's XLA executables instead of paying the 20-40 s per-program
    TPU compile again.  ``JAX_COMPILATION_CACHE_DIR`` wins if set; no-op
    if the backend/plugin cannot serialize executables."""
    import os

    cache_dir = (
        os.environ.get("JAX_COMPILATION_CACHE_DIR")
        or path
        or os.path.expanduser("~/.cache/memvul_jax")
    )
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
    except Exception:  # pragma: no cover — older jax / exotic plugin
        pass


def is_tpu_backend() -> bool:
    """True when the default JAX backend drives TPU hardware, regardless
    of the platform name it registered under."""
    import jax

    if jax.default_backend() == "tpu":
        return True
    try:
        devices = jax.devices()
    except Exception:
        return False
    return any(
        "tpu" in (getattr(d, "device_kind", "") or "").lower()
        or getattr(d, "platform", "") == "tpu"
        for d in devices
    )
