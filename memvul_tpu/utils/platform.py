"""Backend identification.

The Pallas kernels must know whether real TPU hardware is underneath —
but PJRT plugins can register under a platform name other than "tpu"
(e.g. a tunnelled TPU appears as platform "axon" while its devices still
report a TPU ``device_kind``).  Checking ``jax.default_backend() ==
"tpu"`` alone would silently route the flash kernel to its XLA fallback
on such rigs, which is exactly the hardware the kernel exists for.
"""

from __future__ import annotations

import os


def honor_platform_env() -> None:
    """Re-assert the environment's ``JAX_PLATFORMS`` choice.

    A sitecustomize hook may pin jax to the TPU plugin (and hang in its
    tunnel) even when the environment asks for another platform; calling
    this before the first device op makes CPU runs (virtual 8-device
    meshes, tests, tiny benches) work regardless.  Shared by the CLI,
    the bench child, and ``__graft_entry__``."""
    requested = os.environ.get("JAX_PLATFORMS")
    if requested:
        import jax

        jax.config.update("jax_platforms", requested)


def is_tpu_backend() -> bool:
    """True when the default JAX backend drives TPU hardware, regardless
    of the platform name it registered under."""
    import jax

    if jax.default_backend() == "tpu":
        return True
    try:
        devices = jax.devices()
    except Exception:
        return False
    return any(
        "tpu" in (getattr(d, "device_kind", "") or "").lower()
        or getattr(d, "platform", "") == "tpu"
        for d in devices
    )
