"""Backend identification.

The Pallas kernels must know whether real TPU hardware is underneath —
but PJRT plugins can register under a platform name other than "tpu"
(e.g. a tunnelled TPU appears as platform "axon" while its devices still
report a TPU ``device_kind``).  Checking ``jax.default_backend() ==
"tpu"`` alone would silently route the flash kernel to its XLA fallback
on such rigs, which is exactly the hardware the kernel exists for.
"""

from __future__ import annotations


def is_tpu_backend() -> bool:
    """True when the default JAX backend drives TPU hardware, regardless
    of the platform name it registered under."""
    import jax

    if jax.default_backend() == "tpu":
        return True
    try:
        devices = jax.devices()
    except Exception:
        return False
    return any(
        "tpu" in (getattr(d, "device_kind", "") or "").lower()
        or getattr(d, "platform", "") == "tpu"
        for d in devices
    )
