"""The shared static-analysis engine (docs/static_analysis.md).

One ``ast`` parse per file, shared by every registered checker — the
three historical ``tools/lint_*.py`` scripts each re-walked the tree
with a private parser; here a checker is a function over an
:class:`AnalysisContext` that already holds every parsed file, the docs
corpus, and the test corpus, so adding an invariant costs a visitor,
not a pass.

Core contracts:

* findings are structured ``{code, path, line, message}`` records
  (:class:`Finding`; ``path`` is POSIX-relative to the analysis base
  dir, ``line`` is **1-based** — pinned in tests, the historical lints
  diverged here);
* a ``lint: disable=CODE`` comment on the flagged line suppresses it
  (comma-separated list or ``all``); suppressions are justified inline
  and counted, never silent;
* a committed JSON baseline (``analysis/baseline.json``) grandfathers
  findings by ``(code, path, message)`` — deleting an entry makes the
  finding fire again, and stale entries (matching nothing) are
  reported so the baseline can only shrink;
* checkers register through :func:`register` with a stable code; the
  CLI (``python -m memvul_tpu lint``) selects by code and renders
  human or ``--json`` output (analysis/cli.py).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
import time
from pathlib import Path
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

# engine-level finding: a file that does not parse is its own bug
SYNTAX_ERROR_CODE = "MV001"

_SUPPRESS_RE = re.compile(r"#\s*lint:\s*disable=([A-Za-z0-9_,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One structured finding.  ``path`` is POSIX-relative to the
    engine's base dir; ``line`` is 1-based; ``symbol`` (optional) is the
    offending callable/metric/key name, used by the ``tools/`` shims to
    reproduce their historical output format."""

    code: str
    path: str
    line: int
    message: str
    symbol: Optional[str] = None

    def to_json(self) -> Dict[str, Any]:
        return {
            "code": self.code,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "symbol": self.symbol,
        }

    @property
    def baseline_key(self) -> Tuple[str, str, str]:
        # line numbers churn with unrelated edits; identity for the
        # committed baseline is (code, path, message)
        return (self.code, self.path, self.message)


class ParsedFile:
    """One source file, parsed exactly once and shared by all checkers."""

    def __init__(self, path: Path, rel: str, text: str) -> None:
        self.path = path
        self.rel = rel
        self.text = text
        self.tree: Optional[ast.Module] = None
        self.syntax_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(text, filename=str(path))
        except SyntaxError as e:
            self.syntax_error = e
        self._suppressions: Optional[Dict[int, Set[str]]] = None
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None

    @property
    def suppressions(self) -> Dict[int, Set[str]]:
        """1-based line → set of suppressed codes (``all`` wildcard)."""
        if self._suppressions is None:
            table: Dict[int, Set[str]] = {}
            for i, line in enumerate(self.text.splitlines(), start=1):
                m = _SUPPRESS_RE.search(line)
                if m:
                    table[i] = {
                        c.strip() for c in m.group(1).split(",") if c.strip()
                    }
            self._suppressions = table
        return self._suppressions

    @property
    def parents(self) -> Dict[ast.AST, ast.AST]:
        """child node → parent node (built lazily, once per file)."""
        if self._parents is None:
            parents: Dict[ast.AST, ast.AST] = {}
            if self.tree is not None:
                for node in ast.walk(self.tree):
                    for child in ast.iter_child_nodes(node):
                        parents[child] = node
            self._parents = parents
        return self._parents

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        parents = self.parents
        while node in parents:
            node = parents[node]
            yield node


class TextFile:
    """A non-Python corpus member (docs, test sources scanned as text)."""

    def __init__(self, path: Path, rel: str, text: str) -> None:
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()


class AnalysisContext:
    """Everything a checker may look at.  Built once per run; the
    parse counters prove the whole-tree pass parses each file exactly
    once (pinned by the tier-1 engine test)."""

    def __init__(
        self,
        root: Path,
        base_dir: Optional[Path] = None,
        docs_dir: Optional[Path] = None,
        tests_dir: Optional[Path] = None,
    ) -> None:
        self.root = Path(root).resolve()
        self.base_dir = (
            Path(base_dir).resolve() if base_dir else self.root.parent
        )
        # "package mode" scopes dir-specific checkers to their
        # subsystems; on an arbitrary fixture dir every checker sees
        # every file (the tools/ shim + unit-test contract)
        self.is_package = (self.root / "__main__.py").is_file()
        self.files: List[ParsedFile] = []
        self.parse_count = 0
        for path in sorted(self.root.rglob("*.py")):
            if "__pycache__" in path.parts:
                continue
            rel = path.resolve().relative_to(self.base_dir).as_posix()
            self.files.append(ParsedFile(path, rel, _read(path)))
            self.parse_count += 1
        self.docs: List[TextFile] = _text_corpus(docs_dir, self.base_dir, "*.md")
        self.tests: List[TextFile] = _text_corpus(tests_dir, self.base_dir, "*.py")
        self._by_rel = {pf.rel: pf for pf in self.files}

    # -- helpers shared by checkers -------------------------------------------

    def file(self, rel: str) -> Optional[ParsedFile]:
        return self._by_rel.get(rel)

    def rel_to_root(self, pf: ParsedFile) -> str:
        """Path relative to the analysis root (subsystem scoping)."""
        return pf.path.relative_to(self.root).as_posix()

    def in_dirs(self, pf: ParsedFile, dirs: Sequence[str]) -> bool:
        """Whether ``pf`` lives under one of ``dirs`` (root-relative).
        Outside package mode every file is in scope — fixture trees
        don't reproduce the package layout."""
        if not self.is_package:
            return True
        rel = self.rel_to_root(pf)
        return any(rel == d or rel.startswith(d.rstrip("/") + "/") for d in dirs)

    def suppressed(self, finding: Finding) -> bool:
        pf = self._by_rel.get(finding.path)
        if pf is None:
            return False
        codes = pf.suppressions.get(finding.line, set())
        return finding.code in codes or "all" in codes


def _read(path: Path) -> str:
    try:
        return path.read_text(encoding="utf-8")
    except UnicodeDecodeError:
        return path.read_text(encoding="utf-8", errors="replace")


def _text_corpus(
    directory: Optional[Path], base_dir: Path, pattern: str
) -> List[TextFile]:
    if directory is None or not Path(directory).is_dir():
        return []
    directory = Path(directory).resolve()
    out = []
    for path in sorted(directory.rglob(pattern)):
        if "__pycache__" in path.parts:
            continue
        try:
            rel = path.relative_to(base_dir).as_posix()
        except ValueError:
            rel = path.as_posix()
        out.append(TextFile(path, rel, _read(path)))
    return out


# -- checker registry ----------------------------------------------------------

CheckerFn = Callable[[AnalysisContext], Iterable[Finding]]


@dataclasses.dataclass(frozen=True)
class Checker:
    code: str
    name: str
    description: str
    fn: CheckerFn


CHECKERS: Dict[str, Checker] = {}


def register(code: str, name: str, description: str):
    """Register ``fn(ctx) -> Iterable[Finding]`` under a stable code.
    Codes are the suppression/selection currency; re-registering a code
    is a programming error."""

    def deco(fn: CheckerFn) -> CheckerFn:
        if code in CHECKERS:
            raise ValueError(f"checker code {code!r} already registered")
        CHECKERS[code] = Checker(code, name, description, fn)
        return fn

    return deco


# -- baseline ------------------------------------------------------------------

def load_baseline(path: Optional[Path]) -> List[Dict[str, str]]:
    """The committed baseline: ``{"version": 1, "findings": [...]}`` or
    a bare list of ``{code, path, message}`` entries."""
    if path is None or not Path(path).is_file():
        return []
    obj = json.loads(Path(path).read_text(encoding="utf-8"))
    entries = obj.get("findings", []) if isinstance(obj, dict) else obj
    out = []
    for e in entries:
        out.append({
            "code": str(e["code"]),
            "path": str(e["path"]),
            "message": str(e["message"]),
        })
    return out


def baseline_document(findings: Sequence[Finding]) -> str:
    entries = sorted(
        {f.baseline_key for f in findings}
    )
    return json.dumps(
        {
            "version": 1,
            "findings": [
                {"code": c, "path": p, "message": m} for c, p, m in entries
            ],
        },
        indent=2,
    ) + "\n"


@dataclasses.dataclass
class AnalysisResult:
    """Partitioned output of one engine run."""

    active: List[Finding]
    suppressed: List[Finding]
    baselined: List[Finding]
    stale_baseline: List[Dict[str, str]]
    parse_count: int
    checked_codes: List[str]
    elapsed_s: float

    def to_json(self) -> Dict[str, Any]:
        """The ``--json`` schema (stability pinned in tests)."""
        by_code: Dict[str, int] = {}
        for f in self.active:
            by_code[f.code] = by_code.get(f.code, 0) + 1
        return {
            "version": 1,
            "findings": [f.to_json() for f in self.active],
            "counts": {
                "active": len(self.active),
                "suppressed": len(self.suppressed),
                "baselined": len(self.baselined),
                "stale_baseline": len(self.stale_baseline),
                "by_code": dict(sorted(by_code.items())),
            },
            "stale_baseline": list(self.stale_baseline),
            "files": self.parse_count,
            "codes": self.checked_codes,
            "elapsed_s": round(self.elapsed_s, 3),
        }


def analyze(
    root: Path,
    base_dir: Optional[Path] = None,
    docs_dir: Optional[Path] = None,
    tests_dir: Optional[Path] = None,
    select: Optional[Iterable[str]] = None,
    baseline: Optional[Sequence[Dict[str, str]]] = None,
) -> AnalysisResult:
    """Run the selected checkers (default: all registered) over one
    shared parse of ``root``, apply inline suppressions and the
    baseline, and return the partitioned result."""
    start = time.perf_counter()
    ctx = AnalysisContext(
        root, base_dir=base_dir, docs_dir=docs_dir, tests_dir=tests_dir
    )
    codes = sorted(CHECKERS) if select is None else list(select)
    unknown = [c for c in codes if c not in CHECKERS and c != SYNTAX_ERROR_CODE]
    if unknown:
        raise ValueError(
            f"unknown checker code(s) {unknown} (known: {sorted(CHECKERS)})"
        )
    findings: List[Finding] = []
    if SYNTAX_ERROR_CODE in codes or select is None:
        for pf in ctx.files:
            if pf.syntax_error is not None:
                e = pf.syntax_error
                findings.append(Finding(
                    SYNTAX_ERROR_CODE, pf.rel, int(e.lineno or 1),
                    f"syntax error: {e.msg}",
                ))
    for code in codes:
        checker = CHECKERS.get(code)
        if checker is not None:
            findings.extend(checker.fn(ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.code, f.message))

    active: List[Finding] = []
    suppressed: List[Finding] = []
    baselined: List[Finding] = []
    entries = [dict(e) for e in (baseline or [])]
    keys = {(e["code"], e["path"], e["message"]) for e in entries}
    used: Set[Tuple[str, str, str]] = set()
    for f in findings:
        if ctx.suppressed(f):
            suppressed.append(f)
        elif f.baseline_key in keys:
            used.add(f.baseline_key)
            baselined.append(f)
        else:
            active.append(f)
    stale = [
        e for e in entries
        if (e["code"], e["path"], e["message"]) not in used
    ]
    return AnalysisResult(
        active=active,
        suppressed=suppressed,
        baselined=baselined,
        stale_baseline=stale,
        parse_count=ctx.parse_count,
        checked_codes=codes,
        elapsed_s=time.perf_counter() - start,
    )


# -- small AST helpers shared by checkers --------------------------------------

def called_name(node: ast.Call) -> str:
    """Terminal name of a call: ``time.sleep(...)`` → ``"sleep"``,
    ``predict_file(...)`` → ``"predict_file"``."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def fstring_prefix(node: ast.AST) -> Optional[str]:
    """Literal prefix of an f-string (``f"step.{n}"`` → ``"step."``) —
    how dynamic metric/fault names are matched against registries."""
    if not isinstance(node, ast.JoinedStr) or not node.values:
        return None
    first = node.values[0]
    if isinstance(first, ast.Constant) and isinstance(first.value, str):
        return first.value
    return None


def module_str_constants(pf: ParsedFile) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` bindings (resolves e.g.
    ``registry.gauge(DRIFT_GAUGE)``)."""
    out: Dict[str, str] = {}
    if pf.tree is None:
        return out
    for node in pf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            value = const_str(node.value)
            if isinstance(target, ast.Name) and value is not None:
                out[target.id] = value
    return out
