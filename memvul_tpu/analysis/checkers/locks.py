"""MV301/MV302/MV303 — lock discipline in thread-spawning classes.

PRs 4–8 grew a five-thread serving tier (micro-batcher, router
monitor, shadow worker, drift monitor, prefetch feeder) whose lock
discipline was enforced only by convention.  These checkers make the
conventions machine-checked, scoped to classes that actually spawn a
``threading.Thread`` (the only classes where two threads can contend):

* **MV301 blocking-under-lock** — inside a ``with self._lock:`` /
  ``with self._cond:`` block, no blocking work: ``sleep``/``join``/
  ``result``, scoring/encoding entry points (``predict*``, ``score_*``,
  ``encode_bank``/``encode_anchors``/``encode_many``, ``warmup_*``),
  device syncs (``device_get``, ``block_until_ready``) or file I/O
  (``open``, ``read_text``, ``write_text``, ``write_bytes``,
  ``atomic_write_text``).  A batcher holding its queue condition while
  the device scores starves every submitter in the process.
  ``Condition.wait`` is the one sanctioned block — it *releases* the
  lock.
* **MV302 bare-acquire** — ``lock.acquire()`` outside a
  ``try/finally: release()`` (and not as a ``with``): an exception
  between acquire and release deadlocks every other thread forever.
* **MV303 unguarded-shared-attr** — an instance attribute assigned
  both from a thread-target method (or a method reachable from one
  inside the class) and from a public method, where at least one of
  the writes is not under a ``with <lock>`` block.  That is the
  classic torn-state race: control plane and worker both write, nobody
  synchronizes.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..engine import AnalysisContext, Finding, ParsedFile, called_name, register

BLOCKING_NAMES = {
    "sleep", "join", "result",
    "encode_bank", "encode_anchors", "encode_many",
    "device_get", "block_until_ready",
    "open", "read_text", "write_text", "write_bytes", "atomic_write_text",
}
BLOCKING_PREFIXES = ("predict", "score_", "warmup_")

_LOCKISH = ("lock", "cond", "mutex")


def _lockish_expr(expr: ast.expr) -> Optional[str]:
    """The lock-ish name a ``with`` context manages, if any:
    ``self._lock`` / ``self._cond`` / a bare ``lock`` variable."""
    name = ""
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    low = name.lower()
    return name if any(t in low for t in _LOCKISH) else None


def _spawns_thread(cls: ast.ClassDef) -> bool:
    return any(
        isinstance(n, ast.Call) and called_name(n) == "Thread"
        for n in ast.walk(cls)
    )


def _with_lock_blocks(node: ast.AST) -> Iterator[Tuple[str, ast.With]]:
    for n in ast.walk(node):
        if isinstance(n, ast.With):
            for item in n.items:
                lock = _lockish_expr(item.context_expr)
                if lock is not None:
                    yield lock, n


def _under_lock(pf: ParsedFile, node: ast.AST) -> bool:
    for anc in pf.ancestors(node):
        if isinstance(anc, ast.With) and any(
            _lockish_expr(i.context_expr) for i in anc.items
        ):
            return True
    return False


@register(
    "MV301",
    "blocking-under-lock",
    "blocking call while holding a lock in a thread-spawning class",
)
def check_blocking_under_lock(ctx: AnalysisContext) -> Iterator[Finding]:
    for pf in ctx.files:
        if pf.tree is None:
            continue
        for cls in ast.walk(pf.tree):
            if not (isinstance(cls, ast.ClassDef) and _spawns_thread(cls)):
                continue
            for lock, block in _with_lock_blocks(cls):
                for stmt in block.body:
                    for call in ast.walk(stmt):
                        if not isinstance(call, ast.Call):
                            continue
                        name = called_name(call)
                        if name in BLOCKING_NAMES or name.startswith(
                            BLOCKING_PREFIXES
                        ):
                            yield Finding(
                                "MV301", pf.rel, call.lineno,
                                f"blocking call {name}() while holding "
                                f"{lock} in {cls.name} — move the work "
                                "outside the lock (snapshot under the "
                                "lock, act outside it)",
                                symbol=name,
                            )


def _releases(try_node: ast.Try) -> bool:
    return any(
        isinstance(n, ast.Call) and called_name(n) == "release"
        for stmt in try_node.finalbody
        for n in ast.walk(stmt)
    )


def _acquire_guarded(pf: ParsedFile, call: ast.Call) -> bool:
    """True for the two sanctioned shapes: the acquire INSIDE a
    ``try/finally: release()``, or the canonical idiom — the acquire
    statement immediately FOLLOWED by such a try."""
    node: ast.AST = call
    for anc in pf.ancestors(call):
        if isinstance(anc, ast.Try) and _releases(anc):
            return True
        body = getattr(anc, "body", None)
        if isinstance(body, list) and node in body:
            idx = body.index(node)
            if (
                idx + 1 < len(body)
                and isinstance(body[idx + 1], ast.Try)
                and _releases(body[idx + 1])
            ):
                return True
        node = anc
    return False


@register(
    "MV302",
    "bare-acquire",
    "lock.acquire() without try/finally release()",
)
def check_bare_acquire(ctx: AnalysisContext) -> Iterator[Finding]:
    for pf in ctx.files:
        if pf.tree is None:
            continue
        for call in ast.walk(pf.tree):
            if not (
                isinstance(call, ast.Call)
                and called_name(call) == "acquire"
                and isinstance(call.func, ast.Attribute)
            ):
                continue
            if not _acquire_guarded(pf, call):
                yield Finding(
                    "MV302", pf.rel, call.lineno,
                    "bare acquire() without try/finally release() — an "
                    "exception between them deadlocks every other "
                    "thread; prefer `with lock:`",
                    symbol="acquire",
                )


def _thread_target_names(cls: ast.ClassDef) -> Set[str]:
    targets: Set[str] = set()
    for call in ast.walk(cls):
        if not (isinstance(call, ast.Call) and called_name(call) == "Thread"):
            continue
        for kw in call.keywords:
            if kw.arg != "target":
                continue
            if isinstance(kw.value, ast.Attribute):
                targets.add(kw.value.attr)
            elif isinstance(kw.value, ast.Name):
                targets.add(kw.value.id)
    return targets


def _self_attr_writes(
    method: ast.FunctionDef, pf: ParsedFile
) -> List[Tuple[str, int, bool]]:
    """(attr, line, under_lock) for every ``self.attr = ...`` write."""
    out: List[Tuple[str, int, bool]] = []
    for node in ast.walk(method):
        targets: List[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                out.append((t.attr, node.lineno, _under_lock(pf, node)))
    return out


@register(
    "MV303",
    "unguarded-shared-attr",
    "instance attribute written by both a worker thread and a public "
    "method without a lock",
)
def check_unguarded_shared_attrs(ctx: AnalysisContext) -> Iterator[Finding]:
    for pf in ctx.files:
        if pf.tree is None:
            continue
        for cls in ast.walk(pf.tree):
            if not (isinstance(cls, ast.ClassDef) and _spawns_thread(cls)):
                continue
            methods: Dict[str, ast.FunctionDef] = {
                n.name: n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            targets = _thread_target_names(cls) & set(methods)
            if not targets:
                continue
            # methods reachable from the thread target within the class
            worker: Set[str] = set()
            frontier = list(targets)
            while frontier:
                name = frontier.pop()
                if name in worker:
                    continue
                worker.add(name)
                for call in ast.walk(methods[name]):
                    if isinstance(call, ast.Call):
                        callee = called_name(call)
                        if callee in methods and callee not in worker:
                            frontier.append(callee)
            writes: Dict[str, List[Tuple[str, int, bool]]] = {}
            for name, method in methods.items():
                if name == "__init__":
                    continue  # construction happens-before the thread
                for attr, line, locked in _self_attr_writes(method, pf):
                    writes.setdefault(attr, []).append((name, line, locked))
            for attr, sites in sorted(writes.items()):
                worker_sites = [s for s in sites if s[0] in worker]
                public_sites = [
                    s for s in sites
                    if s[0] not in worker and not s[0].startswith("_")
                ]
                if not worker_sites or not public_sites:
                    continue
                unlocked = [
                    s for s in worker_sites + public_sites if not s[2]
                ]
                if not unlocked:
                    continue
                name, line, _ = unlocked[0]
                yield Finding(
                    "MV303", pf.rel, line,
                    f"{cls.name}.{attr} is written by worker-thread "
                    f"method {worker_sites[0][0]}() and public method "
                    f"{public_sites[0][0]}() but the write in {name}() "
                    "holds no lock — guard both writes with one lock",
                    symbol=attr,
                )
