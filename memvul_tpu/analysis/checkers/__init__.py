"""Checker families of the static-analysis engine.

Importing this package registers every checker with the engine's
registry (``engine.CHECKERS``); each module is one family:

* :mod:`.prints`    — MV101 bare print (migrated tools/lint_no_bare_print)
* :mod:`.handlers`  — MV102 blocking in handler/router classes
* :mod:`.artifacts` — MV103 artifact-write hygiene (generalized bankops lint)
* :mod:`.purity`    — MV201 trace purity (host effects in jitted code)
* :mod:`.locks`     — MV301/302/303 lock discipline in threaded classes
* :mod:`.drift`     — MV401–405 registry drift (faults / metrics /
  config / compile-chokepoint)
"""

from . import artifacts, drift, handlers, locks, prints, purity  # noqa: F401
