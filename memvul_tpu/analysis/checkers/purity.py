"""MV201 — no host side effects inside traced (jit/Pallas/scan) code.

Keeping the hot path free of implicit host syncs and Python side
effects is exactly what the accelerator roofline demands: a ``print``
or ``time.time()`` inside a jitted function runs at *trace* time
(silently, once — usually a bug's symptom, not its absence), while
``.item()`` / ``np.asarray`` / ``jax.device_get`` on a traced value
forces a device→host sync that stalls the pipeline every step.

The checker builds an intra-package call-graph approximation:

* **roots** — functions passed (by name) to ``jax.jit`` / ``pjit`` /
  ``pl.pallas_call`` / ``checkify`` / ``nn.scan`` / ``lax.scan`` /
  ``remat``, functions *decorated* with jit/pjit, and methods of
  ``nn.Module`` subclasses (flax modules are traced by construction);
* **edges** — call sites resolved by terminal name against the
  function-def index of the scoped files (``models/``, ``ops/``,
  ``training/``, ``evaluate/`` in package mode — the model stack, the
  kernels, and the trainer/predictor step fns).

Inside reachable functions (own body only — nested defs are reached
via edges) it flags: ``print``, ``time.*``, ``random.*`` /
``np.random.*``, ``.item()``, ``jax.device_get`` / ``np.asarray``,
telemetry emission chains (``...counter(...).inc()`` etc. and
registry ``event``/``span``/``heartbeat`` calls), and ``float()`` /
``int()`` applied directly to a parameter of the traced function.

Intentional trace-time effects (the ``score_trace_count`` probe's
cousin — e.g. the fused-kernel degradation counter that ticks once at
trace) carry inline ``lint: disable=MV201`` justifications.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..engine import AnalysisContext, Finding, ParsedFile, called_name, register

CODE = "MV201"

SCOPED_DIRS = ("models", "ops", "training", "evaluate")

# call wrappers whose function-valued arguments are traced
JIT_WRAPPERS = {
    "jit", "pjit", "pallas_call", "checkify", "scan", "remat", "named_call",
}
_TELEMETRY_CHAIN = {"counter", "gauge", "histogram"}
_TELEMETRY_TERMINALS = {"inc", "observe", "set"}
_REGISTRY_CALLS = {"event", "span", "heartbeat", "progress"}

FuncDef = Tuple[ParsedFile, ast.FunctionDef]


def _is_module_class(cls: ast.ClassDef) -> bool:
    for base in cls.bases:
        name = base.attr if isinstance(base, ast.Attribute) else (
            base.id if isinstance(base, ast.Name) else ""
        )
        if name.endswith("Module"):
            return True
    return False


def _own_body_nodes(fn: ast.FunctionDef) -> Iterator[ast.AST]:
    """Walk a function's body without descending into nested function/
    class definitions (those are separate graph nodes)."""
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _receiver_is_registry(func: ast.Attribute) -> bool:
    """``tel.event(...)`` / ``get_registry().span(...)`` — the receiver
    chain names a telemetry registry."""
    value = func.value
    if isinstance(value, ast.Call) and called_name(value) == "get_registry":
        return True
    name = ""
    if isinstance(value, ast.Name):
        name = value.id
    elif isinstance(value, ast.Attribute):
        name = value.attr
    return name.lstrip("_") in {"tel", "telemetry", "registry"}


def _host_effect(node: ast.AST, params: Set[str]) -> Optional[Tuple[str, str]]:
    """(symbol, description) when ``node`` is a host side effect."""
    if not isinstance(node, ast.Call):
        return None
    func = node.func
    name = called_name(node)
    if isinstance(func, ast.Name):
        if name == "print":
            return "print", "print() call"
        if name in ("float", "int") and len(node.args) == 1:
            arg = node.args[0]
            if isinstance(arg, ast.Name) and arg.id in params:
                return name, (
                    f"{name}() on traced argument {arg.id!r} "
                    "(forces a device→host sync)"
                )
        return None
    if not isinstance(func, ast.Attribute):
        return None
    value = func.value
    if isinstance(value, ast.Name):
        if value.id == "time":
            return f"time.{name}", f"time.{name}() host clock call"
        if value.id == "random":
            return f"random.{name}", f"random.{name}() host RNG call"
        if value.id in ("np", "numpy") and name in ("asarray", "random"):
            return f"np.{name}", f"np.{name}() materializes on host"
        if value.id == "jax" and name == "device_get":
            return "jax.device_get", "jax.device_get() device→host sync"
    if (
        isinstance(value, ast.Attribute)
        and value.attr == "random"
        and isinstance(value.value, ast.Name)
        and value.value.id in ("np", "numpy")
    ):
        return f"np.random.{name}", f"np.random.{name}() host RNG call"
    if name == "item" and not node.args:
        return ".item", ".item() device→host sync"
    if name in _TELEMETRY_TERMINALS and isinstance(value, ast.Call):
        if called_name(value) in _TELEMETRY_CHAIN:
            chain = called_name(value)
            return (
                f"{chain}().{name}",
                f"telemetry {chain}().{name}() emission",
            )
    if name in _REGISTRY_CALLS and _receiver_is_registry(func):
        return f"registry.{name}", f"telemetry registry {name}() call"
    return None


def _collect_defs(files: List[ParsedFile]) -> Dict[str, List[FuncDef]]:
    index: Dict[str, List[FuncDef]] = {}
    for pf in files:
        if pf.tree is None:
            continue
        for node in ast.walk(pf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                index.setdefault(node.name, []).append((pf, node))
    return index


def _collect_roots(
    files: List[ParsedFile], index: Dict[str, List[FuncDef]]
) -> Set[str]:
    roots: Set[str] = set()
    for pf in files:
        if pf.tree is None:
            continue
        for node in ast.walk(pf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    target = deco.func if isinstance(deco, ast.Call) else deco
                    dname = (
                        target.attr if isinstance(target, ast.Attribute)
                        else target.id if isinstance(target, ast.Name) else ""
                    )
                    if dname in ("jit", "pjit"):
                        roots.add(node.name)
            elif isinstance(node, ast.ClassDef) and _is_module_class(node):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        roots.add(item.name)
            elif isinstance(node, ast.Call) and called_name(node) in JIT_WRAPPERS:
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Name) and sub.id in index:
                            roots.add(sub.id)
    return roots


def _edges(fn: ast.FunctionDef) -> Set[str]:
    out: Set[str] = set()
    for node in _own_body_nodes(fn):
        if isinstance(node, ast.Call):
            name = called_name(node)
            if name:
                out.add(name)
        # nested defs are graph nodes of their own, reached when called;
        # a nested def *defined and returned* is reached via the jit
        # wrapper that captures it (root collection walks every Call)
    return out


@register(
    CODE,
    "trace-impure",
    "host side effect inside code reachable from a jitted/Pallas entry",
)
def check(ctx: AnalysisContext) -> Iterator[Finding]:
    files = [
        pf for pf in ctx.files if ctx.in_dirs(pf, SCOPED_DIRS)
    ]
    index = _collect_defs(files)
    roots = _collect_roots(files, index)
    reachable: Set[str] = set()
    frontier = [r for r in roots if r in index]
    while frontier:
        name = frontier.pop()
        if name in reachable:
            continue
        reachable.add(name)
        for _, fn in index[name]:
            for callee in _edges(fn):
                if callee in index and callee not in reachable:
                    frontier.append(callee)
    seen: Set[Tuple[str, int, str]] = set()
    for name in sorted(reachable):
        for pf, fn in index[name]:
            params = {
                a.arg for a in (
                    fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
                )
                if a.arg not in ("self", "cls")
            }
            for node in _own_body_nodes(fn):
                effect = _host_effect(node, params)
                if effect is None:
                    continue
                symbol, desc = effect
                key = (pf.rel, node.lineno, symbol)
                if key in seen:
                    continue
                seen.add(key)
                yield Finding(
                    CODE, pf.rel, node.lineno,
                    f"host side effect in traced code: {desc} inside "
                    f"{name}() (reachable from a jit/Pallas/nn.Module "
                    "entry) — hoist it out of the traced region",
                    symbol=symbol,
                )
