"""MV102 — handler threads only enqueue + wait; routers only select.

Migrated from ``tools/lint_no_blocking_in_handler.py`` (now a
delegating shim).  Two class families, wherever they live:

* classes with a base whose name ends with ``RequestHandler`` — one
  thread per connection; anything blocking serializes the whole server
  behind one client and can trigger the mid-serve XLA compiles the
  micro-batcher exists to prevent (docs/serving.md);
* classes named ``*Router`` (or deriving from one) — a routing decision
  reads queue depths and picks a replica, nothing more; heavy fleet
  operations belong to control-plane workers.

The forbidden-name set is the serving tier's scoring/encoding/packing
surface plus ``sleep`` and the fleet control-plane entry points
(``swap_bank``/``install_bank``/``rolling_swap``); ``predict*`` is
banned by prefix.  The observability endpoints (``/metrics``,
``/tracez``, ``/profilez``; serving/frontend.py) live under the same
rule: they may only read *snapshots* — registry snapshots, the trace
ring, a monitor's ``status()`` — so a scrape can never stall the
batcher or trigger a compile (the known-bad fixtures in
tests/test_static_analysis.py pin that a handler calling ``predict*``
or ``pack_token_budget`` fails tier-1).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import AnalysisContext, Finding, called_name, register

CODE = "MV102"

FORBIDDEN_NAMES = {
    "sleep",
    "score_instances",
    "score_texts",
    "encode_anchors",
    "encode_bank",
    "warmup_compile",
    "warmup_bank_shapes",
    "swap_bank",
    "install_bank",
    "_score_fn",
    "_ragged_score_fn",
    # the ragged serve path's packing/collation (docs/ragged_serving.md):
    # packing is batcher-thread work; a handler or router that packs
    # inline serializes the process exactly like inline scoring would
    "pack_token_budget",
    "collate_ragged",
    # fleet rollouts are control-plane work (drain + encode + warm per
    # replica); an endpoint that triggers one inline would wedge every
    # connection behind the rollout
    "rolling_swap",
}
FORBIDDEN_PREFIXES = ("predict",)


def _base_name(base: ast.expr) -> str:
    if isinstance(base, ast.Attribute):
        return base.attr
    if isinstance(base, ast.Name):
        return base.id
    return ""


def _is_handler_class(node: ast.ClassDef) -> bool:
    return any(
        _base_name(b).endswith("RequestHandler") for b in node.bases
    )


def _is_router_class(node: ast.ClassDef) -> bool:
    if node.name.endswith("Router"):
        return True
    return any(_base_name(b).endswith("Router") for b in node.bases)


@register(
    CODE,
    "blocking-in-handler",
    "blocking call in an HTTP handler or router dispatch class",
)
def check(ctx: AnalysisContext) -> Iterator[Finding]:
    for pf in ctx.files:
        if pf.tree is None:
            continue
        for node in ast.walk(pf.tree):
            if not (
                isinstance(node, ast.ClassDef)
                and (_is_handler_class(node) or _is_router_class(node))
            ):
                continue
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                name = called_name(call)
                if name in FORBIDDEN_NAMES or name.startswith(FORBIDDEN_PREFIXES):
                    yield Finding(
                        CODE, pf.rel, call.lineno,
                        f"blocking call {name}() inside {node.name} — a "
                        "handler may only submit() and wait on the future; "
                        "a router may only select a replica queue "
                        "(docs/serving.md)",
                        symbol=name,
                    )
