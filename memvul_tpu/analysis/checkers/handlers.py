"""MV102 — handler threads only enqueue + wait; routers only select;
dispatcher admission paths never block.

Migrated from ``tools/lint_no_blocking_in_handler.py`` (now a
delegating shim).  Four class families, wherever they live:

* classes with a base whose name ends with ``RequestHandler`` — one
  thread per connection; anything blocking serializes the whole server
  behind one client and can trigger the mid-serve XLA compiles the
  micro-batcher exists to prevent (docs/serving.md);
* classes named ``*Router`` (or deriving from one) — a routing decision
  reads queue depths and picks a replica, nothing more; heavy fleet
  operations belong to control-plane workers;
* classes named ``*Balancer`` or ``*Autoscaler`` (or deriving from
  one; serving/fleet.py, serving/autoscaler.py) — the same selection-
  only discipline one level up: a host-routing or scale decision reads
  cached health/queue/hint state and picks; kills, restart backoff,
  spawn warmups, and drain waits belong to the module-level recovery/
  scale workers on their own threads;
* classes named ``*Recorder`` (or deriving from one;
  serving/incident.py) — the incident flight recorder's trigger side
  runs on whatever thread noticed the problem (router sweep, fleet
  monitor, alert engine), so the same no-scoring/no-sleeping contract
  applies: a trigger is a bounded-queue put and a dump reads
  snapshots; a recorder that scored or slept inline would couple the
  post-mortem plane to the request path it exists to observe;
* classes named ``*Cache`` (or deriving from one;
  serving/admission_cache.py) — the admission cache sits ON the
  request hot path (every submit probes it), so a lookup/store must be
  a dict probe under a short lock and nothing else: a cache that
  encoded, scored, or slept inline would cost every request what it
  exists to save the occasional duplicate;
* classes named ``*Tenant*`` (name or base contains ``Tenant``;
  serving/tenancy.py) — tenant managers resolve names to stores and
  record liveness; installing banks, encoding, and fleet rollouts are
  the module-level ``configure_tenants``/``promote_tenant`` helpers'
  job, so a manager method that swapped or scored inline would smuggle
  control-plane work onto whatever thread asked for a lookup;
* classes named ``*Dispatcher`` (or deriving from one;
  serving/dispatch.py) — the batcher strategies themselves.  Their JOB
  is to encode, pack, and score, so the serving-surface names stay
  legal here; what the admission path must never do is stall on a
  synchronous convenience API (``score_texts`` round-trips the device
  per call) or a bare ``time.sleep`` (waits go through condition
  variables and queue timeouts so drain/kill flags are noticed), and
  ``predict*`` entry points are offline-evaluation surface, not
  dispatch surface.  Continuous admission makes this structural: a
  blocked admission loop re-couples queue_wait to device latency — the
  exact coupling the dispatcher exists to remove.

The forbidden-name set is the serving tier's scoring/encoding/packing
surface plus ``sleep`` and the fleet control-plane entry points
(``swap_bank``/``install_bank``/``rolling_swap``); ``predict*`` is
banned by prefix.  The observability endpoints (``/metrics``,
``/tracez``, ``/profilez``; serving/frontend.py) live under the same
rule: they may only read *snapshots* — registry snapshots, the trace
ring, a monitor's ``status()`` — so a scrape can never stall the
batcher or trigger a compile (the known-bad fixtures in
tests/test_static_analysis.py pin that a handler calling ``predict*``
or ``pack_token_budget`` fails tier-1).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import AnalysisContext, Finding, called_name, register

CODE = "MV102"

FORBIDDEN_NAMES = {
    "sleep",
    "score_instances",
    "score_texts",
    "encode_anchors",
    "encode_bank",
    "warmup_compile",
    "warmup_bank_shapes",
    "swap_bank",
    "install_bank",
    "_score_fn",
    "_ragged_score_fn",
    # the ragged serve path's packing/collation (docs/ragged_serving.md):
    # packing is batcher-thread work; a handler or router that packs
    # inline serializes the process exactly like inline scoring would
    "pack_token_budget",
    "collate_ragged",
    # fleet rollouts are control-plane work (drain + encode + warm per
    # replica); an endpoint that triggers one inline would wedge every
    # connection behind the rollout
    "rolling_swap",
}
FORBIDDEN_PREFIXES = ("predict",)

# the dispatcher admission-path set is deliberately NARROW: packing,
# collation, encoding and the jitted score fns are a dispatcher's whole
# purpose — only the stall-shaped calls are banned (see module docstring)
DISPATCHER_FORBIDDEN_NAMES = {"sleep", "score_texts"}


def _base_name(base: ast.expr) -> str:
    if isinstance(base, ast.Attribute):
        return base.attr
    if isinstance(base, ast.Name):
        return base.id
    return ""


def _is_handler_class(node: ast.ClassDef) -> bool:
    return any(
        _base_name(b).endswith("RequestHandler") for b in node.bases
    )


def _is_router_class(node: ast.ClassDef) -> bool:
    if node.name.endswith("Router"):
        return True
    return any(_base_name(b).endswith("Router") for b in node.bases)


def _is_balancer_class(node: ast.ClassDef) -> bool:
    # host balancers and autoscalers make routing/control decisions
    # under the same selection-only contract as routers
    for suffix in ("Balancer", "Autoscaler"):
        if node.name.endswith(suffix):
            return True
        if any(_base_name(b).endswith(suffix) for b in node.bases):
            return True
    return False


def _is_recorder_class(node: ast.ClassDef) -> bool:
    # the incident flight recorder (serving/incident.py): its trigger
    # side runs on router/fleet/alert threads, so it inherits the full
    # selection-only forbidden set
    if node.name.endswith("Recorder"):
        return True
    return any(_base_name(b).endswith("Recorder") for b in node.bases)


def _is_cache_class(node: ast.ClassDef) -> bool:
    # the admission cache (serving/admission_cache.py) is probed on
    # every submit: lookup/store are dict ops under a short lock, never
    # encoding/scoring/sleeping
    if node.name.endswith("Cache"):
        return True
    return any(_base_name(b).endswith("Cache") for b in node.bases)


def _is_tenant_class(node: ast.ClassDef) -> bool:
    # tenant managers (serving/tenancy.py) resolve names to stores —
    # selection only; installs/rollouts live in module-level helpers
    if "Tenant" in node.name:
        return True
    return any("Tenant" in _base_name(b) for b in node.bases)


def _is_dispatcher_class(node: ast.ClassDef) -> bool:
    if node.name.endswith("Dispatcher"):
        return True
    return any(_base_name(b).endswith("Dispatcher") for b in node.bases)


@register(
    CODE,
    "blocking-in-handler",
    "blocking call in an HTTP handler, router, or dispatcher class",
)
def check(ctx: AnalysisContext) -> Iterator[Finding]:
    for pf in ctx.files:
        if pf.tree is None:
            continue
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            if (
                _is_handler_class(node)
                or _is_router_class(node)
                or _is_balancer_class(node)
                or _is_recorder_class(node)
                or _is_cache_class(node)
                or _is_tenant_class(node)
            ):
                forbidden = FORBIDDEN_NAMES
                contract = (
                    "a handler may only submit() and wait on the future; "
                    "a router/balancer/autoscaler may only select from "
                    "cached state; a recorder may only enqueue triggers "
                    "and dump snapshots; a cache may only probe its map; "
                    "a tenant manager may only resolve names"
                )
            elif _is_dispatcher_class(node):
                forbidden = DISPATCHER_FORBIDDEN_NAMES
                contract = (
                    "a dispatcher's admission path waits on condition "
                    "variables and queue timeouts, never sleeps or "
                    "round-trips the device per request"
                )
            else:
                continue
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                name = called_name(call)
                if name in forbidden or name.startswith(FORBIDDEN_PREFIXES):
                    yield Finding(
                        CODE, pf.rel, call.lineno,
                        f"blocking call {name}() inside {node.name} — "
                        f"{contract} (docs/serving.md)",
                        symbol=name,
                    )
