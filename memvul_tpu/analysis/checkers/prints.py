"""MV101 — no bare ``print(`` in library code.

Migrated from ``tools/lint_no_bare_print.py`` (which now delegates
here): library output goes through ``logging`` (operator-facing) or the
telemetry registry (machine-facing, docs/observability.md).  A bare
print from deep inside a scoring stream corrupts the one-JSON-line
stdout contract of the bench/CLI entry points and is invisible to
``telemetry-report``.  The two intentional stdout writers are exempt by
filename — ``bench.py`` (its stdout IS the result contract) and
``__main__.py`` (the CLI's user-facing output) — wherever they live,
matching the historical tool's behavior.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import AnalysisContext, Finding, register

CODE = "MV101"

# files whose stdout is an intentional, documented contract
ALLOWED_FILES = {"bench.py", "__main__.py"}
# the lint CLI renders findings on stdout — same contract, but only
# the real one (not a fixture file that happens to share the name)
ALLOWED_PACKAGE_FILES = {"analysis/cli.py"}


@register(
    CODE,
    "bare-print",
    "bare print() in library code — use logging or the telemetry registry",
)
def check(ctx: AnalysisContext) -> Iterator[Finding]:
    for pf in ctx.files:
        if pf.path.name in ALLOWED_FILES or pf.tree is None:
            continue
        if ctx.is_package and ctx.rel_to_root(pf) in ALLOWED_PACKAGE_FILES:
            continue
        for node in ast.walk(pf.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
            ):
                yield Finding(
                    CODE, pf.rel, node.lineno,
                    "bare print() in library code — use logging or the "
                    "telemetry registry (docs/observability.md)",
                    symbol="print",
                )
