"""MV103 — artifact writes go through the committed helpers.

Generalized from ``tools/lint_bank_artifact_writes.py`` (now a
delegating shim): any JSON/manifest/journal artifact written by the
durable subsystems must go through ``resilience.io.atomic_write_text``
(whole-document commits) or the telemetry ``JsonlSink`` (append-only
trails) — a bare ``open(..., "w")`` or ``Path.write_text`` is a
torn-write hazard where a kill mid-write leaves half a manifest.

Scope in package mode: ``bankops/`` (the historical lint), plus
``serving/``, ``resilience/`` and ``telemetry/`` (this engine's
generalization).  The two modules that *implement* the committed
helpers carry inline ``lint: disable=MV103`` justifications — the
open calls there ARE the helper.  On a fixture dir every file is in
scope (the shim/unit-test contract).

Flagged:

* ``open(...)`` whose mode (2nd positional or ``mode=``) contains any
  of ``w``/``a``/``x``/``+`` — read-only opens are fine; a *dynamic*
  mode is flagged too (artifact writes must be static);
* ``.write_text(...)`` / ``.write_bytes(...)`` attribute calls.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import AnalysisContext, Finding, register

CODE = "MV103"

SCOPED_DIRS = ("bankops", "serving", "resilience", "telemetry")
WRITE_MODE_CHARS = set("wax+")
FORBIDDEN_ATTRS = {"write_text", "write_bytes"}


def _open_write_mode(node: ast.Call) -> bool:
    func = node.func
    name = func.id if isinstance(func, ast.Name) else (
        func.attr if isinstance(func, ast.Attribute) else ""
    )
    if name != "open":
        return False
    mode = node.args[1] if len(node.args) >= 2 else None
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return False  # default "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return bool(set(mode.value) & WRITE_MODE_CHARS)
    return True  # dynamic mode: flag it — artifact writes must be static


@register(
    CODE,
    "artifact-write",
    "direct artifact write — use atomic_write_text or JsonlSink",
)
def check(ctx: AnalysisContext) -> Iterator[Finding]:
    for pf in ctx.files:
        if pf.tree is None or not ctx.in_dirs(pf, SCOPED_DIRS):
            continue
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            if _open_write_mode(node):
                symbol = "open"
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in FORBIDDEN_ATTRS
            ):
                symbol = node.func.attr
            else:
                continue
            yield Finding(
                CODE, pf.rel, node.lineno,
                f"direct artifact write ({symbol}) — commit through "
                "resilience.io.atomic_write_text or the telemetry "
                "JsonlSink (docs/anchor_bank.md)",
                symbol=symbol,
            )
