"""MV401–MV405 — cross-file registry drift.

The repo keeps several name registries that code, tests and docs must
agree on; nothing enforced that agreement until now, so it drifted
(PR 5–8 added counters the observability doc never learned about).
Five checkers, all over the one shared parse:

* **MV401 unregistered-fault-point** — every fault point named in a
  ``MEMVUL_FAULTS`` spec (tests/docs) or passed to ``fault_point()``
  in package code must be registered in
  ``resilience/faults.py:REGISTERED_POINTS`` (dynamic families like
  ``step.<n>`` register their prefix in
  ``REGISTERED_POINT_PREFIXES``).  A typo'd chaos spec otherwise tests
  nothing, silently.
* **MV402 undocumented-metric** — every ``counter(...)`` /
  ``gauge(...)`` / ``histogram(...)`` name emitted in package code
  must appear in the metric tables of ``docs/`` (the catalog in
  docs/observability.md; per-subsystem tables in docs/serving.md).
  Dynamic names (``bank.anchor_wins.<id>``) match by literal prefix.
* **MV403 stale-metric-doc** — the reverse direction: every
  counter/gauge/histogram row in those tables must correspond to a
  name the code can emit (``span``/``derived`` rows are exempt — spans
  are emitted by the registry itself, derived values by
  telemetry-report).
* **MV404 unknown-config-key** — every ``cfg["key"]`` / ``cfg.get``
  access on a variable assigned from a ``config.*_config()`` section
  reader must resolve against the matching ``config.*_DEFAULTS`` dict;
  a typo'd key otherwise silently reads the default forever.
* **MV405 registry-bypass-compile** — every ``.lower(...).compile(``
  chain outside ``telemetry/programs.py`` bypasses the compiled-program
  registry's ``compile_and_register`` chokepoint, so the executable is
  invisible to ``/programz``, the ``xla.*`` gauges and the roofline
  report.  Pass the lowered object to ``compile_and_register`` instead
  (an intentionally-raw compile carries a justified inline disable).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..engine import (
    AnalysisContext,
    Finding,
    ParsedFile,
    called_name,
    const_str,
    fstring_prefix,
    module_str_constants,
    register,
)

# -- MV401: fault points -------------------------------------------------------

# point[@n]=action clauses inside MEMVUL_FAULTS-style spec strings; real
# injection points are dotted — single-token names ("a=raise") are the
# fault-parser unit tests' fixtures, not registry members
_FAULT_SPEC_RE = re.compile(
    r"([A-Za-z_][\w-]*(?:\.[\w.-]+)+)(?:@\d+)?=(?:raise|sigterm|sigint|sigkill)\b"
)
_FAULT_CALL_RE = re.compile(r"""fault_point\(\s*["']([^"']+)["']\s*\)""")


def _fault_registry(
    ctx: AnalysisContext,
) -> Optional[Tuple[Set[str], Tuple[str, ...]]]:
    pf = next(
        (p for p in ctx.files
         if ctx.rel_to_root(p) == "resilience/faults.py"),
        None,
    )
    if pf is None or pf.tree is None:
        return None
    points: Set[str] = set()
    prefixes: List[str] = []
    for node in pf.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        values = node.value
        if isinstance(values, ast.Call) and called_name(values) in (
            "frozenset", "set", "tuple",
        ):
            values = values.args[0] if values.args else None
        if not isinstance(values, (ast.Set, ast.Tuple, ast.List)):
            continue
        items = [const_str(e) for e in values.elts]
        if any(i is None for i in items):
            continue
        if target.id == "REGISTERED_POINTS":
            points.update(items)  # type: ignore[arg-type]
        elif target.id == "REGISTERED_POINT_PREFIXES":
            prefixes.extend(items)  # type: ignore[arg-type]
    if not points:
        return None
    return points, tuple(prefixes)


def _fault_registered(
    name: str, points: Set[str], prefixes: Tuple[str, ...]
) -> bool:
    if name in points:
        return True
    return any(name.startswith(p) or name == p.rstrip(".") for p in prefixes)


@register(
    "MV401",
    "unregistered-fault-point",
    "fault point name not registered in resilience/faults.py",
)
def check_fault_points(ctx: AnalysisContext) -> Iterator[Finding]:
    registry = _fault_registry(ctx)
    if registry is None:
        return  # no machine-readable registry to check against
    points, prefixes = registry
    for pf in ctx.files:
        if pf.tree is None or ctx.rel_to_root(pf).startswith("resilience/"):
            continue
        for node in ast.walk(pf.tree):
            if not (
                isinstance(node, ast.Call)
                and called_name(node) == "fault_point"
                and node.args
            ):
                continue
            name = const_str(node.args[0])
            if name is None:
                prefix = fstring_prefix(node.args[0])
                if prefix is None or _fault_registered(
                    prefix, points, prefixes
                ):
                    continue
                name = prefix
            elif _fault_registered(name, points, prefixes):
                continue
            yield Finding(
                "MV401", pf.rel, node.lineno,
                f"fault point {name!r} is not registered in "
                "resilience/faults.py REGISTERED_POINTS — register it "
                "(and document it in the table) or fix the name",
                symbol=name,
            )
    for tf in list(ctx.tests) + list(ctx.docs):
        for i, line in enumerate(tf.lines, start=1):
            for m in list(_FAULT_SPEC_RE.finditer(line)) + list(
                _FAULT_CALL_RE.finditer(line)
            ):
                name = m.group(1)
                if "." not in name:
                    continue
                if not _fault_registered(name, points, prefixes):
                    yield Finding(
                        "MV401", tf.rel, i,
                        f"fault point {name!r} referenced here is not "
                        "registered in resilience/faults.py "
                        "REGISTERED_POINTS — the chaos spec would arm "
                        "nothing",
                        symbol=name,
                    )


# -- MV402/MV403: metric names vs docs tables ----------------------------------

_METRIC_NAME_RE = re.compile(
    r"^[a-z][a-z0-9_]*(?:\.[a-z0-9_<>*-]+)+$"
)
_BACKTICK_RE = re.compile(r"`([^`]+)`")
_METRIC_KINDS = ("counter", "gauge", "histogram", "span", "derived")

_EMITTERS = {"counter", "gauge", "histogram"}
# the registry/report machinery itself and the engine are not emitters
_EMITTER_EXEMPT_DIRS = ("telemetry", "analysis")


class _DocEntry:
    def __init__(self, name: str, kind: str, rel: str, line: int) -> None:
        self.name = name
        self.kind = kind
        self.rel = rel
        self.line = line
        # "bank.anchor_wins.<id>" → literal prefix "bank.anchor_wins."
        cut = len(name)
        for marker in ("<", "*"):
            pos = name.find(marker)
            if pos != -1:
                cut = min(cut, pos)
        self.prefix = name[:cut] if cut < len(name) else None

    def matches(self, emitted: str, dynamic: bool) -> bool:
        if self.prefix is None:
            return not dynamic and emitted == self.name
        return emitted.startswith(self.prefix) or (
            dynamic and self.prefix.startswith(emitted)
        )


def _doc_metric_entries(ctx: AnalysisContext) -> List[_DocEntry]:
    entries: List[_DocEntry] = []
    for tf in ctx.docs:
        for i, line in enumerate(tf.lines, start=1):
            stripped = line.strip()
            if not stripped.startswith("|"):
                continue
            cells = [c.strip() for c in stripped.strip("|").split("|")]
            if len(cells) < 2:
                continue
            kind = next(
                (k for k in _METRIC_KINDS
                 if any(re.search(rf"\b{k}s?\b", c) for c in cells[1:])),
                None,
            )
            if kind is None:
                continue
            for token in _BACKTICK_RE.findall(cells[0]):
                if _METRIC_NAME_RE.match(token):
                    entries.append(_DocEntry(token, kind, tf.rel, i))
    return entries


def _emitted_metrics(
    ctx: AnalysisContext,
) -> List[Tuple[str, bool, str, int]]:
    """(name, is_dynamic_prefix, rel, line) for every metric emission."""
    out: List[Tuple[str, bool, str, int]] = []
    for pf in ctx.files:
        if pf.tree is None or not pf.rel.endswith(".py"):
            continue
        if ctx.is_package and ctx.rel_to_root(pf).split("/")[0] in (
            _EMITTER_EXEMPT_DIRS
        ):
            continue
        constants = module_str_constants(pf)
        for node in ast.walk(pf.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _EMITTERS
                and node.args
            ):
                continue
            arg = node.args[0]
            name = const_str(arg)
            dynamic = False
            if name is None and isinstance(arg, ast.Name):
                name = constants.get(arg.id)
            if name is None:
                name = fstring_prefix(arg)
                dynamic = name is not None
            if name is None or "." not in name:
                continue
            out.append((name, dynamic, pf.rel, node.lineno))
    return out


@register(
    "MV402",
    "undocumented-metric",
    "metric emitted in code but absent from the docs metric tables",
)
def check_undocumented_metrics(ctx: AnalysisContext) -> Iterator[Finding]:
    entries = _doc_metric_entries(ctx)
    if not entries:
        return  # nothing to reconcile against (no docs corpus)
    for name, dynamic, rel, line in _emitted_metrics(ctx):
        if any(e.matches(name, dynamic) for e in entries):
            continue
        shown = f"{name}<…>" if dynamic else name
        yield Finding(
            "MV402", rel, line,
            f"metric {shown!r} is emitted here but missing from the "
            "docs metric tables (docs/observability.md catalog) — "
            "document it or drop the emission",
            symbol=name,
        )


@register(
    "MV403",
    "stale-metric-doc",
    "documented metric that no code emits",
)
def check_stale_metric_docs(ctx: AnalysisContext) -> Iterator[Finding]:
    entries = _doc_metric_entries(ctx)
    if not entries:
        return
    emitted = _emitted_metrics(ctx)
    emitted_exact = {name for name, dynamic, _, _ in emitted if not dynamic}
    emitted_prefixes = {name for name, dynamic, _, _ in emitted if dynamic}
    # fallback: a name carried through a variable (e.g. a status→counter
    # dict) still appears as a string constant somewhere in the package
    all_strings: Set[str] = set()
    for pf in ctx.files:
        if pf.tree is None:
            continue
        for node in ast.walk(pf.tree):
            value = const_str(node)
            if value is not None and "." in value:
                all_strings.add(value)
    reported: Set[Tuple[str, str, int]] = set()
    for e in entries:
        if e.kind in ("span", "derived"):
            continue
        if e.prefix is None:
            ok = e.name in emitted_exact or e.name in all_strings
        else:
            ok = any(
                p.startswith(e.prefix) or e.prefix.startswith(p)
                for p in emitted_prefixes
            )
        if ok:
            continue
        key = (e.name, e.rel, e.line)
        if key in reported:
            continue
        reported.add(key)
        yield Finding(
            "MV403", e.rel, e.line,
            f"documented metric {e.name!r} is emitted nowhere in the "
            "package — update the table or restore the emission",
            symbol=e.name,
        )


# -- MV404: config keys vs *_DEFAULTS ------------------------------------------

def _config_defaults(ctx: AnalysisContext) -> Dict[str, Set[str]]:
    """``serving_config`` → key set of ``SERVING_DEFAULTS`` (statically
    extracted from config.py — the engine never imports the package)."""
    pf = next(
        (p for p in ctx.files if ctx.rel_to_root(p) == "config.py"), None
    )
    if pf is None or pf.tree is None:
        return {}
    defaults: Dict[str, Set[str]] = {}
    for node in pf.tree.body:
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for t in targets:
            if (
                isinstance(t, ast.Name)
                and t.id.endswith("_DEFAULTS")
                and isinstance(value, ast.Dict)
            ):
                keys = {
                    const_str(k) for k in value.keys if const_str(k)
                }
                defaults[t.id] = {k for k in keys if k}
    out: Dict[str, Set[str]] = {}
    for name, keys in defaults.items():
        fn_name = name[: -len("_DEFAULTS")].lower() + "_config"
        out[fn_name] = keys
    return out


@register(
    "MV404",
    "unknown-config-key",
    "cfg[\"key\"] access that no config.*_DEFAULTS dict declares",
)
def check_config_keys(ctx: AnalysisContext) -> Iterator[Finding]:
    fn_keys = _config_defaults(ctx)
    if not fn_keys:
        return
    for pf in ctx.files:
        if pf.tree is None or ctx.rel_to_root(pf) == "config.py":
            continue
        # variable → the section reader that produced it (file-scoped
        # name resolution is enough: the readers are called once per
        # entry point and the variable names are idiomatic)
        var_fn: Dict[str, str] = {}
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                fn = called_name(node.value)
                if fn in fn_keys:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            var_fn[t.id] = fn
        if not var_fn:
            continue
        for node in ast.walk(pf.tree):
            key = None
            var = None
            if (
                isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id in var_fn
            ):
                var = node.value.id
                key = const_str(node.slice)
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in var_fn
                and node.args
            ):
                var = node.func.value.id
                key = const_str(node.args[0])
            if key is None or var is None:
                continue
            fn = var_fn[var]
            if key not in fn_keys[fn]:
                defaults_name = fn[: -len("_config")].upper() + "_DEFAULTS"
                yield Finding(
                    "MV404", pf.rel, node.lineno,
                    f"config key {key!r} read from {var} "
                    f"({fn}(...)) is not declared in "
                    f"config.{defaults_name} — a typo here silently "
                    "reads the default forever",
                    symbol=key,
                )


# -- MV405: raw .lower().compile() outside the program registry ----------------

# the one sanctioned compile site: ProgramRegistry.compile_and_register
_COMPILE_CHOKEPOINT = "telemetry/programs.py"


@register(
    "MV405",
    "registry-bypass-compile",
    ".lower(...).compile() outside telemetry/programs.py bypasses the "
    "program registry",
)
def check_registry_bypass_compile(ctx: AnalysisContext) -> Iterator[Finding]:
    for pf in ctx.files:
        if pf.tree is None or ctx.rel_to_root(pf) == _COMPILE_CHOKEPOINT:
            continue
        for node in ast.walk(pf.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "compile"
                and isinstance(node.func.value, ast.Call)
                and isinstance(node.func.value.func, ast.Attribute)
                and node.func.value.func.attr == "lower"
            ):
                continue
            yield Finding(
                "MV405", pf.rel, node.lineno,
                "raw .lower(...).compile() bypasses the compiled-program "
                "registry — pass the lowered object to "
                "ProgramRegistry.compile_and_register so the executable "
                "shows up in /programz, the xla.* metrics and the "
                "roofline report (lint: disable=MV405 with a "
                "justification if a raw compile is intentional)",
                symbol="lower().compile()",
            )
