"""Unified static analysis for memvul_tpu (docs/static_analysis.md).

One ``ast`` parse per file shared by all registered checkers; findings
as structured ``{code, path, line, message}`` records; inline
``lint: disable=CODE`` comment suppressions plus a committed baseline;
``python -m memvul_tpu lint [--select CODE,...] [--json]`` CLI.

The three historical one-file lints under ``tools/`` delegate here
(:func:`run_tool_checkers` preserves their path:line output contract);
the new checker families (trace purity, lock discipline, registry
drift) live in :mod:`.checkers` and need the shared multi-file context
to be tractable at all.
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterable, Optional

from .engine import (  # noqa: F401
    CHECKERS,
    AnalysisResult,
    Finding,
    analyze,
    baseline_document,
    load_baseline,
    register,
)
from . import checkers  # noqa: F401  (registers every checker family)

PACKAGE_ROOT = Path(__file__).resolve().parents[1]
REPO_ROOT = PACKAGE_ROOT.parent
BASELINE_PATH = Path(__file__).resolve().parent / "baseline.json"


def analyze_repo(
    select: Optional[Iterable[str]] = None,
    baseline_path: Optional[Path] = BASELINE_PATH,
) -> AnalysisResult:
    """Run the engine over the real tree with the committed baseline —
    what the CLI, the tier-1 gate test and ``BENCH_LINT=1`` all call."""
    return analyze(
        PACKAGE_ROOT,
        base_dir=REPO_ROOT,
        docs_dir=REPO_ROOT / "docs",
        tests_dir=REPO_ROOT / "tests",
        select=list(select) if select is not None else None,
        baseline=load_baseline(baseline_path),
    )


def run_tool_checkers(
    codes: Iterable[str], root: Path
) -> AnalysisResult:
    """Engine run scoped the way the legacy ``tools/lint_*.py`` entry
    points ran: one checker family over an arbitrary directory, paths
    relative to that directory, no baseline."""
    root = Path(root)
    return analyze(root, base_dir=root, select=list(codes))
