"""``python -m memvul_tpu lint`` — the engine's command line.

Human output is one ``path:line: CODE message`` per active finding;
``--json`` emits the stable machine schema (pinned in tests).  Exit
codes: 0 clean (inline suppressions and baselined findings don't
fail), 1 active findings, 2 usage error.  ``--write-baseline``
rewrites the committed baseline from the current active findings —
the sanctioned way to grandfather a finding (prefer an inline
suppression comment with a one-line justification; see
docs/static_analysis.md for the workflow).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional


def add_lint_arguments(parser: argparse.ArgumentParser) -> None:
    """The ``lint`` subcommand's flag surface (shared with tests)."""
    parser.add_argument(
        "--select", default=None, metavar="CODE,...",
        help="run only these checker codes (e.g. MV101,MV301)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="emit the machine-readable result document on stdout",
    )
    parser.add_argument(
        "--root", default=None, metavar="DIR",
        help="analyze this directory instead of the installed package "
        "(docs/tests reconciliation only runs against the repo layout)",
    )
    parser.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="baseline JSON (default: the committed analysis/baseline.json)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline — every finding is active",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="rewrite the baseline file from the current active findings",
    )
    parser.add_argument(
        "--list-codes", action="store_true",
        help="print the checker catalog (code, name, description) and exit",
    )


def run_lint(args: argparse.Namespace) -> int:
    from . import (
        BASELINE_PATH,
        CHECKERS,
        analyze,
        analyze_repo,
        baseline_document,
        load_baseline,
    )

    if args.list_codes:
        from .engine import SYNTAX_ERROR_CODE

        print(f"{SYNTAX_ERROR_CODE}  syntax-error  file does not parse")
        for code in sorted(CHECKERS):
            c = CHECKERS[code]
            print(f"{c.code}  {c.name}  {c.description}")
        return 0

    select: Optional[List[str]] = None
    if args.select:
        select = [c.strip() for c in args.select.split(",") if c.strip()]
    baseline_path: Optional[Path]
    if args.no_baseline:
        baseline_path = None
    elif args.baseline:
        baseline_path = Path(args.baseline)
    else:
        baseline_path = BASELINE_PATH

    try:
        if args.root:
            root = Path(args.root)
            if not root.is_dir():
                print(f"lint: {root} is not a directory", file=sys.stderr)
                return 2
            result = analyze(
                root, base_dir=root, select=select,
                baseline=load_baseline(baseline_path) if baseline_path else [],
            )
        else:
            result = analyze_repo(select=select, baseline_path=baseline_path)
    except ValueError as e:  # unknown --select code
        print(f"lint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        target = baseline_path or BASELINE_PATH
        target.write_text(
            baseline_document(result.active + result.baselined)
        )
        print(f"baseline written: {target} "
              f"({len(result.active) + len(result.baselined)} entries)")
        return 0

    if args.json:
        print(json.dumps(result.to_json(), indent=2))
        return 1 if result.active else 0

    for f in result.active:
        print(f"{f.path}:{f.line}: {f.code} {f.message}")
    for e in result.stale_baseline:
        print(
            f"stale baseline entry (delete it): {e['code']} {e['path']} "
            f"{e['message']!r}",
            file=sys.stderr,
        )
    print(
        f"{len(result.active)} finding(s) "
        f"({len(result.suppressed)} suppressed, "
        f"{len(result.baselined)} baselined) — "
        f"{result.parse_count} file(s) parsed once in "
        f"{result.elapsed_s:.2f}s"
    )
    return 1 if result.active else 0
