"""Benchmark: Siamese anchor-bank scoring throughput on TPU.

Measures the north-star workload (SURVEY.md §6): stream issue reports
through the full inference path — BERT-base encode (bf16), anchor-bank
match against 129 anchors, per-anchor softmax + best-anchor reduce —
exactly what ``predict_memory`` does over the 1.2M-report corpus.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline (denominator). The reference repo publishes no throughput number
(BASELINE.md).  The GTX-3090 estimate: ~71 TFLOP/s dense fp16 tensor peak
at ~30% achieved MFU for PyTorch-1.8 BERT-base inference ≈ 21 TFLOP/s
effective; one report at eval length 512 costs ≈ 2·110e6·512 ≈ 1.13e11
FLOP → ≈ 190 reports/s.  MFU sensitivity (the free parameter): 20% → 127
rps, 30% → 190 rps, 40% → 253 rps; vs_baseline uses the middle estimate.

Why 190 stays the baseline for the mixed-length corpus: the reference
collates with AllenNLP's per-batch pad-to-longest at eval batch 512 in
stream order (reference: predict_memory.py:92-99,208).  Under any
long-tailed length distribution (~12% of reports at the 512 cap here) the
probability that a 512-report batch contains no capped report is
(0.88)^512 ≈ 1e-29 — every reference batch pads to 512, so its per-report
cost IS the 512-token cost.  Our length-binned batcher is the structural
win being measured.

Env knobs: BENCH_SEQ_LEN (cap, default 512), BENCH_BUCKETS (comma list,
default "64,128,256,512"; empty string = pad-everything-to-cap mode),
BENCH_TOKENS (token budget per batch, default 524288 ≈ batch 1024 at 512),
BENCH_REPORTS (default 16384).
"""

import json
import os
import sys
import tempfile
import time

BASELINE_RPS_512 = 190.0  # estimated GTX-3090 throughput at seq_len 512 (above)


def main() -> None:
    import numpy as np
    import jax
    import jax.numpy as jnp

    from memvul_tpu.data.synthetic import build_workspace
    from memvul_tpu.data.readers import MemoryReader
    from memvul_tpu.evaluate.predict_memory import SiamesePredictor
    from memvul_tpu.models import BertConfig, MemoryModel

    seq_len = int(os.environ.get("BENCH_SEQ_LEN", "512"))
    buckets_env = os.environ.get("BENCH_BUCKETS", "64,128,256,512")
    buckets = (
        tuple(int(b) for b in buckets_env.split(",") if b) if buckets_env else None
    )
    if buckets:
        buckets = tuple(b for b in buckets if b <= seq_len) or (seq_len,)
    # token budget per batch: 256k (batch 512 at seq 512, scaling up to
    # 4096 at seq 64) measured best on v5e — larger budgets waste rows on
    # partially-filled bucket tails, smaller ones under-fill the MXU;
    # sweep on hardware: 512k → 11.5×, 256k → 12.3× at 32k reports
    tokens_per_batch = int(os.environ.get("BENCH_TOKENS", str(256 * 1024)))
    n_reports = int(os.environ.get("BENCH_REPORTS", "32768"))
    n_anchors = 129  # reference external-memory size (utils.py:347)

    ws = build_workspace(
        tempfile.mkdtemp(),
        seed=0,
        num_projects=8,
        reports_per_project=max(4, n_reports // 8),
        realistic_lengths=True,
    )
    cfg = BertConfig.base(
        vocab_size=max(30522, ws["tokenizer"].vocab_size), dtype=jnp.bfloat16
    )
    model = MemoryModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    params = model.init(jax.random.PRNGKey(0), dummy, dummy)

    predictor = SiamesePredictor(
        model,
        params,
        ws["tokenizer"],
        batch_size=tokens_per_batch // seq_len,
        max_length=seq_len,
        buckets=buckets,
        tokens_per_batch=tokens_per_batch if buckets else None,
    )
    # 129-anchor bank from synthetic anchor texts (cycled to reference size)
    base_anchors = list(ws["anchors"].items())
    instances = []
    for i in range(n_anchors):
        cat, text = base_anchors[i % len(base_anchors)]
        instances.append(
            {"text1": text, "meta": {"label": f"{cat}#{i}", "type": "golden"}}
        )
    predictor.encode_anchors(instances)

    reader = MemoryReader(
        cve_path=ws["paths"]["cve"], anchor_path=ws["paths"]["anchors"]
    )
    test_instances = list(reader.read(ws["paths"]["test"], split="test"))
    while len(test_instances) < n_reports:
        test_instances = test_instances + test_instances
    test_instances = test_instances[:n_reports]

    def run_pass():
        total = 0
        start = time.perf_counter()
        for probs, metas in predictor.score_instances(iter(test_instances)):
            total += len(metas)
        return total, time.perf_counter() - start

    run_pass()  # warmup: compile (one program per bucket) + tokenizer cache
    total, elapsed = run_pass()
    rps = total / elapsed

    # the baseline estimate is FLOP-derived at padded length 512 (the
    # reference pads essentially every batch to the cap — see module
    # docstring); scale only when the cap itself is overridden
    baseline = BASELINE_RPS_512 * (512.0 / seq_len)
    print(
        json.dumps(
            {
                "metric": "siamese_scoring_throughput",
                "value": round(rps, 1),
                "unit": "reports/sec",
                "vs_baseline": round(rps / baseline, 2),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
