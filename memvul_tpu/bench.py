"""Benchmark: Siamese anchor-bank scoring throughput on TPU.

Measures the north-star workload (SURVEY.md §6): stream issue reports
through the full inference path — BERT-base encode (bf16), anchor-bank
match against 129 anchors, per-anchor softmax + best-anchor reduce —
exactly what ``predict_memory`` does over the 1.2M-report corpus.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline (denominator). The reference repo publishes no throughput number
(BASELINE.md).  The GTX-3090 estimate: ~71 TFLOP/s dense fp16 tensor peak
at ~30% achieved MFU for PyTorch-1.8 BERT-base inference ≈ 21 TFLOP/s
effective; one report at eval length 512 costs ≈ 2·110e6·512 ≈ 1.13e11
FLOP → ≈ 190 reports/s.  MFU sensitivity (the free parameter): 20% → 127
rps, 30% → 190 rps, 40% → 253 rps; vs_baseline uses the middle estimate.

Why 190 stays the baseline for the mixed-length corpus: the reference
collates with AllenNLP's per-batch pad-to-longest at eval batch 512 in
stream order (reference: predict_memory.py:92-99,208).  Under any
long-tailed length distribution (~12% of reports at the 512 cap here) the
probability that a 512-report batch contains no capped report is
(0.88)^512 ≈ 1e-29 — every reference batch pads to 512, so its per-report
cost IS the 512-token cost.  Our length-binned batcher is the structural
win being measured.

Env knobs: BENCH_SEQ_LEN (cap, default 512), BENCH_BUCKETS ("auto" —
the default — derives padding-minimizing DP boundaries from a corpus
length sample, BENCH_BUCKET_COUNT of them, default 8: the static cost
model puts auto-8 at 1.339x emitted/true tokens vs hand-list 1.445x;
a comma list pins explicit boundaries; empty string = pad-to-cap mode),
BENCH_TOKENS (token budget per batch, default 262144 ≈ batch 512 at 512;
the on-chip sweep measured it ahead of 512k),
BENCH_REPORTS (default 32768), BENCH_ATTENTION (xla | flash, default xla),
BENCH_QUANT (int8_dynamic — route dense contractions through the MXU's
int8 path; same params, numerics bounded by the quantdrift proof),
BENCH_MODEL (base | tiny — tiny is plumbing-validation only),
BENCH_INFLIGHT (async device dispatch depth, default 2),
BENCH_PROFILE (dir — capture a jax.profiler trace of the timed pass),
BENCH_MICRO (anchor_match — run the isolated bank-match microbench,
fused Pallas kernel vs decomposed einsum, instead of the full scoring
pass, BENCH_MICRO_{B,A,D,ITERS} set its shape; serve — drive the online
scoring service (docs/serving.md) with closed-loop in-process clients
and report request throughput + latency percentiles,
BENCH_MICRO_REQUESTS/BENCH_MICRO_CLIENTS set the load,
BENCH_SERVE_MAX_BATCH/BENCH_SERVE_WAIT_MS the micro-batcher,
BENCH_SERVE_IMPL the dispatch strategy (bucketed | ragged | continuous |
cascade | ab — ab drives all four over one seeded schedule),
BENCH_CASCADE_BAND="low,high" the cascade leg's fp32 rescue band,
BENCH_SERVE_CACHE=1 the admission-cache leg — duplicate-heavy seeded
dedup schedule through a content-addressed cache
(BENCH_SERVE_CACHE_CAPACITY/BENCH_SERVE_CACHE_UNIQUE size it), the
record gaining hit-rate / device-calls-avoided / real-tokens-saved;
train_step — A/B the Siamese train step's collation, pad-to-max vs
bucketed+anchor-dedup over one identical pair stream, reporting padded-
vs real-token throughput for both paths,
BENCH_TRAIN_{STEPS,BATCH,ACCUM} set the load — docs/training_throughput.md;
corpus — sharded full-corpus scoring through the supervised worker fleet,
BENCH_CORPUS_SHARDS/BENCH_CORPUS_REPORTS set the shape —
docs/full_corpus.md;
tune — run the offline autotuner in-process (docs/tuning.md) and emit
one tuned-vs-default record over the train_step and serve microbenches
with the parity-gate evidence, BENCH_TUNE=1 is an alias,
BENCH_TUNE_MODE/BENCH_TUNE_CASCADE/BENCH_TUNE_OUT steer it),
BENCH_PHASE_TIMEOUT (per-phase watchdog deadline inside the child,
default 600 s, 0 disables — a stuck phase emits a parseable JSON
failure record naming the phase, its last-heartbeat age (stuck phase vs
slow backend, cf. BENCH_r05) and exits 124 fast instead of sitting
silent until the external ``timeout`` kill; the supervisor retries it),
BENCH_TELEMETRY_DIR (write a telemetry run dir — phase spans in
events.jsonl, HEARTBEAT.json liveness, telemetry.json rollup — readable
via ``python -m memvul_tpu telemetry-report``; docs/observability.md),
BENCH_LINT=1 (the supervisor first prints one ``{"metric": "lint"}``
JSON record from the static-analysis engine — docs/static_analysis.md —
so a sweep collects code-health alongside throughput).

Supervision. The TPU backend behind the axon tunnel can be transiently
UNAVAILABLE (it was at the round-2 snapshot, which lost the headline
number) or silently WEDGED — a dead client's lease held server-side makes
the first device op hang, not error, for tens of minutes.  ``main``
therefore first waits for the device with cheap short-timeout probe
children (BENCH_DEVICE_WAIT total seconds, default 1800; BENCH_PROBE_TIMEOUT
per probe, default 240 — generous vs observed ~20 s healthy init so a slow
but healthy backend is never killed mid-op; 0 disables), then runs the
measurement in a child
process with a hard per-attempt deadline and retries backend-initialisation
failures with backoff (BENCH_ATTEMPTS, default 3; BENCH_ATTEMPT_TIMEOUT
seconds, default 1500).  On unrecoverable failure it still prints exactly
one JSON line — ``{"metric": ..., "value": 0.0, ..., "error": "..."}`` —
never a bare traceback, and kills the child's whole process group so no
stray process is left holding the TPU.
"""

import contextlib
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import threading
import time

from memvul_tpu.resilience.retry import RETRYABLE_MARKERS, RetryPolicy

BASELINE_RPS_512 = 190.0  # estimated GTX-3090 throughput at seq_len 512 (above)

# The transient-failure classification now lives in resilience/retry.py
# (shared with the corpus-scoring path — the backend that answers
# UNAVAILABLE to the bench is the one that throws it at batch 900k of a
# scoring run).  The old private name stays as an alias for external
# importers.
_RETRYABLE_MARKERS = RETRYABLE_MARKERS

_CHILD_ENV_FLAG = "MEMVUL_BENCH_CHILD"


def _metric_name() -> str:
    micro = os.environ.get("BENCH_MICRO")
    if not micro and os.environ.get("BENCH_TUNE") == "1":
        micro = "tune"  # BENCH_TUNE=1 alias for BENCH_MICRO=tune
    return f"{micro}_microbench" if micro else "siamese_scoring_throughput"


def _program_blocks() -> dict:
    """Per-program compile/cost rows + the roofline summary for a bench
    record (telemetry/programs.py).  Off-TPU the rows still carry
    analyzed FLOPs/compile times with ``interpret_only`` set, so a CPU
    smoke run and a TPU run emit the same record shape.  Empty when the
    bench path registered nothing (keeps old record shapes intact)."""
    from memvul_tpu.telemetry.programs import get_program_registry

    registry = get_program_registry()
    programs = registry.snapshot()
    if not programs:
        return {}
    roof = registry.roofline()
    return {
        "programs": [
            {
                "key": p["key"],
                "scope": p["scope"],
                "compile_s": p["compile_s"],
                "flops": p["flops"],
                "bytes_accessed": p["bytes_accessed"],
                "hbm_bytes": p["hbm_bytes"],
                "invocations": p["invocations"],
                "device_time_s": p["device_time_s"],
                "mfu": p["mfu"],
            }
            for p in programs
        ],
        "xla": {
            "device_kind": roof["device_kind"],
            "interpret_only": roof["interpret_only"],
            "mfu": roof["mfu"],
            "membw_util": roof["membw_util"],
            "flops_total": roof["flops_total"],
            "device_time_s": roof["device_time_s"],
        },
    }


class _PhaseWatchdog:
    """Hard per-phase deadline inside the bench child.

    The round-5 run died at the external ``timeout`` kill (rc=124) with
    nothing on stdout: a wedged backend hung one device op for the whole
    attempt budget and the only evidence was the driver's SIGKILL.  This
    watchdog runs on a daemon thread, so when a phase (workspace build,
    anchor encode, warmup, the timed pass) exceeds its deadline it can
    still emit a parseable JSON failure record naming the stuck phase
    and hard-exit 124 — even while the main thread is blocked inside a
    device op that will never return.  ``os._exit`` (not sys.exit) is
    deliberate: a wedged PJRT client may hang interpreter teardown too.

    The record carries ``"error"``/``"watchdog_timeout"`` so the
    supervisor's result extraction skips it and retries the attempt
    (the marker is in ``_RETRYABLE_MARKERS``).
    """

    def __init__(self, timeout: float, metric: str):
        self.timeout = timeout
        self.metric = metric

    @contextlib.contextmanager
    def phase(self, name: str):
        # every phase is a telemetry span: the liveness phase + progress
        # clock update even without a run dir, and with BENCH_TELEMETRY_DIR
        # set the spans land in events.jsonl for telemetry-report
        from memvul_tpu.telemetry import get_registry

        if self.timeout <= 0:  # BENCH_PHASE_TIMEOUT=0 disables
            with get_registry().span(f"bench.{name}"):
                yield
            return
        timer = threading.Timer(self.timeout, self._expire, args=(name,))
        timer.daemon = True
        timer.start()
        try:
            with get_registry().span(f"bench.{name}"):
                yield
        finally:
            timer.cancel()

    def _expire(self, name: str) -> None:
        from memvul_tpu.telemetry import get_registry

        # last-heartbeat age separates "stuck phase" (age ≈ the whole
        # phase timeout: nothing progressed since the phase opened) from
        # "slow backend" (small age: batches were still completing when
        # the deadline hit) — the rc=124 ambiguity of BENCH_r05
        age = get_registry().heartbeat_age_s()
        record = {
            "metric": self.metric,
            "value": 0.0,
            "unit": "reports/sec",
            "vs_baseline": 0.0,
            "error": f"watchdog: phase {name!r} exceeded {self.timeout:.0f}s",
            "phase": name,
            "watchdog_timeout": True,
            "heartbeat_age_s": round(age, 1),
        }
        # program-registry attribution: a recent compile with a small
        # age means the phase is wedged INSIDE (or right after) that
        # key's kernel.lower/compile; no compiles at all means the hang
        # predates the first program — different bugs, same rc=124
        try:
            from memvul_tpu.telemetry.programs import get_program_registry

            last = get_program_registry().last_compile()
            if last is not None:
                record["last_compile_key"] = last["key"]
                record["last_compile_age_s"] = round(last["age_s"], 1)
        except Exception:  # the failure record must always emit
            pass
        sys.stdout.write(json.dumps(record) + "\n")
        sys.stdout.flush()
        sys.stderr.write(
            f"bench watchdog: phase {name!r} exceeded {self.timeout:.0f}s; "
            "aborting attempt\n"
        )
        sys.stderr.flush()
        os._exit(124)


def _watchdog() -> _PhaseWatchdog:
    return _PhaseWatchdog(
        float(os.environ.get("BENCH_PHASE_TIMEOUT", "600")), _metric_name()
    )


def _run_bench() -> None:
    if os.environ.get("BENCH_MICRO") == "anchor_match":
        _run_anchor_match_micro()
        return
    if os.environ.get("BENCH_MICRO") == "serve":
        _run_serve_micro()
        return
    if os.environ.get("BENCH_MICRO") == "train_step":
        _run_train_step_micro()
        return
    if os.environ.get("BENCH_MICRO") == "corpus":
        _run_corpus_micro()
        return
    if os.environ.get("BENCH_MICRO") == "tune" or (
        not os.environ.get("BENCH_MICRO")
        and os.environ.get("BENCH_TUNE") == "1"
    ):
        _run_tune_micro()
        return
    if os.environ.get("BENCH_MICRO"):
        raise ValueError(
            f"unknown BENCH_MICRO mode {os.environ['BENCH_MICRO']!r} "
            "(known: anchor_match, corpus, serve, train_step, tune)"
        )
    import numpy as np
    import jax

    from memvul_tpu.utils.platform import enable_compilation_cache, honor_platform_env

    honor_platform_env()
    enable_compilation_cache()
    import jax.numpy as jnp

    from memvul_tpu.data.synthetic import build_workspace
    from memvul_tpu.data.readers import MemoryReader
    from memvul_tpu.evaluate.predict_memory import SiamesePredictor
    from memvul_tpu.models import BertConfig, MemoryModel

    watchdog = _watchdog()

    seq_len = int(os.environ.get("BENCH_SEQ_LEN", "512"))
    # default flipped to auto-8 in round 5: simulating the REAL batcher
    # over the realistic 32k-report corpus at the 256k token budget emits
    # 1.339x the true token count with auto-8 boundaries vs 1.445x with
    # the hand 64/128/256/512 (and 1.391x with auto-6) — ~7% less device
    # work at identical batch counts (26-28); the staged on-chip sweep
    # (bench_auto8 vs bench_hand16k) confirms the flip with wall-clock
    buckets_env = os.environ.get("BENCH_BUCKETS", "auto")
    auto_bucket_mode = buckets_env == "auto"
    if auto_bucket_mode:
        buckets = None  # derived from a corpus length sample below
    else:
        buckets = (
            tuple(int(b) for b in buckets_env.split(",") if b) if buckets_env else None
        )
        if buckets:
            buckets = tuple(b for b in buckets if b <= seq_len) or (seq_len,)
    # token budget per batch: 256k (batch 512 at seq 512, scaling up to
    # 4096 at seq 64) measured best on v5e — larger budgets waste rows on
    # partially-filled bucket tails, smaller ones under-fill the MXU;
    # sweep on hardware: 512k → 11.5×, 256k → 12.3× at 32k reports
    tokens_per_batch = int(os.environ.get("BENCH_TOKENS", str(256 * 1024)))
    n_reports = int(os.environ.get("BENCH_REPORTS", "32768"))
    n_anchors = 129  # reference external-memory size (utils.py:347)

    with watchdog.phase("workspace"):
        ws = build_workspace(
            tempfile.mkdtemp(),
            seed=0,
            num_projects=8,
            reports_per_project=max(4, n_reports // 8),
            realistic_lengths=True,
        )
    # BENCH_MODEL=tiny swaps in the 2-layer test geometry so the FULL
    # child path (workspace → anchors → bucketed scoring → JSON line) can
    # be exercised off-TPU in seconds; the recorded number is only
    # meaningful at the default "base" geometry
    if os.environ.get("BENCH_MODEL", "base") == "tiny":
        cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
        if seq_len > cfg.max_position_embeddings:
            seq_len = cfg.max_position_embeddings
            if buckets:
                buckets = tuple(b for b in buckets if b <= seq_len) or (seq_len,)
            print(
                f"tiny geometry: clamped BENCH_SEQ_LEN to {seq_len}",
                file=sys.stderr,
            )
    else:
        cfg = BertConfig.base(
            vocab_size=max(30522, ws["tokenizer"].vocab_size), dtype=jnp.bfloat16
        )
        if seq_len > cfg.max_position_embeddings:
            # long-context rows (configs/config_memory_longctx.json is the
            # production shape): extend the position table to the cap —
            # bench params are random-init, so only the geometry matters
            cfg = cfg.replace(max_position_embeddings=seq_len)
    attn = os.environ.get("BENCH_ATTENTION", "xla")
    if attn != "xla":
        cfg = cfg.replace(attention_impl=attn)
    quant = os.environ.get("BENCH_QUANT")
    if quant:
        cfg = cfg.replace(quant=quant)
    model = MemoryModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    # first device op: where a wedged backend historically hangs
    with watchdog.phase("model_init"):
        params = model.init(jax.random.PRNGKey(0), dummy, dummy)

    reader = MemoryReader(
        cve_path=ws["paths"]["cve"], anchor_path=ws["paths"]["anchors"]
    )
    test_instances = list(reader.read(ws["paths"]["test"], split="test"))
    while len(test_instances) < n_reports:
        test_instances = test_instances + test_instances
    test_instances = test_instances[:n_reports]

    if auto_bucket_mode:
        # boundaries at the corpus's natural knees instead of hand-picked
        # powers of two — same sampling recipe as the `"buckets": "auto"`
        # evaluation-config path so bench and production eval measure one
        # bucketing policy.  8 boundaries is the measured knee (emitted/
        # true tokens 1.339x vs 1.391x at 6, 1.445x hand — the cost model
        # above); more buckets add per-shape compile cost for thin gains
        from memvul_tpu.build import _auto_buckets_for_corpus

        n_buckets = int(os.environ.get("BENCH_BUCKET_COUNT", "8"))
        buckets = _auto_buckets_for_corpus(
            reader, ws["tokenizer"], ws["paths"]["test"], seq_len,
            n_buckets=n_buckets,
        )
        print(f"auto buckets: {buckets}", file=sys.stderr)

    predictor = SiamesePredictor(
        model,
        params,
        ws["tokenizer"],
        batch_size=tokens_per_batch // seq_len,
        max_length=seq_len,
        buckets=buckets,
        tokens_per_batch=tokens_per_batch if buckets else None,
    )
    # 129-anchor bank from synthetic anchor texts (cycled to reference size)
    base_anchors = list(ws["anchors"].items())
    instances = []
    for i in range(n_anchors):
        cat, text = base_anchors[i % len(base_anchors)]
        instances.append(
            {"text1": text, "meta": {"label": f"{cat}#{i}", "type": "golden"}}
        )
    # includes the AOT shape warmup: every bucket program compiles here,
    # not at its first mid-stream occurrence
    with watchdog.phase("anchor_encode"):
        predictor.encode_anchors(instances)

    inflight = int(os.environ.get("BENCH_INFLIGHT", "2"))

    def run_pass():
        total = 0
        start = time.perf_counter()
        for probs, metas in predictor.score_instances(
            iter(test_instances), inflight=inflight
        ):
            total += len(metas)
        return total, time.perf_counter() - start

    from memvul_tpu.utils.profiling import trace_context

    with watchdog.phase("warmup_pass"):
        run_pass()  # warmup: tokenizer cache + any shape the AOT set missed
    # BENCH_PROFILE=<dir>: capture a jax.profiler trace of the timed pass
    with watchdog.phase("timed_pass"):
        with trace_context(os.environ.get("BENCH_PROFILE")):
            total, elapsed = run_pass()
    rps = total / elapsed

    # the baseline estimate is FLOP-derived at padded length 512 (the
    # reference pads essentially every batch to the cap — see module
    # docstring); scale only when the cap itself is overridden
    baseline = BASELINE_RPS_512 * (512.0 / seq_len)
    # the denominator is an MFU *estimate* (20-40% band around the 30%
    # point, module docstring); the headline must always carry that
    # uncertainty, so emit the vs_baseline band over the MFU range
    point = rps / baseline
    lo = point * (0.30 / 0.40)  # baseline at 40% MFU (fastest plausible GPU)
    hi = point * (0.30 / 0.20)  # baseline at 20% MFU (slowest plausible GPU)
    print(
        json.dumps(
            {
                "metric": "siamese_scoring_throughput",
                "value": round(rps, 1),
                "unit": "reports/sec",
                "vs_baseline": round(rps / baseline, 2),
                "vs_baseline_band": [round(lo, 2), round(hi, 2)],
                # self-describing: which knobs produced this number, so a
                # sweep's artifacts can't be cross-compared blind
                "config": {
                    "model": os.environ.get("BENCH_MODEL", "base"),
                    "seq_len": seq_len,
                    "buckets": list(buckets) if buckets else None,
                    "tokens_per_batch": tokens_per_batch,
                    "reports": n_reports,
                    "attention": attn,
                    "quant": quant,
                    "inflight": inflight,
                },
                **_program_blocks(),
            }
        )
    )


def _run_anchor_match_micro() -> None:
    """BENCH_MICRO=anchor_match: the bank-match op in isolation.

    Times the fused Pallas anchor-match against the decomposed-einsum
    XLA formulation at the production shape (B=512 reports × A=129
    anchors × D=512, overridable via BENCH_MICRO_{B,A,D,ITERS}) and
    prints one JSON line reporting both variants plus the analytic
    HBM-traffic estimates the kernel exists to eliminate.

    Off-TPU the "fused" variant measures what production dispatch
    actually runs there — the jnp decomposition (``fused_backend`` says
    so in the record); interpret-mode timings are meaningless and are
    opt-in via BENCH_MICRO_INTERPRET=1 for kernel-logic smoke only.
    """
    from memvul_tpu.utils.platform import (
        enable_compilation_cache,
        honor_platform_env,
        is_tpu_backend,
    )

    honor_platform_env()
    enable_compilation_cache()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from memvul_tpu.ops.pallas.anchor_match import (
        anchor_match_reference,
        fused_anchor_match,
    )

    watchdog = _watchdog()
    b = int(os.environ.get("BENCH_MICRO_B", "512"))
    a = int(os.environ.get("BENCH_MICRO_A", "129"))
    d = int(os.environ.get("BENCH_MICRO_D", "512"))
    iters = int(os.environ.get("BENCH_MICRO_ITERS", "50"))
    interpret = os.environ.get("BENCH_MICRO_INTERPRET") == "1"
    c = 2

    with watchdog.phase("micro_setup"):
        on_tpu = is_tpu_backend()
        dtype = jnp.bfloat16 if on_tpu else jnp.float32
        rng = np.random.default_rng(0)
        u = jax.device_put(jnp.asarray(rng.normal(size=(b, d)), dtype))
        v = jax.device_put(jnp.asarray(rng.normal(size=(a, d)), dtype))
        k = jax.device_put(jnp.asarray(rng.normal(size=(3 * d, c)) * 0.1, dtype))

    if on_tpu or interpret:
        fused_backend = "pallas-interpret" if not on_tpu else "pallas"
        fused = jax.jit(
            lambda u, v, k: fused_anchor_match(u, v, k, interpret=not on_tpu)
        )
        if interpret:
            iters = min(iters, 2)  # interpret mode is orders slower
    else:
        # production dispatch on this backend IS the decomposition
        fused_backend = "xla-fallback"
        fused = jax.jit(anchor_match_reference)
    decomposed = jax.jit(anchor_match_reference)

    def rep(fn):
        start = time.perf_counter()
        for _ in range(iters):
            out = fn(u, v, k)
        out.block_until_ready()
        return (time.perf_counter() - start) / iters

    # compile + warm BOTH variants before timing either, then interleave
    # the timed reps and keep each variant's best — a fresh process ramps
    # thread pools/allocator over the first calls, which would otherwise
    # be billed entirely to whichever variant ran first
    with watchdog.phase("micro_compile"):
        for fn in (decomposed, fused):
            fn(u, v, k).block_until_ready()
    with watchdog.phase("micro_timing"):
        xla_s, fused_s = float("inf"), float("inf")
        for _ in range(3):
            xla_s = min(xla_s, rep(decomposed))
            fused_s = min(fused_s, rep(fused))

    # analytic HBM-traffic estimate: the decomposed path writes the
    # [B, A, D] abs-diff then reads it back for the einsum; the fused
    # path touches inputs once and the [B, A, C] logits once
    sz = jnp.dtype(dtype).itemsize
    io_bytes = (b * d + a * d + 3 * d * c) * sz + b * a * c * sz
    bytes_decomposed = io_bytes + 2 * b * a * d * sz
    print(
        json.dumps(
            {
                "metric": "anchor_match_microbench",
                "value": round(xla_s / fused_s, 3),
                "unit": "x (decomposed_ms / fused_ms)",
                "fused_ms": round(fused_s * 1e3, 4),
                "decomposed_ms": round(xla_s * 1e3, 4),
                "matches_per_s_fused": round(b * a / fused_s),
                "matches_per_s_decomposed": round(b * a / xla_s),
                "hbm_bytes_est": {
                    "decomposed": bytes_decomposed,
                    "fused": io_bytes,
                    "ratio": round(bytes_decomposed / io_bytes, 1),
                },
                "config": {
                    "B": b, "A": a, "D": d, "iters": iters,
                    "dtype": str(jnp.dtype(dtype)),
                    "fused_backend": fused_backend,
                },
            }
        )
    )


def _run_train_step_micro() -> None:
    """BENCH_MICRO=train_step: Siamese train-step throughput, pad-to-max
    vs bucketed+dedup collation (docs/training_throughput.md).

    Runs the SAME epoch pair stream (identical reader seed → identical
    pairs) through two MemoryTrainers that differ only in collation:
    ``train_buckets=None`` (the pre-PR-5 pad-to-max baseline) vs the
    default bucket grid with in-batch anchor deduplication.  Each path
    gets one warmup epoch (compiles) and one timed epoch over the same
    stream, then one JSON line reports wall-clock plus BOTH token
    throughputs per path — padded tokens/s is what the device computed,
    real tokens/s is what the corpus contained; the bucketed path's win
    is real-token throughput at a lower padded-token bill.

    Knobs: BENCH_TRAIN_STEPS (optimizer steps per epoch, default 16),
    BENCH_TRAIN_BATCH (default 32), BENCH_TRAIN_ACCUM (default 2),
    BENCH_TRAIN_REPORTS (workspace reports per project, default 256),
    BENCH_SEQ_LEN (max_length cap, default 512), BENCH_MODEL
    (base | tiny — tiny exercises the full path off-TPU in seconds; the
    recorded number is only meaningful at base geometry on hardware).
    """
    import numpy as np
    import jax

    from memvul_tpu.utils.platform import enable_compilation_cache, honor_platform_env

    honor_platform_env()
    enable_compilation_cache()
    import jax.numpy as jnp

    from memvul_tpu.data.readers import MemoryReader
    from memvul_tpu.data.synthetic import build_workspace
    from memvul_tpu.models import BertConfig, MemoryModel
    from memvul_tpu.training.trainer import MemoryTrainer, TrainerConfig

    watchdog = _watchdog()
    steps = int(os.environ.get("BENCH_TRAIN_STEPS", "16"))
    batch = int(os.environ.get("BENCH_TRAIN_BATCH", "32"))
    accum = int(os.environ.get("BENCH_TRAIN_ACCUM", "2"))
    seq_len = int(os.environ.get("BENCH_SEQ_LEN", "512"))
    per_project = int(os.environ.get("BENCH_TRAIN_REPORTS", "256"))

    with watchdog.phase("workspace"):
        ws = build_workspace(
            tempfile.mkdtemp(), seed=0, num_projects=8,
            reports_per_project=per_project, realistic_lengths=True,
        )
    if os.environ.get("BENCH_MODEL", "base") == "tiny":
        cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
        seq_len = min(seq_len, cfg.max_position_embeddings)
    else:
        cfg = BertConfig.base(
            vocab_size=max(30522, ws["tokenizer"].vocab_size), dtype=jnp.bfloat16
        )
        if seq_len > cfg.max_position_embeddings:
            cfg = cfg.replace(max_position_embeddings=seq_len)
    model = MemoryModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    with watchdog.phase("model_init"):
        params = model.init(jax.random.PRNGKey(0), dummy, dummy)

    def run_path(name: str, **cfg_kw):
        reader = MemoryReader(
            cve_path=ws["paths"]["cve"], anchor_path=ws["paths"]["anchors"],
            sample_neg=0.5, seed=2021,
        )
        trainer = MemoryTrainer(
            model,
            # each path gets its own buffers: the jitted step DONATES
            # params/opt-state, so sharing one pytree across the A/B
            # would hand path B already-deleted arrays
            jax.tree_util.tree_map(jnp.array, params),
            ws["tokenizer"], reader,
            train_path=ws["paths"]["train"],
            config=TrainerConfig(
                batch_size=batch, grad_accum=accum, max_length=seq_len,
                steps_per_epoch=steps, num_epochs=1, warmup_steps=1,
                serialization_dir=None, **cfg_kw,
            ),
        )
        # warmup epoch compiles every stack shape; the timed epoch
        # replays the SAME epoch-0 stream (train_epoch does not advance
        # trainer.epoch), so both epochs and both paths see one stream
        with watchdog.phase(f"{name}_warmup"):
            trainer.train_epoch()
        with watchdog.phase(f"{name}_timed"):
            m = trainer.train_epoch()
        return {
            "epoch_s": round(m["epoch_seconds"], 4),
            "steps": m["num_steps"],
            "padded_tokens": m["padded_tokens"],
            "real_tokens": m["real_tokens"],
            "padded_tokens_per_s": round(m["tokens_per_sec"], 1),
            "real_tokens_per_s": round(m["real_tokens_per_sec"], 1),
            "compiled_step_shapes": trainer.train_trace_count,
        }

    pad = run_path("pad_to_max", train_buckets=None, dedup_anchors=False)
    bucketed = run_path("bucketed_dedup")  # defaults: pow2 grid + dedup

    print(
        json.dumps(
            {
                "metric": "train_step_microbench",
                # headline: wall-clock speedup over the identical stream
                "value": round(pad["epoch_s"] / max(bucketed["epoch_s"], 1e-9), 3),
                "unit": "x (pad_to_max_s / bucketed_dedup_s)",
                "vs_baseline": 0.0,  # no external training baseline (BASELINE.md)
                "pad_to_max": pad,
                "bucketed_dedup": bucketed,
                "config": {
                    "model": os.environ.get("BENCH_MODEL", "base"),
                    "seq_len": seq_len,
                    "batch_size": batch,
                    "grad_accum": accum,
                    "steps_per_epoch": steps,
                },
                **_program_blocks(),
            }
        )
    )


def _run_tune_micro() -> None:
    """BENCH_MICRO=tune (or BENCH_TUNE=1): the offline autotuner as a
    bench leg (docs/tuning.md) — one tuned-vs-default JSON record for
    the chip-window sweep.

    Runs :func:`memvul_tpu.tuning.autotune.run_tune` in-process over
    the slim knob grids, then reports the tuned winner against the
    hand-set defaults on BOTH microbenches: real-token train throughput
    (the train_step harness contract) and serve requests/sec, with the
    parity-gate refusal counts proving scores were never traded for
    speed.  The headline ``value`` is the geometric mean of the
    available tuned/default speedups.

    Knobs: BENCH_MODEL (tiny | base), BENCH_SEQ_LEN,
    BENCH_TUNE_MODE (train | serve | all, default all),
    BENCH_TUNE_CASCADE=1 (also tune the rescue band),
    BENCH_TUNE_OUT (persist the tuned profile store there),
    BENCH_TRAIN_STEPS / BENCH_TRAIN_BATCH (training microbench load),
    BENCH_MICRO_REQUESTS / BENCH_MICRO_CLIENTS /
    BENCH_SERVE_MAX_BATCH (serving microbench load).
    """
    from memvul_tpu.utils.platform import enable_compilation_cache, honor_platform_env

    honor_platform_env()
    enable_compilation_cache()

    from memvul_tpu.tuning.autotune import run_tune

    watchdog = _watchdog()
    mode = os.environ.get("BENCH_TUNE_MODE", "all")
    bench_kwargs = dict(
        seed=0,
        model_size=os.environ.get("BENCH_MODEL", "tiny"),
        seq_len=int(os.environ.get("BENCH_SEQ_LEN", "128")),
        batch_size=int(os.environ.get("BENCH_TRAIN_BATCH", "8")),
        steps_per_epoch=int(os.environ.get("BENCH_TRAIN_STEPS", "4")),
        n_requests=int(os.environ.get("BENCH_MICRO_REQUESTS", "96")),
        n_clients=int(os.environ.get("BENCH_MICRO_CLIENTS", "4")),
        max_batch=int(os.environ.get("BENCH_SERVE_MAX_BATCH", "8")),
    )
    with watchdog.phase("tune_sweep"):
        record = run_tune(
            mode,
            allow_unknown_device=True,  # CPU harness: measurement-only
            out_dir=os.environ.get("BENCH_TUNE_OUT") or None,
            cascade=os.environ.get("BENCH_TUNE_CASCADE") == "1",
            bench_kwargs=bench_kwargs,
            train_space_kwargs=dict(
                bucket_grids=[None, "pow2"], dedup_options=(True,),
                prefetch_depths=(2, 8),
            ),
            serve_space_kwargs=dict(
                wait_ms_options=(2.0, 5.0), budget_factors=(2, 4),
                rows_factors=(1,),
            ),
        )

    speedups = [
        s for s in (
            (record.get("train") or {}).get("speedup_real_tokens"),
            (record.get("serve") or {}).get("speedup_rps"),
        ) if s
    ]
    value = round(
        float(np_geomean(speedups)) if speedups else 0.0, 3
    )

    def _leg(section, metric_key):
        block = record.get(section) or {}
        winner = block.get("winner") or {}
        return {
            "default_knobs": block.get("default_knobs"),
            "default": block.get("default_bench"),
            "tuned_knobs": (
                winner.get("prune", {}).get("candidate", {}).get("knobs")
            ),
            "tuned": winner.get("bench"),
            "speedup": block.get(metric_key),
            "parity": (winner.get("parity") or {}).get("passed"),
        }

    parity_refused = sum(
        1
        for section in ("train", "serve")
        for row in (record.get(section) or {}).get("candidates", [])
        if row.get("parity") and not row["parity"]["passed"]
    )
    print(
        json.dumps(
            {
                "metric": "tune_microbench",
                "value": value,
                "unit": "x geomean(tuned/default: train real-tokens, serve rps)",
                "vs_baseline": 0.0,  # no external tuning baseline (BASELINE.md)
                "device_class": record.get("device_class"),
                "mode": mode,
                "train": _leg("train", "speedup_real_tokens"),
                "serve": _leg("serve", "speedup_rps"),
                "cascade": record.get("cascade"),
                "parity_refused": parity_refused,
                "profile_path": record.get("profile_path"),
                "config": record.get("bench"),
                **_program_blocks(),
            }
        )
    )


def np_geomean(values):
    """Geometric mean without importing numpy at module scope."""
    import math

    vals = [float(v) for v in values if v and v > 0]
    if not vals:
        return 0.0
    return math.exp(sum(math.log(v) for v in vals) / len(vals))


def _run_serve_micro() -> None:
    """BENCH_MICRO=serve: latency/throughput of the online scoring
    service (docs/serving.md).

    Closed-loop load: BENCH_MICRO_CLIENTS in-process client threads each
    score their share of BENCH_MICRO_REQUESTS mixed-length reports
    through the micro-batcher (deadlines disabled — this measures the
    service, not the shed path) and record end-to-end latencies.  One
    JSON line reports requests/sec plus the latency percentiles an SLO
    would be written against.  BENCH_MODEL=tiny exercises the full path
    off-TPU in seconds; the recorded number is only meaningful at base
    geometry on hardware.

    Router mode (BENCH_SERVE_REPLICAS > 1): the same load drives a
    :class:`~memvul_tpu.serving.ReplicaRouter` over that many replica
    services through the SLO harness (serving/loadgen.py) —
    BENCH_SERVE_PATTERN picks the arrival process (closed, poisson,
    burst, diurnal, slowloris; BENCH_SERVE_RPS the open-loop rate) —
    and the record gains per-cause shed/error counts, per-replica
    utilization, and the fleet-wide counter invariant.
    """
    import queue as _queue

    import numpy as np
    import jax

    from memvul_tpu.utils.platform import enable_compilation_cache, honor_platform_env

    honor_platform_env()
    enable_compilation_cache()
    import jax.numpy as jnp

    from memvul_tpu.data.readers import MemoryReader
    from memvul_tpu.data.synthetic import build_workspace
    from memvul_tpu.evaluate.predict_memory import SiamesePredictor
    from memvul_tpu.models import BertConfig, MemoryModel
    from memvul_tpu.serving import InprocessClient, ScoringService, ServiceConfig

    watchdog = _watchdog()
    n_requests = int(os.environ.get("BENCH_MICRO_REQUESTS", "2048"))
    n_clients = int(os.environ.get("BENCH_MICRO_CLIENTS", "8"))
    max_batch = int(os.environ.get("BENCH_SERVE_MAX_BATCH", "16"))
    max_wait_ms = float(os.environ.get("BENCH_SERVE_WAIT_MS", "5"))
    seq_len = int(os.environ.get("BENCH_SEQ_LEN", "512"))
    n_replicas = int(os.environ.get("BENCH_SERVE_REPLICAS", "1"))
    n_anchors = 129

    with watchdog.phase("workspace"):
        ws = build_workspace(
            tempfile.mkdtemp(), seed=0, num_projects=8,
            reports_per_project=64, realistic_lengths=True,
        )
    if os.environ.get("BENCH_MODEL", "base") == "tiny":
        cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
        seq_len = min(seq_len, cfg.max_position_embeddings)
    else:
        cfg = BertConfig.base(
            vocab_size=max(30522, ws["tokenizer"].vocab_size), dtype=jnp.bfloat16
        )
    buckets = tuple(
        b for b in (64, 128, 256, 512) if b <= seq_len
    ) or (seq_len,)
    model = MemoryModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    with watchdog.phase("model_init"):
        params = model.init(jax.random.PRNGKey(0), dummy, dummy)

    reader = MemoryReader(
        cve_path=ws["paths"]["cve"], anchor_path=ws["paths"]["anchors"]
    )
    texts = [
        inst["text1"] for inst in reader.read(ws["paths"]["test"], split="test")
    ]
    while len(texts) < n_requests:
        texts = texts + texts
    texts = texts[:n_requests]

    base_anchors = list(ws["anchors"].items())
    anchor_instances = [
        {
            "text1": base_anchors[i % len(base_anchors)][1],
            "meta": {"label": f"{base_anchors[i % len(base_anchors)][0]}#{i}",
                     "type": "golden"},
        }
        for i in range(n_anchors)
    ]
    # serve dispatch A/B (docs/ragged_serving.md, docs/serving.md,
    # docs/quantized_serving.md): BENCH_SERVE_IMPL picks the dispatch
    # strategy — "bucketed" (default), "ragged", "continuous",
    # "cascade" (int8 tier + fp32 rescue band), or "ab", which drives
    # ALL FOUR with the identical seeded schedule so one record
    # quantifies the padding win (real_token_utilization, ragged vs
    # bucketed), the admission win (queue_wait_gain, continuous vs
    # ragged), and the quantization win (cascade_rescore_rate + the
    # cascade leg's throughput vs bucketed)
    impl_mode = os.environ.get("BENCH_SERVE_IMPL", "bucketed")
    if impl_mode not in ("bucketed", "ragged", "continuous", "cascade", "ab"):
        raise SystemExit(
            "BENCH_SERVE_IMPL must be bucketed|ragged|continuous|cascade|ab, "
            f"got {impl_mode!r}"
        )
    # BENCH_CASCADE_BAND="low,high" sets the fp32 rescue band for the
    # cascade leg (default: config.SERVING_DEFAULTS)
    from memvul_tpu.config import SERVING_DEFAULTS as _serving_defaults

    band_env = os.environ.get("BENCH_CASCADE_BAND")
    if band_env:
        try:
            cascade_low, cascade_high = (float(x) for x in band_env.split(","))
        except ValueError:
            raise SystemExit(
                f"BENCH_CASCADE_BAND must be 'low,high', got {band_env!r}"
            )
    else:
        cascade_low = float(_serving_defaults["cascade_low"])
        cascade_high = float(_serving_defaults["cascade_high"])
    # the queue_wait comparison needs the per-stage trace histograms;
    # tracing stays off for single-leg runs so their numbers keep the
    # zero-overhead default (override with BENCH_SERVE_TRACE_RATE)
    trace_rate = float(
        os.environ.get(
            "BENCH_SERVE_TRACE_RATE", "1.0" if impl_mode == "ab" else "0.0"
        )
    )
    # content-addressed admission-cache leg (docs/multitenancy.md):
    # BENCH_SERVE_CACHE=1 sizes an exact-duplicate cache AND swaps the
    # text schedule to the seeded dedup pattern (serving/loadgen.py),
    # so the record measures what repeats are worth — hit rate, device
    # calls avoided, real tokens never tokenized.  Off by default: the
    # uncached record stays byte-identical.
    cache_on = os.environ.get("BENCH_SERVE_CACHE") == "1"
    cache_capacity = (
        int(os.environ.get("BENCH_SERVE_CACHE_CAPACITY", "512"))
        if cache_on else 0
    )
    if cache_on:
        from memvul_tpu.serving.loadgen import LoadConfig, request_texts

        texts = request_texts(
            LoadConfig(
                pattern="dedup",
                requests=n_requests,
                dedup_unique=int(
                    os.environ.get("BENCH_SERVE_CACHE_UNIQUE", "32")
                ),
                seed=0,
            ),
            texts,
        )
    service_config = ServiceConfig(
        max_batch=max_batch, max_wait_ms=max_wait_ms,
        max_queue=max(256, 2 * n_clients * max_batch),
        default_deadline_ms=0.0,  # measure latency, don't shed it
        trace_sample_rate=trace_rate,
        cache_capacity=cache_capacity,
    )
    token_budget = int(
        os.environ.get("BENCH_SERVE_TOKEN_BUDGET", str(4 * seq_len))
    )

    def build_service(registry=None, impl: str = "bucketed") -> ScoringService:
        if impl in ("ragged", "continuous"):
            kwargs = dict(
                score_impl=impl, token_budget=token_budget,
                max_rows_per_pack=max_batch,
            )
        elif impl == "cascade":
            kwargs = dict(
                score_impl="cascade", encoder_precision="int8",
                cascade_low=cascade_low, cascade_high=cascade_high,
            )
        else:
            kwargs = {}
        predictor = SiamesePredictor(
            model, params, ws["tokenizer"],
            batch_size=max_batch, max_length=seq_len, buckets=buckets,
            **kwargs,
        )
        predictor.encode_anchors(anchor_instances)
        return ScoringService(predictor, config=service_config, registry=registry)

    if n_replicas > 1 or os.environ.get("BENCH_SERVE_AUTOSCALE") == "1":
        router_impl = "bucketed" if impl_mode == "ab" else impl_mode
        _run_serve_router_micro(
            watchdog,
            lambda registry=None: build_service(registry, impl=router_impl),
            texts,
            n_requests=n_requests, n_clients=n_clients,
            n_replicas=n_replicas, seq_len=seq_len, buckets=buckets,
            max_batch=max_batch, max_wait_ms=max_wait_ms,
        )
        return

    def _drive_leg(impl: str, tsdb_cadence: float = 0.0,
                   tag: str = "") -> dict:
        """One closed-loop run: build the service for ``impl``, push the
        SAME seeded text schedule through it, return the leg record
        (rps, latency percentiles, and the padding ledger read from the
        leg's own registry).  ``tsdb_cadence > 0`` attaches a live
        :class:`~memvul_tpu.telemetry.timeseries.MetricsSampler` for the
        duration of the load — the sampler-overhead leg."""
        from memvul_tpu.telemetry.registry import TelemetryRegistry

        registry = TelemetryRegistry(enabled=True)
        with watchdog.phase(f"anchor_encode_{impl}{tag}"):
            service = build_service(registry=registry, impl=impl)
        sampler = None
        if tsdb_cadence > 0:
            from memvul_tpu.telemetry.timeseries import MetricsSampler

            sampler = MetricsSampler(
                service, cadence_s=tsdb_cadence, registry=registry
            )
        client = InprocessClient(service)
        work: "_queue.SimpleQueue" = _queue.SimpleQueue()
        for text in texts:
            work.put(text)
        latencies: list = []
        lat_lock = threading.Lock()
        errors = [0]

        def _client_loop():
            own: list = []
            while True:
                try:
                    text = work.get_nowait()
                except _queue.Empty:
                    break
                t0 = time.perf_counter()
                resp = client.score(text, deadline_ms=0)
                own.append(time.perf_counter() - t0)
                if resp["status"] != "ok":
                    errors[0] += 1
            with lat_lock:
                latencies.extend(own)

        # warmup trickle so pools/allocator ramp isn't billed to the load
        with watchdog.phase(f"serve_warmup_{impl}{tag}"):
            client.score(texts[0], deadline_ms=0)
        with watchdog.phase(f"serve_load_{impl}{tag}"):
            threads = [
                threading.Thread(target=_client_loop, daemon=True)
                for _ in range(n_clients)
            ]
            start = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - start
        if sampler is not None:
            sampler.stop()
        service.drain()
        snap = registry.snapshot()
        counters = snap["counters"]
        real = int(counters.get("serve.tokens_real", 0))
        padded = int(counters.get("serve.tokens_padded", 0))
        # admission latency (enqueued→coalesced), only populated when the
        # per-stage trace histograms are on (trace_rate > 0 — ab mode)
        qw = snap.get("histograms", {}).get("serve.queue_wait_s")
        queue_wait_ms = (
            {
                "p50": round(qw["p50"] * 1e3, 3),
                "p95": round(qw["p95"] * 1e3, 3),
            }
            if qw and qw.get("count") else None
        )
        lat_ms = np.sort(np.asarray(latencies)) * 1e3
        pct = (
            lambda q: round(float(np.percentile(lat_ms, q)), 3)
            if len(lat_ms) else None
        )
        leg = {
            "impl": impl,
            "requests_per_sec": round(n_requests / elapsed, 1),
            "latency_ms": {
                "p50": pct(50), "p95": pct(95), "p99": pct(99),
                "max": round(float(lat_ms[-1]), 3) if len(lat_ms) else None,
                "mean": round(float(lat_ms.mean()), 3) if len(lat_ms) else None,
            },
            "errors": errors[0],
            # the padding ledger: tokens requests carried vs token slots
            # the dispatched shapes paid for — the FLOP-waste fraction
            # the ragged path exists to reclaim
            "real_tokens": real,
            "padded_tokens": padded,
            "real_token_utilization": (
                round(real / padded, 4) if padded else None
            ),
            "queue_wait_ms": queue_wait_ms,
        }
        if sampler is not None:
            ts = snap.get("histograms", {}).get("tsdb.sample_s")
            leg["tsdb"] = {
                "cadence_s": tsdb_cadence,
                "samples": int(counters.get("tsdb.samples", 0)),
                "sample_errors": int(counters.get("tsdb.sample_errors", 0)),
                "series": sampler.store.series_count,
                "sample_ms": (
                    {"mean": round(ts["mean"] * 1e3, 3),
                     "p95": round(ts["p95"] * 1e3, 3)}
                    if ts and ts.get("count") else None
                ),
            }
        if cache_on:
            # the dedup ledger: a hit IS a device call avoided (the
            # response is rebuilt from the cached payload without a
            # dispatch), and tokens_saved is the real-token reduction —
            # work the tokenizer+device never saw
            hits = int(counters.get("cache.hits", 0))
            misses = int(counters.get("cache.misses", 0))
            leg["cache"] = {
                "capacity": cache_capacity,
                "hits": hits,
                "misses": misses,
                "hit_rate": (
                    round(hits / (hits + misses), 4)
                    if (hits + misses) else None
                ),
                "device_calls_avoided": hits,
                "real_tokens_saved": int(
                    counters.get("cache.tokens_saved", 0)
                ),
                "evictions": int(counters.get("cache.evictions", 0)),
            }
        if impl == "cascade":
            # the quantization ledger: how much traffic the int8 tier
            # answered alone vs re-dispatched into the fp32 rescue band
            rescored = int(counters.get("serve.cascade_rescored", 0))
            shortcut = int(counters.get("serve.cascade_shortcircuit", 0))
            leg["cascade_rescored"] = rescored
            leg["cascade_shortcircuit"] = shortcut
            leg["cascade_rescore_rate"] = (
                round(rescored / (rescored + shortcut), 4)
                if (rescored + shortcut) else None
            )
            leg["cascade_band"] = [cascade_low, cascade_high]
        return leg

    legs = (
        ["bucketed", "ragged", "continuous", "cascade"] if impl_mode == "ab"
        else [impl_mode]
    )
    records = [_drive_leg(impl) for impl in legs]
    by_leg = {leg["impl"]: leg for leg in records}
    # the ab headline stays the continuous leg (the pre-cascade primary,
    # so the metric's meaning is stable across records); single-leg runs
    # report their own leg
    primary = by_leg["continuous"] if impl_mode == "ab" else records[-1]
    # TSDB sampler-overhead leg (ROADMAP chip-window item): re-drive the
    # primary impl with a live MetricsSampler attached and report
    # on-vs-off; the "0.0" default keeps the record byte-identical
    tsdb_cadence = float(os.environ.get("BENCH_SERVE_TSDB_CADENCE", "0.0"))
    tsdb_on = (
        _drive_leg(primary["impl"], tsdb_cadence=tsdb_cadence, tag="_tsdb")
        if tsdb_cadence > 0 else None
    )
    record = {
        "metric": "serve_microbench",
        "value": primary["requests_per_sec"],
        "unit": "requests/sec",
        "vs_baseline": 0.0,  # no serving baseline exists (BASELINE.md)
        "impl": primary["impl"],
        "latency_ms": primary["latency_ms"],
        "errors": primary["errors"],
        "real_tokens": primary["real_tokens"],
        "padded_tokens": primary["padded_tokens"],
        "real_token_utilization": primary["real_token_utilization"],
        "queue_wait_ms": primary["queue_wait_ms"],
        **{
            k: primary[k]
            for k in (
                "cascade_rescored", "cascade_shortcircuit",
                "cascade_rescore_rate", "cascade_band", "cache",
            )
            if k in primary
        },
        "config": {
            "model": os.environ.get("BENCH_MODEL", "base"),
            "seq_len": seq_len,
            "buckets": list(buckets),
            "requests": n_requests,
            "clients": n_clients,
            "max_batch": max_batch,
            "max_wait_ms": max_wait_ms,
            "impl_mode": impl_mode,
            "token_budget": token_budget,
            "cache_capacity": cache_capacity,
        },
        **_program_blocks(),
    }
    if tsdb_on is not None:
        off_rps = primary["requests_per_sec"]
        record["tsdb"] = {
            "cadence_s": tsdb_cadence,
            "off": {
                "requests_per_sec": off_rps,
                "latency_ms": primary["latency_ms"],
            },
            "on": {
                "requests_per_sec": tsdb_on["requests_per_sec"],
                "latency_ms": tsdb_on["latency_ms"],
            },
            "sampler": tsdb_on.get("tsdb"),
            "throughput_ratio": (
                round(tsdb_on["requests_per_sec"] / off_rps, 4)
                if off_rps else None
            ),
        }
    if impl_mode == "ab":
        by_impl = by_leg
        record["ab"] = by_impl
        bucketed_util = by_impl["bucketed"]["real_token_utilization"]
        ragged_util = by_impl["ragged"]["real_token_utilization"]
        if bucketed_util and ragged_util:
            record["utilization_gain"] = round(
                ragged_util / bucketed_util, 3
            )
        # the continuous win: p50 admission wait vs the seal-then-admit
        # ragged loop on the identical seeded schedule
        ragged_qw = by_impl["ragged"]["queue_wait_ms"]
        cont_qw = by_impl["continuous"]["queue_wait_ms"]
        if ragged_qw and cont_qw and cont_qw["p50"]:
            record["queue_wait_gain"] = round(
                ragged_qw["p50"] / cont_qw["p50"], 2
            )
        # the quantization win: cascade vs bucketed throughput over the
        # identical schedule, plus how often the band forced a rescore
        casc = by_impl.get("cascade")
        if casc:
            record["cascade_rescore_rate"] = casc["cascade_rescore_rate"]
            bucketed_rps = by_impl["bucketed"]["requests_per_sec"]
            if bucketed_rps:
                record["cascade_throughput_gain"] = round(
                    casc["requests_per_sec"] / bucketed_rps, 3
                )
    print(json.dumps(record))


def _run_serve_router_micro(
    watchdog, build_service, texts, *, n_requests, n_clients, n_replicas,
    seq_len, buckets, max_batch, max_wait_ms,
) -> None:
    """The router leg of BENCH_MICRO=serve (docs/serving.md, "SLO
    harness"): N replica services behind a :class:`ReplicaRouter`,
    driven by the deterministic load generator, reported as one JSON
    record with per-cause outcome counts and per-replica utilization.
    CPU-runnable at tiny geometry; the recorded rps is only meaningful
    at base geometry on hardware (ROADMAP chip-window item).

    Autoscale leg (BENCH_SERVE_AUTOSCALE=1; docs/serving.md,
    "Autoscaling"): the fleet starts at ONE replica with an
    :class:`~memvul_tpu.serving.Autoscaler` closing the scale_hint loop
    (BENCH_SERVE_REPLICAS is the max), the pattern defaults to diurnal,
    and the record gains the replica-count trajectory, per-phase SLO
    burn over the diurnal cycle, scale-event counts, and the
    lost-request count — which must be 0: every request is served,
    shed, or errored somewhere, retirements included."""
    from memvul_tpu.serving import (
        LoadConfig,
        Replica,
        ReplicaRouter,
        RouterConfig,
        run_slo_harness,
    )
    from memvul_tpu.telemetry.registry import TelemetryRegistry

    autoscale = os.environ.get("BENCH_SERVE_AUTOSCALE") == "1"
    pattern = os.environ.get(
        "BENCH_SERVE_PATTERN", "diurnal" if autoscale else "closed"
    )
    rps = float(os.environ.get("BENCH_SERVE_RPS", "200"))
    diurnal_period_s = float(os.environ.get("BENCH_SERVE_PERIOD_S", "2.0"))
    max_replicas = max(n_replicas, 2) if autoscale else n_replicas
    with watchdog.phase("replica_warmup"):
        replicas = [
            Replica(i, lambda registry: build_service(registry=registry),
                    telemetry_enabled=True)
            for i in range(1 if autoscale else n_replicas)
        ]
    router_registry = TelemetryRegistry(enabled=True)
    router = ReplicaRouter(
        replicas, config=RouterConfig(), registry=router_registry,
    )
    # the SLO evaluator (serving/slo.py): its availability/burn-rate/
    # scale_hint block rides the harness record (the harness ticks it)
    from memvul_tpu.serving.slo import SLOConfig, SLOMonitor

    router.slo_monitor = SLOMonitor(
        router, registry=router_registry,
        config=SLOConfig(interval_s=1.0), start=False,
    )
    router.slo_monitor.tick()  # the pre-load baseline sample
    scaler = None
    driver_stop = threading.Event()
    driver = None
    if autoscale:
        from memvul_tpu.serving.autoscaler import Autoscaler, AutoscalerConfig

        scaler = Autoscaler(
            router,
            replica_factory=lambda index: (
                lambda registry: build_service(registry=registry)
            ),
            slo_monitor=router.slo_monitor,
            # bench-tight stability knobs: the diurnal period is seconds,
            # not hours, so cooldowns/hysteresis compress with it
            config=AutoscalerConfig(
                min_replicas=1, max_replicas=max_replicas,
                interval_s=0.1, up_cooldown_s=0.3, down_cooldown_s=0.5,
                up_consecutive=1, down_consecutive=2,
                drain_timeout_s=30.0,
            ),
            registry=router_registry,
            start=False,  # the driver thread below paces the ticks
        )
        router.autoscaler = scaler  # the harness record's status block

        def _drive() -> None:
            # the closed control loop: sample the SLO, act on the hint;
            # sync=True keeps one spawn/retire at a time deterministic
            while not driver_stop.wait(0.1):
                try:
                    router.slo_monitor.tick()
                    scaler.tick(sync=True)
                except Exception:
                    pass  # one bad sample must not end the bench loop

        driver = threading.Thread(
            target=_drive, name="bench-autoscale-driver", daemon=True
        )
    load = LoadConfig(
        pattern=pattern, requests=n_requests, clients=n_clients, rps=rps,
        diurnal_period_s=diurnal_period_s,
        deadline_ms=None if pattern != "slowloris" else 60_000.0,
    )
    with watchdog.phase("serve_warmup"):
        router.submit(texts[0], deadline_ms=0).result(timeout=120)
    with watchdog.phase("serve_load"):
        if driver is not None:
            driver.start()
        try:
            record = run_slo_harness(router, texts, config=load)
        finally:
            driver_stop.set()
            if driver is not None:
                driver.join(timeout=30)
    router.drain()

    report = record["load"]
    fleet = record.get("fleet", {})
    autoscale_block = None
    if scaler is not None:
        counters = router_registry.snapshot()["counters"]
        members = fleet.get("replicas", [])
        # the lost-request detector: hangs + any invariant deficit —
        # a request admitted somewhere but never served/shed/errored
        deficit = sum(
            m["requests"] - m["served"] - m["shed"] - m["errors"]
            for m in members
        )
        lost = report["outcomes"]["hang"] + max(0, deficit)
        # per-phase SLO burn over the diurnal cycle: bucket the
        # trajectory by quarter-period (rise/peak/fall/trough)
        phase_names = ("rise", "peak", "fall", "trough")
        phases = {name: [] for name in phase_names}
        for point in scaler.history:
            frac = (point["t_s"] % diurnal_period_s) / diurnal_period_s
            phases[phase_names[min(3, int(frac * 4))]].append(point)
        autoscale_block = {
            "min_replicas": 1,
            "max_replicas": max_replicas,
            "final_replicas": len(router._members()),
            "scale_ups": counters.get("scaler.scale_ups", 0),
            "scale_downs": counters.get("scaler.scale_downs", 0),
            "spawn_failures": counters.get("scaler.spawn_failures", 0),
            "lost_requests": lost,  # MUST be 0
            "replica_trajectory": [
                {k: point[k] for k in ("t_s", "replicas", "hint", "action")}
                for point in scaler.history
            ],
            "phase_burn": {
                name: {
                    "ticks": len(points),
                    "mean_replicas": (
                        round(
                            sum(p["replicas"] for p in points) / len(points),
                            2,
                        ) if points else None
                    ),
                    "max_burn_fast": max(
                        (p["burn_rate_fast"] or 0.0 for p in points),
                        default=None,
                    ),
                }
                for name, points in phases.items()
            },
        }
    print(
        json.dumps(
            {
                "metric": (
                    "serve_autoscale_microbench" if autoscale
                    else "serve_router_microbench"
                ),
                "value": report["achieved_rps"],
                "unit": "requests/sec",
                "vs_baseline": 0.0,  # no router baseline exists (BASELINE.md)
                "latency_ms": report["latency_ms"],
                "outcomes": report["outcomes"],  # per-cause ok/shed/deadline/...
                "offered_rps": report["offered_rps"],
                "duration_s": report["duration_s"],
                "fleet": {
                    "invariant_ok": fleet.get("invariant_ok"),
                    "served_total": fleet.get("served_total"),
                    "replicas": [
                        {
                            "name": member["name"],
                            "served": member["served"],
                            "shed": member["shed"],
                            "errors": member["errors"],
                            "restarts": member["restarts"],
                            "utilization": member["utilization"],
                        }
                        for member in fleet.get("replicas", [])
                    ],
                },
                "router": record.get("router", {}),
                "slo": record.get("slo", {}),
                "autoscale": autoscale_block,
                "config": {
                    "model": os.environ.get("BENCH_MODEL", "base"),
                    "seq_len": seq_len,
                    "buckets": list(buckets),
                    "requests": n_requests,
                    "clients": n_clients,
                    "replicas": n_replicas,
                    "pattern": pattern,
                    "rps": rps,
                    "max_batch": max_batch,
                    "max_wait_ms": max_wait_ms,
                },
            }
        )
    )


def _run_corpus_micro() -> None:
    """BENCH_MICRO=corpus: sharded full-corpus scoring throughput
    (docs/full_corpus.md).

    Builds a tiny untrained archive over a synthetic workspace, runs
    ``score_corpus`` across BENCH_CORPUS_SHARDS supervised worker
    subprocesses, and reports total rows/s plus the per-shard rates the
    coordinator's merge verified exactly-once.  No training happens —
    the number measures the distribution machinery (spawn, heartbeat
    supervision, journal replay, merge verification), not the model.

    Knobs: BENCH_CORPUS_SHARDS (worker count, default 2),
    BENCH_CORPUS_REPORTS (workspace reports per project, default 64),
    BENCH_SEQ_LEN (max_length cap, default 64).
    """
    from pathlib import Path

    from memvul_tpu.utils.platform import enable_compilation_cache, honor_platform_env

    honor_platform_env()
    enable_compilation_cache()

    from memvul_tpu.archive import save_archive
    from memvul_tpu.build import build_model, init_params
    from memvul_tpu.data.synthetic import build_workspace
    from memvul_tpu.distributed import score_corpus
    from memvul_tpu.telemetry.sinks import HeartbeatFile

    watchdog = _watchdog()
    n_shards = int(os.environ.get("BENCH_CORPUS_SHARDS", "2"))
    per_project = int(os.environ.get("BENCH_CORPUS_REPORTS", "64"))
    seq_len = int(os.environ.get("BENCH_SEQ_LEN", "64"))

    with watchdog.phase("workspace"):
        ws = build_workspace(
            tempfile.mkdtemp(), seed=0, num_projects=8,
            reports_per_project=per_project, realistic_lengths=True,
        )
    root = Path(tempfile.mkdtemp())
    model_cfg = {
        "type": "model_memory",
        "encoder": {"preset": "tiny", "vocab_size": ws["tokenizer"].vocab_size},
        "header_dim": 32,
    }
    config = {
        "tokenizer": {
            "type": "wordpiece", "tokenizer_path": ws["paths"]["tokenizer"],
        },
        "dataset_reader": {
            "type": "reader_memory",
            "anchor_path": ws["paths"]["anchors"],
            "cve_path": ws["paths"]["cve"],
        },
        "model": model_cfg,
        "evaluation": {"batch_size": 8, "max_length": seq_len},
        "telemetry": {"heartbeat_every_s": 1.0},
    }
    with watchdog.phase("archive"):
        model = build_model(dict(model_cfg), ws["tokenizer"].vocab_size)
        params = init_params(model, seed=0)
        archive = save_archive(
            root / "model.tar.gz", config, params,
            tokenizer_file=ws["paths"]["tokenizer"],
        )

    out_dir = root / "corpus_run"
    with watchdog.phase("score_corpus"):
        t0 = time.perf_counter()
        result = score_corpus(
            archive, ws["paths"]["test"], out_dir, shards=n_shards,
        )
        wall = time.perf_counter() - t0

    per_shard = []
    for summary in result["shards"]:
        hb = HeartbeatFile(
            out_dir / summary["shard"] / "HEARTBEAT.json"
        ).read()
        uptime = float(hb.get("uptime_s") or 0.0)
        rows = summary["rows"]
        per_shard.append({
            "shard": summary["shard"],
            "rows": rows,
            "restarts": summary["restarts"],
            "rows_per_s": round(rows / uptime, 2) if uptime > 0 else 0.0,
        })

    print(
        json.dumps(
            {
                "metric": "corpus_microbench",
                "value": round(result["corpus_rows"] / max(wall, 1e-9), 2),
                "unit": "rows/s",
                "vs_baseline": 0.0,  # no corpus-scoring baseline (BASELINE.md)
                "corpus_rows": result["corpus_rows"],
                "wall_s": round(wall, 3),
                "merge_wall_s": round(result["merge_wall_s"], 3),
                "restarts": result["restarts"],
                "per_shard": per_shard,
                "verification": result["verification"],
                "config": {
                    "shards": n_shards,
                    "seq_len": seq_len,
                    "reports_per_project": per_project,
                },
                **_program_blocks(),
            }
        )
    )


def _extract_result_line(text: str):
    """Last stdout line that parses as the bench result dict, else None.

    Records carrying an ``error`` field (the watchdog's phase-timeout
    record) are NOT results — skipping them here is what lets the
    supervisor retry a watchdog-killed attempt instead of reporting its
    failure record as a measurement."""
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and "metric" in obj and "error" not in obj:
            return line
    return None


def _kill_process_group(proc: "subprocess.Popen", grace: float = 0.0) -> None:
    """Kill the child's whole process group — nothing may be left holding
    the TPU after a timed-out attempt.  With ``grace`` > 0, SIGTERM first
    and give the child that long to run its PJRT client destructors (a
    cleanly-closed tunnel connection releases the device lease; an abrupt
    kill can leave it held server-side)."""
    if grace > 0:
        try:
            os.killpg(proc.pid, signal.SIGTERM)
            proc.wait(timeout=grace)
            return
        except (ProcessLookupError, PermissionError, OSError):
            pass
        except subprocess.TimeoutExpired:
            pass
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        pass
    try:
        proc.wait(timeout=10)
    except Exception:
        pass


_PROBE_BODY = (
    "import os, jax\n"
    "req = os.environ.get('JAX_PLATFORMS')\n"
    "if req: jax.config.update('jax_platforms', req)\n"
    "import jax.numpy as jnp\n"
    "x = jnp.ones((8, 128))\n"
    "print('DEVICE_OK', float((x @ x.T).sum()))\n"
)


def _wait_for_device(
    total_budget: float, probe_timeout: float, interval: float, env=None
) -> bool:
    """Block until the backend answers a trivial device op, or give up.

    The axon tunnel can wedge for tens of minutes (a dead client's lease is
    held server-side); a wedged backend makes the bench child HANG at its
    first device op rather than error.  Burning full attempt timeouts on
    that is wasteful — instead spend cheap ~2-min probes until the device
    responds, then run the real measurement.  Returns False once
    ``total_budget`` seconds have elapsed without an answer.
    """
    deadline = time.monotonic() + total_budget
    first = True
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return False
        proc = subprocess.Popen(
            [sys.executable, "-c", _PROBE_BODY],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
            env=env,
            start_new_session=True,
        )
        try:
            # never overshoot the caller's total budget on a hung probe
            out, _ = proc.communicate(timeout=min(probe_timeout, remaining))
        except subprocess.TimeoutExpired:
            # graceful first: a SIGTERM'd probe closes its tunnel
            # connection cleanly instead of becoming one more dead client
            # holding the device lease (the wedge this wait exists for)
            _kill_process_group(proc, grace=10.0)
            out = ""
        if "DEVICE_OK" in out:
            return True
        if first:
            sys.stderr.write(
                "bench: backend not answering; probing until it recovers\n"
            )
            first = False
        if time.monotonic() + interval >= deadline:
            return False
        time.sleep(interval)


def _supervise(cmd, attempts: int, attempt_timeout: float, backoff: float, env=None):
    """Run ``cmd`` until it emits a bench-result JSON line.

    Returns (result_line, None) on success or (None, short_error) after the
    retry budget is exhausted.  Only transient backend failures (the shared
    classification in resilience/retry.py) and deadline kills are retried;
    a genuine bug fails fast.
    """
    policy = RetryPolicy(attempts=attempts, backoff=backoff)
    last_error = "no attempts were made"
    for attempt in range(1, attempts + 1):
        proc = subprocess.Popen(
            cmd,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            start_new_session=True,  # own process group, killable as a unit
        )
        try:
            out, err = proc.communicate(timeout=attempt_timeout)
            timed_out = False
        except subprocess.TimeoutExpired:
            _kill_process_group(proc)
            # harvest whatever the child wrote before hanging — a result
            # line printed before a teardown hang is still a result
            try:
                out, err = proc.communicate(timeout=10)
            except Exception:
                out, err = "", ""
            out, err, timed_out = out or "", err or "", True

        # the child's logs (e.g. the vocab-parity warning) must reach the
        # operator even when the run succeeds
        if err:
            sys.stderr.write(err if len(err) < 20000 else err[-20000:])

        line = _extract_result_line(out)
        if line is not None:
            return line, None
        if not timed_out and proc.returncode == 0:
            # deterministic bug (result contract broken): fail fast
            return None, "child exited 0 without a result line"
        if timed_out:
            last_error = f"attempt timed out after {attempt_timeout:.0f}s"
        else:
            # the real error lives on stderr; stdout only as a fallback so
            # progress noise can't mask the exception text
            err_lines = [l for l in (err or "").splitlines() if l.strip()]
            out_lines = [l for l in (out or "").splitlines() if l.strip()]
            tail = err_lines or out_lines
            # prefer the actual exception line over trailing boilerplate
            # (JAX appends a traceback-filtering notice AFTER the error)
            exc = [l for l in tail if re.match(r"^[\w.]+(Error|Exception)\b", l)]
            pick = exc[-1] if exc else (tail[-1] if tail else None)
            last_error = pick[:300] if pick else f"rc={proc.returncode}"
            if not policy.is_transient(err + out):
                return None, last_error  # not transient: don't burn retries

        if attempt < attempts:
            sys.stderr.write(
                f"bench attempt {attempt}/{attempts} failed ({last_error}); "
                f"retrying in {policy.delay(attempt):.0f}s\n"
            )
            time.sleep(policy.delay(attempt))
    return None, last_error


def _lint_record() -> dict:
    """BENCH_LINT=1: run the static-analysis engine over the tree and
    return one parseable JSON record (docs/static_analysis.md).  The
    supervisor prints it as its own line BEFORE the bench result, so a
    sweep harness can collect code-health alongside throughput without
    a second process."""
    from memvul_tpu.analysis import analyze_repo

    result = analyze_repo()
    return {
        "metric": "lint",
        "clean": not result.active,
        "findings": [f.to_json() for f in result.active],
        "suppressed": len(result.suppressed),
        "baselined": len(result.baselined),
        "files": result.parse_count,
        "elapsed_s": round(result.elapsed_s, 3),
    }


def main() -> int:
    if os.environ.get(_CHILD_ENV_FLAG) == "1":
        # BENCH_TELEMETRY_DIR=<dir>: the child keeps a full telemetry run
        # dir (events.jsonl phase spans, HEARTBEAT.json, telemetry.json)
        # readable via `python -m memvul_tpu telemetry-report <dir>` —
        # the registry works in-memory (watchdog heartbeat age) either way
        tel_dir = os.environ.get("BENCH_TELEMETRY_DIR")
        if tel_dir:
            from memvul_tpu.telemetry import configure as _tel_configure

            _tel_configure(run_dir=tel_dir, heartbeat_every_s=10.0)
        try:
            _run_bench()
        finally:
            if tel_dir:
                from memvul_tpu.telemetry import get_registry

                get_registry().close()
        return 0

    if os.environ.get("BENCH_LINT") == "1":
        # surfaced by the supervisor (one JSON line of its own) so the
        # record rides the same stdout contract as the bench result
        print(json.dumps(_lint_record()))
        sys.stdout.flush()

    attempts = max(1, int(os.environ.get("BENCH_ATTEMPTS", "3")))
    attempt_timeout = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT", "1500"))
    backoff = float(os.environ.get("BENCH_BACKOFF", "20"))

    cmd = [sys.executable, "-m", "memvul_tpu.bench"]
    child_env = dict(os.environ, **{_CHILD_ENV_FLAG: "1"})
    device_wait = float(os.environ.get("BENCH_DEVICE_WAIT", "1800"))
    if device_wait > 0 and not _wait_for_device(
        device_wait,
        probe_timeout=float(os.environ.get("BENCH_PROBE_TIMEOUT", "240")),
        interval=45.0,
        env=child_env,
    ):
        print(
            json.dumps(
                {
                    "metric": _metric_name(),
                    "value": 0.0,
                    "unit": "reports/sec",
                    "vs_baseline": 0.0,
                    "error": f"device did not answer within {device_wait:.0f}s "
                    "(backend wedged/unavailable)",
                }
            )
        )
        return 1
    line, error = _supervise(cmd, attempts, attempt_timeout, backoff, env=child_env)
    if line is not None:
        print(line)
        return 0
    print(
        json.dumps(
            {
                "metric": _metric_name(),
                "value": 0.0,
                "unit": "reports/sec",
                "vs_baseline": 0.0,
                "error": error,
            }
        )
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
