"""A small Registrable/FromParams-style component registry.

The reference framework wires every component through AllenNLP's registry:
``@Model.register("model_memory")`` etc., constructed from JSON configs by
``"type"`` key (reference: MemVul/model_memory.py:39, reader_memory.py:35,
custom_trainer.py:38).  This module provides the same ergonomics without
AllenNLP: any class deriving from :class:`Registrable` gains ``register``,
``by_name`` and ``from_config``; ``from_config`` recursively constructs
nested registrable components found in the config dict by inspecting the
constructor's type annotations.
"""

from __future__ import annotations

import inspect
import types
import typing
from typing import Any, Callable, Dict, Optional, Type, TypeVar

T = TypeVar("T", bound="Registrable")


class RegistryError(KeyError):
    pass


class Registrable:
    """Base class giving subclasses a per-hierarchy name registry.

    The registry is keyed by the *base* class (the direct subclass of
    ``Registrable``), so e.g. readers and models live in separate
    namespaces even if they share a type name.
    """

    _registry: Dict[type, Dict[str, type]] = {}
    default_implementation: Optional[str] = None

    @classmethod
    def _base(cls) -> type:
        # walk up to the class directly under Registrable
        for klass in cls.__mro__:
            if Registrable in klass.__bases__:
                return klass
        return cls

    @classmethod
    def register(cls, name: str, exist_ok: bool = False) -> Callable[[Type[T]], Type[T]]:
        base = cls._base() if cls is not Registrable else cls

        def decorator(subclass: Type[T]) -> Type[T]:
            space = Registrable._registry.setdefault(base, {})
            if name in space and not exist_ok and space[name] is not subclass:
                raise RegistryError(
                    f"{name!r} already registered for {base.__name__} "
                    f"as {space[name].__name__}"
                )
            space[name] = subclass
            subclass.registered_name = name
            return subclass

        return decorator

    @classmethod
    def by_name(cls, name: str) -> type:
        base = cls._base() if cls is not Registrable else cls
        space = Registrable._registry.get(base, {})
        if name not in space:
            known = sorted(space)
            raise RegistryError(
                f"{name!r} is not a registered {base.__name__}; known: {known}"
            )
        return space[name]

    @classmethod
    def list_available(cls) -> list:
        base = cls._base() if cls is not Registrable else cls
        return sorted(Registrable._registry.get(base, {}))

    @classmethod
    def from_config(cls: Type[T], config: Any, **extras: Any) -> T:
        """Construct a component from a config dict.

        ``config`` may be an instance (returned as-is), or a dict with an
        optional ``"type"`` key selecting the registered subclass (falling
        back to ``default_implementation``).  Remaining keys become
        constructor kwargs; nested dicts whose parameter annotation is a
        Registrable subclass are constructed recursively.  ``extras`` are
        injected for matching parameter names not present in the config.
        """
        if isinstance(config, cls):
            return config
        if config is None:
            config = {}
        if not isinstance(config, dict):
            raise TypeError(f"cannot construct {cls.__name__} from {type(config)}")
        params = dict(config)
        type_name = params.pop("type", None) or cls.default_implementation
        subclass = cls.by_name(type_name) if type_name else cls
        return _construct(subclass, params, extras)


def _construct(subclass: type, params: Dict[str, Any], extras: Dict[str, Any]) -> Any:
    sig = inspect.signature(subclass.__init__)
    hints = typing.get_type_hints(subclass.__init__) if subclass.__init__ is not object.__init__ else {}
    kwargs: Dict[str, Any] = {}
    accepts_kwargs = any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in sig.parameters.values()
    )
    for pname, param in sig.parameters.items():
        if pname == "self" or param.kind in (
            inspect.Parameter.VAR_POSITIONAL,
            inspect.Parameter.VAR_KEYWORD,
        ):
            continue
        if pname in params:
            value = params.pop(pname)
            kwargs[pname] = _resolve(hints.get(pname), value, extras)
        elif pname in extras:
            kwargs[pname] = extras[pname]
        elif param.default is inspect.Parameter.empty:
            raise TypeError(
                f"{subclass.__name__} missing required config key {pname!r}"
            )
    if params:
        if accepts_kwargs:
            kwargs.update(params)
        else:
            raise TypeError(
                f"{subclass.__name__} got unexpected config keys {sorted(params)}"
            )
    return subclass(**kwargs)


def _resolve(annotation: Any, value: Any, extras: Dict[str, Any]) -> Any:
    """Recursively build registrable sub-components from nested dicts."""
    if annotation is None or value is None:
        return value
    origin = typing.get_origin(annotation)
    if origin in (typing.Union, types.UnionType):
        # prefer an arm that actually transforms the value (a Registrable
        # built from a dict); plain arms like int would pass it through raw
        arms = [a for a in typing.get_args(annotation) if a is not type(None)]
        for arg in arms:
            if (
                inspect.isclass(arg)
                and issubclass(arg, Registrable)
                and isinstance(value, dict)
            ):
                try:
                    return _resolve(arg, value, extras)
                except (TypeError, RegistryError):
                    continue
        for arg in arms:
            try:
                return _resolve(arg, value, extras)
            except (TypeError, RegistryError):
                continue
        return value
    if (
        inspect.isclass(annotation)
        and issubclass(annotation, Registrable)
        and isinstance(value, dict)
    ):
        return annotation.from_config(value, **extras)
    if origin in (list, tuple) and isinstance(value, (list, tuple)):
        args = typing.get_args(annotation)
        inner = args[0] if args else None
        return type(value)(_resolve(inner, v, extras) for v in value)
    if origin is dict and isinstance(value, dict):
        args = typing.get_args(annotation)
        inner = args[1] if len(args) == 2 else None
        return {k: _resolve(inner, v, extras) for k, v in value.items()}
    return value
