"""The single (no-memory) classifier — "MemVul-m".

Plain BERT sequence classification: tanh-pooled CLS → FeedForward
(hidden→512, ReLU, dropout) → bias-free Linear(512→2)
(reference: MemVul/model_single.py:56-65,84-94).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from .bert import BertConfig, BertEncoder, BertPooler
from .losses import masked_cross_entropy
from .memory import ProjectionHeader


class SingleModel(nn.Module):
    config: BertConfig
    header_dim: int = 512
    num_classes: int = 2

    def setup(self):
        self.encoder = BertEncoder(self.config, name="bert")
        self.pooler = BertPooler(self.config, name="pooler")
        self.header = ProjectionHeader(self.config, self.header_dim, name="header")
        self.classifier = nn.Dense(
            self.num_classes, use_bias=False, dtype=self.config.dtype,
            name="classifier",
        )

    def __call__(self, sample1, deterministic: bool = True) -> jax.Array:
        hidden = self.encoder(
            sample1["input_ids"],
            sample1["attention_mask"],
            sample1.get("token_type_ids"),
            deterministic=deterministic,
        )
        pooled = self.pooler(hidden, deterministic=deterministic)
        pooled = self.header(pooled, deterministic=deterministic)
        return self.classifier(pooled)


def classification_loss(
    logits: jax.Array, labels: jax.Array, weights: jax.Array
) -> jax.Array:
    """Mean CE over real rows (reference: model_single.py:95-97)."""
    return masked_cross_entropy(logits, labels, weights)
