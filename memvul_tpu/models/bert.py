"""A TPU-first BERT encoder in Flax linen.

Replaces the reference's HF/AllenNLP PyTorch BERT stack (reference:
MemVul/custom_PTM_embedder.py loads ``AutoModel.from_pretrained``).  This
implementation is built for XLA:

* activations in a configurable ``dtype`` (bf16 on TPU; params stay f32);
* attention goes through ``memvul_tpu.ops.dot_product_attention`` so the
  kernel (XLA einsum / Pallas flash / ring) is swappable per config;
* the layer stack can run under ``nn.scan`` + ``nn.remat`` — one compiled
  layer body, rematerialized activations — which keeps compile time flat
  and HBM use low at depth;
* parameter naming mirrors HF's FlaxBERT layout so torch checkpoints can
  be converted mechanically (models/convert.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.attention import dot_product_attention, mask_to_bias


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    dtype: Any = jnp.float32
    attention_impl: str = "xla"
    remat: bool = False
    scan_layers: bool = False
    # False = ScalarMix over all layer outputs instead of the last layer
    # (reference: custom_PTM_embedder.py:107-118; unused by every shipped
    # reference config, provided for drop-in parity)
    last_layer_only: bool = True
    # "int8_dynamic" routes the encoder's dense contractions through the
    # MXU's native int8 path, re-quantizing weights inside every forward;
    # "int8" additionally caches the per-column weight quant ONCE in the
    # "quant" variable collection (materialize via one apply under
    # mutable=["quant"] — SiamesePredictor does this at build time).
    # Both are inference-only speedups over the SAME params/checkpoints —
    # quantization is a property of the forward.  None = full precision
    quant: Optional[str] = None
    # bank-match backend for MemoryModel.match_anchors: "auto" runs the
    # fused Pallas kernel on TPU hardware and the jnp decomposition
    # elsewhere; "fused" / "xla" pin a backend (ops/pallas/anchor_match)
    anchor_match_impl: str = "auto"

    @classmethod
    def tiny(cls, vocab_size: int = 2048, **kw) -> "BertConfig":
        """2-layer config for tests (the fake-encoder strategy, SURVEY §4)."""
        defaults = dict(
            vocab_size=vocab_size,
            hidden_size=64,
            num_layers=2,
            num_heads=4,
            intermediate_size=128,
            max_position_embeddings=128,
        )
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def base(cls, vocab_size: int = 30522, **kw) -> "BertConfig":
        """bert-base-uncased geometry (the reference's encoder)."""
        return cls(vocab_size=vocab_size, **kw)

    @classmethod
    def large(cls, vocab_size: int = 30522, **kw) -> "BertConfig":
        """bert-large geometry — the SURVEY §7 stretch encoder (the
        reference never scales past base; this is where the ``model``
        mesh axis starts paying: 16 heads / 4096 FFN split cleanly over
        tp=2/4/8)."""
        defaults = dict(
            vocab_size=vocab_size,
            hidden_size=1024,
            num_layers=24,
            num_heads=16,
            intermediate_size=4096,
        )
        defaults.update(kw)
        return cls(**defaults)

    def replace(self, **kw) -> "BertConfig":
        return dataclasses.replace(self, **kw)


def _dense_init(config: BertConfig):
    return nn.initializers.normal(stddev=config.initializer_range)


def _dense(c: BertConfig, features: int, name: str):
    """nn.Dense, or its dynamic-int8 twin when ``c.quant`` asks for it
    (identical param tree either way)."""
    if c.quant == "int8_dynamic":
        from ..ops.quant import QuantDense

        return QuantDense(
            features, dtype=c.dtype, kernel_init=_dense_init(c), name=name
        )
    if c.quant == "int8":
        from ..ops.quant import Int8Dense

        return Int8Dense(
            features, dtype=c.dtype, kernel_init=_dense_init(c), name=name
        )
    if c.quant is not None:
        raise ValueError(f"unknown quant mode {c.quant!r}")
    return nn.Dense(features, kernel_init=_dense_init(c), dtype=c.dtype, name=name)


def _dense_general(c: BertConfig, features, name: str, axis=-1):
    if c.quant == "int8_dynamic":
        from ..ops.quant import QuantDenseGeneral

        return QuantDenseGeneral(
            features, axis=axis, dtype=c.dtype, kernel_init=_dense_init(c),
            name=name,
        )
    if c.quant == "int8":
        from ..ops.quant import Int8DenseGeneral

        return Int8DenseGeneral(
            features, axis=axis, dtype=c.dtype, kernel_init=_dense_init(c),
            name=name,
        )
    if c.quant is not None:
        raise ValueError(f"unknown quant mode {c.quant!r}")
    return nn.DenseGeneral(
        features, axis=axis, kernel_init=_dense_init(c), dtype=c.dtype, name=name
    )


class BertEmbeddings(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(
        self, input_ids, token_type_ids, deterministic: bool, position_ids=None
    ):
        c = self.config
        word = nn.Embed(
            c.vocab_size, c.hidden_size, embedding_init=_dense_init(c),
            dtype=c.dtype, name="word_embeddings",
        )(input_ids)
        if position_ids is None:
            # explicit ids matter under sequence parallelism, where each
            # shard sees a slice and must use its global offsets
            position_ids = jnp.arange(input_ids.shape[-1])[None, :]
        pos = nn.Embed(
            c.max_position_embeddings, c.hidden_size, embedding_init=_dense_init(c),
            dtype=c.dtype, name="position_embeddings",
        )(position_ids)
        typ = nn.Embed(
            c.type_vocab_size, c.hidden_size, embedding_init=_dense_init(c),
            dtype=c.dtype, name="token_type_embeddings",
        )(token_type_ids)
        x = word + pos + typ
        x = nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=c.dtype, name="LayerNorm")(x)
        return nn.Dropout(c.hidden_dropout)(x, deterministic=deterministic)


class BertSelfAttention(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, hidden, bias, deterministic: bool, segment_ids=None):
        c = self.config
        head_dim = c.hidden_size // c.num_heads

        def qkv(name):
            return _dense_general(c, (c.num_heads, head_dim), name)(hidden)

        query, key, value = qkv("query"), qkv("key"), qkv("value")
        dropout_rng = None
        if not deterministic and c.attention_dropout > 0.0:
            dropout_rng = self.make_rng("dropout")
        attn = dot_product_attention(
            query, key, value, bias=bias,
            dropout_rng=dropout_rng, dropout_rate=c.attention_dropout,
            deterministic=deterministic, impl=c.attention_impl,
            segment_ids=segment_ids,
        )
        out = _dense_general(c, c.hidden_size, "output", axis=(-2, -1))(attn)
        out = nn.Dropout(c.hidden_dropout)(out, deterministic=deterministic)
        return nn.LayerNorm(
            epsilon=c.layer_norm_eps, dtype=c.dtype, name="output_LayerNorm"
        )(hidden + out)


class BertLayer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, hidden, bias, deterministic: bool, segment_ids=None):
        c = self.config
        hidden = BertSelfAttention(c, name="attention")(
            hidden, bias, deterministic, segment_ids
        )
        inter = _dense(c, c.intermediate_size, "intermediate")(hidden)
        inter = nn.gelu(inter, approximate=False)
        out = _dense(c, c.hidden_size, "output")(inter)
        out = nn.Dropout(c.hidden_dropout)(out, deterministic=deterministic)
        return nn.LayerNorm(
            epsilon=c.layer_norm_eps, dtype=c.dtype, name="output_LayerNorm"
        )(hidden + out)


class _ScanBody(nn.Module):
    """BertLayer adapted to the (carry, y) contract nn.scan expects.
    ``collect`` additionally emits each layer's output as the scan ys
    (stacked [L, B, T, H] by nn.scan) for the ScalarMix path."""

    config: BertConfig
    deterministic: bool
    collect: bool = False

    @nn.compact
    def __call__(self, hidden, bias, segment_ids=None):
        out = BertLayer(self.config, name="layer")(
            hidden, bias, self.deterministic, segment_ids
        )
        return out, (out if self.collect else None)


class BertEncoderStack(nn.Module):
    """Returns the last layer's hidden states, or the stacked per-layer
    outputs [L, B, T, H] when ``config.last_layer_only`` is False (the
    ScalarMix path)."""

    config: BertConfig

    @nn.compact
    def __call__(self, hidden, bias, deterministic: bool, segment_ids=None):
        c = self.config
        collect = not c.last_layer_only
        if c.scan_layers:
            # one compiled layer body scanned over the depth axis: flat
            # compile time, stacked params [L, ...]
            body = nn.remat(_ScanBody) if c.remat else _ScanBody
            scanned = nn.scan(
                body,
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                length=c.num_layers,
                in_axes=(nn.broadcast, nn.broadcast),
            )(c, deterministic, collect, name="layers")
            hidden, stacked = scanned(hidden, bias, segment_ids)
            return stacked if collect else hidden
        layer_cls = nn.remat(BertLayer, static_argnums=(3,)) if c.remat else BertLayer
        outputs = []
        for i in range(c.num_layers):
            hidden = layer_cls(c, name=f"layer_{i}")(
                hidden, bias, deterministic, segment_ids
            )
            if collect:
                outputs.append(hidden)
        return jnp.stack(outputs) if collect else hidden


class ScalarMix(nn.Module):
    """Learned softmax-weighted combination of all layer outputs, scaled
    by a learned gamma — the option the reference's PTM embedder enables
    when ``last_layer_only=False`` (reference: custom_PTM_embedder.py:
    107-118, wiring AllenNLP's ScalarMix).  Weights mix in f32; the
    result returns to the compute dtype."""

    config: BertConfig

    @nn.compact
    def __call__(self, stacked):  # [L, B, T, H] -> [B, T, H]
        num_layers = stacked.shape[0]
        weights = self.param("scalar_weights", nn.initializers.zeros, (num_layers,))
        gamma = self.param("gamma", nn.initializers.ones, ())
        norm = jax.nn.softmax(weights.astype(jnp.float32))
        mixed = jnp.einsum(
            "l,l...->...", norm.astype(stacked.dtype), stacked
        )
        return gamma.astype(stacked.dtype) * mixed


class BertEncoder(nn.Module):
    """input ids → contextual embeddings [B, T, H]."""

    config: BertConfig

    @nn.compact
    def __call__(
        self,
        input_ids,
        attention_mask,
        token_type_ids=None,
        deterministic: bool = True,
        position_ids=None,
        segment_ids=None,
    ):
        c = self.config
        if position_ids is None and input_ids.shape[-1] > c.max_position_embeddings:
            # with explicit position ids (the packed ragged batch, whose
            # flat token row is LONGER than any one request) the caller
            # owns keeping every id < max_position_embeddings — the
            # packer restarts positions at each segment boundary
            raise ValueError(
                f"sequence length {input_ids.shape[-1]} exceeds "
                f"max_position_embeddings={c.max_position_embeddings}; "
                "fold or truncate long inputs before encoding"
            )
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        # named scopes: profile/jaxpr attribution (docs/observability.md)
        with jax.named_scope("bert_embeddings"):
            hidden = BertEmbeddings(c, name="embeddings")(
                input_ids, token_type_ids, deterministic, position_ids=position_ids
            )
            # the ragged path masks attention on segment equality inside
            # the kernel; the padding-mask bias is the bucketed path's
            bias = (
                None if segment_ids is not None
                else mask_to_bias(attention_mask, dtype=c.dtype)
            )
        with jax.named_scope("bert_layers"):
            out = BertEncoderStack(c, name="encoder")(
                hidden, bias, deterministic, segment_ids
            )
        if c.last_layer_only:
            return out
        with jax.named_scope("scalar_mix"):
            return ScalarMix(c, name="scalar_mix")(out)


class BertPooler(nn.Module):
    """dropout(tanh(dense(CLS))) — the reference's BertPooler including its
    post-pool dropout (reference: model_memory.py:64,99)."""

    config: BertConfig

    @nn.compact
    def __call__(self, hidden, deterministic: bool = True):
        cls = hidden[:, 0]
        pooled = nn.tanh(
            nn.Dense(
                self.config.hidden_size, kernel_init=_dense_init(self.config),
                dtype=self.config.dtype, name="dense",
            )(cls)
        )
        return nn.Dropout(self.config.hidden_dropout)(
            pooled, deterministic=deterministic
        )
