"""TextCNN baseline classifier.

Reference (TextCNN/model_cnn.py): SpaCy word tokens → 300-d trainable
embedding (GloVe-initialized when vectors are available) → CNN encoder
with 256 filters per ngram size 2-5 → FeedForward(→512, ReLU) →
Linear(→2).  Inputs shorter than the largest ngram are padded up to it
(reference: model_cnn.py:36-46,101).

TPU note: the convolution bank is expressed as `nn.Conv` over the token
axis; all four ngram branches run in one program and XLA fuses the
max-pool reductions.  GloVe vectors are optional — zero-egress
environments train the embedding from scratch (`glove_path=None`).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from .losses import masked_cross_entropy  # noqa: F401  (re-exported for users)


class TextCNN(nn.Module):
    vocab_size: int
    embed_dim: int = 300
    num_filters: int = 256
    ngram_sizes: Sequence[int] = (2, 3, 4, 5)
    header_dim: int = 512
    num_classes: int = 2
    dropout: float = 0.1
    pad_id: int = 0

    @nn.compact
    def __call__(self, sample1, deterministic: bool = True) -> jax.Array:
        ids = sample1["input_ids"]
        mask = sample1["attention_mask"]
        min_len = max(self.ngram_sizes)
        if ids.shape[-1] < min_len:
            pad = min_len - ids.shape[-1]
            ids = jnp.pad(ids, ((0, 0), (0, pad)), constant_values=self.pad_id)
            mask = jnp.pad(mask, ((0, 0), (0, pad)))

        x = nn.Embed(self.vocab_size, self.embed_dim, name="embedding")(ids)
        # zero out padding embeddings so max-pool cannot pick them... except
        # where a row is fully padded; a -inf floor keeps the pool defined
        neg = jnp.finfo(x.dtype).min
        x = jnp.where(mask[..., None] > 0, x, 0.0)

        pooled = []
        for n in self.ngram_sizes:
            conv = nn.Conv(
                self.num_filters, kernel_size=(n,), padding="VALID",
                name=f"conv_{n}",
            )(x)
            conv = nn.relu(conv)
            # mask windows that begin beyond the real tokens
            starts = mask[:, : conv.shape[1]]
            conv = jnp.where(starts[..., None] > 0, conv, neg)
            pooled.append(conv.max(axis=1))
        features = jnp.concatenate(pooled, axis=-1)
        features = jnp.maximum(features, 0.0)  # all-padding rows → zeros
        features = nn.Dropout(self.dropout)(features, deterministic=deterministic)
        hidden = nn.relu(nn.Dense(self.header_dim, name="header")(features))
        hidden = nn.Dropout(self.dropout)(hidden, deterministic=deterministic)
        return nn.Dense(self.num_classes, use_bias=False, name="classifier")(hidden)

    def load_pretrained_embedding(self, params, vectors: np.ndarray):
        """Replace the embedding table (e.g. with GloVe vectors laid out by
        the tokenizer's vocab order).  Returns updated params."""
        if vectors.shape != params["params"]["embedding"]["embedding"].shape:
            raise ValueError(
                f"vector table {vectors.shape} != embedding "
                f"{params['params']['embedding']['embedding'].shape}"
            )
        import flax

        flat = flax.traverse_util.flatten_dict(params)
        flat[("params", "embedding", "embedding")] = jnp.asarray(vectors)
        return flax.traverse_util.unflatten_dict(flat)


def load_glove_vectors(
    path: str, vocab: Sequence[str], dim: int = 300, seed: int = 0
) -> np.ndarray:
    """Read a GloVe .txt file and assemble a [V, dim] table in vocab order;
    missing words get small random vectors."""
    rng = np.random.default_rng(seed)
    table = rng.normal(scale=0.1, size=(len(vocab), dim)).astype(np.float32)
    wanted = {w: i for i, w in enumerate(vocab)}
    with open(path, encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip().split(" ")
            if parts[0] in wanted and len(parts) == dim + 1:
                table[wanted[parts[0]]] = np.asarray(parts[1:], dtype=np.float32)
    return table
