"""Shared loss primitives."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def masked_cross_entropy(
    logits: jax.Array, labels: jax.Array, weights: jax.Array
) -> jax.Array:
    """Mean cross-entropy over rows with nonzero weight (padding rows are
    dead).  Softmax is taken in float32."""
    log_probs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(log_probs, labels[:, None], axis=-1)[:, 0]
    total = jnp.maximum(weights.sum(), 1.0)
    return (nll * weights).sum() / total
