from .bert import BertConfig, BertEncoder, BertPooler, ScalarMix  # noqa: F401
from .memory import (  # noqa: F401
    MemoryModel,
    anchor_probs,
    best_anchor_score,
    pair_loss,
)
from .single import SingleModel, classification_loss  # noqa: F401
