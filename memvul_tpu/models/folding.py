"""Long-sequence segment folding.

The reference handles inputs longer than ``max_length`` not with sequence
parallelism but by *folding*: the token stream is split into segments,
each wrapped with [CLS]...[SEP], all segments encoded independently as a
bigger batch, then the embeddings are unfolded and re-stitched to
[B, total_len, D] (reference: custom_PTM_embedder.py:208-242,244-284,
286-381).

On TPU this is just a reshape: [B, S·L'] → [B·S, L] is embarrassingly
parallel and keeps shapes static.  Note that for CLS-pooled classifiers
(both models here) folding is prediction-equivalent to truncation — the
pooled vector is segment 0's CLS either way — so the scoring paths use
plain truncation; this module exists for embedder-level parity and for
consumers that pool over the full token stream.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def fold_tokens(
    ids: np.ndarray,
    mask: np.ndarray,
    max_length: int,
    cls_id: int,
    sep_id: int,
    pad_id: int,
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Fold [B, T] token ids (already CLS/SEP framed) into
    [B·S, max_length] segments, each re-framed with CLS/SEP.

    Returns (folded_ids, folded_mask, num_segments).
    """
    batch, total = ids.shape
    inner = max_length - 2  # room for the per-segment CLS/SEP
    # copies: the SEP-strip below must not write through into caller arrays
    body = ids[:, 1:].copy()  # drop the leading CLS; keep content + SEP
    body_mask = mask[:, 1:].copy()
    # strip the final SEP from the content stream (it is re-added per segment)
    lengths = body_mask.sum(axis=1)
    for b in range(batch):
        if lengths[b] > 0 and body[b, lengths[b] - 1] == sep_id:
            body[b, lengths[b] - 1] = pad_id
            body_mask[b, lengths[b] - 1] = 0
    # number of segments from the longest *actual* content run (masks are
    # contiguous prefixes by construction)
    longest = int(body_mask.sum(axis=1).max()) if batch else 0
    num_segments = max(1, -(-longest // inner))
    width = num_segments * inner
    copy = min(width, body.shape[1])
    padded = np.full((batch, width), pad_id, dtype=ids.dtype)
    padded_mask = np.zeros_like(padded)
    padded[:, :copy] = body[:, :copy]
    padded_mask[:, :copy] = body_mask[:, :copy]

    segments = padded.reshape(batch * num_segments, inner)
    seg_mask = padded_mask.reshape(batch * num_segments, inner)

    folded = np.full((batch * num_segments, max_length), pad_id, dtype=ids.dtype)
    folded_mask = np.zeros_like(folded)
    has_content = seg_mask.sum(axis=1) > 0
    # the first segment of each report always participates (CLS pooling)
    has_content[:: num_segments] = True
    folded[:, 0] = cls_id
    folded[:, 1:-1] = segments
    folded_mask[:, 0] = 1
    folded_mask[:, 1:-1] = seg_mask
    # close each non-empty segment with SEP at the end of its content
    content_len = folded_mask.sum(axis=1)
    for i in range(folded.shape[0]):
        if has_content[i]:
            end = int(content_len[i])
            folded[i, end] = sep_id
            folded_mask[i, end] = 1
        else:
            folded_mask[i, :] = 0
    return folded, folded_mask, num_segments


def unfold_embeddings(
    embeddings: np.ndarray,
    num_segments: int,
    folded_mask: np.ndarray = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """[B·S, L, D] per-segment embeddings → ([B, S·(L-2), D] stitched
    stream, [B, S·(L-2)] validity mask), mirroring the reference's unfold
    (custom_PTM_embedder.py:286-381).

    Positions 0 and L-1 of every segment (the re-inserted CLS and the
    worst-case SEP slot) are dropped structurally; ``folded_mask`` (the
    mask returned by :func:`fold_tokens`) additionally invalidates the SEP
    of partially-filled segments and padding, which sit *inside* the
    [1:-1] window.  Without it the validity mask only reflects the
    structural trim."""
    bs, length, dim = embeddings.shape
    batch = bs // num_segments
    inner = embeddings[:, 1:-1, :]
    stream = inner.reshape(batch, num_segments * (length - 2), dim)
    if folded_mask is not None:
        valid = folded_mask.copy()
        # invalidate each segment's trailing SEP (last masked position)
        lengths = valid.sum(axis=1)
        for i in range(bs):
            if lengths[i] > 0:
                valid[i, lengths[i] - 1] = 0
        valid = valid[:, 1:-1].reshape(batch, num_segments * (length - 2))
    else:
        valid = np.ones((batch, num_segments * (length - 2)), dtype=np.int32)
    return stream, valid
